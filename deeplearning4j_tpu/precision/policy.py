"""Precision policies: which dtype each role of a training/serving step
runs in (ISSUE 4 tentpole).

Reference capability: the reference stack exposes a single global
``DataType`` knob (``dataType(DataType.HALF)``); cuDNN-era experience
(PAPERS.md "cuDNN: Efficient Primitives") and every TPU framework since
made precision a *policy* instead — separate dtypes for the stored
(master) parameters, the compute that feeds the MXU, and the loss/output
boundary, plus loss scaling for narrow-exponent compute types.

A ``Policy`` names three dtypes and an optional loss-scaling mode:

- ``param_dtype``: what ``init()`` allocates and the updater state
  mirrors (the *master* weights — fp32 under any ``*_mixed`` policy);
- ``compute_dtype``: what the forward/backward matmuls run in (params
  and inputs are cast at the step boundary; the cast's transpose
  upcasts the gradients back, so grads/moments stay ``param_dtype``);
- ``output_dtype``: what inference returns at the serving boundary;
- ``loss_scaling``: ``None``, ``"dynamic"`` (DynamicLossScaler compiled
  into the jitted step), or a fixed float scale.

Named policies::

    "float32"     fp32 / fp32 / fp32, no scaling       (the default)
    "bfloat16"    bf16 / bf16 / bf16, no scaling       (pure bf16)
    "bf16_mixed"  fp32 master, bf16 compute, fp32 out, dynamic scaling
    "fp16_mixed"  fp32 master, fp16 compute, fp32 out, dynamic scaling

bf16 shares fp32's exponent range, so overflow under ``bf16_mixed`` is
rare — the dynamic scaler is then a cheap insurance policy (one fused
finite-check reduction riding with the gradients, a ``jnp.where`` gate
on the donated buffers: a bad step costs zero host syncs).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax.numpy as jnp


@dataclass(frozen=True)
class Policy:
    name: str = "float32"
    param_dtype: str = "float32"
    compute_dtype: str = "float32"
    output_dtype: str = "float32"
    # None | "dynamic" | fixed float scale
    loss_scaling: object = None
    # DynamicLossScaler knobs (ignored unless loss_scaling == "dynamic")
    init_scale: float = 2.0 ** 15
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 2000

    @property
    def param_jnp(self):
        return jnp.dtype(self.param_dtype)

    @property
    def compute_jnp(self):
        return jnp.dtype(self.compute_dtype)

    @property
    def output_jnp(self):
        return jnp.dtype(self.output_dtype)

    @property
    def is_mixed(self) -> bool:
        return self.compute_dtype != self.param_dtype

    @property
    def scaling_enabled(self) -> bool:
        return self.loss_scaling is not None

    def to_json(self):
        """Serialize for configuration.json round-trips. Named policies
        collapse to their string (stable across releases); customized
        ones serialize field-by-field."""
        if self.name in NAMED_POLICIES and self == NAMED_POLICIES[self.name]:
            return self.name
        d = {"@policy": self.name}
        for k, v in self.__dict__.items():
            d[k] = v
        return d

    @staticmethod
    def from_json(d):
        if d is None or isinstance(d, Policy):
            return d
        if isinstance(d, str):
            return named_policy(d)
        d = dict(d)
        name = d.pop("@policy", d.pop("name", "custom"))
        return Policy(name=name, **{k: v for k, v in d.items()
                                    if k in Policy.__dataclass_fields__})


def _uniform(name, dtype):
    return Policy(name=name, param_dtype=dtype, compute_dtype=dtype,
                  output_dtype=dtype)


NAMED_POLICIES = {
    "float32": _uniform("float32", "float32"),
    "fp32": _uniform("fp32", "float32"),
    "bfloat16": _uniform("bfloat16", "bfloat16"),
    "bf16": _uniform("bf16", "bfloat16"),
    "bf16_mixed": Policy(name="bf16_mixed", param_dtype="float32",
                         compute_dtype="bfloat16", output_dtype="float32",
                         loss_scaling="dynamic"),
    "fp16_mixed": Policy(name="fp16_mixed", param_dtype="float32",
                         compute_dtype="float16", output_dtype="float32",
                         loss_scaling="dynamic"),
}


def named_policy(name: str) -> Policy:
    try:
        return NAMED_POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown precision policy {name!r}; choose from "
            f"{sorted(NAMED_POLICIES)} or pass a precision.Policy") from None


def resolve_policy(precision, data_type) -> Policy:
    """The effective Policy for a net configuration: ``precision`` may be
    None (uniform policy in the configured dataType), a policy name, a
    Policy, or a serialized policy dict."""
    if precision is None:
        dt = str(jnp.dtype(data_type))
        return _uniform(dt, dt)
    if isinstance(precision, Policy):
        return precision
    if isinstance(precision, str):
        return named_policy(precision)
    return Policy.from_json(precision)


def cast_floating(tree, dtype):
    """Cast every inexact leaf of a pytree to ``dtype``, leaving integer
    leaves (embedding ids) and fp64 leaves (the gradient-check harness
    runs whole nets in fp64) untouched. Identity when nothing needs a
    cast, so inactive policies add zero ops to the jaxpr."""
    import jax

    dtype = jnp.dtype(dtype)

    def one(x):
        xd = getattr(x, "dtype", None)
        if xd is None or not jnp.issubdtype(xd, jnp.floating):
            return x
        if xd == dtype or xd == jnp.float64:
            return x
        return x.astype(dtype)

    return jax.tree_util.tree_map(one, tree)
