"""Int8 post-training quantization for serving (ISSUE 4 tentpole).

``quantize(net, calibration_iter)`` snapshots a trained
MultiLayerNetwork's dense weight matrices as **int8 with per-output-
channel dequant scales** (symmetric absmax), keeps biases, every
non-matrix parameter, and embedding tables (auto-detected by layer
class; extend with ``skip_layers=``) in float, runs activations in a
configurable compute dtype (bf16 by default — the TPU-idiomatic
pairing: int8 weight storage halves HBM traffic, bf16 math keeps the
MXU fed), and returns a ``QuantizedServable`` that registers /
AOT-warms / batches through the existing ModelRegistry +
DynamicBatcher + ``/serving/v1`` route completely unchanged.

Calibration does three jobs:

- feeds the existing bucket ladder (the shapes it covers are the shapes
  warmup compiles — no new bucketing machinery);
- collects per-layer, per-channel activation absmax stats (reported in
  ``describe()``; the hook static activation quantization would consume);
- measures output fidelity: ``calibration_max_err`` is the max absolute
  difference between the float net and the quantized servable over the
  calibration batches, so a registry can refuse a quantization that
  drifted (acceptance here: atol <= 0.05 on MNIST-scale nets).

Dequantization is traced into the inference function
(``(q_int8 -> f32) * scale -> compute_dtype``), so XLA schedules it next
to the matmul it feeds; the weights live in device memory as int8.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.serving.servable import Servable

_EPS = 1e-12


def _is_qleaf(x):
    return isinstance(x, tuple) and len(x) == 2


def quantize_array(w) -> tuple:
    """Symmetric per-output-channel int8 quantization of a 2-D weight
    [in, out]: scale[c] = absmax(w[:, c]) / 127. Returns (q_int8,
    scale_f32)."""
    w = np.asarray(jax.device_get(w), np.float32)
    absmax = np.maximum(np.abs(w).max(axis=0), _EPS)
    scale = (absmax / 127.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return q, scale


def dequantize_array(q, scale, compute_dtype):
    """Traced inverse: int8 * scale in f32, then down to compute."""
    return (q.astype(jnp.float32) * scale).astype(compute_dtype)


def quantize_params(params, skip_layers=()):
    """Per-layer param list -> same structure with every eligible 2-D
    float leaf replaced by an (int8, scale) pair. Conv kernels, biases,
    and vectors ride through untouched (weight-only dense quantization —
    the safe, high-leverage subset); embedding tables are excluded by
    the caller via skip_layers (quantize() auto-detects them)."""
    out = []
    for i, p in enumerate(params):
        if i in skip_layers:
            out.append(jax.device_get(p))
            continue

        def one(v):
            v = np.asarray(jax.device_get(v))
            if v.ndim == 2 and np.issubdtype(v.dtype, np.floating):
                return quantize_array(v)
            return v

        out.append(jax.tree_util.tree_map(one, p))
    return out


def _dequant_tree(qparams, compute_dtype):
    """Traced: per-layer qparams -> plain param trees; (int8, scale)
    pairs dequantize, float leaves pass through (biases stay float32)."""
    return [jax.tree_util.tree_map(
        lambda l: (dequantize_array(*l, compute_dtype) if _is_qleaf(l)
                   else l), p, is_leaf=_is_qleaf)
        for p in qparams]


def quantized_bytes(qparams) -> dict:
    """{'int8': n, 'float': n} payload accounting for describe()."""
    int8 = flt = 0
    for leaf in jax.tree_util.tree_leaves(qparams, is_leaf=_is_qleaf):
        if _is_qleaf(leaf):
            q, s = leaf
            int8 += q.size
            flt += s.size * 4
        else:
            a = np.asarray(leaf)
            flt += a.size * a.dtype.itemsize
    return {"int8": int(int8), "float": int(flt)}


class QuantizedServable(Servable):
    """A frozen int8 snapshot of a MultiLayerNetwork, served through the
    standard Servable contract (AOT bucket warmup, DynamicBatcher
    coalescing, zero steady-state recompiles).

    PTQ semantics: the weights AND layer states (BN running stats, ...)
    are a snapshot — training the source net afterwards does NOT update
    this servable (re-quantize to refresh). Only the layer/preprocessor
    structure is captured, never the net object: dropping the training
    net after quantize() frees its fp32 master params and optimizer
    state; the servable keeps just the int8 payload + float leftovers.
    """

    def __init__(self, net, example_shape, dtype=None,
                 compute_dtype="bfloat16", skip_layers=()):
        from deeplearning4j_tpu.precision.policy import resolve_policy

        pol = resolve_policy(getattr(net.conf, "precision", None),
                             net.conf.dataType)
        if dtype is None:
            dtype = np.dtype(pol.output_jnp)
        super().__init__(example_shape, dtype)
        net._check_init()
        # structure only — layer config objects and the preprocessor
        # list carry no parameters
        self._layers = list(net.layers)
        self._preprocessors = list(net.conf.preprocessors)
        self.compute_dtype = jnp.dtype(compute_dtype)
        self.output_dtype = pol.output_jnp
        skip = set(skip_layers) | {
            i for i, lr in enumerate(self._layers)
            if "Embedding" in type(lr).__name__}
        self._qparams = quantize_params(net._params, skip)
        self._qstates = jax.device_get(net._states)
        self._jitted = None
        self.calibration_max_err = None
        self.activation_absmax = None

    def _jit_fn(self):
        if self._jitted is None:
            from deeplearning4j_tpu.nn.conf.configuration import (
                _apply_preprocessor)

            layers = self._layers
            pps = self._preprocessors
            cd, od = self.compute_dtype, self.output_dtype

            def fn(qparams, states, x):
                params = _dequant_tree(qparams, cd)
                x = jnp.asarray(x)
                if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != cd:
                    x = x.astype(cd)   # int8 weights, compute-dtype acts
                for i, lr in enumerate(layers):
                    x = _apply_preprocessor(pps[i], x)
                    x, _ = lr.apply(params[i], states[i], x, False, None)
                return x.astype(od) if x.dtype != od else x

            self._jitted = jax.jit(fn)
        return self._jitted

    def _call_args(self):
        return (self._qparams, self._qstates)

    def describe_extra(self) -> dict:
        d = {"quantization": "int8_per_channel_absmax",
             "compute_dtype": str(self.compute_dtype),
             "bytes": quantized_bytes(self._qparams)}
        if self.calibration_max_err is not None:
            d["calibration_max_err"] = round(
                float(self.calibration_max_err), 6)
        return d


def _calibration_features(calibration_iter):
    """Accept arrays, (features, labels) tuples, DataSet-likes, or any
    iterable of those."""
    for item in calibration_iter:
        if hasattr(item, "getFeatures"):
            yield np.asarray(item.getFeatures())
        elif isinstance(item, (tuple, list)):
            yield np.asarray(item[0])
        else:
            yield np.asarray(item)


def quantize(model, calibration_iter, example_shape=None, dtype=None,
             compute_dtype="bfloat16", skip_layers=()) -> QuantizedServable:
    """Int8 PTQ entry point.

    model: a MultiLayerNetwork or a NetworkServable wrapping one;
    calibration_iter: batches the quantized model will be checked
      against (and whose per-layer activation absmax is recorded);
    example_shape: per-example input shape — inferred from the wrapped
      servable when a NetworkServable is passed.
    """
    from deeplearning4j_tpu.serving.servable import NetworkServable

    if isinstance(model, NetworkServable):
        if example_shape is None:
            example_shape = model.example_shape
        net = model.net
    else:
        net = model
    if type(net).__name__ != "MultiLayerNetwork":
        raise TypeError(
            f"int8 PTQ currently supports MultiLayerNetwork (got "
            f"{type(net).__name__}); wrap graphs in a distilled "
            f"sequential net or serve them in float")
    sv = QuantizedServable(net, example_shape, dtype=dtype,
                           compute_dtype=compute_dtype,
                           skip_layers=skip_layers)
    batches = list(_calibration_features(calibration_iter))
    if batches:
        act_absmax: list = [None] * len(net.layers)
        max_err = 0.0
        for f in batches:
            acts = net.feedForward(f)
            for i in range(len(net.layers)):
                a = np.abs(np.asarray(acts[i + 1].numpy(),
                                      np.float32))
                # per-channel over axis 1, everything else batched away
                red = tuple(ax for ax in range(a.ndim) if ax != 1) \
                    if a.ndim > 1 else (0,)
                cur = a.max(axis=red)
                act_absmax[i] = cur if act_absmax[i] is None else \
                    np.maximum(act_absmax[i], cur)
            ref = np.asarray(net.output(f).numpy(), np.float32)
            got = np.asarray(sv.infer(f), np.float32)
            max_err = max(max_err, float(np.abs(got - ref).max()))
        sv.calibration_max_err = max_err
        sv.activation_absmax = [None if a is None else a.tolist()
                                for a in act_absmax]
    from deeplearning4j_tpu.telemetry import flight

    flight.record("quantize", layers=len(net.layers),
                  calibration_batches=len(batches),
                  max_err=sv.calibration_max_err)
    return sv
