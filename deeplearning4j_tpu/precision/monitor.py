"""Host side of the loss-scaling pipeline: one-step-behind publication
of the scaler state (mirroring telemetry.health.HealthMonitor).

The jitted step returns the scaler's NEW state every step; the monitor
keeps a one-deep pending slot and processes the PREVIOUS step's state —
already materialized in steady state, so reading it never stalls the
dispatch queue. An overflow is detected as a delta in the cumulative
device-side ``overflows`` counter, so no extra per-step flag output is
needed and scan-of-K-steps launches (fitMultiBatch) publish correctly
from their final state.

Metrics (documented in docs/OBSERVABILITY.md):

- ``dl4j_precision_loss_scale{loop}``        current loss scale (gauge)
- ``dl4j_precision_overflow_total{loop}``    non-finite scaled-gradient
  steps observed by the scaler (counter)
- ``dl4j_precision_skipped_steps_total{loop}`` steps discarded on device
  by the overflow gate (counter; == overflow_total for the in-step gate)

Every overflow also lands in the flight recorder as a ``precision``
event naming the loop, step, and the halved scale. The monitor exposes
``skipped_at(step)`` so the health monitor's SKIP_BATCH accounting can
defer to it when both gates fire on the same step (ISSUE 4 satellite:
one skipped step must not count twice).
"""

from __future__ import annotations

from collections import deque

import numpy as np

from deeplearning4j_tpu.telemetry import flight
from deeplearning4j_tpu.telemetry import registry as _registry
from deeplearning4j_tpu.telemetry.registry import get_registry

SCALE_HELP = "Current dynamic loss scale per training loop"
OVERFLOW_HELP = ("Training steps whose scaled gradients went non-finite "
                 "(the dynamic loss scaler backed off)")
SKIPPED_HELP = ("Training steps discarded on device by the loss-scaler "
                "overflow gate")


class PrecisionInstruments:
    __slots__ = ("scale", "overflows", "skipped")

    def __init__(self, registry, loop):
        self.scale = registry.gauge(
            "dl4j_precision_loss_scale", SCALE_HELP,
            ("loop",)).labels(loop=loop)
        self.overflows = registry.counter(
            "dl4j_precision_overflow_total", OVERFLOW_HELP,
            ("loop",)).labels(loop=loop)
        self.skipped = registry.counter(
            "dl4j_precision_skipped_steps_total", SKIPPED_HELP,
            ("loop",)).labels(loop=loop)


def _host(x) -> float:
    if getattr(x, "is_fully_addressable", True):
        return float(np.asarray(x))
    return float(np.asarray(x.addressable_data(0)))


class PrecisionMonitor:
    """One per fit loop (created by ``monitor_for``); call
    ``on_step(step, prec_state)`` after each step and ``flush()`` at the
    end of the loop — BEFORE the health monitor's equivalents, so the
    skip set is populated when health accounting asks."""

    def __init__(self, loop, instruments=None):
        self.loop = loop
        self.instruments = instruments
        self._pending = None
        self._last_overflows = 0
        # recent overflow steps for the health-monitor handshake; bounded
        # so a pathological run cannot grow host memory
        self._recent_skips: deque = deque(maxlen=256)

    def skipped_at(self, step) -> bool:
        return step in self._recent_skips

    def baseline_from(self, state):
        """Anchor the overflow-delta detection to the CURRENT cumulative
        device count (call once before the hot loop: the monitor is
        per-fit, the device counter is per-net-lifetime). The state is
        materialized — produced by init() or a previous step — so this
        read does not stall anything mid-loop."""
        if state:
            self._last_overflows = int(_host(state["overflows"]))

    def on_step(self, step, prec_state):
        if not prec_state:
            return
        prev, self._pending = self._pending, (step, prec_state)
        if prev is not None:
            self._process(*prev)

    def flush(self):
        prev, self._pending = self._pending, None
        if prev is not None:
            self._process(*prev)

    def on_launch(self, steps, state):
        """Scan-of-K-steps launch (fitMultiBatch): publish from the
        launch's final scaler state. Per-step attribution is not
        available from a fused launch, so any overflows are attributed
        to the whole `steps` range (keeps the health-monitor handshake
        sound: a skip inside the launch never double-counts)."""
        if not state:
            return
        scale = _host(state["scale"])
        overflows = int(_host(state["overflows"]))
        inst = self.instruments
        if inst is not None:
            inst.scale.set(scale)
        delta = overflows - self._last_overflows
        if delta > 0:
            self._last_overflows = overflows
            # only the last maxlen indices can survive the deque — slice
            # the range instead of iterating a potentially huge launch
            self._recent_skips.extend(
                steps[-(self._recent_skips.maxlen or len(steps)):])
            if inst is not None:
                inst.overflows.inc(delta)
                inst.skipped.inc(delta)
            flight.record("precision", loop=self.loop,
                          step=[min(steps), max(steps)],
                          event="overflow", skipped=delta,
                          loss_scale=scale, overflows_total=overflows)

    def _process(self, step, state):
        scale = _host(state["scale"])
        overflows = int(_host(state["overflows"]))
        inst = self.instruments
        if inst is not None:
            inst.scale.set(scale)
        delta = overflows - self._last_overflows
        if delta > 0:
            self._last_overflows = overflows
            self._recent_skips.append(step)
            if inst is not None:
                inst.overflows.inc(delta)
                inst.skipped.inc(delta)
            flight.record("precision", loop=self.loop, step=step,
                          event="overflow", skipped=delta,
                          loss_scale=scale, overflows_total=overflows)


def monitor_for(loop, policy) -> PrecisionMonitor | None:
    """The per-fit PrecisionMonitor, or None when the policy has no loss
    scaling or telemetry is disabled (preserving the zero-registry-calls
    -per-step contract; the on-device gate runs regardless)."""
    if policy is None or not policy.scaling_enabled:
        return None
    if not _registry.enabled():
        return None
    return PrecisionMonitor(loop, PrecisionInstruments(get_registry(), loop))
