"""Hyperparameter search.

Reference capability: arbiter (org.deeplearning4j.arbiter.optimize.*,
SURVEY.md §2.7): ParameterSpace declarations, candidate generators
(random / grid), an OptimizationConfiguration, and a LocalOptimizationRunner
that builds-trains-scores each candidate and tracks the best. The model
builder is a user callable candidate_params -> model; the score function a
callable (model, data) -> float."""

from __future__ import annotations

import itertools
import math

import numpy as np


# -- parameter spaces --------------------------------------------------------

class ContinuousParameterSpace:
    def __init__(self, minValue, maxValue, log=False):
        self.lo = float(minValue)
        self.hi = float(maxValue)
        self.log = log

    def sample(self, rng):
        if self.log:
            return float(np.exp(rng.uniform(math.log(self.lo),
                                            math.log(self.hi))))
        return float(rng.uniform(self.lo, self.hi))

    def grid(self, n):
        if self.log:
            return list(np.exp(np.linspace(math.log(self.lo),
                                           math.log(self.hi), n)))
        return list(np.linspace(self.lo, self.hi, n))


class IntegerParameterSpace:
    def __init__(self, minValue, maxValue):
        self.lo = int(minValue)
        self.hi = int(maxValue)

    def sample(self, rng):
        return int(rng.integers(self.lo, self.hi + 1))

    def grid(self, n):
        return sorted({int(v) for v in
                       np.linspace(self.lo, self.hi, n)})


class DiscreteParameterSpace:
    def __init__(self, *values):
        self.values = list(values[0]) if len(values) == 1 and isinstance(
            values[0], (list, tuple)) else list(values)

    def sample(self, rng):
        return self.values[rng.integers(len(self.values))]

    def grid(self, n):
        return list(self.values)


# -- candidate generators ----------------------------------------------------

class CandidateGenerator:
    def __init__(self, space: dict):
        self.space = space

    def candidates(self, limit):
        raise NotImplementedError


class RandomSearchGenerator(CandidateGenerator):
    def __init__(self, space: dict, seed=0):
        super().__init__(space)
        self.seed = seed

    def candidates(self, limit):
        rng = np.random.default_rng(self.seed)
        for _ in range(limit):
            yield {k: (v.sample(rng) if hasattr(v, "sample") else v)
                   for k, v in self.space.items()}


class GridSearchCandidateGenerator(CandidateGenerator):
    def __init__(self, space: dict, discretizationCount=3):
        super().__init__(space)
        self.n = discretizationCount

    def candidates(self, limit):
        keys = list(self.space)
        axes = [self.space[k].grid(self.n) if hasattr(self.space[k], "grid")
                else [self.space[k]] for k in keys]
        for i, combo in enumerate(itertools.product(*axes)):
            if i >= limit:
                return
            yield dict(zip(keys, combo))


# -- runner ------------------------------------------------------------------

class OptimizationConfiguration:
    class Builder:
        def __init__(self):
            self._kw = {}

        def candidateGenerator(self, g):
            self._kw["generator"] = g
            return self

        def modelBuilder(self, fn):
            """fn(candidate: dict) -> model with fit/score capability."""
            self._kw["model_builder"] = fn
            return self

        def scoreFunction(self, fn, minimize=True):
            self._kw["score_fn"] = fn
            self._kw["minimize"] = minimize
            return self

        def terminationConditions(self, maxCandidates=10,
                                  maxTimeSeconds=None):
            self._kw["max_candidates"] = maxCandidates
            self._kw["max_time"] = maxTimeSeconds
            return self

        def build(self):
            cfg = OptimizationConfiguration()
            cfg.__dict__.update(self._kw)
            return cfg


class OptimizationResult:
    def __init__(self, candidate, score, index, model):
        self.candidate = candidate
        self.score = score
        self.index = index
        self.model = model

    def getBestCandidate(self):
        return self.candidate

    def getBestScore(self):
        return self.score


class LocalOptimizationRunner:
    def __init__(self, config: OptimizationConfiguration):
        self.config = config
        self.results: list[OptimizationResult] = []

    def execute(self) -> OptimizationResult:
        import time

        cfg = self.config
        minimize = getattr(cfg, "minimize", True)
        best = None
        t0 = time.time()
        for i, cand in enumerate(
                cfg.generator.candidates(cfg.max_candidates)):
            if cfg.max_time and time.time() - t0 > cfg.max_time:
                break
            model = cfg.model_builder(cand)
            score = cfg.score_fn(model)
            res = OptimizationResult(cand, score, i, model)
            self.results.append(res)
            if best is None or ((score < best.score) if minimize
                                else (score > best.score)):
                best = res
        if best is None:
            raise ValueError("no candidates evaluated")
        return best

    def bestScore(self):
        if not self.results:
            return None
        minimize = getattr(self.config, "minimize", True)
        return (min if minimize else max)(r.score for r in self.results)
