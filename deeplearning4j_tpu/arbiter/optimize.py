"""Hyperparameter search.

Reference capability: arbiter (org.deeplearning4j.arbiter.optimize.*,
SURVEY.md §2.7): ParameterSpace declarations, candidate generators
(random / grid), an OptimizationConfiguration, and a LocalOptimizationRunner
that builds-trains-scores each candidate and tracks the best. The model
builder is a user callable candidate_params -> model; the score function a
callable (model, data) -> float."""

from __future__ import annotations

import itertools
import math

import numpy as np


# -- parameter spaces --------------------------------------------------------

class ContinuousParameterSpace:
    def __init__(self, minValue, maxValue, log=False):
        self.lo = float(minValue)
        self.hi = float(maxValue)
        self.log = log

    def sample(self, rng):
        if self.log:
            return float(np.exp(rng.uniform(math.log(self.lo),
                                            math.log(self.hi))))
        return float(rng.uniform(self.lo, self.hi))

    def grid(self, n):
        if self.log:
            return list(np.exp(np.linspace(math.log(self.lo),
                                           math.log(self.hi), n)))
        return list(np.linspace(self.lo, self.hi, n))


class IntegerParameterSpace:
    def __init__(self, minValue, maxValue):
        self.lo = int(minValue)
        self.hi = int(maxValue)

    def sample(self, rng):
        return int(rng.integers(self.lo, self.hi + 1))

    def grid(self, n):
        return sorted({int(v) for v in
                       np.linspace(self.lo, self.hi, n)})


class DiscreteParameterSpace:
    def __init__(self, *values):
        self.values = list(values[0]) if len(values) == 1 and isinstance(
            values[0], (list, tuple)) else list(values)

    def sample(self, rng):
        return self.values[rng.integers(len(self.values))]

    def grid(self, n):
        return list(self.values)


# -- candidate generators ----------------------------------------------------

class CandidateGenerator:
    def __init__(self, space: dict):
        self.space = space

    def candidates(self, limit):
        raise NotImplementedError


class RandomSearchGenerator(CandidateGenerator):
    def __init__(self, space: dict, seed=0):
        super().__init__(space)
        self.seed = seed

    def candidates(self, limit):
        rng = np.random.default_rng(self.seed)
        for _ in range(limit):
            yield {k: (v.sample(rng) if hasattr(v, "sample") else v)
                   for k, v in self.space.items()}


class GridSearchCandidateGenerator(CandidateGenerator):
    def __init__(self, space: dict, discretizationCount=3):
        super().__init__(space)
        self.n = discretizationCount

    def candidates(self, limit):
        keys = list(self.space)
        axes = [self.space[k].grid(self.n) if hasattr(self.space[k], "grid")
                else [self.space[k]] for k in keys]
        for i, combo in enumerate(itertools.product(*axes)):
            if i >= limit:
                return
            yield dict(zip(keys, combo))


# -- runner ------------------------------------------------------------------

class OptimizationConfiguration:
    class Builder:
        def __init__(self):
            self._kw = {}

        def candidateGenerator(self, g):
            self._kw["generator"] = g
            return self

        def modelBuilder(self, fn):
            """fn(candidate: dict) -> model with fit/score capability."""
            self._kw["model_builder"] = fn
            return self

        def scoreFunction(self, fn, minimize=True):
            self._kw["score_fn"] = fn
            self._kw["minimize"] = minimize
            return self

        def terminationConditions(self, maxCandidates=10,
                                  maxTimeSeconds=None):
            self._kw["max_candidates"] = maxCandidates
            self._kw["max_time"] = maxTimeSeconds
            return self

        def build(self):
            cfg = OptimizationConfiguration()
            cfg.__dict__.update(self._kw)
            return cfg


class OptimizationResult:
    def __init__(self, candidate, score, index, model):
        self.candidate = candidate
        self.score = score
        self.index = index
        self.model = model

    def getBestCandidate(self):
        return self.candidate

    def getBestScore(self):
        return self.score


class LocalOptimizationRunner:
    def __init__(self, config: OptimizationConfiguration):
        self.config = config
        self.results: list[OptimizationResult] = []

    def execute(self) -> OptimizationResult:
        import time

        cfg = self.config
        minimize = getattr(cfg, "minimize", True)
        if hasattr(cfg.generator, "minimize"):
            # model-based generators rank observations themselves; their
            # good/bad split must agree with the runner's objective sense
            cfg.generator.minimize = minimize
        best = None
        t0 = time.time()
        for i, cand in enumerate(
                cfg.generator.candidates(cfg.max_candidates)):
            if cfg.max_time and time.time() - t0 > cfg.max_time:
                break
            model = cfg.model_builder(cand)
            score = cfg.score_fn(model)
            res = OptimizationResult(cand, score, i, model)
            self.results.append(res)
            if hasattr(cfg.generator, "observe"):
                cfg.generator.observe(cand, score)
            if best is None or ((score < best.score) if minimize
                                else (score > best.score)):
                best = res
        if best is None:
            raise ValueError("no candidates evaluated")
        return best

    def bestScore(self):
        if not self.results:
            return None
        minimize = getattr(self.config, "minimize", True)
        return (min if minimize else max)(r.score for r in self.results)


class TpeCandidateGenerator(CandidateGenerator):
    """Tree-structured Parzen Estimator candidate generator — the
    model-based ("Bayesian-ish") search the reference's arbiter offers
    beyond random/grid (SURVEY.md §2.7 arbiter row).

    Standard TPE recipe (Bergstra et al. 2011), per-parameter factored:
    observations are split at the gamma-quantile into good/bad sets; each
    parameter fits a Parzen (Gaussian-kernel) density l(x) over the good
    set and g(x) over the bad; candidates are drawn from l and ranked by
    l(x)/g(x), maximizing expected improvement. Discrete parameters use
    smoothed category frequencies.

    The runner feeds scores back via observe(); until n_startup
    observations arrive the generator emits random samples (TPE needs a
    seed population)."""

    def __init__(self, space: dict, seed=0, n_startup=8, gamma=0.25,
                 n_ei_candidates=24, minimize=True):
        super().__init__(space)
        self.rng = np.random.default_rng(seed)
        self.n_startup = n_startup
        self.gamma = gamma
        self.n_ei = n_ei_candidates
        self.minimize = minimize
        self._obs: list[tuple[dict, float]] = []

    def observe(self, candidate: dict, score: float):
        self._obs.append((candidate, float(score)))

    # -- per-parameter parzen machinery ---------------------------------
    def _split(self):
        obs = sorted(self._obs, key=lambda cs: cs[1],
                     reverse=not self.minimize)
        n_good = max(1, int(np.ceil(self.gamma * len(obs))))
        good = [c for c, _ in obs[:n_good]]
        bad = [c for c, _ in obs[n_good:]] or good
        return good, bad

    def _transform(self, p, v):
        if isinstance(p, ContinuousParameterSpace) and p.log:
            return math.log(v)
        return float(v)

    def _bounds(self, p):
        if isinstance(p, ContinuousParameterSpace) and p.log:
            return math.log(p.lo), math.log(p.hi)
        return float(p.lo), float(p.hi)

    def _parzen_sample(self, p, values):
        lo, hi = self._bounds(p)
        zs = [self._transform(p, v) for v in values]
        bw = max((hi - lo) / max(len(zs), 1) * 2.0, 1e-6 * (hi - lo))
        z = self.rng.choice(zs) + self.rng.normal(0.0, bw)
        z = float(np.clip(z, lo, hi))
        x = math.exp(z) if (isinstance(p, ContinuousParameterSpace)
                            and p.log) else z
        if isinstance(p, IntegerParameterSpace):
            x = int(round(np.clip(x, p.lo, p.hi)))
        return x

    def _parzen_logpdf(self, p, values, x):
        lo, hi = self._bounds(p)
        zs = np.asarray([self._transform(p, v) for v in values])
        bw = max((hi - lo) / max(len(zs), 1) * 2.0, 1e-6 * (hi - lo))
        z = self._transform(p, x)
        comp = -0.5 * ((z - zs) / bw) ** 2 - math.log(bw)
        m = float(np.max(comp))
        return m + math.log(float(np.mean(np.exp(comp - m))))

    def _propose(self):
        good, bad = self._split()
        best_cand, best_ratio = None, -np.inf
        for _ in range(self.n_ei):
            cand, ratio = {}, 0.0
            for k, p in self.space.items():
                if not hasattr(p, "sample"):
                    cand[k] = p
                    continue
                if isinstance(p, DiscreteParameterSpace):
                    vals = p.values
                    cg = [g[k] for g in good]
                    cb = [b[k] for b in bad]
                    pg = np.asarray([1.0 + cg.count(v) for v in vals])
                    pb = np.asarray([1.0 + cb.count(v) for v in vals])
                    pg = pg / pg.sum()
                    pb = pb / pb.sum()
                    idx = self.rng.choice(len(vals), p=pg)
                    cand[k] = vals[idx]
                    ratio += math.log(pg[idx] / pb[idx])
                else:
                    x = self._parzen_sample(p, [g[k] for g in good])
                    cand[k] = x
                    ratio += (self._parzen_logpdf(p, [g[k] for g in good],
                                                  x)
                              - self._parzen_logpdf(p,
                                                    [b[k] for b in bad],
                                                    x))
            if ratio > best_ratio:
                best_cand, best_ratio = cand, ratio
        return best_cand

    def candidates(self, limit):
        for i in range(limit):
            if len(self._obs) < self.n_startup:
                yield {k: (v.sample(self.rng) if hasattr(v, "sample")
                           else v) for k, v in self.space.items()}
            else:
                yield self._propose()
