from deeplearning4j_tpu.arbiter.optimize import (  # noqa: F401
    CandidateGenerator, ContinuousParameterSpace, DiscreteParameterSpace,
    GridSearchCandidateGenerator, IntegerParameterSpace,
    LocalOptimizationRunner, OptimizationConfiguration, OptimizationResult,
    RandomSearchGenerator, TpeCandidateGenerator)
