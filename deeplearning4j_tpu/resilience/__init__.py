"""Resilience subsystem (ISSUE 5): async checkpointing, a training
supervisor with auto-resume, and a deterministic fault-injection
harness.

The paper's blueprint replaces the reference's Aeron parameter server
(whose fault model was "workers rejoin and re-sync") with ICI
collectives — preemption tolerance therefore lives in the framework:

- :class:`AsyncCheckpointer` — periodic checkpoints whose train-loop
  cost is a device-side snapshot clone; serialization and the atomic
  commit run on a background writer (depth-1 queue, newer snapshot
  supersedes a queued one). ``latest_agreed()`` resolves the newest
  checkpoint complete on every host.
- :class:`Supervisor` — wraps ``ElasticTrainer`` (plain or
  ShardedTrainer-driven) runs: watchdog stall detection, automatic
  resume-from-latest after crash / preemption / divergence, bounded
  restarts with exponential backoff, all published as
  ``dl4j_resilience_*`` metrics and /healthz readiness detail.
- :class:`FaultPlan` — seedable, step-exact injection of preemptions,
  checkpoint IO errors, data-iterator failures, and stalls: the test
  substrate proving the two pieces above (see docs/RESILIENCE.md for
  the crash matrix).

Quick use::

    from deeplearning4j_tpu.resilience import Supervisor, SupervisorConfig

    sup = Supervisor(build_net, "/ckpts",
                     config=SupervisorConfig(max_restarts=5,
                                             stall_timeout=120.0),
                     everyNIterations=200, asyncSave=True)
    net = sup.run(batches, epochs=TOTAL)   # survives kill -TERM et al.
"""

from deeplearning4j_tpu.resilience.async_ckpt import (
    AsyncCheckpointer, Snapshot, checkpoint_status, latest_agreed,
    note_commit, refresh_metrics, reset_state)
from deeplearning4j_tpu.resilience.faults import (
    FaultError, FaultInjector, FaultPlan, InjectedCheckpointIOError,
    InjectedCrash, InjectedDataError)
from deeplearning4j_tpu.resilience.supervisor import (
    RestartBudgetExceeded, Supervisor, SupervisorConfig, Watchdog)

__all__ = [
    "AsyncCheckpointer", "FaultError", "FaultInjector", "FaultPlan",
    "InjectedCheckpointIOError", "InjectedCrash", "InjectedDataError",
    "RestartBudgetExceeded", "Snapshot", "Supervisor",
    "SupervisorConfig", "Watchdog", "checkpoint_status", "latest_agreed",
    "note_commit", "refresh_metrics", "reset_state",
]
