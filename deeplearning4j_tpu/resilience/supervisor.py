"""Training supervisor: watchdog + auto-resume (ISSUE 5 tentpole,
piece 2).

The reference's Spark training master re-submitted failed stages; on a
TPU pod the ICI collectives carry no recovery protocol, so surviving a
crash, preemption, hung step, or divergence is the framework's job:

- **auto-resume**: each attempt restores the newest complete checkpoint
  (``latest_agreed`` on multi-host shared storage) via
  ``ElasticTrainer.resume`` and continues the SAME total epoch budget —
  with the mid-epoch offset skip, a resumed run is bit-identical to an
  uninterrupted one at the same step;
- **bounded restarts with exponential backoff**: a persistent fault
  (bad batch, diverging config) cannot spin the job forever;
- **watchdog**: no step progress within ``stall_timeout`` → dump the
  flight recorder, then a *controlled abort*: the watchdog sets the
  abort event (cooperative fault-injected stalls observe it) and
  interrupts the main thread, which lands in ``ElasticTrainer``'s
  signal handler → checkpoint-then-exit, and the supervisor restarts
  the attempt. A step hung inside a C call cannot be interrupted from
  within the process — that case needs an external process manager,
  which is exactly what the flight-recorder dump is for;
- **accounting**: every restart increments
  ``dl4j_resilience_restarts_total{reason}`` and records a flight
  event; ``/healthz`` shows supervisor state via the resilience
  readiness section.

Works with plain ``MultiLayerNetwork`` / ``ComputationGraph`` fits and
with ``ShardedTrainer`` runs (pass ``runner_factory=lambda net:
ShardedTrainer(net, mesh)``).
"""

from __future__ import annotations

import _thread
import threading
import time

from deeplearning4j_tpu.telemetry.health import DivergenceError

__all__ = ["Supervisor", "SupervisorConfig", "RestartBudgetExceeded",
           "Watchdog", "status", "resume_grace"]

RESTARTS_HELP = ("Supervised training restarts by reason "
                 "(preemption|stall|divergence|exception)")

_current = {"status": None}
_lock = threading.Lock()


def status():
    """The active (or last) supervisor's state for /healthz, or None."""
    with _lock:
        st = _current["status"]
        return dict(st) if st else None


def _set_status(**kw):
    with _lock:
        st = _current["status"] or {}
        st.update(kw)
        _current["status"] = st


def reset_status():
    with _lock:
        _current["status"] = None


class RestartBudgetExceeded(RuntimeError):
    """The supervisor gave up: more failures than ``max_restarts``.
    Carries the last reason/exception and the restart count."""

    def __init__(self, message, reason, restarts, last_error):
        super().__init__(message)
        self.reason = reason
        self.restarts = restarts
        self.last_error = last_error


class SupervisorConfig:
    """Restart policy. ``stall_timeout=None`` disables the watchdog."""

    def __init__(self, max_restarts=3, backoff_base=0.5,
                 backoff_factor=2.0, backoff_max=30.0,
                 stall_timeout=None, stall_poll=None, stall_warmup=None):
        self.max_restarts = int(max_restarts)
        self.backoff_base = float(backoff_base)
        self.backoff_factor = float(backoff_factor)
        self.backoff_max = float(backoff_max)
        self.stall_timeout = stall_timeout
        self.stall_poll = stall_poll
        # grace before the first iteration of an attempt (jit compile /
        # checkpoint restore are not stalls); default max(timeout, 30 s)
        self.stall_warmup = stall_warmup

    def backoff(self, restart_index):
        """Delay before restart #`restart_index` (1-based)."""
        return min(self.backoff_base *
                   self.backoff_factor ** (restart_index - 1),
                   self.backoff_max)


class Watchdog:
    """No-progress detector for one fit attempt. ``listener()`` yields
    a DL4J-style listener that timestamps every finished iteration; the
    watchdog thread trips when the gap exceeds ``timeout``."""

    def __init__(self, timeout, poll=None, abort_event=None,
                 loop="supervised", warmup_grace=None):
        self.timeout = float(timeout)
        self.poll = float(poll) if poll else max(0.05, self.timeout / 4.0)
        self.abort_event = abort_event or threading.Event()
        self.loop = loop
        # before the FIRST iteration of an attempt the loop is (re)
        # compiling the train step, not stalling — give it more rope
        self.warmup_grace = (float(warmup_grace) if warmup_grace
                             else max(self.timeout, 30.0))
        self.stalled = False
        self.last_step = None
        self._seen_progress = False
        self._last_progress = None
        self._stop = threading.Event()
        self._thread = None

    class _Progress:
        def __init__(self, outer):
            self.outer = outer

        def iterationDone(self, model, iteration, epoch=None, loss=None):
            self.outer._last_progress = time.monotonic()
            self.outer._seen_progress = True
            self.outer.last_step = iteration

    def listener(self):
        return Watchdog._Progress(self)

    def start(self):
        self._last_progress = time.monotonic()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dl4j:ckpt:watchdog")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _run(self):
        while not self._stop.wait(self.poll):
            gap = time.monotonic() - self._last_progress
            limit = self.timeout if self._seen_progress \
                else self.warmup_grace
            if gap < limit:
                continue
            self.stalled = True
            from deeplearning4j_tpu.telemetry import flight

            flight.record("stall", loop=self.loop, step=self.last_step,
                          no_progress_seconds=round(gap, 3))
            try:
                path = flight.get_recorder().dump()
                flight.record("stall_dump", path=path)
            except Exception:
                pass
            # controlled abort: cooperative stalls watch the event;
            # interrupt_main lands in ElasticTrainer's installed signal
            # handler -> checkpoint-then-PreemptionCheckpoint. Re-check
            # stop() first: firing after fit returned would deliver a
            # stray KeyboardInterrupt to the supervisor loop instead
            self.abort_event.set()
            if not self._stop.is_set():
                _thread.interrupt_main()
            return


# executable-store sites that hold TRAIN-step executables — what a
# supervised resume actually needs warm (serving ladders don't count)
TRAIN_STEP_SITES = ("fit", "graph", "sharded")


def resume_grace(cfg):
    """The watchdog's pre-first-iteration grace for one attempt.
    ``cfg.stall_warmup`` wins when set; otherwise a WARM executable
    store tightens the default (ISSUE 13): the post-resume "recompile"
    is a deserialize (milliseconds), so granting the default 30 s
    compile allowance would let a genuinely stalled resume hide inside
    it — the grace drops to the ordinary stall timeout (floor 5 s for
    checkpoint-restore I/O). Warmth is judged on TRAIN-step entries
    only, and the run loop falls back to the cold grace after a
    warmup-phase stall (a store that misses anyway — config changed,
    shared dir holding someone else's program — costs at most one
    restart, never the whole budget). None lets the Watchdog apply its
    cold default of ``max(timeout, 30)``."""
    if cfg.stall_warmup is not None:
        return cfg.stall_warmup
    from deeplearning4j_tpu import compilestore

    if compilestore.is_warm(sites=TRAIN_STEP_SITES):
        return max(float(cfg.stall_timeout), 5.0)
    return None


class Supervisor:
    """Run a checkpointed fit to completion across failures.

    factory: zero-arg callable building a FRESH initialized net (used
        when no checkpoint exists yet, i.e. the first attempt);
    checkpointDir: shared storage in multi-host runs (the
        ``ElasticTrainer`` contract);
    runner_factory: optional ``net -> object with .fit(data, epochs)``
        (e.g. a ``ShardedTrainer``) rebuilt around each restored net;
    setup: optional ``net -> None`` applied to EVERY attempt's net —
        fresh or restored. Listeners (divergence policies, stats) are
        not serialized into checkpoints, so per-net configuration must
        be reapplied here, not in ``factory``;
    faults: optional :class:`FaultPlan` — its listener is installed,
        its data wrapper applied, and its abort event wired to the
        watchdog (deterministic fault-injection tests);
    trainer_kw: forwarded to ``ElasticTrainer`` (everyNIterations,
        keepLast, asyncSave, sharded, saveUpdaterState).
    """

    def __init__(self, factory, checkpointDir, config=None, graph=False,
                 runner_factory=None, setup=None, faults=None,
                 sleep=time.sleep, **trainer_kw):
        self.factory = factory
        self.dir = str(checkpointDir)
        self.config = config or SupervisorConfig()
        self.graph = graph
        self.runner_factory = runner_factory
        self.setup = setup
        self.faults = faults
        self.sleep = sleep
        self.trainer_kw = trainer_kw
        self.restarts = 0
        self.reasons: list = []
        from deeplearning4j_tpu.resilience import async_ckpt

        async_ckpt._ensure_provider()
        # start the executable store's code-epoch sweep now (background,
        # no-op when unconfigured): a resume should find it ready
        from deeplearning4j_tpu import compilestore

        compilestore.get_store()

    # -- metrics -------------------------------------------------------------
    def _count_restart(self, reason, step):
        self.restarts += 1
        self.reasons.append(reason)
        from deeplearning4j_tpu import telemetry
        from deeplearning4j_tpu.telemetry import flight

        if telemetry.enabled():
            telemetry.get_registry().counter(
                "dl4j_resilience_restarts_total", RESTARTS_HELP,
                ("reason",)).labels(reason=reason).inc()
        flight.record("restart", reason=reason, step=step,
                      restarts=self.restarts)

    # -- the loop ------------------------------------------------------------
    def _build_trainer(self):
        from deeplearning4j_tpu.parallel.elastic import ElasticTrainer

        trainer = ElasticTrainer.resume(self.dir, graph=self.graph,
                                        faults=self.faults,
                                        **self.trainer_kw)
        resumed = trainer is not None
        if trainer is None:
            trainer = ElasticTrainer(self.factory(), self.dir,
                                     faults=self.faults, **self.trainer_kw)
        if self.setup is not None:
            self.setup(trainer.net)
        if self.runner_factory is not None:
            trainer.runner = self.runner_factory(trainer.net)
        return trainer, resumed

    def run(self, data, epochs=1):
        """Fit to the TOTAL `epochs` budget, restarting through
        failures; returns the trained net. Raises
        :class:`RestartBudgetExceeded` when the budget runs out, with
        the final checkpoint still on disk."""
        from deeplearning4j_tpu.parallel.elastic import PreemptionCheckpoint
        from deeplearning4j_tpu.telemetry import flight
        from deeplearning4j_tpu.telemetry import health as _health

        cfg = self.config
        wrapped = self.faults.wrap_data(data) if self.faults else data
        _set_status(state="starting", restarts=0, last_reason=None,
                    max_restarts=cfg.max_restarts)
        # a stall BEFORE the first iteration means the warm-store
        # tightened grace (resume_grace) was wrong for this program —
        # the store missed and the step really was compiling; the next
        # attempt reverts to the cold grace so a misjudged hint costs
        # one restart, not the budget
        warmup_stalled = False
        while True:
            from deeplearning4j_tpu import compilestore

            trainer, resumed = self._build_trainer()
            net = trainer.net
            if resumed:
                flight.record("resume", step=net._iteration,
                              attempt=self.restarts + 1,
                              store_warm=compilestore.is_warm())
            wd = None
            prior = list(net._listeners)
            if cfg.stall_timeout:
                grace = cfg.stall_warmup if warmup_stalled \
                    else resume_grace(cfg)
                wd = Watchdog(cfg.stall_timeout, cfg.stall_poll,
                              abort_event=(self.faults.abort_event
                                           if self.faults else None),
                              warmup_grace=grace)
                net.setListeners(*(prior + [wd.listener()]))
                wd.start()
            _set_status(state="running", restarts=self.restarts,
                        resumed_from=net._iteration if resumed else None)
            reason = err = None
            try:
                trainer.fit(wrapped, epochs)
                _set_status(state="completed", restarts=self.restarts)
                return net
            except PreemptionCheckpoint as e:
                reason = "stall" if (wd is not None and wd.stalled) \
                    else "preemption"
                err = e
            except KeyboardInterrupt:
                # the watchdog's interrupt_main can land after fit()
                # already returned (handlers restored): if the watchdog
                # DID trip, treat it as the stall abort it was meant to
                # be; a real Ctrl-C propagates
                if not (wd is not None and wd.stalled):
                    raise
                reason, err = "stall", None
            except DivergenceError as e:
                reason, err = "divergence", e
                # the restart rolls back to the last checkpoint; clear
                # the recorded divergence so /healthz readiness recovers
                _health.reset_status()
            except Exception as e:
                reason, err = "exception", e
            finally:
                if wd is not None:
                    wd.stop()
                    warmup_stalled = (wd.stalled
                                      and not wd._seen_progress)
                net.setListeners(*prior)
                if self.faults is not None:
                    self.faults.abort_event.clear()
                trainer.close()
            self._count_restart(reason, net._iteration)
            _set_status(state="restarting", restarts=self.restarts,
                        last_reason=reason)
            if self.restarts > cfg.max_restarts:
                _set_status(state="failed", last_reason=reason)
                raise RestartBudgetExceeded(
                    f"supervised training failed {self.restarts} times "
                    f"(last reason: {reason}: {err}); restart budget "
                    f"{cfg.max_restarts} exhausted", reason,
                    self.restarts, err) from err
            delay = cfg.backoff(self.restarts)
            flight.record("backoff", seconds=round(delay, 3),
                          restarts=self.restarts)
            self.sleep(delay)
