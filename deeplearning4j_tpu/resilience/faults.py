"""Deterministic fault injection: the test substrate of the resilience
subsystem (ISSUE 5 tentpole, piece 3).

The paper's blueprint moves fault tolerance out of the transport (the
reference's Aeron parameter server let workers rejoin and re-sync) and
into the framework — which means the framework's claims ("resumes after
preemption", "never exposes a partial checkpoint") need a way to be
*proved*, repeatably, in CI. A :class:`FaultPlan` is a seedable,
inspectable schedule of failures injected at exact train-loop steps:

- **preemption signals** (``preempt_at``): SIGTERM delivered to this
  process, exercising the real ``ElasticTrainer`` maintenance-event
  drill (checkpoint-then-``PreemptionCheckpoint``);
- **crashes** (``crash_at``): an exception raised between iterations,
  simulating process death at the Python level;
- **checkpoint-write IO errors** (``io_error_at``): raised inside the
  checkpoint writer, either mid-``write`` or between write and
  ``commit`` — the window where a partial artifact must never become
  ``latest()``;
- **data-iterator exceptions** (``data_error_at``): raised from the
  batch iterator at a chosen global batch ordinal;
- **stalls** (``stall_at``): a cooperative sleep that simulates a hung
  step; it watches ``abort_event`` so a supervisor watchdog can break
  it the way an external process manager would kill a hung worker.

Every event fires a bounded number of times (default once) so a
resumed run replaying the same step numbers does not re-fire it, and
every firing is appended to ``plan.log`` for assertions. Plans are
deterministic by construction (explicit steps); ``random_steps`` draws
steps from a seeded generator for soak-style tests.
"""

from __future__ import annotations

import os
import signal
import threading
import time

__all__ = [
    "FaultError", "InjectedCrash", "InjectedDataError",
    "InjectedCheckpointIOError", "InjectedOom", "FaultPlan",
    "FaultInjector",
]


class FaultError(Exception):
    """Base of every injected failure (lets tests and the supervisor
    distinguish planned faults from real bugs)."""


class InjectedCrash(FaultError, RuntimeError):
    """Simulated process death between iterations."""


class InjectedDataError(FaultError, RuntimeError):
    """Simulated ETL failure raised from the data iterator."""


class InjectedCheckpointIOError(FaultError, OSError):
    """Simulated storage failure inside a checkpoint write/commit."""


class InjectedOom(FaultError, RuntimeError):
    """Simulated device allocation failure: the message mimics XLA's
    ``RESOURCE_EXHAUSTED`` shape so the ISSUE 14 OOM-forensics seams
    (``memledger.is_oom`` / ``raise_if_oom``) treat it exactly like the
    real thing — the fault-injected half of proving the typed
    DeviceOomError + flight ``oom`` path at every instrumented seam."""

    def __init__(self, nbytes=1 << 34, where="injected"):
        self.nbytes = int(nbytes)
        super().__init__(
            f"RESOURCE_EXHAUSTED: Out of memory allocating "
            f"{self.nbytes} bytes ({where}).")


# event kinds
PREEMPT = "preempt"
CRASH = "crash"
STALL = "stall"
IO_ERROR = "io_error"
DATA_ERROR = "data_error"
OOM = "oom"


class _Event:
    __slots__ = ("kind", "at", "times", "args")

    def __init__(self, kind, at, times=1, **args):
        self.kind = kind
        self.at = int(at)
        self.times = int(times)
        self.args = args


class FaultPlan:
    """A deterministic schedule of injected failures.

    Builders are chainable::

        plan = (FaultPlan()
                .preempt_at(7)
                .io_error_at(step=12, phase="commit")
                .data_error_at(batch=30)
                .stall_at(20, seconds=30.0))

    Thread-safe: the train loop fires iteration events while a
    background checkpoint writer consults ``check_write``.
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.abort_event = threading.Event()
        self.log: list = []          # (kind, step_or_batch) per firing
        self._events: list = []
        self._batches_drawn = 0      # global next() ordinal across epochs
        self._lock = threading.Lock()

    # -- builders ------------------------------------------------------------
    def preempt_at(self, step, times=1):
        """Deliver SIGTERM to this process after iteration ``step``."""
        self._events.append(_Event(PREEMPT, step, times))
        return self

    def crash_at(self, step, times=1, message="injected crash"):
        """Raise :class:`InjectedCrash` after iteration ``step``."""
        self._events.append(_Event(CRASH, step, times, message=message))
        return self

    def stall_at(self, step, seconds, times=1):
        """Sleep cooperatively for ``seconds`` after iteration ``step``
        (broken early when ``abort_event`` is set — the supervisor
        watchdog's controlled abort)."""
        self._events.append(
            _Event(STALL, step, times, seconds=float(seconds)))
        return self

    def io_error_at(self, step, phase="write", times=1):
        """Fail the checkpoint write for iteration ``step``: phase
        ``"write"`` fails while producing the tmp artifact, ``"commit"``
        fails between the finished write and the atomic rename."""
        if phase not in ("write", "commit"):
            raise ValueError(f"phase must be write|commit, got {phase!r}")
        self._events.append(_Event(IO_ERROR, step, times, phase=phase))
        return self

    def data_error_at(self, batch, times=1):
        """Raise :class:`InjectedDataError` when the data iterator
        serves global batch ordinal ``batch`` (counted across epochs
        and restarts — a resumed run does not re-draw consumed
        ordinals' failures)."""
        self._events.append(_Event(DATA_ERROR, batch, times))
        return self

    def oom_at(self, batch, nbytes=1 << 34, times=1):
        """Raise :class:`InjectedOom` (a RESOURCE_EXHAUSTED-shaped
        allocation failure) when the data path serves global batch
        ordinal ``batch`` — through ``wrap_data`` + a DevicePrefetcher
        this exercises the prefetch ``device_put`` seam's ISSUE 14 OOM
        forensics end to end."""
        self._events.append(_Event(OOM, batch, times, nbytes=int(nbytes)))
        return self

    def random_steps(self, n, max_step):
        """``n`` deterministic pseudo-random steps in ``[1, max_step]``
        drawn from this plan's seed (soak tests)."""
        import random

        rng = random.Random(self.seed)
        return sorted(rng.randrange(1, int(max_step) + 1)
                      for _ in range(int(n)))

    # -- runtime hooks -------------------------------------------------------
    def _take(self, kind, at, pred=None):
        """Pop one firing of a matching armed event (thread-safe)."""
        with self._lock:
            for ev in self._events:
                if ev.kind == kind and ev.at == int(at) and ev.times > 0 \
                        and (pred is None or pred(ev)):
                    ev.times -= 1
                    self.log.append((kind, int(at)))
                    return ev
        return None

    def fired(self, kind=None):
        """Firings so far, optionally filtered by kind."""
        with self._lock:
            return [f for f in self.log if kind is None or f[0] == kind]

    def on_iteration(self, iteration):
        """Called by :class:`FaultInjector` after each train iteration;
        executes any preempt/crash/stall armed for it."""
        ev = self._take(STALL, iteration)
        if ev is not None:
            self._stall(ev.args["seconds"])
        ev = self._take(PREEMPT, iteration)
        if ev is not None:
            os.kill(os.getpid(), signal.SIGTERM)
        ev = self._take(CRASH, iteration)
        if ev is not None:
            raise InjectedCrash(
                f"{ev.args['message']} at iteration {iteration}")

    def _stall(self, seconds, tick=0.02):
        """Cooperative hang: sleeps in short ticks so a watchdog's
        ``abort_event`` (or a delivered signal's Python-level handler)
        can end it — the in-process analogue of a hung step that an
        external supervisor would eventually shoot."""
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            if self.abort_event.wait(tick):
                return

    def check_write(self, step, phase):
        """Called by checkpoint writers around the atomic commit; raises
        :class:`InjectedCheckpointIOError` when armed for (step, phase).
        """
        ev = self._take(IO_ERROR, step,
                        pred=lambda e: e.args["phase"] == phase)
        if ev is not None:
            raise InjectedCheckpointIOError(
                f"injected checkpoint {phase} failure at step {step}")
        return None

    def on_batch(self):
        """Called by the data wrapper per served batch; raises when the
        global ordinal has an armed data error (or injected OOM)."""
        with self._lock:
            ordinal = self._batches_drawn
            self._batches_drawn += 1
        ev = self._take(DATA_ERROR, ordinal)
        if ev is not None:
            raise InjectedDataError(
                f"injected data-iterator failure at batch {ordinal}")
        ev = self._take(OOM, ordinal)
        if ev is not None:
            raise InjectedOom(nbytes=ev.args["nbytes"],
                              where=f"batch {ordinal}")

    # -- adapters ------------------------------------------------------------
    def listener(self):
        """A DL4J-style listener injecting iteration faults (install
        alongside training listeners; ``ElasticTrainer``/``Supervisor``
        do this when handed a plan)."""
        return FaultInjector(self)

    def wrap_data(self, data):
        """Wrap a batch source so armed data errors fire at their global
        ordinal. Preserves ``len()`` so epoch accounting (and the
        bit-identical resume offset math) still works. DataSetIterators
        are wrapped by a forwarding proxy, NOT materialized — an
        epoch-shuffling iterator must keep producing different batches
        per epoch through the wrapper (ISSUE 6)."""
        from deeplearning4j_tpu.datasets.iterator import DataSetIterator

        if isinstance(data, DataSetIterator):
            return _FaultyIterator(self, data)
        return _FaultyData(self, data)


class FaultInjector:
    """Listener-shaped adapter: fires the plan's iteration faults."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def iterationDone(self, model, iteration, epoch=None, loss=None):
        self.plan.on_iteration(iteration)


class _FaultyIterator:
    """Forwarding proxy around a DataSetIterator that fires the plan's
    data faults per drawn batch. Forwards the epoch-resume protocol
    (``len``, ``[offset:]`` tail slices, ``set_epoch``, ``reset``) so
    ``ElasticTrainer``'s bit-identical mid-epoch resume works through
    the wrapper for epoch-shuffling iterators."""

    def __init__(self, plan, base):
        self._plan = plan
        self._base = base

    def __len__(self):
        return len(self._base)

    def __getitem__(self, idx):
        return _FaultyIterator(self._plan, self._base[idx])

    def __getattr__(self, name):
        # batch(), asyncSupported(), getLabels(), set_epoch(), close(),
        # ... all forward (so hasattr probes see exactly the base's
        # protocol); only the draw path is intercepted below
        return getattr(self._base, name)

    def reset(self):
        self._base.reset()

    def hasNext(self):
        return self._base.hasNext()

    def next(self):
        self._plan.on_batch()
        return self._base.next()

    def __iter__(self):
        self.reset()
        return self

    def __next__(self):
        if not self._base.hasNext():
            raise StopIteration
        return self.next()


class _FaultyData(list):
    """A list of batches whose iteration consults the plan per draw.
    Subclassing list keeps the training loops' sized-data fast paths
    (len, slicing, no per-epoch materialization by _prepare_batches)
    while every ``for batch in data`` goes through :meth:`__iter__`."""

    def __init__(self, plan, data):
        super().__init__(data)
        self._plan = plan

    def __getitem__(self, idx):
        # slicing support keeps ElasticTrainer's mid-epoch resume offset
        # working through the wrapper
        if isinstance(idx, slice):
            return _FaultyData(self._plan, super().__getitem__(idx))
        return super().__getitem__(idx)

    def __iter__(self):
        it = super().__iter__()
        for batch in it:
            self._plan.on_batch()
            yield batch
