"""Async checkpointing (ISSUE 5 tentpole, piece 1).

Every ``ElasticTrainer`` checkpoint used to block the train loop for
the full device→host transfer *and* serialization *and* disk write —
exactly the "framework overhead off the math path" the Java-framework
performance paper flags once kernels are fast. The async design splits
the write into the two halves that actually have different costs:

1. **Snapshot** (train thread, the only part the loop waits for): a
   jitted device-side *clone* of params / updater state / loss-scale
   state. The clone rides the dispatch queue like any other step — it
   returns as soon as the copy computations are enqueued, and because
   the clone owns fresh buffers the train step is free to donate the
   originals on the very next iteration. ``copy_to_host_async`` is
   issued immediately, so the device→host DMA overlaps training.
2. **Write** (background thread): materialize the host copies (the DMA
   has usually already landed), serialize with the *same*
   ``ModelSerializer`` zip / ``save_sharded`` npz layout as the sync
   path, and commit with the same tmp + ``os.replace`` protocol
   (``utils.checkpoint.atomic_save``) — so a crash at any point leaves
   the previous checkpoint current, never a partial one.

The in-flight queue is bounded at depth 1 and a **newer snapshot
supersedes a queued one** (the queued write had not started; its state
is strictly older than what we now hold — writing both would just
delay the newer commit). In multi-host runs supersede is disabled:
whether a snapshot is still queued at submit time is a thread-timing
race, so hosts could disagree on which steps exist at all.

Multi-host async writes issue **no collectives from the writer
thread** — a background barrier would interleave with the train loop's
in-step collectives and desync the hosts (gloo context-init deadlock).
Instead, each host's writer commits its shard independently and
:func:`latest_agreed` certifies completeness at read time: a sharded
checkpoint counts only when its committed manifest AND every shard
file it references exist on the shared directory. (The synchronous
durable writes at preemption/end-of-fit run on the train thread with
the full ``save_sharded`` barrier, so the final checkpoint of a run
keeps the manifest-after-sync property.)

Commit bookkeeping (timestamps, steps, write durations) is published
through the PR-1 registry (``dl4j_ckpt_*``) and feeds the /healthz
checkpoint-staleness readiness detail.
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time

log = logging.getLogger("deeplearning4j_tpu")

__all__ = ["AsyncCheckpointer", "Snapshot", "latest_agreed",
           "checkpoint_status", "note_commit", "reset_state",
           "refresh_metrics", "rotate_checkpoints"]

_CKPT_RE = re.compile(r"^checkpoint_(\d+)")


def rotate_checkpoints(directory, keep):
    """keepLast rotation + garbage collection for a checkpoint
    directory (process 0 only): drops complete checkpoints beyond the
    newest ``keep``, plus mid-save remnants — incomplete shard
    directories and ``*.tmp`` files — once a complete checkpoint at the
    same or a later iteration exists. An in-flight async write (always
    newer than the newest commit) is never touched. Shared by the sync
    ``ElasticTrainer`` writer and the async background writer."""
    import shutil

    import jax

    if jax.process_index() != 0:
        return
    from deeplearning4j_tpu.utils.sharded_checkpoint import is_complete

    complete, partial, tmps = [], [], []
    for f in sorted(os.listdir(directory)):
        if not f.startswith("checkpoint_"):
            continue
        full = os.path.join(directory, f)
        if f.endswith(".tmp"):
            tmps.append(f)
        elif os.path.isdir(full):
            # an incomplete directory (no manifest, or a manifest
            # referencing shard files that never landed) must not count
            # toward keepLast, and it never becomes restorable
            (complete if is_complete(full) else partial).append(f)
        else:
            complete.append(f)
    newest_iter = -1
    if complete:
        m = _CKPT_RE.match(complete[-1])
        newest_iter = int(m.group(1)) if m else -1

    def stale(f):
        m = _CKPT_RE.match(f)
        return m and int(m.group(1)) <= newest_iter

    for old in complete[:-int(keep)] + [f for f in partial + tmps
                                        if stale(f)]:
        full = os.path.join(directory, old)
        if os.path.isdir(full):
            shutil.rmtree(full)
        else:
            os.remove(full)


# ---------------------------------------------------------------------------
# commit bookkeeping + metrics (shared by sync AND async writers)
# ---------------------------------------------------------------------------

AGE_HELP = ("Seconds since the last committed training checkpoint "
            "(refreshed at commit and on /metrics and /healthz reads)")
QUEUE_DEPTH_HELP = "Async-checkpoint snapshots queued or being written"
SNAPSHOT_HELP = ("Seconds the train loop was blocked taking a checkpoint "
                 "snapshot (device-side clone dispatch + enqueue — the "
                 "async-mode per-checkpoint stall)")
WRITE_HELP = ("Seconds spent serializing + committing one checkpoint "
              "(mode=sync blocks the train loop; mode=async runs in the "
              "background writer)")
SUPERSEDED_HELP = ("Queued checkpoint snapshots replaced by a newer one "
                   "before their write started")
FAILURES_HELP = "Checkpoint writes that failed, by phase (write|commit)"

_state = {
    "commits": [],        # (ts, step) of recent commits, bounded
    "last": None,         # {"ts", "step", "path", "seconds", "mode"}
    "failures": 0,
    "queue_depth": 0,
    "active": 0,          # checkpointed fits currently in flight
    "provider": False,    # healthz provider registered?
}
_lock = threading.Lock()
_MAX_COMMITS = 16


def reset_state():
    """Forget commit history (tests)."""
    with _lock:
        _state["commits"] = []
        _state["last"] = None
        _state["failures"] = 0
        _state["queue_depth"] = 0
        _state["active"] = 0


def mark_active():
    """A checkpointed fit started: staleness judgements apply until the
    matching :func:`mark_idle`. (A finished run's checkpoint aging is
    not a degradation — nothing more is expected to land.)"""
    with _lock:
        _state["active"] += 1


def mark_idle():
    with _lock:
        _state["active"] = max(0, _state["active"] - 1)


def _registry():
    from deeplearning4j_tpu import telemetry

    if not telemetry.enabled():
        return None
    return telemetry.get_registry()


def _ensure_provider():
    """Register the /healthz resilience section once (checkpoint
    staleness + supervisor state)."""
    with _lock:
        if _state["provider"]:
            return
        _state["provider"] = True
    from deeplearning4j_tpu.telemetry import health

    health.register_healthz_provider("resilience", healthz_section)


def note_commit(path, step, seconds, mode, registry=None):
    """Record one committed checkpoint (called by both the sync
    ``ElasticTrainer._write`` path and the async writer) and refresh
    the ``dl4j_ckpt_*`` gauges."""
    now = time.time()
    with _lock:
        _state["commits"].append((now, int(step)))
        del _state["commits"][:-_MAX_COMMITS]
        _state["last"] = {"ts": now, "step": int(step), "path": str(path),
                          "seconds": float(seconds), "mode": mode}
    _ensure_provider()
    reg = registry if registry is not None else _registry()
    if reg is None:
        return
    reg.gauge("dl4j_ckpt_age_seconds", AGE_HELP).set(0.0)
    reg.histogram("dl4j_ckpt_write_seconds", WRITE_HELP,
                  ("mode",)).labels(mode=mode).observe(seconds)
    from deeplearning4j_tpu.telemetry import flight

    flight.record("checkpoint", step=int(step), mode=mode,
                  seconds=round(float(seconds), 6))


def note_failure(step, phase, error):
    with _lock:
        _state["failures"] += 1
    reg = _registry()
    if reg is not None:
        reg.counter("dl4j_ckpt_failures_total", FAILURES_HELP,
                    ("phase",)).labels(phase=phase).inc()
    from deeplearning4j_tpu.telemetry import flight

    flight.record("checkpoint_failure", step=int(step), phase=phase,
                  error=f"{type(error).__name__}: {error}")
    log.warning("checkpoint write for step %s failed during %s: %s "
                "(previous checkpoint remains current)", step, phase, error)


def _set_queue_depth(depth):
    with _lock:
        _state["queue_depth"] = depth
    reg = _registry()
    if reg is not None:
        reg.gauge("dl4j_ckpt_async_queue_depth", QUEUE_DEPTH_HELP).set(depth)


def refresh_metrics():
    """Recompute the time-derived gauge(s) — called by the /metrics and
    /healthz handlers so scrapes see a live age, not the age as of the
    last commit."""
    with _lock:
        last = _state["last"]
    if last is None:
        return
    reg = _registry()
    if reg is not None:
        reg.gauge("dl4j_ckpt_age_seconds", AGE_HELP).set(
            time.time() - last["ts"])


def checkpoint_status(stale_after=None):
    """Current checkpoint recency: ``{"step", "age_seconds",
    "expected_interval_seconds", "stale"}`` (or None before the first
    commit). Staleness: age > ``stale_after`` when given, else >
    2 × the median inter-commit interval once two commits exist —
    "two missed checkpoints' worth of steps"."""
    with _lock:
        last = _state["last"]
        commits = list(_state["commits"])
        active = _state["active"]
    if last is None:
        return None
    age = time.time() - last["ts"]
    expected = None
    if len(commits) >= 2:
        gaps = sorted(b[0] - a[0] for a, b in zip(commits, commits[1:]))
        expected = gaps[len(gaps) // 2]
    if stale_after is not None:
        threshold = float(stale_after)
    elif expected:
        threshold = 2.0 * expected
    else:
        threshold = None
    # staleness is only meaningful while a checkpointed fit is running:
    # an idle process is not "behind on checkpoints"
    stale = bool(active > 0 and threshold is not None and age > threshold)
    return {"step": last["step"], "age_seconds": round(age, 3),
            "mode": last["mode"], "active": active > 0,
            "expected_interval_seconds": (round(expected, 3)
                                          if expected else None),
            "stale": stale}


def healthz_section():
    """The /healthz ``resilience`` readiness detail: checkpoint
    staleness (degraded, never 503 — a live trainer that is behind on
    checkpoints should keep serving) plus supervisor state."""
    refresh_metrics()
    out = {}
    ck = checkpoint_status()
    if ck is not None:
        out["checkpoint"] = ck
        if ck["stale"]:
            out["degraded"] = True
            out["detail"] = (
                f"last checkpoint (step {ck['step']}) is "
                f"{ck['age_seconds']}s old, > 2x the expected "
                f"{ck['expected_interval_seconds']}s interval")
    from deeplearning4j_tpu.resilience import supervisor as _sup

    sup = _sup.status()
    if sup is not None:
        out["supervisor"] = sup
    return out


# ---------------------------------------------------------------------------
# the checkpointer
# ---------------------------------------------------------------------------

class Snapshot:
    """A device-side clone of one model state, plus everything the
    background writer needs to serialize it without ever touching the
    live (mutating, donation-recycled) net."""

    __slots__ = ("step", "params", "states", "opt_states", "prec",
                 "iteration", "epoch", "conf", "model_type",
                 "save_updater", "taken_at", "trace", "mem_claim")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))

    # ModelSerializer.writeModel duck-types against these:
    @property
    def _params(self):
        return self.params

    @property
    def _states(self):
        return self.states

    @property
    def _opt_states(self):
        return self.opt_states

    @property
    def _prec_state(self):
        return self.prec

    @property
    def _iteration(self):
        return self.iteration

    @property
    def _epoch(self):
        return self.epoch


_CLONER = []


def _clone_to_device(tree):
    """Fresh device buffers holding a copy of ``tree`` — dispatched
    asynchronously (jit), preserving shardings, and safe against the
    train step donating the originals afterwards."""
    if not _CLONER:
        import jax
        import jax.numpy as jnp

        _CLONER.append(jax.jit(
            lambda t: jax.tree_util.tree_map(jnp.copy, t)))
    return _CLONER[0](tree)


def _start_host_copies(tree):
    import jax

    def start(x):
        if isinstance(x, jax.Array):
            try:
                x.copy_to_host_async()
            except Exception:
                pass
        return x

    jax.tree_util.tree_map(start, tree)


class AsyncCheckpointer:
    """Background checkpoint writer with a depth-1 supersede queue.

    ``snapshot(net, step)`` (train thread) clones state on device and
    returns the handle; ``submit(snap)`` enqueues it. The writer thread
    serializes and atomically commits using the same artifact layout as
    the sync path, so sync and async checkpoints are interchangeable at
    restore time. ``drain()`` blocks until the queue is empty (end of
    fit / preemption); ``close()`` drains and stops the thread.
    """

    def __init__(self, directory, keepLast=3, sharded=False,
                 saveUpdater=True, supersede=None, faults=None,
                 rotate=None):
        import jax

        self.dir = str(directory)
        self.keep = int(keepLast)
        self.sharded = bool(sharded)
        self.save_updater = bool(saveUpdater)
        self.faults = faults
        # rotation: ElasticTrainer injects its own; standalone use gets
        # the shared keepLast rotation so checkpoints never pile up
        self._rotate = rotate if rotate is not None else (
            lambda: rotate_checkpoints(self.dir, self.keep))
        multi = jax.process_count() > 1
        # supersede is a submit-time race in multi-host (see module
        # docstring): force every submitted snapshot to be written there
        self.supersede = (not multi) if supersede is None \
            else (bool(supersede) and not multi)
        self._pending = None
        self._busy = False
        self._closing = False
        self._error = None
        self._cond = threading.Condition()
        self._thread = threading.Thread(
            target=self._writer_loop, daemon=True,
            name=f"dl4j:ckpt:writer-{os.path.basename(self.dir)}")
        self._thread.start()
        os.makedirs(self.dir, exist_ok=True)
        _ensure_provider()

    # -- train-thread half ---------------------------------------------------
    def snapshot(self, net, step) -> Snapshot:
        """Clone the net's training state on device (async dispatch)
        and start the device→host copies. This is the ONLY part of a
        checkpoint the train loop waits for."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.telemetry import tracing

        # sampled training-trace context (None = unsampled): the
        # snapshot span lands here; the ctx rides the Snapshot so the
        # background writer's span joins the SAME tree (ISSUE 10)
        trace_ctx = tracing.current()
        t0 = time.perf_counter()
        tree = {"p": net._params, "s": net._states}
        if self.save_updater:
            tree["o"] = net._opt_states
        if getattr(net, "_prec_state", None):
            tree["prec"] = net._prec_state
        from deeplearning4j_tpu.telemetry import memledger

        try:
            clone = _clone_to_device(tree)
            _start_host_copies(clone)
        except Exception as e:
            # OOM forensics (ISSUE 14): the snapshot clone doubles the
            # training state for a moment — the classic last-straw
            # allocation. Name the seam and the top HBM claims.
            memledger.raise_if_oom(e, site="ckpt.snapshot",
                                   step=int(step))
            raise
        # HBM ledger claim: the clone pins a full copy of the training
        # state until the background writer commits it
        mem_claim = memledger.claim(
            "checkpoint",
            f"snapshot:{os.path.basename(self.dir)}:{int(step)}",
            tree=clone, step=int(step))
        snap = Snapshot(
            step=int(step),
            params=clone["p"], states=clone["s"],
            opt_states=clone.get("o"),
            prec=clone.get("prec", {}),
            iteration=int(net._iteration), epoch=int(net._epoch),
            conf=net.conf,
            model_type=("ComputationGraph"
                        if isinstance(net, ComputationGraph)
                        else "MultiLayerNetwork"),
            save_updater=self.save_updater,
            taken_at=time.time(),
            trace=trace_ctx,
            mem_claim=mem_claim)
        t1 = time.perf_counter()
        if trace_ctx is not None:
            tracing.emit("ckpt.snapshot", trace_ctx, t0, t1,
                         step=int(step))
        reg = _registry()
        if reg is not None:
            reg.histogram("dl4j_ckpt_snapshot_seconds",
                          SNAPSHOT_HELP).observe(t1 - t0)
        return snap

    def submit(self, snap: Snapshot):
        """Queue a snapshot for background write. Depth-1: with
        supersede on, a still-queued older snapshot is replaced (and
        counted); otherwise blocks until the slot frees."""
        with self._cond:
            if self._closing:
                raise RuntimeError("AsyncCheckpointer is closed")
            if self._pending is not None:
                if self.supersede:
                    reg = _registry()
                    if reg is not None:
                        reg.counter("dl4j_ckpt_superseded_total",
                                    SUPERSEDED_HELP).inc()
                    from deeplearning4j_tpu.telemetry import flight

                    flight.record("checkpoint_superseded",
                                  step=self._pending.step,
                                  by_step=snap.step)
                    # the superseded clone is dropped here: its HBM
                    # claim goes with it (ISSUE 14)
                    if getattr(self._pending, "mem_claim", None) \
                            is not None:
                        self._pending.mem_claim.release()
                else:
                    while self._pending is not None and not self._closing:
                        self._cond.wait(0.05)
            self._pending = snap
            self._cond.notify_all()
        self._update_depth_locked()

    def checkpoint(self, net, step):
        """snapshot + submit (the ElasticTrainer hook entry point)."""
        import jax

        if not self.sharded and jax.process_index() != 0:
            # single-file mode: process 0 owns the write — skip the
            # device clone entirely on other hosts, but keep their
            # instrument sets identical (the multi-host aggregate
            # contract, same as the sync path's zero-byte records)
            note_commit(self._path(int(step)), step, 0.0, "async")
            return
        self.submit(self.snapshot(net, step))

    def drain(self, timeout=30.0):
        """Block until every queued snapshot is committed (or failed).
        Re-raises nothing: write failures are recorded and the previous
        checkpoint stays current — the caller's durable fallback is a
        final synchronous write."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while (self._pending is not None or self._busy) \
                    and time.monotonic() < deadline:
                self._cond.wait(0.05)
            return self._pending is None and not self._busy

    def close(self, timeout=30.0):
        self.drain(timeout)
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)

    def _update_depth_locked(self):
        with self._cond:
            depth = (1 if self._pending is not None else 0) + \
                (1 if self._busy else 0)
        _set_queue_depth(depth)

    # -- background half -----------------------------------------------------
    def _writer_loop(self):
        while True:
            with self._cond:
                while self._pending is None and not self._closing:
                    self._cond.wait(0.2)
                if self._pending is None and self._closing:
                    return
                snap, self._pending = self._pending, None
                self._busy = True
                self._cond.notify_all()
            self._update_depth_locked()
            try:
                self._write(snap)
            except Exception as e:  # injected or real IO failure
                phase = "commit" if getattr(e, "_dl4j_commit", False) \
                    else "write"
                from deeplearning4j_tpu.resilience.faults import FaultError

                note_failure(snap.step, phase, e)
                if not isinstance(e, (OSError, FaultError)):
                    log.exception("unexpected async checkpoint failure")
                self._error = e
            finally:
                # written or failed, the clone is no longer pinned by
                # this writer: release its HBM claim (ISSUE 14)
                if getattr(snap, "mem_claim", None) is not None:
                    snap.mem_claim.release()
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()
                self._update_depth_locked()

    def _path(self, iteration):
        suffix = "" if self.sharded else ".zip"
        return os.path.join(self.dir, f"checkpoint_{iteration:010d}{suffix}")

    def _write(self, snap: Snapshot):
        from deeplearning4j_tpu.utils import ModelSerializer
        from deeplearning4j_tpu.utils.checkpoint import atomic_save

        t0 = time.perf_counter()
        path = self._path(snap.step)
        if self.faults is not None:
            self.faults.check_write(snap.step, "write")

        def pre_commit():
            if self.faults is not None:
                try:
                    self.faults.check_write(snap.step, "commit")
                except Exception as e:
                    e._dl4j_commit = True
                    raise

        if self.sharded:
            from deeplearning4j_tpu.utils.sharded_checkpoint import (
                extract_snapshot, write_snapshot)

            tree = {"p": snap.params, "s": snap.states}
            if snap.save_updater:
                tree["o"] = snap.opt_states
            if snap.prec:
                tree["prec"] = snap.prec
            meta = {"modelType": snap.model_type,
                    "configuration": snap.conf.to_json(),
                    "saveUpdater": bool(snap.save_updater),
                    "hasPrecState": bool(snap.save_updater and snap.prec),
                    "trainingState": {"iteration": snap.iteration,
                                      "epoch": snap.epoch}}
            # write_snapshot is collective-free by construction — a
            # background thread must not issue collectives (they would
            # interleave with the train loop's in-step collectives and
            # desync the hosts); completeness is certified at read time
            # by latest_agreed() instead
            write_snapshot(self._path(snap.step),
                           extract_snapshot(tree, snap.step, meta),
                           pre_commit=pre_commit)
        else:
            import jax

            if jax.process_index() == 0:
                atomic_save(
                    path,
                    lambda tmp: ModelSerializer.writeModel(
                        snap, tmp, snap.save_updater,
                        modelType=snap.model_type),
                    pre_commit=pre_commit)
            # non-writers fall through: identical instrument sets on
            # every host (multi-host aggregate contract)
        dt = time.perf_counter() - t0
        if getattr(snap, "trace", None) is not None:
            from deeplearning4j_tpu.telemetry import tracing

            # background-writer half of the checkpoint, parented to the
            # training trace the snapshot rode in on (cross-thread)
            tracing.emit("ckpt.write", snap.trace, t0, t0 + dt,
                         step=snap.step, mode="async")
        note_commit(path, snap.step, dt, "async")
        try:
            self._rotate()
        except Exception:
            log.exception("checkpoint rotation failed")


# ---------------------------------------------------------------------------
# latest_agreed
# ---------------------------------------------------------------------------

def latest_agreed(checkpointDir):
    """Newest checkpoint that is complete on EVERY host: zip files are
    atomic (committed == complete); sharded directories must hold a
    committed manifest AND every shard file it references (the manifest
    is written only after the cross-process sync, so on shared storage
    this certifies all hosts finished). Returns a path or None."""
    if not os.path.isdir(checkpointDir):
        return None
    from deeplearning4j_tpu.utils.sharded_checkpoint import is_complete

    for name in sorted(os.listdir(checkpointDir), reverse=True):
        if not name.startswith("checkpoint_") or name.endswith(".tmp"):
            continue
        full = os.path.join(checkpointDir, name)
        if os.path.isdir(full):
            if is_complete(full):
                return full
        elif name.endswith(".zip"):
            return full
    return None
