"""TF GraphDef import into SameDiff.

Reference capability: `nd4j-api` `org.nd4j.imports.graphmapper.tf.
TFGraphMapper#importGraph` (SURVEY.md §2.3/§3.4: ~30k LoC of per-op
mapping classes; VERDICT.md round-1 missing item 1 — the reference's
BERT baseline config exists only through this path). The reference walks
a frozen GraphDef and interprets each NodeDef op-by-op at execution
time; here import is a one-shot translation into the native define-then-
run SameDiff graph, which then compiles to a single XLA executable —
imported models get the same jit/sharding treatment as natively built
ones.

Scope: the frozen-inference op set of BERT-class encoders and the
baseline MLP/CNN/LSTM architectures — constants, placeholders, linear
algebra, elementwise math, reductions, shape manipulation, gather/
concat/split/strided-slice, softmax/layer-norm/gelu decompositions,
conv/pool/fused-batch-norm (NHWC handled via explicit permutes), and
host-side constant folding for shape-carrying tensors (Shape/Pack/
Range/... feeding Reshape etc.), mirroring how the reference resolves
"array args that are really attributes".

Control deps (`^name`) are dropped: a frozen graph's control edges only
sequence stateful ops, and the imported graph is purely functional.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu.autodiff.samediff import SameDiff
from deeplearning4j_tpu.modelimport.protobuf import (
    GraphDef, dtype_to_numpy)


class TFImportError(ValueError):
    pass


def _ref_parts(name):
    """'node:k' -> (node, None, k); 'node:out_arg:k' -> (node, out_arg, k);
    '^node' -> (None, None, 0)."""
    if name.startswith("^"):
        return None, None, 0
    if ":" in name:
        node, idx = name.rsplit(":", 1)
        arg = None
        if ":" in node:
            node, arg = node.split(":", 1)
        return node, arg, int(idx)
    return name, None, 0


def _ref(name):
    """'node:k' -> (node, k); '^node' -> control dep (None). FunctionDef
    bodies use the 3-part form 'node:out_arg:k'; this bare helper drops
    the arg name (flat index k is only correct for a sole-output-arg op)
    — the importer's _resolve() adds the layout-aware mapping plus the
    distinct-arg-name rejection for ops outside the layout table."""
    node, _, idx = _ref_parts(name)
    return node, idx


# TF ops with multiple NAMED output args, in OpDef order (matching the
# order our handlers bind outputs): lets _resolve() compute the true flat
# index for 3-part FunctionDef refs like 'u:idx:0'. Ops not listed here
# are assumed single-output-arg; a reference through a second distinct
# arg name is detected and rejected (see _resolve), but a LONE reference
# to a non-first arg of an unlisted op cannot be detected without the TF
# OpDef and would resolve to flat index k.
_OUT_ARG_LAYOUTS = {
    "Unique": ("y", "idx"),
    "UniqueV2": ("y", "idx"),
    "UniqueWithCounts": ("y", "idx", "count"),
    "TopKV2": ("values", "indices"),
    "NonMaxSuppressionV4": ("selected_indices", "valid_outputs"),
    "MaxPoolWithArgmax": ("output", "argmax"),
}


class TFGraphMapper:
    """Entry points mirroring org.nd4j.imports.graphmapper.tf."""

    @staticmethod
    def importGraph(path_or_graphdef, placeholder_shapes=None,
                    trainable=False, strict=False) -> SameDiff:
        """placeholder_shapes: {placeholder_name: concrete shape} for
        graphs whose recorded input shapes have unknown (-1) dims; the
        import specializes to them (like feeding fixed shapes to the
        reference's TFGraphMapper).

        trainable=True converts the imported weight constants to
        VARIABLEs (see makeTrainable) so the graph can be fine-tuned —
        the reference's imported-BERT training flow (SURVEY.md §3.4).

        strict=True turns documented-deviation warnings (e.g. TF1-legacy
        resize sampling) into TFImportError."""
        if isinstance(path_or_graphdef, GraphDef):
            gd = path_or_graphdef
        else:
            gd = GraphDef.parse(path_or_graphdef)
        sd = _Importer(gd, placeholder_shapes, strict=strict).run()
        if trainable:
            TFGraphMapper.makeTrainable(sd)
        return sd

    @staticmethod
    def makeTrainable(sd: SameDiff, names=None) -> list:
        """Convert imported weight constants to trainable VARIABLEs.

        A frozen GraphDef stores every weight as a Const; fine-tuning
        needs them as variables (reference: imported SameDiff graphs
        train after TFGraphMapper import). names=None converts every
        float constant with more than one element (weights/biases),
        leaving scalars and integer tables (shape consts, ids) frozen.
        Returns the converted names."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.autodiff.samediff import VariableType

        converted = []
        for name, v in sd._vars.items():
            if v.variableType != VariableType.CONSTANT:
                continue
            if names is not None:
                if name in names:
                    sd.convertToVariable(v)
                    converted.append(name)
                continue
            arr = sd._values.get(name)
            if arr is None:
                continue
            arr = jnp.asarray(arr)
            if jnp.issubdtype(arr.dtype, jnp.floating) and arr.size > 1:
                sd.convertToVariable(v)
                converted.append(name)
        return converted


class _Importer:
    def __init__(self, gd: GraphDef, placeholder_shapes=None,
                 strict=False):
        gd = _rewrite_v1_loops(gd)
        self.gd = gd
        self.strict = strict
        self.placeholder_shapes = dict(placeholder_shapes or {})
        self.nodes = {n.name: n for n in gd.nodes}
        self.functions = {f.signature.name: f
                          for f in getattr(gd, "functions", [])}
        self.sd = SameDiff.create()
        self.vars = {}        # tf tensor name "node:k" -> SDVariable
        self.shapes = {}      # tf tensor name -> tuple (static)
        self.dtypes = {}      # tf tensor name -> np.dtype
        self.consts = {}      # node name -> np.ndarray (host-foldable)
        self._out_args = {}   # node name -> out_arg name seen in 3-part refs
        self.tensor_arrays = {}  # TensorArrayV3 node -> {size,dtype,elem}

    # -- public ------------------------------------------------------------

    def run(self) -> SameDiff:
        for node in self._topo_order():
            handler = _HANDLERS.get(node.op)
            if handler is None:
                raise TFImportError(
                    f"unsupported TF op {node.op!r} (node {node.name!r})")
            handler(self, node)
        return self.sd

    # -- graph walking -----------------------------------------------------

    def _topo_order(self):
        """Iterative DFS post-order (BERT-class graphs have serial chains
        far deeper than Python's recursion limit)."""
        order, seen, visiting = [], set(), set()
        for root in self.gd.nodes:
            stack = [(root.name, False)]
            while stack:
                name, expanded = stack.pop()
                if expanded:
                    visiting.discard(name)
                    seen.add(name)
                    order.append(self.nodes[name])
                    continue
                if name in seen:
                    continue
                if name in visiting:
                    raise TFImportError(
                        f"cycle at node {name!r} (control flow loops are "
                        "not supported)")
                node = self.nodes.get(name)
                if node is None:
                    raise TFImportError(f"missing node {name!r}")
                visiting.add(name)
                stack.append((name, True))
                for inp in node.inputs:
                    src, _ = _ref(inp)
                    if src is not None and src not in seen:
                        stack.append((src, False))
        return order

    # -- tensor accessors ----------------------------------------------------

    def data_inputs(self, node):
        return [i for i in node.inputs if not i.startswith("^")]

    def _resolve(self, ref):
        """(node, flat_output_index) for a tensor ref, honouring the
        FunctionDef 3-part form 'node:out_arg:k'. Ops in _OUT_ARG_LAYOUTS
        get the exact layout-based index; for unlisted ops flat=k is only
        correct when out_arg is the node's sole output arg, so two
        DISTINCT arg names on one node (which would alias to the same
        index and silently wire the wrong tensor, ADVICE r3) are
        rejected."""
        node, arg, k = _ref_parts(ref)
        if node is None or arg is None:
            return node, k
        nd = self.nodes.get(node)
        layout = _OUT_ARG_LAYOUTS.get(nd.op) if nd is not None else None
        if layout is not None:
            if arg not in layout:
                raise TFImportError(
                    f"ref {ref!r}: op {nd.op} has output args {layout}, "
                    f"not {arg!r}")
            return node, layout.index(arg) + k
        seen = self._out_args.setdefault(node, arg)
        if seen != arg:
            raise TFImportError(
                f"node {node!r} is referenced through two distinct output "
                f"args ({seen!r} and {arg!r}); ops with multiple named "
                "output args inside While/If function bodies cannot be "
                "flat-indexed without the TF OpDef layout — re-export the "
                "graph with such multi-output ops outside the function "
                "body, or split the op")
        return node, k

    def var(self, ref):
        """SDVariable for a tf tensor ref, materializing host constants."""
        node, idx = self._resolve(ref)
        key = f"{node}:{idx}"
        if key in self.vars:
            return self.vars[key]
        if node in self.consts and idx == 0:
            v = self.sd.constant(node, np.asarray(self.consts[node]))
            self.vars[key] = v
            return v
        raise TFImportError(f"no tensor produced for {ref!r}")

    def const(self, ref):
        """numpy value of a host-foldable tensor ref, or None."""
        node, idx = self._resolve(ref)
        if idx != 0:
            return None
        return self._fold(node)

    def need_const(self, ref, what):
        v = self.const(ref)
        if v is None:
            raise TFImportError(
                f"{what} must be statically resolvable, but {ref!r} is not "
                "constant-foldable")
        return v

    def shape(self, ref):
        node, idx = self._resolve(ref)
        key = f"{node}:{idx}"
        if key not in self.shapes:
            raise TFImportError(f"no static shape for {ref!r}")
        return self.shapes[key]

    def dtype(self, ref):
        node, idx = self._resolve(ref)
        return self.dtypes.get(f"{node}:{idx}", np.dtype(np.float32))

    # -- emission ------------------------------------------------------------

    def bind(self, node_name, var, shape, dtype, out_idx=0):
        key = f"{node_name}:{out_idx}"
        self.vars[key] = var
        self.shapes[key] = tuple(int(s) for s in shape)
        self.dtypes[key] = np.dtype(dtype)
        return var

    def emit(self, node, fn_name, in_refs, attrs=None, out_dtype=None,
             out_idx_base=0, out_name=None):
        """Emit one SameDiff op; static shape via jax.eval_shape.
        out_name overrides the bound tensor name (for handlers that emit
        helper ops around the TF node, e.g. NHWC permutes)."""
        import jax

        from deeplearning4j_tpu.autodiff.ops import OPS

        name = out_name or node.name
        in_vars = [self.var(r) for r in in_refs]
        structs = [jax.ShapeDtypeStruct(self.shape(r), self.dtype(r))
                   for r in in_refs]
        attrs = {k: v for k, v in (attrs or {}).items() if v is not None}
        try:
            out_struct = jax.eval_shape(
                lambda *a: OPS[fn_name](*a, **attrs), *structs)
        except TFImportError:
            raise
        except Exception as e:
            # surface op-level shape/config errors with graph context
            raise TFImportError(
                f"node {node.name!r} ({node.op}): {fn_name} rejected "
                f"the configuration: {e}") from e
        multi = isinstance(out_struct, (tuple, list))
        n_out = len(out_struct) if multi else 1
        res = self.sd._op(fn_name, in_vars, attrs, name, n_out=n_out)
        outs = res if multi else (res,)
        structs_out = out_struct if multi else (out_struct,)
        for i, (v, st) in enumerate(zip(outs, structs_out)):
            self.bind(name, v, st.shape,
                      out_dtype or st.dtype, out_idx=i)
        return res

    # -- host constant folding ----------------------------------------------

    def _fold(self, node_name, _depth=0):
        """numpy value of node_name if computable on the host (memoized)."""
        if node_name in self.consts:
            return self.consts[node_name]
        if _depth > 64:
            return None
        node = self.nodes.get(node_name)
        if node is None:
            return None
        ins = self.data_inputs(node)

        def rec(ref):
            src, idx = _ref(ref)
            if idx != 0:
                return None
            return self._fold(src, _depth + 1)

        val = None
        op = node.op
        if op in ("Identity", "StopGradient", "PreventGradient"):
            val = rec(ins[0])
        elif op in ("Shape", "Size", "Rank"):
            key = f"{_ref(ins[0])[0]}:{_ref(ins[0])[1]}"
            if key in self.shapes:
                sh = self.shapes[key]
                val = {"Shape": np.asarray(sh, np.int32),
                       "Size": np.asarray(int(np.prod(sh)), np.int32),
                       "Rank": np.asarray(len(sh), np.int32)}[op]
        elif op in ("Pack", "ConcatV2", "Add", "AddV2", "Sub", "Mul",
                    "Cast", "Range", "StridedSlice", "Reshape", "Squeeze",
                    "ExpandDims", "Prod", "Maximum", "Minimum", "Floor",
                    "GatherV2", "Neg", "RealDiv", "FloorDiv"):
            vals = [rec(r) for r in ins]
            if all(v is not None for v in vals):
                val = self._fold_compute(node, vals)
        if val is not None:
            self.consts[node_name] = val
        return val

    @staticmethod
    def _fold_compute(node, vals):
        op = node.op
        if op == "Pack":
            return np.stack(vals, axis=node.attrs.get("axis").i
                            if "axis" in node.attrs else 0)
        if op == "ConcatV2":
            axis = int(vals[-1])
            return np.concatenate(vals[:-1], axis=axis)
        if op in ("Add", "AddV2"):
            return vals[0] + vals[1]
        if op == "Sub":
            return vals[0] - vals[1]
        if op == "Mul":
            return vals[0] * vals[1]
        if op == "RealDiv":
            return vals[0] / vals[1]
        if op == "FloorDiv":
            return vals[0] // vals[1]
        if op == "Maximum":
            return np.maximum(vals[0], vals[1])
        if op == "Minimum":
            return np.minimum(vals[0], vals[1])
        if op == "Floor":
            return np.floor(vals[0])
        if op == "Neg":
            return -vals[0]
        if op == "Cast":
            return np.asarray(
                vals[0], dtype_to_numpy(node.attrs["DstT"].type))
        if op == "Range":
            start, limit, delta = (np.asarray(v) for v in vals)
            try:
                out = np.arange(start[()], limit[()], delta[()])
            except ValueError as e:
                raise TFImportError(f"Range node {node.name!r}: {e}")
            all_int = all(np.issubdtype(v.dtype, np.integer)
                          for v in (start, limit, delta))
            return out.astype(np.int32 if all_int else np.float32)
        if op == "StridedSlice":
            return _apply_strided_slice(node, vals[0], vals[1], vals[2],
                                        vals[3])[0]
        if op == "Reshape":
            return np.reshape(vals[0], [int(s) for s in vals[1]])
        if op == "Squeeze":
            dims = [d.i if hasattr(d, "i") else int(d) for d in
                    (node.attrs.get("squeeze_dims").list["i"]
                     if "squeeze_dims" in node.attrs else [])]
            return np.squeeze(vals[0], axis=tuple(dims) if dims else None)
        if op == "ExpandDims":
            return np.expand_dims(vals[0], int(vals[1]))
        if op == "Prod":
            return np.prod(vals[0], axis=tuple(np.atleast_1d(vals[1])))
        if op == "GatherV2":
            axis = int(vals[2]) if len(vals) > 2 else 0
            return np.take(vals[0], np.asarray(vals[1], np.int64), axis=axis)
        return None


def _apply_strided_slice(node, x, begin, end, strides):
    """numpy semantics of TF StridedSlice incl. masks. Returns (result,
    py_slices) — py_slices reusable for the symbolic path."""
    begin = np.atleast_1d(begin).astype(np.int64)
    end = np.atleast_1d(end).astype(np.int64)
    strides = (np.atleast_1d(strides).astype(np.int64) if strides is not None
               else np.ones_like(begin))
    get = lambda a: node.attrs[a].i if a in node.attrs else 0  # noqa: E731
    bm, em = get("begin_mask"), get("end_mask")
    sm, nm = get("shrink_axis_mask"), get("new_axis_mask")
    elm = get("ellipsis_mask")
    if elm:
        # expand the (single) ellipsis into full slices over the dims
        # not covered by the other spec entries (TF allows exactly one)
        if bin(elm).count("1") > 1:
            raise TFImportError(
                "StridedSlice with multiple ellipses is invalid")
        pos = elm.bit_length() - 1
        n_spec = len(begin) - 1  # entries besides the ellipsis
        n_new = bin(nm).count("1")
        rank = np.asarray(x).ndim
        fill = rank - (n_spec - n_new)

        def expand(arr, fill_val):
            return np.concatenate([
                arr[:pos], np.full(fill, fill_val, np.int64),
                arr[pos + 1:]])

        begin = expand(begin, 0)
        end = expand(end, 0)
        strides = expand(strides, 1)

        def expand_mask(mask, set_fill):
            lo = mask & ((1 << pos) - 1)
            hi = (mask >> (pos + 1)) << (pos + fill)
            mid = (((1 << fill) - 1) << pos) if set_fill else 0
            return lo | hi | mid

        bm = expand_mask(bm, True)
        em = expand_mask(em, True)
        sm = expand_mask(sm, False)
        nm = expand_mask(nm, False)
    idx = []
    for i in range(len(begin)):
        if nm & (1 << i):
            idx.append(None)  # np.newaxis
            continue
        if sm & (1 << i):
            idx.append(int(begin[i]))
            continue
        b = None if bm & (1 << i) else int(begin[i])
        e = None if em & (1 << i) else int(end[i])
        idx.append(slice(b, e, int(strides[i])))
    return np.asarray(x)[tuple(idx)], idx


# ---------------------------------------------------------------------------
# per-op handlers
# ---------------------------------------------------------------------------

_HANDLERS = {}


def handler(*names):
    def deco(fn):
        for n in names:
            _HANDLERS[n] = fn
        return fn

    return deco


@handler("Const")
def _h_const(im, node):
    arr = node.attrs["value"].tensor.to_numpy()
    im.consts[node.name] = np.asarray(arr)
    v = im.sd.constant(node.name, np.asarray(arr))
    im.bind(node.name, v, arr.shape, arr.dtype)


@handler("Placeholder", "PlaceholderWithDefault")
def _h_placeholder(im, node):
    dt = dtype_to_numpy(node.attrs["dtype"].type)
    shape = None
    if "shape" in node.attrs and node.attrs["shape"].shape is not None \
            and not node.attrs["shape"].shape.unknown_rank:
        shape = [int(d) if d is not None else -1
                 for d in node.attrs["shape"].shape.dims]
    if node.name in im.placeholder_shapes:
        given = [int(d) for d in im.placeholder_shapes[node.name]]
        if shape is not None and len(given) != len(shape):
            raise TFImportError(
                f"placeholder_shapes[{node.name!r}] rank {len(given)} != "
                f"recorded rank {len(shape)}")
        shape = given
    if shape is None or any(d is None or d < 0 for d in shape):
        # Do NOT fabricate unknown dims: Shape const-folding would bake
        # them into every downstream Reshape (silently wrong at runtime).
        raise TFImportError(
            f"placeholder {node.name!r} has unknown dims {shape}; pass "
            "concrete shapes via importGraph(..., placeholder_shapes="
            "{name: shape}) — the import specializes the graph to them "
            "(re-import to run a different batch size)")
    v = im.sd.placeHolder(node.name, jnp.dtype(dt), *shape)
    im.bind(node.name, v, shape, dt)


@handler("Identity", "StopGradient", "PreventGradient", "Snapshot")
def _h_identity(im, node):
    # Emit a real identity op so the node's name is fetchable from the
    # SameDiff graph — freeze_graph conventionally names the OUTPUT with
    # tf.identity(logits, name='output'), and sd.output(..., 'output')
    # must resolve it.
    im.emit(node, "identity", [im.data_inputs(node)[0]])


@handler("NoOp", "Assert")
def _h_noop(im, node):
    pass


_UNARY = {
    "Relu": "relu", "Relu6": "relu6", "Elu": "elu", "Selu": "selu",
    "Softplus": "softplus", "Softsign": "softsign", "Tanh": "tanh",
    "Sigmoid": "sigmoid", "Erf": "erf", "Exp": "exp", "Log": "log",
    "Log1p": "log1p", "Neg": "neg", "Sqrt": "sqrt", "Rsqrt": "rsqrt",
    "Square": "square", "Abs": "abs", "Sign": "sign", "Floor": "floor",
    "Ceil": "ceil", "Round": "round", "Sin": "sin", "Cos": "cos",
    "Tan": "tan", "Asin": "asin", "Acos": "acos", "Atan": "atan",
    "Sinh": "sinh", "Cosh": "cosh", "Reciprocal": "reciprocal",
    "IsNan": "isnan", "IsInf": "isinf", "LogicalNot": "not_op",
    "Erfc": "erfc", "Lgamma": "lgamma", "Digamma": "digamma",
    "Expm1": "expm1", "Asinh": "asinh", "Acosh": "acosh",
    "Atanh": "atanh", "Cholesky": "cholesky",
    "MatrixInverse": "matrixInverse",
    "MatrixDeterminant": "matrixDeterminant",
}


@handler(*_UNARY)
def _h_unary(im, node):
    im.emit(node, _UNARY[node.op], im.data_inputs(node))


_BINARY = {
    "Add": "add", "AddV2": "add", "Sub": "sub", "Mul": "mul",
    "RealDiv": "div", "Div": "div", "FloorDiv": "floordiv",
    "Pow": "pow", "Maximum": "maximum", "Minimum": "minimum",
    "SquaredDifference": "squaredDifference", "FloorMod": "mod",
    "Equal": "eq", "NotEqual": "neq", "Greater": "gt",
    "GreaterEqual": "gte", "Less": "lt", "LessEqual": "lte",
    "LogicalAnd": "and_op", "LogicalOr": "or_op", "Atan2": "atan2",
    "Igamma": "igamma", "Igammac": "igammac",
}


@handler(*_BINARY)
def _h_binary(im, node):
    im.emit(node, _BINARY[node.op], im.data_inputs(node))


@handler("AddN")
def _h_addn(im, node):
    ins = im.data_inputs(node)
    ref = ins[0]
    acc = im.var(ref)
    if len(ins) == 1:
        im.bind(node.name, acc, im.shape(ref), im.dtype(ref))
        return
    for i, nxt in enumerate(ins[1:]):
        last = i == len(ins) - 2
        nm = node.name if last else f"{node.name}__addn{i}"
        acc = im.sd._op("add", [acc, im.var(nxt)], {}, nm)
    im.bind(node.name, acc, im.shape(ref), im.dtype(ref))


@handler("MatMul")
def _h_matmul(im, node):
    a = node.attrs.get("transpose_a")
    b = node.attrs.get("transpose_b")
    im.emit(node, "matmul", im.data_inputs(node),
            {"transposeA": bool(a.b) if a else False,
             "transposeB": bool(b.b) if b else False})


@handler("BatchMatMul", "BatchMatMulV2")
def _h_batch_matmul(im, node):
    adj_x = node.attrs.get("adj_x")
    adj_y = node.attrs.get("adj_y")
    im.emit(node, "matmul", im.data_inputs(node),
            {"transposeA": bool(adj_x.b) if adj_x else False,
             "transposeB": bool(adj_y.b) if adj_y else False})


@handler("BiasAdd")
def _h_bias_add(im, node):
    fmt = node.attrs.get("data_format")
    ins = im.data_inputs(node)
    if fmt is not None and fmt.s == b"NCHW":
        x_shape = im.shape(ins[0])
        bshape = [1] * len(x_shape)
        bshape[1] = x_shape[1]
        b = im.sd._op("reshape", [im.var(ins[1])],
                      {"shape": bshape}, f"{node.name}__b")
        im.bind(f"{node.name}__b", b, bshape, im.dtype(ins[1]))
        im.emit(node, "add", [ins[0], f"{node.name}__b:0"])
        return
    im.emit(node, "add", ins)


@handler("Softmax")
def _h_softmax(im, node):
    im.emit(node, "softmax", im.data_inputs(node), {"dimension": -1})


@handler("LogSoftmax")
def _h_log_softmax(im, node):
    im.emit(node, "logSoftmax", im.data_inputs(node), {"dimension": -1})


_REDUCTIONS = {"Mean": "mean", "Sum": "sum", "Max": "max", "Min": "min",
               "Prod": "prod", "All": "all", "Any": "any"}


@handler(*_REDUCTIONS)
def _h_reduce(im, node):
    ins = im.data_inputs(node)
    axes = im.need_const(ins[1], f"{node.op} reduction indices")
    keep = node.attrs.get("keep_dims")
    rank = len(im.shape(ins[0]))
    dims = [int(a) % rank for a in np.atleast_1d(axes)]
    im.emit(node, _REDUCTIONS[node.op], [ins[0]],
            {"dimensions": dims, "keepDims": bool(keep.b) if keep else False})


@handler("ArgMax", "ArgMin")
def _h_argmax(im, node):
    ins = im.data_inputs(node)
    axis = int(im.need_const(ins[1], "ArgMax axis")) if len(ins) > 1 else 0
    im.emit(node, "_argmax" if node.op == "ArgMax" else "_argmin", [ins[0]],
            {"dim": axis}, out_dtype=np.int64)


@handler("Reshape")
def _h_reshape(im, node):
    ins = im.data_inputs(node)
    target = [int(s) for s in
              im.need_const(ins[1], "Reshape shape")]
    in_shape = im.shape(ins[0])
    if -1 in target:
        known = int(np.prod([s for s in target if s != -1]))
        total = int(np.prod(in_shape))
        target[target.index(-1)] = total // max(known, 1)
    im.emit(node, "reshape", [ins[0]], {"shape": target})


@handler("Transpose")
def _h_transpose(im, node):
    ins = im.data_inputs(node)
    perm = [int(p) for p in im.need_const(ins[1], "Transpose perm")]
    im.emit(node, "permute", [ins[0]], {"dimensions": perm})


@handler("ExpandDims")
def _h_expand_dims(im, node):
    ins = im.data_inputs(node)
    axis = int(im.need_const(ins[1], "ExpandDims axis"))
    im.emit(node, "expandDims", [ins[0]], {"axis": axis})


@handler("Squeeze")
def _h_squeeze(im, node):
    ins = im.data_inputs(node)
    dims = None
    if "squeeze_dims" in node.attrs:
        lst = node.attrs["squeeze_dims"].list
        if lst and lst["i"]:
            dims = tuple(int(i) for i in lst["i"])
    im.emit(node, "squeeze", ins, {"axis": dims})


@handler("ConcatV2")
def _h_concat(im, node):
    ins = im.data_inputs(node)
    axis = int(im.need_const(ins[-1], "ConcatV2 axis"))
    im.emit(node, "concat", ins[:-1], {"dimension": axis})


@handler("Pack")
def _h_pack(im, node):
    axis = node.attrs["axis"].i if "axis" in node.attrs else 0
    im.emit(node, "stack", im.data_inputs(node), {"axis": int(axis)})


@handler("Unpack")
def _h_unpack(im, node):
    axis = node.attrs["axis"].i if "axis" in node.attrs else 0
    num = node.attrs["num"].i
    im.emit(node, "unstack", im.data_inputs(node),
            {"axis": int(axis), "num": int(num)})


@handler("Split")
def _h_split(im, node):
    ins = im.data_inputs(node)  # [axis, value]
    axis = int(im.need_const(ins[0], "Split axis"))
    num = int(node.attrs["num_split"].i)
    im.emit(node, "split", [ins[1]],
            {"numSplit": num, "dimension": axis})


@handler("StridedSlice")
def _h_strided_slice(im, node):
    ins = im.data_inputs(node)
    begin = im.need_const(ins[1], "StridedSlice begin")
    end = im.need_const(ins[2], "StridedSlice end")
    strides = im.need_const(ins[3], "StridedSlice strides") \
        if len(ins) > 3 else None
    in_shape = im.shape(ins[0])
    # allocation-free shape probe (broadcast view, never materialized)
    probe = np.broadcast_to(np.int8(0), in_shape)
    _, idx = _apply_strided_slice(node, probe, begin, end, strides)

    ser = [None if i is None else
           ([i.start, i.stop, i.step] if isinstance(i, slice) else int(i))
           for i in idx]
    im.emit(node, "tfStridedSlice", [ins[0]], {"idx": tuple(
        tuple(s) if isinstance(s, list) else s for s in ser)})


@handler("Slice")
def _h_slice(im, node):
    ins = im.data_inputs(node)
    begin = [int(b) for b in im.need_const(ins[1], "Slice begin")]
    size = [int(s) for s in im.need_const(ins[2], "Slice size")]
    im.emit(node, "slice", [ins[0]], {"begin": begin, "size": size})


@handler("Gather", "GatherV2")
def _h_gather(im, node):
    ins = im.data_inputs(node)
    axis = 0
    if node.op == "GatherV2" and len(ins) > 2:
        axis = int(im.need_const(ins[2], "GatherV2 axis"))
    im.emit(node, "gather", ins[:2], {"axis": axis})


@handler("GatherNd")
def _h_gather_nd(im, node):
    im.emit(node, "gatherNd", im.data_inputs(node))


@handler("OneHot")
def _h_one_hot(im, node):
    ins = im.data_inputs(node)
    depth = int(im.need_const(ins[1], "OneHot depth"))
    on = float(im.need_const(ins[2], "OneHot on_value"))
    off = float(im.need_const(ins[3], "OneHot off_value"))
    axis = node.attrs["axis"].i if "axis" in node.attrs else -1
    im.emit(node, "oneHot", [ins[0]],
            {"depth": depth, "on": on, "off": off, "axis": int(axis)})


@handler("Cast")
def _h_cast(im, node):
    dt = dtype_to_numpy(node.attrs["DstT"].type)
    im.emit(node, "cast", im.data_inputs(node), {"dtype": jnp.dtype(dt)},
            out_dtype=dt)


@handler("Shape", "Size", "Rank")
def _h_shape(im, node):
    ins = im.data_inputs(node)
    val = im._fold(node.name)
    if val is None:
        sh = im.shape(ins[0])
        val = {"Shape": np.asarray(sh, np.int32),
               "Size": np.asarray(int(np.prod(sh)), np.int32),
               "Rank": np.asarray(len(sh), np.int32)}[node.op]
        im.consts[node.name] = val
    v = im.sd.constant(node.name, val)
    im.bind(node.name, v, np.asarray(val).shape, np.asarray(val).dtype)


@handler("Range")
def _h_range(im, node):
    val = im._fold(node.name)
    if val is None:
        raise TFImportError(f"Range node {node.name!r} with non-constant "
                            "inputs")
    v = im.sd.constant(node.name, val)
    im.bind(node.name, v, val.shape, val.dtype)


@handler("Fill")
def _h_fill(im, node):
    ins = im.data_inputs(node)
    dims = [int(d) for d in im.need_const(ins[0], "Fill dims")]
    value = im.need_const(ins[1], "Fill value")
    arr = np.full(dims, value)
    im.consts[node.name] = arr
    v = im.sd.constant(node.name, arr)
    im.bind(node.name, v, arr.shape, arr.dtype)


@handler("Tile")
def _h_tile(im, node):
    ins = im.data_inputs(node)
    reps = [int(r) for r in im.need_const(ins[1], "Tile multiples")]
    im.emit(node, "tile", [ins[0]], {"reps": reps})


@handler("Pad", "PadV2")
def _h_pad(im, node):
    ins = im.data_inputs(node)
    pads = [[int(a), int(b)] for a, b in
            im.need_const(ins[1], "Pad paddings")]
    const = 0.0
    if node.op == "PadV2" and len(ins) > 2:
        const = float(im.need_const(ins[2], "PadV2 constant"))
    im.emit(node, "pad", [ins[0]], {"paddings": pads, "constant": const})


@handler("Select", "SelectV2")
def _h_select(im, node):
    im.emit(node, "where_op", im.data_inputs(node))


@handler("Einsum")
def _h_einsum(im, node):
    """tf.einsum with a static equation attr — XLA-exported BERT graphs
    express their projections this way."""
    eq = node.attrs["equation"].s.decode()
    im.emit(node, "tfEinsum", im.data_inputs(node), {"equation": eq})


@handler("SpaceToDepth", "DepthToSpace")
def _h_space_depth(im, node):
    """NOTE: emitted against our NCHW ops. TF's DEFAULT data_format for
    these ops is NHWC, so an absent attr is NHWC and must be rejected —
    only graphs declaring NCHW import exactly."""
    fmt = node.attrs.get("data_format")
    fmt_s = fmt.s.decode() if fmt is not None else "NHWC"
    if fmt_s != "NCHW":
        raise ValueError(
            f"{node.op} data_format {fmt_s!r} unsupported (NCHW only)")
    bs = int(node.attrs["block_size"].i)
    opname = ("spaceToDepth" if node.op == "SpaceToDepth"
              else "depthToSpace")
    im.emit(node, opname, im.data_inputs(node), {"blockSize": bs})


@handler("TopKV2")
def _h_topk(im, node):
    ins = im.data_inputs(node)
    k = int(im.need_const(ins[1], "TopKV2 k"))
    im.emit(node, "topK", [ins[0]], {"k": k})


@handler("Cumsum")
def _h_cumsum(im, node):
    ins = im.data_inputs(node)
    axis = int(im.need_const(ins[1], "Cumsum axis"))
    excl = node.attrs.get("exclusive")
    rev = node.attrs.get("reverse")
    im.emit(node, "cumsum", [ins[0]],
            {"axis": axis, "exclusive": bool(excl.b) if excl else False,
             "reverse": bool(rev.b) if rev else False})


@handler("ZerosLike", "OnesLike")
def _h_fill_like(im, node):
    key = "tfZerosLike" if node.op == "ZerosLike" else "tfOnesLike"
    im.emit(node, key, im.data_inputs(node))


@handler("Conv2D")
def _h_conv2d(im, node):
    ins = im.data_inputs(node)
    fmt = node.attrs.get("data_format")
    nhwc = fmt is None or fmt.s in (b"NHWC", None)
    strides = [int(s) for s in node.attrs["strides"].list["i"]]
    pad = node.attrs["padding"].s.decode()
    dil = [int(d) for d in node.attrs["dilations"].list["i"]] \
        if "dilations" in node.attrs else [1, 1, 1, 1]
    x_ref = ins[0]
    if nhwc:
        x_ref = _permute(im, node, ins[0], (0, 3, 1, 2), "__nchw")
        s_hw, d_hw = (strides[1], strides[2]), (dil[1], dil[2])
    else:
        s_hw, d_hw = (strides[2], strides[3]), (dil[2], dil[3])
    # TF kernel HWIO -> our OIHW
    w_ref = _permute(im, node, ins[1], (3, 2, 0, 1), "__oihw")
    attrs = {"strides": s_hw,
             "dilation": d_hw,
             "sameMode": pad == "SAME",
             "padding": (0, 0)}
    out_name = node.name if not nhwc else f"{node.name}__conv"
    conv = im.sd._op("conv2d", [im.var(x_ref), im.var(w_ref)], attrs,
                     out_name)
    import jax

    from deeplearning4j_tpu.autodiff.ops import OPS

    st = jax.eval_shape(
        lambda x, w: OPS["conv2d"](x, w, **attrs),
        jax.ShapeDtypeStruct(im.shape(x_ref), im.dtype(x_ref)),
        jax.ShapeDtypeStruct(im.shape(w_ref), im.dtype(w_ref)))
    im.bind(out_name, conv, st.shape, st.dtype)
    if nhwc:
        _permute(im, node, f"{out_name}:0", (0, 2, 3, 1), "", node.name)


@handler("MaxPool", "AvgPool")
def _h_pool(im, node):
    ins = im.data_inputs(node)
    fmt = node.attrs.get("data_format")
    nhwc = fmt is None or fmt.s in (b"NHWC", None)
    ks = [int(s) for s in node.attrs["ksize"].list["i"]]
    st = [int(s) for s in node.attrs["strides"].list["i"]]
    pad = node.attrs["padding"].s.decode()
    x_ref = ins[0]
    if nhwc:
        x_ref = _permute(im, node, ins[0], (0, 3, 1, 2), "__nchw")
        k_hw, s_hw = (ks[1], ks[2]), (st[1], st[2])
    else:
        k_hw, s_hw = (ks[2], ks[3]), (st[2], st[3])
    fn = "maxPooling2d" if node.op == "MaxPool" else "avgPooling2d"
    out_name = node.name if not nhwc else f"{node.name}__pool"
    attrs = {"kernel": k_hw, "strides": s_hw, "sameMode": pad == "SAME",
             "padding": (0, 0)}
    import jax

    from deeplearning4j_tpu.autodiff.ops import OPS

    v = im.sd._op(fn, [im.var(x_ref)], attrs, out_name)
    sh = jax.eval_shape(lambda x: OPS[fn](x, **attrs),
                        jax.ShapeDtypeStruct(im.shape(x_ref),
                                             im.dtype(x_ref)))
    im.bind(out_name, v, sh.shape, sh.dtype)
    if nhwc:
        _permute(im, node, f"{out_name}:0", (0, 2, 3, 1), "", node.name)


@handler("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3")
def _h_fused_bn(im, node):
    ins = im.data_inputs(node)  # x, scale, offset, mean, variance
    fmt = node.attrs.get("data_format")
    nhwc = fmt is None or fmt.s in (b"NHWC", None)
    eps = node.attrs["epsilon"].f if "epsilon" in node.attrs else 1e-3
    axis = 3 if nhwc else 1
    im.emit(node, "batchNorm",
            [ins[0], ins[3], ins[4], ins[1], ins[2]],
            {"epsilon": float(eps), "axis": axis})


def _permute(im, node, ref, perm, suffix, out_name=None):
    """Emit a permute helper node; returns the new tensor ref string."""
    import jax

    from deeplearning4j_tpu.autodiff.ops import OPS

    name = out_name or f"{node.name}{suffix}"
    v = im.sd._op("permute", [im.var(ref)],
                  {"dimensions": tuple(perm)}, name)
    sh = jax.eval_shape(
        lambda x: OPS["permute"](x, dimensions=tuple(perm)),
        jax.ShapeDtypeStruct(im.shape(ref), im.dtype(ref)))
    im.bind(name, v, sh.shape, sh.dtype)
    return f"{name}:0"


# ---------------------------------------------------------------------------
# control flow (SURVEY.md §3.4: "control flow from TF interpreted in
# Java" — here v2 FUNCTIONAL control flow (While/StatelessWhile/If/
# StatelessIf + FunctionDef library) lowers onto the SameDiff
# whileLoop/ifCond ops, whose bodies are the imported function sub-graphs
# (serializable, lax.while_loop/cond at execution). v1 dataflow loops
# (Enter/Merge/Switch/NextIteration/Exit) are frame-encoded and cyclic;
# they are rejected with guidance to re-export functionally, which is
# what TF2's own importer requires too.)
# ---------------------------------------------------------------------------

def _function_subgraph(im, fname, arg_refs, what):
    """Import FunctionDef `fname` into a child SameDiff wrapped as a
    SubGraph; arg shapes/dtypes come from the outer tensors feeding it.
    Returns (SubGraph, out_shapes, out_dtypes)."""
    from deeplearning4j_tpu.autodiff.samediff import SubGraph
    from deeplearning4j_tpu.modelimport.protobuf import (
        AttrValue, NodeDef, TensorShapeProto, numpy_to_dtype)

    fdef = im.functions.get(fname)
    if fdef is None:
        raise TFImportError(
            f"{what} references function {fname!r} which is not in the "
            f"GraphDef library (have: {sorted(im.functions)})")
    sig = fdef.signature
    if len(sig.input_args) != len(arg_refs):
        raise TFImportError(
            f"{what} function {fname!r} takes {len(sig.input_args)} args "
            f"but {len(arg_refs)} were passed")

    nodes, ph_shapes = [], {}
    for arg, ref in zip(sig.input_args, arg_refs):
        shape = im.shape(ref)
        dt = im.dtype(ref)
        nodes.append(NodeDef(arg.name, "Placeholder", [], {
            "dtype": AttrValue(type=numpy_to_dtype(dt)),
            "shape": AttrValue(shape=TensorShapeProto(list(shape))),
        }))
        ph_shapes[arg.name] = shape
    nodes += fdef.nodes

    sub = _Importer(GraphDef(nodes, functions=list(im.functions.values())),
                    ph_shapes, strict=im.strict)
    child = sub.run()

    out_names, out_shapes, out_dtypes = [], [], []
    for arg in sig.output_args:
        ret_ref = fdef.ret.get(arg.name)
        if ret_ref is None:
            raise TFImportError(
                f"{what} function {fname!r} has no ret mapping for "
                f"output {arg.name!r}")
        v = sub.var(ret_ref)
        out_names.append(v.name())
        node_name, idx = sub._resolve(ret_ref)
        out_shapes.append(sub.shapes[f"{node_name}:{idx}"])
        out_dtypes.append(sub.dtypes[f"{node_name}:{idx}"])
    return (SubGraph(child, [a.name for a in sig.input_args], out_names),
            out_shapes, out_dtypes)


@handler("While", "StatelessWhile")
def _h_while(im, node):
    ins = im.data_inputs(node)
    cond, _, _ = _function_subgraph(im, node.attrs["cond"].func, ins,
                                    f"While node {node.name!r} cond")
    body, body_shapes, body_dtypes = _function_subgraph(
        im, node.attrs["body"].func, ins, f"While node {node.name!r} body")
    if len(body.out_names) != len(ins):
        raise TFImportError(
            f"While body must return {len(ins)} loop vars, got "
            f"{len(body.out_names)}")
    in_vars = [im.var(r) for r in ins]
    attrs = {"cond_graph": cond, "cond_fn": cond.callable(squeeze=True),
             "body_graph": body, "body_fn": body.callable()}
    n = len(in_vars)
    res = im.sd._op("whileLoop", in_vars, attrs, node.name,
                    n_out=n if n > 1 else 1)
    outs = res if isinstance(res, tuple) else (res,)
    for i, v in enumerate(outs):
        im.bind(node.name, v, body_shapes[i], body_dtypes[i], out_idx=i)


@handler("If", "StatelessIf")
def _h_if(im, node):
    ins = im.data_inputs(node)
    pred, rest = ins[0], ins[1:]
    tb, t_shapes, t_dtypes = _function_subgraph(
        im, node.attrs["then_branch"].func, rest,
        f"If node {node.name!r} then_branch")
    fb, f_shapes, f_dtypes = _function_subgraph(
        im, node.attrs["else_branch"].func, rest,
        f"If node {node.name!r} else_branch")
    if len(tb.out_names) != len(fb.out_names):
        raise TFImportError(
            f"If branches return different arities: {len(tb.out_names)} "
            f"vs {len(fb.out_names)}")
    if list(t_shapes) != list(f_shapes) or \
            [np.dtype(d) for d in t_dtypes] != \
            [np.dtype(d) for d in f_dtypes]:
        raise TFImportError(
            f"If node {node.name!r} branches disagree on output "
            f"shapes/dtypes: then {list(zip(t_shapes, t_dtypes))} vs "
            f"else {list(zip(f_shapes, f_dtypes))} — lax.cond requires "
            f"identical branch signatures")
    attrs = {"true_graph": tb, "true_fn": tb.callable(),
             "false_graph": fb, "false_fn": fb.callable()}
    n_out = len(tb.out_names)
    res = im.sd._op("ifCond", [im.var(pred)] + [im.var(r) for r in rest],
                    attrs, node.name, n_out=n_out)
    outs = res if isinstance(res, tuple) else (res,)
    for i, v in enumerate(outs):
        im.bind(node.name, v, t_shapes[i], t_dtypes[i], out_idx=i)


@handler("Enter", "Exit", "Merge", "Switch", "NextIteration", "LoopCond")
def _h_v1_control_flow(im, node):
    # single-frame while loops (incl. single-frame TensorArray loops) are
    # rewritten into _V1While by _rewrite_v1_loops before import; anything
    # that still reaches this handler is outside the supported subset
    raise TFImportError(
        f"node {node.name!r} uses TF v1 dataflow control flow "
        f"({node.op}) outside the supported single-frame while-loop "
        "subset (nested frames / cond-via-Switch are frame-encoded and "
        "cyclic) — re-export the model with TF2 functional control flow "
        "(While/If + function library), which imports onto SameDiff "
        "whileLoop/ifCond")


# ---------------------------------------------------------------------------
# TF v1 dataflow while-loops (VERDICT r3 item 4): the acyclic-frame
# subset — ONE frame per loop, no nesting, no TensorArray — is rewritten
# into a synthetic functional node before import and lowered onto the
# same SameDiff whileLoop the TF2 While handler uses. The reference
# interprets Enter/Merge/Switch/Exit in Java (SURVEY.md §3.4); here the
# frame is translated once at import time:
#   Enter_i -> loop var i's init value (outer graph)
#   Merge_i -> cond-graph placeholder i   (cond computes LoopCond input)
#   Switch_i:1 -> body-graph placeholder i (body computes NextIteration)
#   Exit_i -> whileLoop output i
# Loop-invariant Enters (is_constant=true) and references to outer
# tensors inline as constants when host-foldable; otherwise rejected.
# ---------------------------------------------------------------------------

class _V1Frame:
    def __init__(self, name):
        self.name = name
        self.enters = []        # loop-var Enter nodes
        self.const_enters = []  # is_constant Enters (loop invariants)
        self.handle_enters = {}  # enter name -> TensorArrayV3 node name
        self.nodes = {}         # interior name -> NodeDef (incl. merges)
        self.merges = []
        self.switches = {}      # merge name -> Switch node
        self.exits = {}         # merge name -> Exit node
        self.next_iters = {}    # merge name -> NextIteration input ref
        self.loop_cond = None


# interior TensorArray ops lower onto a loop-carried [size, ...] buffer
# (the TF "flow" edge is reinterpreted as the buffer tensor itself):
# op -> (synthetic op, original input positions kept, in order)
_TA_INTERIOR = {
    "TensorArrayReadV3": ("_TARead", (2, 1)),      # (flow, index)
    "TensorArrayWriteV3": ("_TAWrite", (3, 1, 2)),  # (flow, index, value)
    "TensorArrayGatherV3": ("_TAGather", (2, 1)),   # (flow, indices)
    "TensorArrayScatterV3": ("_TAWrite", (3, 1, 2)),
    "TensorArraySizeV3": ("_TASize", (1,)),         # (flow,)
}


def _find_v1_frames(gd):
    """Group v1 control-flow nodes by frame_name; returns
    {frame: _V1Frame} or raises for unsupported shapes."""
    producers = {n.name: n for n in gd.nodes}
    frames = {}
    frame_of = {}  # node name -> frame name (propagated)

    def frame_attr(n):
        a = n.attrs.get("frame_name")
        if a is None:
            raise TFImportError(
                f"Enter node {n.name!r} has no frame_name attr")
        return a.s.decode() if isinstance(a.s, bytes) else a.s

    enters = [n for n in gd.nodes if n.op == "Enter"]
    if not enters:
        return {}
    for n in enters:
        f = frames.setdefault(frame_attr(n), _V1Frame(frame_attr(n)))
        const = n.attrs.get("is_constant")
        if const is not None and const.b:
            f.const_enters.append(n)
        else:
            f.enters.append(n)
        frame_of[n.name] = f.name
    # forward-propagate frame membership (Exit leaves the frame)
    changed = True
    while changed:
        changed = False
        for n in gd.nodes:
            if n.name in frame_of or n.op in ("Enter", "Exit"):
                continue
            for inp in n.inputs:
                src, _ = _ref(inp)
                if src in frame_of:
                    fname = frame_of[src]
                    if producers[src].op == "Exit":
                        continue
                    frame_of[n.name] = fname
                    frames[fname].nodes[n.name] = n
                    changed = True
                    break
    for n in gd.nodes:
        if n.op == "Exit":
            src, _ = _ref(n.inputs[0])
            if src not in frame_of:
                raise TFImportError(
                    f"Exit node {n.name!r} input does not trace to a "
                    "frame")
            frame_of[n.name] = None  # Exit output is outer
    for f in frames.values():
        _classify_frame(f, producers)
    return frames


def _classify_frame(f, producers):
    _rewrite_frame_tensor_arrays(f, producers)
    for name, n in list(f.nodes.items()):
        if n.op == "Merge":
            f.merges.append(n)
        elif n.op == "LoopCond":
            f.loop_cond = n
    if f.loop_cond is None:
        raise TFImportError(
            f"v1 frame {f.name!r} has no LoopCond — not a while loop")
    f.merges.sort(key=lambda n: n.name)
    enters_by_name = {n.name: n for n in f.enters}
    for m in f.merges:
        srcs = [_ref(i)[0] for i in m.inputs]
        enter = next((s for s in srcs if s in enters_by_name), None)
        ni = next((producers[s] for s in srcs
                   if producers[s].op == "NextIteration"), None)
        if enter is None or ni is None:
            raise TFImportError(
                f"v1 Merge {m.name!r} is not an (Enter, NextIteration) "
                "merge — unsupported frame shape")
        m._enter = enters_by_name[enter]
        f.next_iters[m.name] = ni.inputs[0]
    # order enters to match merges
    f.enters = [m._enter for m in f.merges]
    for n in f.nodes.values():
        if n.op == "Switch":
            src, _ = _ref(n.inputs[0])
            if src in {m.name for m in f.merges}:
                f.switches[src] = n
    for m in f.merges:
        if m.name not in f.switches:
            raise TFImportError(
                f"v1 Merge {m.name!r} has no Switch — unsupported "
                "frame shape")


def _rewrite_frame_tensor_arrays(f, producers):
    """Lower interior TensorArrayV3 ops to synthetic _TA* nodes over the
    flow edge, reinterpreted as the [size, ...] buffer tensor (the
    dynamic_rnn idiom: per-step reads from an input array, per-step
    writes of cell outputs). The array handle (a TF resource) is only an
    identity token — every TA op also carries the flow — so handle
    Enters are dropped and each op keeps (flow, index[, value]) inputs.
    Reference: SURVEY.md §3.4 (v1 control flow interpreted in Java);
    §2.3 TF-import row."""
    from deeplearning4j_tpu.modelimport.protobuf import NodeDef

    ta_nodes = [n for n in f.nodes.values()
                if n.op.startswith("TensorArray")]
    if not ta_nodes:
        return
    # handle Enters: loop-invariant Enters fed from a TensorArrayV3:0
    for e in list(f.const_enters):
        src, idx = _ref(e.inputs[0])
        p = producers.get(src)
        if p is not None and p.op == "TensorArrayV3" and idx == 0:
            f.handle_enters[e.name] = src
            f.const_enters.remove(e)
    for n in ta_nodes:
        if n.op == "TensorArrayCloseV3":
            del f.nodes[n.name]
            continue
        spec = _TA_INTERIOR.get(n.op)
        if spec is None:
            raise TFImportError(
                f"v1 frame {f.name!r} uses {n.op}, which has no "
                "loop-carried-buffer lowering (supported inside a "
                "frame: TensorArray Read/Write/Scatter/Gather/Size) — "
                "re-export with TF2 functional control flow")
        new_op, keep = spec
        h_src, _h_idx = _ref(n.inputs[0])
        ta_name = f.handle_enters.get(h_src)
        if ta_name is None:
            raise TFImportError(
                f"v1 frame {f.name!r}: {n.op} node {n.name!r} handle "
                "does not come from a loop-invariant Enter of a "
                "TensorArrayV3 created outside the frame — TensorArrays "
                "created inside the loop are unsupported")
        f.nodes[n.name] = NodeDef(
            n.name, new_op, [n.inputs[p] for p in keep], dict(n.attrs))


def _rewrite_v1_loops(gd):
    """Replace each supported v1 while frame with one synthetic
    _V1While node (frame object stashed on the NodeDef); returns the
    rewritten GraphDef (or the original when no frames exist)."""
    from deeplearning4j_tpu.modelimport.protobuf import NodeDef

    frames = _find_v1_frames(gd)
    if not frames:
        return gd
    drop = set()
    synth = []
    exits_of = {}
    for f in frames.values():
        names = set(f.nodes)
        names.update(n.name for n in f.enters + f.const_enters)
        names.update(f.handle_enters)
        # exits: outer nodes consuming a Switch:0 of this frame
        f.exit_nodes = []
        sw_names = {sw.name: mn for mn, sw in f.switches.items()}
        for n in gd.nodes:
            if n.op == "Exit":
                src, _ = _ref(n.inputs[0])
                if src in sw_names:
                    n._merge = sw_names[src]
                    f.exit_nodes.append(n)
                    names.add(n.name)
        drop |= names
        init_refs = [e.inputs[0] for e in f.enters]
        # loop-invariant Enter refs ride as extra inputs so the importer
        # visits their producers first; at import time each is either
        # inlined as a constant (host-foldable) or promoted to a
        # pass-through loop variable (e.g. an input TensorArray buffer)
        inv_refs = [e.inputs[0] for e in f.const_enters]
        node = NodeDef(f"__v1while_{len(synth)}", "_V1While",
                       list(init_refs) + inv_refs, {})
        node._frame = f
        node._n_loop = len(init_refs)
        synth.append(node)
        exits_of[node.name] = f.exit_nodes
    # Exit nodes become Identity over the synthetic node's outputs:
    # their names stay addressable both for downstream refs and as
    # user-requested output tensors
    exit_identities = []
    for node in synth:
        f = node._frame
        merge_pos = {m.name: i for i, m in enumerate(f.merges)}
        for ex in f.exit_nodes:
            i = merge_pos[ex._merge]
            ref = f"{node.name}:{i}" if i else node.name
            exit_identities.append(
                type(ex)(ex.name, "Identity", [ref], dict(ex.attrs)))

    kept = [n for n in gd.nodes if n.name not in drop]
    gd2 = type(gd)(kept + synth + exit_identities, functions=list(
        getattr(gd, "functions", []) or []))
    return gd2


def _const_nodedef(name, arr):
    from deeplearning4j_tpu.modelimport.protobuf import (
        NodeDef, attr_tensor, attr_type)

    return NodeDef(name, "Const", [], {
        "dtype": attr_type(arr.dtype), "value": attr_tensor(arr)})


def _subgraph_from_nodes(im, frame, targets, placeholder_map, what):
    """Child SameDiff over the frame interior: `targets` are the refs to
    return; placeholder_map maps interior node names to (shape, dtype)
    formal args (Merge or Switch). Outer refs inline as constants when
    foldable."""
    from deeplearning4j_tpu.autodiff.samediff import SubGraph
    from deeplearning4j_tpu.modelimport.protobuf import (
        AttrValue, NodeDef, TensorShapeProto, numpy_to_dtype)

    ph_nodes, ph_shapes = [], {}
    for name, (shape, dt) in placeholder_map.items():
        ph_nodes.append(NodeDef(name, "Placeholder", [], {
            "dtype": AttrValue(type=numpy_to_dtype(dt)),
            "shape": AttrValue(shape=TensorShapeProto(list(shape))),
        }))
        ph_shapes[name] = shape

    # backward closure over interior nodes from the targets; a target
    # that is itself a Switch:1 ref (pass-through loop var: NextIteration
    # fed straight from the Switch) must seed the stack as its Merge
    # placeholder, or the Switch/LoopCond chain gets pulled into the
    # body subgraph (ADVICE r4)
    const_enter_names = {n.name: n for n in frame.const_enters}
    sw_to_merge = {sw.name: mn for mn, sw in frame.switches.items()}
    needed, stack = set(), [
        sw_to_merge.get(_ref(t)[0], _ref(t)[0]) for t in targets]
    interior = dict(frame.nodes)
    rewritten = {}
    while stack:
        nm = stack.pop()
        if nm in needed or nm in placeholder_map:
            continue
        needed.add(nm)
        n = interior.get(nm)
        if n is None:
            if nm in const_enter_names:
                outer_ref = const_enter_names[nm].inputs[0]
                val = im.const(outer_ref)
                if val is None:
                    raise TFImportError(
                        f"{what}: loop-invariant Enter {nm!r} is not "
                        "host-foldable — pass it through the loop state "
                        "or re-export with TF2 control flow")
                arr = np.asarray(val)
                rewritten[nm] = _const_nodedef(nm, arr)
                continue
            val = im.const(nm)
            if val is None:
                raise TFImportError(
                    f"{what}: body references outer tensor {nm!r} "
                    "which is not host-foldable — pass it through the "
                    "loop state or re-export with TF2 control flow")
            rewritten[nm] = _const_nodedef(nm, np.asarray(val))
            continue
        # strip Switch:1 refs down to the placeholder names
        new_inputs = []
        for inp in n.inputs:
            if inp.startswith("^"):
                continue
            src, idx = _ref(inp)
            if src in sw_to_merge:
                new_inputs.append(sw_to_merge[src])
                stack.append(sw_to_merge[src])
            else:
                new_inputs.append(inp)
                stack.append(src)
        rewritten[nm] = NodeDef(nm, n.op, new_inputs, dict(n.attrs))

    gd_nodes = ph_nodes + [rewritten[nm] for nm in rewritten]
    from deeplearning4j_tpu.modelimport.protobuf import GraphDef
    sub = _Importer(GraphDef(gd_nodes, functions=[]), ph_shapes,
                    strict=im.strict)
    child = sub.run()
    out_names, out_shapes, out_dtypes = [], [], []
    for t in targets:
        src, idx = _ref(t)
        if src in sw_to_merge:  # Switch:1 -> single-output placeholder
            src, idx = sw_to_merge[src], 0
        v = sub.var(f"{src}:{idx}" if idx else src)
        out_names.append(v.name())
        out_shapes.append(sub.shapes[f"{src}:{idx}"])
        out_dtypes.append(sub.dtypes[f"{src}:{idx}"])
    return (SubGraph(child, list(placeholder_map), out_names),
            out_shapes, out_dtypes)


def _resolve_ta_flow_init(im, f, merge, ref, ph_known, what):
    """An output-TensorArray flow loop var whose init is an unbound
    TensorArrayV3 flow (element_shape unknown at creation): infer the
    element shape by importing just the frame's write-value expression,
    then bind a zeros buffer at the TA's flow output."""
    src, idx = _ref(ref)
    if f"{src}:{idx}" in im.shapes:
        return
    nd = im.nodes.get(src)
    if nd is None or nd.op != "TensorArrayV3" or src not in \
            im.tensor_arrays:
        im.shape(ref)  # raises the standard "no static shape" error
        return
    info = im.tensor_arrays[src]
    if info["elem"] is not None:  # declared element_shape: no probe
        _bind_ta_zeros(im, src, info["elem"], None, out_idx=idx)
        return
    # find a _TAWrite into this loop var: its flow input chain ends at
    # this merge's Switch:1 (possibly through other writes/Identity)
    sw = f.switches[merge.name].name
    write = None
    for n in f.nodes.values():
        if n.op != "_TAWrite":
            continue
        chain, seen = _ref(n.inputs[0])[0], set()
        while chain not in seen:
            seen.add(chain)
            if chain == sw:
                write = n
                break
            p = f.nodes.get(chain)
            if p is None or p.op not in ("_TAWrite", "Identity"):
                break
            chain = _ref(p.inputs[0])[0]
        if write is not None:
            break
    if write is None:
        raise TFImportError(
            f"{what}: TensorArray {src!r} has no element_shape and no "
            "write inside the frame to infer it from")
    try:
        _, shapes, dtypes = _subgraph_from_nodes(
            im, f, [write.inputs[2]], ph_known,
            what + f" (element-shape probe for TensorArray {src!r})")
    except TFImportError as e:
        raise TFImportError(
            f"{what}: cannot infer the element shape of TensorArray "
            f"{src!r} — the written value depends on state that is not "
            f"resolvable before the loop: {e}") from e
    _bind_ta_zeros(im, src, tuple(shapes[0]), dtypes[0], out_idx=idx)


def _cond_cone_inits(im, f, init_refs):
    """The loop-cond cone's merge variables with their host-foldable
    integer/bool inits, or None when the cond depends on anything that
    cannot be tracked on the host (floats, TensorArrays, non-foldable
    outer tensors)."""
    sw_to_merge = {sw.name: mn for mn, sw in f.switches.items()}
    merge_idx = {m.name: i for i, m in enumerate(f.merges)}
    const_enter_names = {n.name for n in f.const_enters}

    needed, frontier, visited = set(), [f.loop_cond.inputs[0]], set()
    while frontier:
        ref = frontier.pop()
        nm = _ref(ref)[0]
        nm = sw_to_merge.get(nm, nm)
        if nm in visited:
            continue
        visited.add(nm)
        if nm in merge_idx:
            if nm not in needed:
                needed.add(nm)
                frontier.append(f.next_iters[nm])
            continue
        n = f.nodes.get(nm)
        if n is None:
            # outer tensor or loop-invariant Enter: must be foldable
            if nm in const_enter_names:
                e = next(e for e in f.const_enters if e.name == nm)
                if im.const(e.inputs[0]) is None:
                    return None
            elif im.const(nm) is None:
                return None
            continue
        if n.op.startswith("_TA"):
            return None  # depends on a buffer: not simulable
        frontier.extend(i for i in n.inputs if not i.startswith("^"))

    inits = []
    for mn in sorted(needed, key=lambda x: merge_idx[x]):
        val = im.const(init_refs[merge_idx[mn]])
        if val is None:
            return None
        val = np.asarray(val)
        if not (np.issubdtype(val.dtype, np.integer)
                or val.dtype == np.bool_):
            return None  # float counters: simulation could drift
        inits.append((mn, val))
    if not inits:
        return None  # cond is loop-invariant: either 0 or infinite
    return inits


def _resolve_scalar(im, f, ref, sw_to_merge, merge_names):
    """('var', merge_name) for a loop-variable ref, ('const', ndarray)
    for a host-foldable value, or None — following Identity chains and
    Switch:1 edges inside the frame."""
    src, idx = _ref(ref)
    seen = set()
    while True:
        if src in seen:
            return None
        seen.add(src)
        mn = sw_to_merge.get(src, src)
        if mn in merge_names:
            return ("var", mn)
        n = f.nodes.get(src)
        if n is None:
            for e in f.const_enters:
                if e.name == src:
                    v = im.const(e.inputs[0])
                    return None if v is None else ("const", np.asarray(v))
            v = im.const(f"{src}:{idx}" if idx else src)
            return None if v is None else ("const", np.asarray(v))
        if n.op in ("Identity", "StopGradient") and idx == 0:
            src, idx = _ref(n.inputs[0])
            continue
        if n.op == "Const":
            return ("const",
                    np.asarray(n.attrs["value"].tensor.to_numpy()))
        return None


_CMP_FLIP = {"Less": "Greater", "Greater": "Less",
             "LessEqual": "GreaterEqual", "GreaterEqual": "LessEqual"}


def _affine_trip_count(im, f, init_refs):
    """Closed-form trip count for the affine counter idiom TF1 emits
    for counted loops and dynamic_rnn (`i = i0; while cmp(i, n): i +=
    c`) — O(1) instead of simulating the loop at import time (ADVICE
    r5). None when the cond/update are not that shape."""
    merge_idx = {m.name: i for i, m in enumerate(f.merges)}
    sw_to_merge = {sw.name: mn for mn, sw in f.switches.items()}

    cond = f.nodes.get(_ref(f.loop_cond.inputs[0])[0])
    while cond is not None and cond.op == "Identity":
        cond = f.nodes.get(_ref(cond.inputs[0])[0])
    if cond is None or cond.op not in _CMP_FLIP:
        return None
    lhs = _resolve_scalar(im, f, cond.inputs[0], sw_to_merge, merge_idx)
    rhs = _resolve_scalar(im, f, cond.inputs[1], sw_to_merge, merge_idx)
    if lhs is None or rhs is None:
        return None
    op = cond.op
    if lhs[0] == "const" and rhs[0] == "var":
        lhs, rhs, op = rhs, lhs, _CMP_FLIP[op]
    if lhs[0] != "var" or rhs[0] != "const":
        return None
    mn, bound_arr = lhs[1], rhs[1]
    if bound_arr.size != 1 or \
            not np.issubdtype(bound_arr.dtype, np.integer):
        return None
    bound = int(bound_arr.reshape(()))

    init = im.const(init_refs[merge_idx[mn]])
    if init is None:
        return None
    init = np.asarray(init)
    if init.size != 1 or not np.issubdtype(init.dtype, np.integer):
        return None
    i0 = int(init.reshape(()))

    upd = f.nodes.get(_ref(f.next_iters[mn])[0])
    while upd is not None and upd.op == "Identity":
        upd = f.nodes.get(_ref(upd.inputs[0])[0])
    if upd is None or upd.op not in ("Add", "AddV2", "Sub"):
        return None
    a = _resolve_scalar(im, f, upd.inputs[0], sw_to_merge, merge_idx)
    b = _resolve_scalar(im, f, upd.inputs[1], sw_to_merge, merge_idx)
    step = None
    if a is not None and b is not None:
        if a[0] == "var" and a[1] == mn and b[0] == "const" \
                and b[1].size == 1:
            step = int(b[1].reshape(()))
            if upd.op == "Sub":
                step = -step
        elif upd.op != "Sub" and b[0] == "var" and b[1] == mn \
                and a[0] == "const" and a[1].size == 1:
            step = int(a[1].reshape(()))
    if step is None or step == 0:
        return None

    # trips = #{t >= 0 : cmp(i0 + t*step, bound)} with cmp checked
    # before each body run; None when the counter moves away from the
    # exit (non-terminating — leave it to whileLoop)
    if op == "Less":
        if i0 >= bound:
            return 0
        return (bound - i0 + step - 1) // step if step > 0 else None
    if op == "LessEqual":
        if i0 > bound:
            return 0
        return (bound - i0) // step + 1 if step > 0 else None
    if op == "Greater":
        if i0 <= bound:
            return 0
        return (i0 - bound - step - 1) // -step if step < 0 else None
    if i0 < bound:  # GreaterEqual
        return 0
    return (i0 - bound) // -step + 1 if step < 0 else None


_SIM_BINOPS = {
    "Add": np.add, "AddV2": np.add, "Sub": np.subtract,
    "Mul": np.multiply, "FloorDiv": np.floor_divide,
    "Maximum": np.maximum, "Minimum": np.minimum,
    "FloorMod": np.mod, "Mod": np.mod,
    "Less": np.less, "LessEqual": np.less_equal,
    "Greater": np.greater, "GreaterEqual": np.greater_equal,
    "Equal": np.equal, "NotEqual": np.not_equal,
    "LogicalAnd": np.logical_and, "LogicalOr": np.logical_or,
}
_SIM_UNOPS = {"Neg": np.negative, "LogicalNot": np.logical_not,
              "Abs": np.abs, "Square": np.square}


def _np_eval(im, f, ref, env, sw_to_merge, memo):
    """numpy value of a frame-interior ref given the loop-variable env;
    None when an op outside the host-simulable set is reached."""
    src, idx = _ref(ref)
    mn = sw_to_merge.get(src, src)
    if mn in env:
        return env[mn]
    if src in memo:
        return memo[src]
    n = f.nodes.get(src)
    if n is None:
        for e in f.const_enters:
            if e.name == src:
                v = im.const(e.inputs[0])
                memo[src] = None if v is None else np.asarray(v)
                return memo[src]
        v = im.const(f"{src}:{idx}" if idx else src)
        memo[src] = None if v is None else np.asarray(v)
        return memo[src]
    ins = [i for i in n.inputs if not i.startswith("^")]
    if n.op == "Const":
        v = np.asarray(n.attrs["value"].tensor.to_numpy())
    elif n.op in ("Identity", "StopGradient"):
        v = _np_eval(im, f, ins[0], env, sw_to_merge, memo)
    elif n.op == "Cast":
        x = _np_eval(im, f, ins[0], env, sw_to_merge, memo)
        v = None if x is None else np.asarray(
            x, dtype_to_numpy(n.attrs["DstT"].type))
    elif n.op in _SIM_UNOPS:
        x = _np_eval(im, f, ins[0], env, sw_to_merge, memo)
        v = None if x is None else _SIM_UNOPS[n.op](x)
    elif n.op in _SIM_BINOPS and len(ins) == 2:
        xs = [_np_eval(im, f, i, env, sw_to_merge, memo) for i in ins]
        v = None if any(x is None for x in xs) \
            else _SIM_BINOPS[n.op](xs[0], xs[1])
    else:
        v = None
    memo[src] = v
    return v


def _static_trip_count(im, f, init_refs, cap=100_000, jit_cap=10_000):
    """Exact trip count when the loop condition is confined to integer/
    bool loop variables with host-foldable inits whose updates are
    themselves so confined (the counter idiom TF1 emits for dynamic_rnn
    and counted loops); None otherwise. Enables lowering onto forLoop —
    a static-bound fori_loop lowers to scan, which is reverse-mode
    differentiable where XLA's while is not.

    Resolution order (ADVICE r5: the old 100k sequential jitted
    dispatches could add minutes of import latency): the affine `i += c;
    i < n` idiom closes analytically in O(1); irregular counters
    simulate in pure numpy on the host up to `cap`; only a cond cone
    with ops outside the numpy set falls back to the jitted subgraph,
    capped at `jit_cap` dispatches (10x below the numpy cap: bounded
    import latency for exotic counters, at the cost of lowering
    1e4..1e5-trip exotic loops onto whileLoop instead of scan)."""
    inits = _cond_cone_inits(im, f, init_refs)
    if inits is None:
        return None

    trip = _affine_trip_count(im, f, init_refs)
    if trip is not None:
        return trip

    sw_to_merge = {sw.name: mn for mn, sw in f.switches.items()}
    state = {mn: v for mn, v in inits}
    trips = 0
    while trips <= cap:
        memo = {}
        c = _np_eval(im, f, f.loop_cond.inputs[0], state, sw_to_merge,
                     memo)
        if c is None:
            break  # unsupported op: jitted fallback below
        if not bool(np.asarray(c).reshape(())):
            return trips
        new_state = {}
        for mn in state:
            v = _np_eval(im, f, f.next_iters[mn], state, sw_to_merge,
                         memo)
            if v is None:
                break
            new_state[mn] = np.asarray(v)
        if len(new_state) != len(state):
            break
        state = new_state
        trips += 1
    else:
        return None  # numpy sim ran out of cap: not statically counted

    ph = {mn: (tuple(v.shape), v.dtype) for mn, v in inits}
    try:
        sub, _, _ = _subgraph_from_nodes(
            im, f, [f.loop_cond.inputs[0]] +
            [f.next_iters[mn] for mn, _ in inits], ph,
            f"v1 frame {f.name!r} trip-count simulation")
    except TFImportError:
        return None
    import contextlib

    import jax

    fn = jax.jit(sub.callable())  # one tiny compile beats 10^3 dispatches
    state = [v for _, v in inits]
    trips = 0
    try:  # keep the per-iteration dispatch off any remote device
        ctx = jax.default_device(jax.devices("cpu")[0])
    except Exception:
        ctx = contextlib.nullcontext()
    with ctx:
        while trips <= jit_cap:
            outs = fn(*state)
            if not isinstance(outs, (tuple, list)):
                outs = (outs,)
            if not bool(np.asarray(outs[0]).reshape(())):
                return trips
            state = [np.asarray(o) for o in outs[1:]]
            trips += 1
    return None


@handler("_V1While")
def _h_v1_while(im, node):
    f = node._frame
    n_loop = node._n_loop
    init_refs = list(node.inputs[:n_loop])
    inv_refs = list(node.inputs[n_loop:])
    what = f"v1 while frame {f.name!r}"

    # ALL loop-invariant Enters become pass-through loop variables wired
    # to the parent-graph tensor (weights stay parent vars, so
    # makeTrainable + autodiff reach them through the loop; also the
    # only sound choice for non-foldable invariants such as an input
    # TensorArray buffer scattered from a placeholder)
    promoted = list(zip(f.const_enters, inv_refs))
    ph_partial = {e.name: (im.shape(r), im.dtype(r)) for e, r in promoted}
    for m, ref in zip(f.merges, init_refs):
        src, idx = _ref(ref)
        if f"{src}:{idx}" in im.shapes:
            ph_partial[m.name] = (im.shape(ref), im.dtype(ref))
    for m, ref in zip(f.merges, init_refs):
        _resolve_ta_flow_init(im, f, m, ref, ph_partial, what)

    ph_map = {}
    for m, ref in zip(f.merges, init_refs):
        ph_map[m.name] = (im.shape(ref), im.dtype(ref))
    for e, r in promoted:
        ph_map[e.name] = (im.shape(r), im.dtype(r))

    body_targets = [f.next_iters[m.name] for m in f.merges] + \
        [e.name for e, _ in promoted]
    in_refs = init_refs + [r for _, r in promoted]
    in_vars = [im.var(r) for r in in_refs]
    n = len(in_vars)

    trip = _static_trip_count(im, f, init_refs)
    if trip is not None:
        # exact trip count: run the body trip times under forLoop (the
        # body subgraph gets a leading, unused iteration placeholder to
        # match the forLoop body signature body(i, *vars))
        iter_ph = f"{node.name}__iter"
        ph_body = {iter_ph: ((), np.dtype(np.int32))}
        ph_body.update(ph_map)
        body, body_shapes, body_dtypes = _subgraph_from_nodes(
            im, f, body_targets, ph_body, what + " body")
        attrs = {"n": trip, "body_graph": body,
                 "body_fn": body.callable()}
        res = im.sd._op("forLoop", in_vars, attrs, node.name,
                        n_out=n if n > 1 else 1)
    else:
        cond, _, _ = _subgraph_from_nodes(
            im, f, [f.loop_cond.inputs[0]], ph_map, what + " cond")
        body, body_shapes, body_dtypes = _subgraph_from_nodes(
            im, f, body_targets, ph_map, what + " body")
        attrs = {"cond_graph": cond,
                 "cond_fn": cond.callable(squeeze=True),
                 "body_graph": body, "body_fn": body.callable()}
        res = im.sd._op("whileLoop", in_vars, attrs, node.name,
                        n_out=n if n > 1 else 1)
    outs = res if isinstance(res, tuple) else (res,)
    for i, v in enumerate(outs):
        im.bind(node.name, v, body_shapes[i], body_dtypes[i], out_idx=i)


# ---------------------------------------------------------------------------
# TensorArrayV3 family: a TF1 TensorArray lowers to a plain [size, ...]
# buffer tensor carried on the array's "flow" edge (reads are gathers,
# writes are row scatter-updates). The resource handle output (:0) is
# never materialized — every consumer also receives the flow, which
# identifies the buffer. Reference: SURVEY.md §2.3 TF-import row.
# ---------------------------------------------------------------------------

def _ta_resolve(im, handle_ref, what):
    src, idx = _ref(handle_ref)
    while True:
        nd = im.nodes.get(src)
        if nd is not None and nd.op == "Identity" and idx == 0:
            src, idx = _ref(nd.inputs[0])
            continue
        break
    if nd is None or nd.op != "TensorArrayV3" or idx != 0 or \
            src not in im.tensor_arrays:
        raise TFImportError(
            f"{what}: handle input does not trace to a TensorArrayV3 "
            "node in the outer graph")
    return src


def _bind_ta_zeros(im, ta, elem, dtype_hint, out_idx=1):
    """The single place the lazy zeros buffer for an unwritten
    TensorArray gets created and bound at the TA's flow output."""
    info = im.tensor_arrays[ta]
    dt = info["dtype"] or dtype_hint or np.dtype(np.float32)
    size = info["size"]
    if info["elem"] is None and elem is None:
        raise TFImportError(
            f"TensorArray {ta!r} is read before any write and has no "
            "element_shape — declare element_shape on the TensorArrayV3")
    elem = tuple(info["elem"] if info["elem"] is not None else elem)
    v = im.sd.constant(f"{ta}__ta_zeros",
                       np.zeros((size,) + elem, dt))
    im.bind(ta, v, (size,) + elem, dt, out_idx=out_idx)


def _ta_buffer_ref(im, flow_ref, ta, elem, dtype, what):
    """Resolve the TA's current buffer; on first use of an unbound flow
    bind a zeros buffer there (lazily — an eagerly bound zeros constant
    would serialize buffer-size dead weight whenever the first op
    overwrites the whole array)."""
    try:
        im.shape(flow_ref)
        return flow_ref
    except TFImportError:
        pass
    src, idx = _ref(flow_ref)
    info = im.tensor_arrays.get(ta)
    if info is None or src != ta:
        raise TFImportError(
            f"{what}: flow input {flow_ref!r} has no producer and does "
            "not trace to a TensorArrayV3 flow output")
    _bind_ta_zeros(im, ta, elem, dtype, out_idx=idx)
    return flow_ref


@handler("TensorArrayV3")
def _h_tensor_array_v3(im, node):
    dyn = node.attrs.get("dynamic_size")
    if dyn is not None and dyn.b:
        raise TFImportError(
            f"node {node.name!r}: TensorArrayV3 with dynamic_size=True "
            "has no static-shape lowering (XLA buffers are fixed-size) "
            "— re-export with a fixed-size TensorArray")
    ins = im.data_inputs(node)
    size = int(im.need_const(ins[0], "TensorArray size"))
    dt = dtype_to_numpy(node.attrs["dtype"].type) \
        if "dtype" in node.attrs else None
    elem = None
    es = node.attrs.get("element_shape")
    if es is not None and es.shape is not None and \
            not es.shape.unknown_rank:
        dims = [int(d) for d in es.shape.dims]
        if dims and all(d >= 0 for d in dims):
            elem = tuple(dims)
    im.tensor_arrays[node.name] = {"size": size, "dtype": dt,
                                   "elem": elem}
    # the flow output (:1) binds LAZILY on first read/loop use — an
    # eager zeros constant would serialize buffer-size dead weight for
    # the common scatter-everything idiom, which never reads it. The
    # :0 resource handle is deliberately left unbound.


@handler("TensorArrayScatterV3")
def _h_ta_scatter_outer(im, node):
    what = f"node {node.name!r} ({node.op})"
    ins = im.data_inputs(node)  # handle, indices, value, flow
    ta = _ta_resolve(im, ins[0], what)
    info = im.tensor_arrays[ta]
    idxs = im.const(ins[1])  # None: computed indices, general lowering
    vshape, vd = im.shape(ins[2]), im.dtype(ins[2])
    size = info["size"]
    if idxs is not None and vshape and vshape[0] == size and \
            np.array_equal(np.asarray(idxs).ravel(), np.arange(size)):
        im.emit(node, "identity", [ins[2]])  # buffer = value
        return
    flow = _ta_buffer_ref(im, ins[3], ta, tuple(vshape[1:]), vd, what)
    im.emit(node, "scatterUpdate", [flow, ins[1], ins[2]], {})


@handler("TensorArrayWriteV3")
def _h_ta_write_outer(im, node):
    what = f"node {node.name!r} ({node.op})"
    ins = im.data_inputs(node)  # handle, index, value, flow
    ta = _ta_resolve(im, ins[0], what)
    vshape, vd = im.shape(ins[2]), im.dtype(ins[2])
    flow = _ta_buffer_ref(im, ins[3], ta, vshape, vd, what)
    im.emit(node, "scatterUpdate", [flow, ins[1], ins[2]], {})


@handler("TensorArrayGatherV3")
def _h_ta_gather_outer(im, node):
    what = f"node {node.name!r} ({node.op})"
    ins = im.data_inputs(node)  # handle, indices, flow
    ta = _ta_resolve(im, ins[0], what)
    info = im.tensor_arrays[ta]
    idxs = im.const(ins[1])  # None: computed indices, general lowering
    flow = _ta_buffer_ref(im, ins[2], ta, None, None, what)
    fshape = im.shape(flow)
    if idxs is not None and fshape and fshape[0] == info["size"] and \
            np.array_equal(np.asarray(idxs).ravel(),
                           np.arange(info["size"])):
        im.emit(node, "identity", [flow])
        return
    im.emit(node, "gather", [flow, ins[1]], {"axis": 0})


@handler("TensorArrayReadV3")
def _h_ta_read_outer(im, node):
    what = f"node {node.name!r} ({node.op})"
    ins = im.data_inputs(node)  # handle, index, flow
    ta = _ta_resolve(im, ins[0], what)
    flow = _ta_buffer_ref(im, ins[2], ta, None, None, what)
    im.emit(node, "gather", [flow, ins[1]], {"axis": 0})


@handler("TensorArraySizeV3")
def _h_ta_size_outer(im, node):
    ins = im.data_inputs(node)
    ta = _ta_resolve(im, ins[0], f"node {node.name!r} ({node.op})")
    v = im.sd.constant(node.name,
                       np.asarray(im.tensor_arrays[ta]["size"], np.int32))
    im.bind(node.name, v, (), np.int32)


@handler("TensorArrayCloseV3")
def _h_ta_close(im, node):
    pass  # resource cleanup: nothing to materialize


@handler("_TARead", "_TAGather")
def _h_ta_read_interior(im, node):
    im.emit(node, "gather", node.inputs, {"axis": 0})


@handler("_TAWrite")
def _h_ta_write_interior(im, node):
    im.emit(node, "scatterUpdate", node.inputs, {})


@handler("_TASize")
def _h_ta_size_interior(im, node):
    t = im.shape(node.inputs[0])[0]
    v = im.sd.constant(node.name, np.asarray(t, np.int32))
    im.bind(node.name, v, (), np.int32)


@handler("ResizeBilinear", "ResizeNearestNeighbor", "ResizeBicubic",
         "ResizeArea")
def _h_resize(im, node):
    """TF resize ops are NHWC; route through the NCHW imageResize op via
    permutes (same pattern as Conv2D).

    Sampling semantics: jax.image.resize implements half-pixel-center
    sampling (TF2, half_pixel_centers=True). align_corners=True is
    rejected; graphs with the TF1-legacy default (half_pixel_centers
    absent/False) import with a warning — interior samples can shift by
    up to half a source pixel vs TF1. jax 'cubic' is Keys a=-0.5 where
    TF1 ResizeBicubic uses a=-0.75 (documented divergence)."""
    ac = node.attrs.get("align_corners")
    if ac is not None and ac.b:
        raise TFImportError(
            f"node {node.name!r} ({node.op}): align_corners=True has no "
            f"jax.image.resize equivalent — re-export with "
            f"half_pixel_centers=True")
    hpc = node.attrs.get("half_pixel_centers")
    if node.op != "ResizeArea" and (hpc is None or not hpc.b):
        if im.strict:
            raise TFImportError(
                f"node {node.name!r} ({node.op}): TF1-legacy sampling "
                f"(half_pixel_centers=False) rejected under "
                f"strict=True — interior samples would shift by up to "
                f"half a source pixel; re-export with "
                f"half_pixel_centers=True or import with strict=False")
        import warnings

        warnings.warn(
            f"TF import: {node.op} node {node.name!r} uses TF1-legacy "
            f"sampling (half_pixel_centers=False); imported with "
            f"half-pixel-center semantics — interior samples may shift "
            f"by up to half a source pixel", stacklevel=2)
    ins = im.data_inputs(node)
    size = im.need_const(ins[1], "resize size")
    oh, ow = int(size[0]), int(size[1])
    method = {"ResizeBilinear": "bilinear",
              "ResizeNearestNeighbor": "nearest",
              "ResizeBicubic": "cubic",
              "ResizeArea": "area"}[node.op]
    x = _permute(im, node, ins[0], (0, 3, 1, 2), "__nchw")
    im.emit(node, "imageResize", [x],
            {"height": oh, "width": ow, "method": method},
            out_name=f"{node.name}__resize")
    _permute(im, node, f"{node.name}__resize:0", (0, 2, 3, 1), "",
             out_name=node.name)


@handler("NonMaxSuppressionV3", "NonMaxSuppressionV4")
def _h_nms(im, node):
    """STATIC-SHAPE deviation from TF (documented): TF returns a
    dynamic-length [num_selected] index tensor; XLA needs static shapes,
    so the imported op returns [maxOutputSize] padded with -1. V4
    consumers get the real `valid_outputs` count as output :1 and must
    mask before gathering (a -1 fed to gather wraps to the last row);
    V3 consumers should count idx >= 0 themselves."""
    ins = im.data_inputs(node)
    max_out = int(im.need_const(ins[2], "NMS max_output_size"))
    iou = float(im.need_const(ins[3], "NMS iou_threshold"))
    attrs = {"maxOutputSize": max_out, "iouThreshold": iou}
    if len(ins) > 4:
        attrs["scoreThreshold"] = float(
            im.need_const(ins[4], "NMS score_threshold"))
    idx = im.emit(node, "nonMaxSuppression", ins[:2], attrs)
    if node.op == "NonMaxSuppressionV4":
        # second output: valid_outputs = count of non-padding indices
        zero = im.sd.constant(f"{node.name}__zero", np.int32(0))
        ge = im.sd._op("gte", [idx, zero], {}, f"{node.name}__ge")
        cnt = im.sd._op("sum", [ge], {}, f"{node.name}__validsum")
        valid = im.sd._op("cast", [cnt], {"dtype": "int32"},
                          f"{node.name}__valid")
        im.bind(node.name, valid, (), np.int32, out_idx=1)


def _check_padding(node, pad):
    """SAME/VALID only: TF's EXPLICIT padding would otherwise silently
    compute a VALID conv."""
    if pad not in ("SAME", "VALID"):
        raise TFImportError(
            f"node {node.name!r} ({node.op}): padding={pad!r} is not "
            f"supported (SAME/VALID only)")


@handler("DepthwiseConv2dNative")
def _h_depthwise_conv2d(im, node):
    """TF depthwise kernel [kH, kW, inC, mult] -> our
    [mult, inC, kH, kW] (MobileNet-class graphs)."""
    ins = im.data_inputs(node)
    fmt = node.attrs.get("data_format")
    nhwc = fmt is None or fmt.s in (b"NHWC", None)
    strides = [int(s) for s in node.attrs["strides"].list["i"]]
    pad = node.attrs["padding"].s.decode()
    _check_padding(node, pad)
    dil = [int(d) for d in node.attrs["dilations"].list["i"]] \
        if "dilations" in node.attrs else [1, 1, 1, 1]
    x_ref = ins[0]
    if nhwc:
        x_ref = _permute(im, node, ins[0], (0, 3, 1, 2), "__nchw")
        s_hw, d_hw = (strides[1], strides[2]), (dil[1], dil[2])
    else:
        s_hw, d_hw = (strides[2], strides[3]), (dil[2], dil[3])
    w_ref = _permute(im, node, ins[1], (3, 2, 0, 1), "__mihw")
    attrs = {"strides": s_hw, "dilation": d_hw,
             "sameMode": pad == "SAME", "padding": (0, 0)}
    out_name = node.name if not nhwc else f"{node.name}__conv"
    im.emit(node, "depthwiseConv2d", [x_ref, w_ref], attrs,
            out_name=out_name)
    if nhwc:
        _permute(im, node, f"{out_name}:0", (0, 2, 3, 1), "", node.name)


@handler("Conv3D")
def _h_conv3d(im, node):
    """TF NDHWC conv3d -> our NCDHW op via permutes; kernel DHWIO ->
    OIDHW."""
    ins = im.data_inputs(node)
    fmt = node.attrs.get("data_format")
    if fmt is not None and fmt.s not in (b"NDHWC", None):
        raise TFImportError(
            f"Conv3D node {node.name!r}: only NDHWC data_format is "
            f"supported, got {fmt.s!r}")
    strides = [int(s) for s in node.attrs["strides"].list["i"]]
    pad = node.attrs["padding"].s.decode()
    _check_padding(node, pad)
    dil = [int(d) for d in node.attrs["dilations"].list["i"]] \
        if "dilations" in node.attrs else [1, 1, 1, 1, 1]
    x_ref = _permute(im, node, ins[0], (0, 4, 1, 2, 3), "__ncdhw")
    w_ref = _permute(im, node, ins[1], (4, 3, 0, 1, 2), "__oidhw")
    attrs = {"strides": tuple(strides[1:4]),
             "dilation": tuple(dil[1:4]), "sameMode": pad == "SAME",
             "padding": (0, 0, 0)}
    im.emit(node, "conv3d", [x_ref, w_ref], attrs,
            out_name=f"{node.name}__conv")
    _permute(im, node, f"{node.name}__conv:0", (0, 2, 3, 4, 1), "",
             node.name)


@handler("MaxPool3D", "AvgPool3D")
def _h_pool3d(im, node):
    ins = im.data_inputs(node)
    fmt = node.attrs.get("data_format")
    if fmt is not None and fmt.s not in (b"NDHWC", None):
        raise TFImportError(
            f"{node.op} node {node.name!r}: only NDHWC data_format is "
            f"supported, got {fmt.s!r}")
    ksize = [int(k) for k in node.attrs["ksize"].list["i"]]
    strides = [int(s) for s in node.attrs["strides"].list["i"]]
    pad = node.attrs["padding"].s.decode()
    _check_padding(node, pad)
    x_ref = _permute(im, node, ins[0], (0, 4, 1, 2, 3), "__ncdhw")
    fn = "maxPooling3d" if node.op == "MaxPool3D" else "avgPooling3d"
    attrs = {"kernel": tuple(ksize[1:4]), "strides": tuple(strides[1:4]),
             "sameMode": pad == "SAME", "padding": (0, 0, 0)}
    im.emit(node, fn, [x_ref], attrs, out_name=f"{node.name}__pool")
    _permute(im, node, f"{node.name}__pool:0", (0, 2, 3, 4, 1), "",
             node.name)


# ---------------------------------------------------------------------------
# r4 handler widening (VERDICT r3 item 8)
# ---------------------------------------------------------------------------

@handler("SparseSoftmaxCrossEntropyWithLogits")
def _h_sparse_softmax_ce(im, node):
    """TF op with TWO outputs: per-example loss [B] and backprop
    [B, C] (softmax(logits) - onehot(labels))."""
    im.emit(node, "sparseSoftmaxCrossEntropyGrad", im.data_inputs(node))


@handler("MirrorPad")
def _h_mirror_pad(im, node):
    ins = im.data_inputs(node)
    pads = im.need_const(ins[1], "MirrorPad paddings")
    mode = node.attrs.get("mode")
    mode = (mode.s.decode() if mode is not None and
            isinstance(mode.s, bytes) else "REFLECT")
    im.emit(node, "mirrorPad", [ins[0]],
            {"paddings": tuple(map(tuple, np.asarray(pads).tolist())),
             "mode": mode})


@handler("ReverseSequence")
def _h_reverse_sequence(im, node):
    ins = im.data_inputs(node)
    im.emit(node, "reverseSequence", [ins[0], ins[1]],
            {"seqAxis": int(node.attrs["seq_dim"].i),
             "batchAxis": int(node.attrs.get("batch_dim").i
                              if "batch_dim" in node.attrs else 0)})


@handler("LRN")
def _h_lrn(im, node):
    # TF LRN is NHWC with depth_radius; our op is NCHW with full depth
    ins = im.data_inputs(node)
    r = int(node.attrs["depth_radius"].i) \
        if "depth_radius" in node.attrs else 5
    getf = lambda k, d: (node.attrs[k].f  # noqa: E731
                         if k in node.attrs else d)
    x = _permute(im, node, ins[0], (0, 3, 1, 2), "__nchw")
    im.emit(node, "localResponseNormalization", [x],
            {"depth": r, "bias": getf("bias", 1.0),
             "alpha": getf("alpha", 1.0), "beta": getf("beta", 0.5)},
            out_name=f"{node.name}__lrn")
    _permute(im, node, f"{node.name}__lrn:0", (0, 2, 3, 1), "",
             out_name=node.name)


@handler("RGBToHSV")
def _h_rgb_to_hsv(im, node):
    im.emit(node, "rgbToHsv", im.data_inputs(node))


@handler("HSVToRGB")
def _h_hsv_to_rgb(im, node):
    im.emit(node, "hsvToRgb", im.data_inputs(node))


@handler("AdjustContrastv2")
def _h_adjust_contrast(im, node):
    ins = im.data_inputs(node)
    f = float(im.need_const(ins[1], "AdjustContrastv2 factor"))
    im.emit(node, "adjustContrastV2", [ins[0]], {"factor": f})


@handler("AdjustHue")
def _h_adjust_hue(im, node):
    ins = im.data_inputs(node)
    d = float(im.need_const(ins[1], "AdjustHue delta"))
    im.emit(node, "adjustHue", [ins[0]], {"delta": d})


@handler("AdjustSaturation")
def _h_adjust_saturation(im, node):
    ins = im.data_inputs(node)
    f = float(im.need_const(ins[1], "AdjustSaturation scale"))
    im.emit(node, "adjustSaturation", [ins[0]], {"factor": f})


@handler("Cross")
def _h_cross(im, node):
    im.emit(node, "cross", im.data_inputs(node))


@handler("Rint")
def _h_rint(im, node):
    im.emit(node, "rint", im.data_inputs(node))


@handler("Erfinv")
def _h_erfinv(im, node):
    im.emit(node, "erfinv", im.data_inputs(node))


@handler("HistogramFixedWidth")
def _h_histogram(im, node):
    ins = im.data_inputs(node)
    vr = im.need_const(ins[1], "HistogramFixedWidth value_range")
    nbins = int(im.need_const(ins[2], "HistogramFixedWidth nbins")) \
        if len(ins) > 2 else 100
    im.emit(node, "histogramFixedWidth", [ins[0]],
            {"range_lo": float(vr[0]), "range_hi": float(vr[1]),
             "nbins": nbins})


@handler("ScatterNd")
def _h_scatter_nd(im, node):
    ins = im.data_inputs(node)
    shape = im.need_const(ins[2], "ScatterNd shape")
    im.emit(node, "scatterNd", [ins[0], ins[1]],
            {"shape": tuple(int(s) for s in np.asarray(shape))})


@handler("Dilation2D")
def _h_dilation2d(im, node):
    ins = im.data_inputs(node)
    strides = [int(v) for v in node.attrs["strides"].ints] \
        if "strides" in node.attrs else [1, 1, 1, 1]
    rates = [int(v) for v in node.attrs["rates"].ints] \
        if "rates" in node.attrs else [1, 1, 1, 1]
    if any(r != 1 for r in rates):
        raise TFImportError(
            f"node {node.name!r} (Dilation2D): atrous rates {rates} "
            "are unsupported (only [1,1,1,1]) — importing would "
            "silently compute a dense dilation")
    pad = node.attrs.get("padding")
    same = pad is None or pad.s == b"SAME"
    # TF NHWC x [N,H,W,C], filter [kH,kW,C] -> our NCHW op
    x = _permute(im, node, ins[0], (0, 3, 1, 2), "__nchw")
    w = _permute(im, node, ins[1], (2, 0, 1), "__chw")
    im.emit(node, "dilation2d", [x, w],
            {"sH": strides[1], "sW": strides[2], "sameMode": same},
            out_name=f"{node.name}__dil")
    _permute(im, node, f"{node.name}__dil:0", (0, 2, 3, 1), "",
             out_name=node.name)
