"""Model import (reference L6: deeplearning4j-modelimport + nd4j-api
org.nd4j.imports — SURVEY.md §2.7 Keras/TF import rows)."""

from deeplearning4j_tpu.modelimport.keras import (  # noqa: F401
    KerasModelImport)
from deeplearning4j_tpu.modelimport.tensorflow import (  # noqa: F401
    TFGraphMapper, TFImportError)
