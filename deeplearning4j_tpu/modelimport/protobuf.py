"""Minimal protobuf wire-format codec + the TensorFlow framework messages
needed to read/write a frozen GraphDef.

Reference capability: `nd4j-api` `org.nd4j.imports` parses GraphDef
protos via generated bindings (SURVEY.md §2.7 TF-import row). TensorFlow
is not installed here and generated bindings would drag in the whole
proto toolchain, so this module implements the protobuf wire format
directly (varint / 64-bit / length-delimited / 32-bit) plus a tiny
declarative schema layer covering GraphDef, NodeDef, AttrValue,
TensorProto and TensorShapeProto — both decode (import) and encode
(fixture generation for the conformance tests, mirroring the golden-file
strategy in SURVEY.md §4).

The field numbers/types below are the public protobuf schema of
tensorflow/core/framework/{graph,node_def,attr_value,tensor,
tensor_shape,types}.proto.
"""

from __future__ import annotations

import struct

import numpy as np

# wire types
_VARINT, _I64, _LEN, _I32 = 0, 1, 2, 5


# ---------------------------------------------------------------------------
# low-level wire format
# ---------------------------------------------------------------------------

def _read_varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


def _write_varint(out, value):
    if value < 0:
        value &= (1 << 64) - 1  # two's-complement int64
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _zigzag(v):
    return (v << 1) ^ (v >> 63)


def _unzigzag(v):
    return (v >> 1) ^ -(v & 1)


def _signed(v):
    """varint -> signed int64 (two's complement)."""
    return v - (1 << 64) if v >= (1 << 63) else v


def iter_fields(buf):
    """Yield (field_number, wire_type, value) over one serialized message.
    LEN fields yield memoryview payloads; varints yield raw unsigned ints."""
    buf = memoryview(buf)
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt == _VARINT:
            v, pos = _read_varint(buf, pos)
        elif wt == _I64:
            v = bytes(buf[pos:pos + 8])
            pos += 8
        elif wt == _LEN:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wt == _I32:
            v = bytes(buf[pos:pos + 4])
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, v


def _emit_tag(out, field, wt):
    _write_varint(out, (field << 3) | wt)


def emit_varint(out, field, value):
    _emit_tag(out, field, _VARINT)
    _write_varint(out, int(value))


def emit_bytes(out, field, payload):
    _emit_tag(out, field, _LEN)
    _write_varint(out, len(payload))
    out.extend(payload)


def emit_float(out, field, value):
    _emit_tag(out, field, _I32)
    out.extend(struct.pack("<f", value))


def _unpack_packed(payload, fmt_char, itemsize):
    return list(np.frombuffer(bytes(payload), dtype=np.dtype(fmt_char)))


def _decode_packed_varints(payload):
    vals = []
    pos = 0
    buf = memoryview(payload)
    while pos < len(buf):
        v, pos = _read_varint(buf, pos)
        vals.append(_signed(v))
    return vals


# ---------------------------------------------------------------------------
# tensorflow DataType enum (types.proto) <-> numpy
# ---------------------------------------------------------------------------

DT_FLOAT, DT_DOUBLE, DT_INT32, DT_UINT8 = 1, 2, 3, 4
DT_INT16, DT_INT8, DT_STRING = 5, 6, 7
DT_INT64, DT_BOOL = 9, 10
DT_BFLOAT16, DT_HALF = 14, 19

_DT_TO_NP = {
    DT_FLOAT: np.float32, DT_DOUBLE: np.float64, DT_INT32: np.int32,
    DT_UINT8: np.uint8, DT_INT16: np.int16, DT_INT8: np.int8,
    DT_INT64: np.int64, DT_BOOL: np.bool_, DT_HALF: np.float16,
}
_NP_TO_DT = {np.dtype(v): k for k, v in _DT_TO_NP.items()}


def dtype_to_numpy(dt):
    if dt == DT_BFLOAT16:
        import jax.numpy as jnp

        return np.dtype(jnp.bfloat16)
    if dt not in _DT_TO_NP:
        raise ValueError(f"unsupported tf DataType enum {dt}")
    return np.dtype(_DT_TO_NP[dt])


def numpy_to_dtype(np_dtype):
    d = np.dtype(np_dtype)
    if d.name == "bfloat16":
        return DT_BFLOAT16
    if d not in _NP_TO_DT:
        raise ValueError(f"unsupported numpy dtype {d}")
    return _NP_TO_DT[d]


# ---------------------------------------------------------------------------
# message classes (decode + encode)
# ---------------------------------------------------------------------------

class TensorShapeProto:
    """tensor_shape.proto: dim=2 repeated {size=1}, unknown_rank=3."""

    def __init__(self, dims=None, unknown_rank=False):
        self.dims = list(dims) if dims is not None else []
        self.unknown_rank = unknown_rank

    @classmethod
    def decode(cls, buf):
        self = cls()
        for field, wt, v in iter_fields(buf):
            if field == 2 and wt == _LEN:
                size = None
                for f2, _w2, v2 in iter_fields(v):
                    if f2 == 1:
                        size = _signed(v2)
                self.dims.append(size if size is not None else -1)
            elif field == 3:
                self.unknown_rank = bool(v)
        return self

    def encode(self):
        out = bytearray()
        for d in self.dims:
            dim = bytearray()
            emit_varint(dim, 1, d)
            emit_bytes(out, 2, dim)
        if self.unknown_rank:
            emit_varint(out, 3, 1)
        return bytes(out)


class TensorProto:
    """tensor.proto: dtype=1, tensor_shape=2, tensor_content=4,
    float_val=5, double_val=6, int_val=7, string_val=8, int64_val=10,
    bool_val=11, half_val=13."""

    def __init__(self, dtype=DT_FLOAT, shape=None, array=None):
        self.dtype = dtype
        self.shape = shape or TensorShapeProto()
        self._array = array

    @classmethod
    def from_numpy(cls, arr):
        arr = np.asarray(arr)
        return cls(numpy_to_dtype(arr.dtype),
                   TensorShapeProto(list(arr.shape)), arr)

    def to_numpy(self):
        return self._array

    @classmethod
    def decode(cls, buf):
        dtype = DT_FLOAT
        shape = TensorShapeProto()
        content = None
        scalars = []
        strings = []
        for field, wt, v in iter_fields(buf):
            if field == 1:
                dtype = v
            elif field == 2:
                shape = TensorShapeProto.decode(v)
            elif field == 4:
                content = bytes(v)
            elif field == 5:  # float_val
                scalars += (_unpack_packed(v, "<f4", 4) if wt == _LEN
                            else [struct.unpack("<f", v)[0]])
            elif field == 6:  # double_val
                scalars += (_unpack_packed(v, "<f8", 8) if wt == _LEN
                            else [struct.unpack("<d", v)[0]])
            elif field in (7, 10, 11, 13):  # int/int64/bool/half vals
                scalars += (_decode_packed_varints(v) if wt == _LEN
                            else [_signed(v)])
            elif field == 8:  # string_val
                strings.append(bytes(v))
        np_dtype = dtype_to_numpy(dtype)
        dims = shape.dims
        n_elem = int(np.prod(dims)) if dims else 1
        if dtype == DT_STRING:
            arr = np.array(strings, dtype=object).reshape(dims)
        elif content is not None:
            arr = np.frombuffer(content, dtype=np_dtype).reshape(dims)
        elif scalars:
            if dtype in (DT_HALF, DT_BFLOAT16):
                # half_val holds raw uint16 bit patterns for both
                vals = np.array(scalars, np.uint16).view(np_dtype)
            else:
                vals = np.array(scalars, dtype=np_dtype)
            if len(vals) < n_elem:  # proto allows trailing-value elision
                vals = np.concatenate(
                    [vals, np.full(n_elem - len(vals), vals[-1], np_dtype)])
            arr = vals.reshape(dims)
        else:
            arr = np.zeros(dims, dtype=np_dtype)
        return cls(dtype, shape, arr)

    def encode(self):
        out = bytearray()
        emit_varint(out, 1, self.dtype)
        emit_bytes(out, 2, self.shape.encode())
        arr = np.ascontiguousarray(self._array)
        emit_bytes(out, 4, arr.tobytes())
        return bytes(out)


class AttrValue:
    """attr_value.proto: list=1, s=2, i=3, f=4, b=5, type=6, shape=7,
    tensor=8. The `list` payload reuses the same field numbers."""

    def __init__(self, **kw):
        self.s = kw.get("s")
        self.i = kw.get("i")
        self.f = kw.get("f")
        self.b = kw.get("b")
        self.type = kw.get("type")
        self.shape = kw.get("shape")
        self.tensor = kw.get("tensor")
        self.list = kw.get("list")  # dict of name -> list
        self.func = kw.get("func")  # function name (NameAttrList.name)

    @classmethod
    def decode(cls, buf):
        self = cls()
        for field, wt, v in iter_fields(buf):
            if field == 1:
                self.list = cls._decode_list(v)
            elif field == 2:
                self.s = bytes(v)
            elif field == 3:
                self.i = _signed(v)
            elif field == 4:
                self.f = struct.unpack("<f", v)[0]
            elif field == 5:
                self.b = bool(v)
            elif field == 6:
                self.type = v
            elif field == 7:
                self.shape = TensorShapeProto.decode(v)
            elif field == 8:
                self.tensor = TensorProto.decode(v)
            elif field == 10:
                # NameAttrList {name=1, attr=2}: functional control flow
                # (While/If) references its cond/body functions this way
                for f2, _w2, v2 in iter_fields(v):
                    if f2 == 1:
                        self.func = bytes(v2).decode("utf-8")
        return self

    @staticmethod
    def _decode_list(buf):
        out = {"s": [], "i": [], "f": [], "b": [], "type": [], "shape": []}
        for field, wt, v in iter_fields(buf):
            if field == 2:
                out["s"].append(bytes(v))
            elif field == 3:
                out["i"] += (_decode_packed_varints(v) if wt == _LEN
                             else [_signed(v)])
            elif field == 4:
                out["f"] += (_unpack_packed(v, "<f4", 4) if wt == _LEN
                             else [struct.unpack("<f", v)[0]])
            elif field == 5:
                out["b"] += ([bool(b) for b in
                              _decode_packed_varints(v)] if wt == _LEN
                             else [bool(v)])
            elif field == 6:
                out["type"] += (_decode_packed_varints(v) if wt == _LEN
                                else [v])
            elif field == 7:
                out["shape"].append(TensorShapeProto.decode(v))
        return out

    def encode(self):
        out = bytearray()
        if self.list is not None:
            lst = bytearray()
            for s in self.list.get("s", []):
                emit_bytes(lst, 2, s)
            for i in self.list.get("i", []):
                emit_varint(lst, 3, i)
            for f in self.list.get("f", []):
                emit_float(lst, 4, f)
            for b in self.list.get("b", []):
                emit_varint(lst, 5, int(b))
            for t in self.list.get("type", []):
                emit_varint(lst, 6, t)
            for sh in self.list.get("shape", []):
                emit_bytes(lst, 7, sh.encode())
            emit_bytes(out, 1, lst)
        if self.s is not None:
            emit_bytes(out, 2, self.s)
        if self.i is not None:
            emit_varint(out, 3, self.i)
        if self.f is not None:
            emit_float(out, 4, self.f)
        if self.b is not None:
            emit_varint(out, 5, int(self.b))
        if self.type is not None:
            emit_varint(out, 6, self.type)
        if self.shape is not None:
            emit_bytes(out, 7, self.shape.encode())
        if self.tensor is not None:
            emit_bytes(out, 8, self.tensor.encode())
        if self.func is not None:
            nal = bytearray()
            emit_bytes(nal, 1, self.func.encode("utf-8"))
            emit_bytes(out, 10, nal)
        return bytes(out)


class NodeDef:
    """node_def.proto: name=1, op=2, input=3 (repeated), device=4,
    attr=5 (map<string, AttrValue> — repeated entry{key=1, value=2})."""

    def __init__(self, name="", op="", inputs=None, attrs=None, device=""):
        self.name = name
        self.op = op
        self.inputs = list(inputs or [])
        self.attrs = dict(attrs or {})
        self.device = device

    @classmethod
    def decode(cls, buf):
        self = cls()
        for field, wt, v in iter_fields(buf):
            if field == 1:
                self.name = bytes(v).decode("utf-8")
            elif field == 2:
                self.op = bytes(v).decode("utf-8")
            elif field == 3:
                self.inputs.append(bytes(v).decode("utf-8"))
            elif field == 4:
                self.device = bytes(v).decode("utf-8")
            elif field == 5:
                key, val = None, None
                for f2, _w2, v2 in iter_fields(v):
                    if f2 == 1:
                        key = bytes(v2).decode("utf-8")
                    elif f2 == 2:
                        val = AttrValue.decode(v2)
                if key is not None:
                    self.attrs[key] = val
        return self

    def encode(self):
        out = bytearray()
        emit_bytes(out, 1, self.name.encode("utf-8"))
        emit_bytes(out, 2, self.op.encode("utf-8"))
        for inp in self.inputs:
            emit_bytes(out, 3, inp.encode("utf-8"))
        if self.device:
            emit_bytes(out, 4, self.device.encode("utf-8"))
        for key in self.attrs:
            entry = bytearray()
            emit_bytes(entry, 1, key.encode("utf-8"))
            emit_bytes(entry, 2, self.attrs[key].encode())
            emit_bytes(out, 5, entry)
        return bytes(out)


class ArgDef:
    """op_def.proto ArgDef: name=1, type=3 (DataType)."""

    def __init__(self, name="", type=DT_FLOAT):  # noqa: A002
        self.name = name
        self.type = type

    @classmethod
    def decode(cls, buf):
        self = cls()
        for field, _wt, v in iter_fields(buf):
            if field == 1:
                self.name = bytes(v).decode("utf-8")
            elif field == 3:
                self.type = v
        return self

    def encode(self):
        out = bytearray()
        emit_bytes(out, 1, self.name.encode("utf-8"))
        emit_varint(out, 3, self.type)
        return bytes(out)


class OpDefSignature:
    """op_def.proto OpDef (signature subset): name=1, input_arg=2,
    output_arg=3 (repeated ArgDef)."""

    def __init__(self, name="", input_args=None, output_args=None):
        self.name = name
        self.input_args = list(input_args or [])
        self.output_args = list(output_args or [])

    @classmethod
    def decode(cls, buf):
        self = cls()
        for field, _wt, v in iter_fields(buf):
            if field == 1:
                self.name = bytes(v).decode("utf-8")
            elif field == 2:
                self.input_args.append(ArgDef.decode(v))
            elif field == 3:
                self.output_args.append(ArgDef.decode(v))
        return self

    def encode(self):
        out = bytearray()
        emit_bytes(out, 1, self.name.encode("utf-8"))
        for a in self.input_args:
            emit_bytes(out, 2, a.encode())
        for a in self.output_args:
            emit_bytes(out, 3, a.encode())
        return bytes(out)


class FunctionDef:
    """function.proto FunctionDef: signature=1 (OpDef), node_def=3
    (repeated NodeDef), ret=4 (map<string,string>: output_arg name ->
    internal tensor ref)."""

    def __init__(self, signature=None, nodes=None, ret=None):
        self.signature = signature or OpDefSignature()
        self.nodes = list(nodes or [])
        self.ret = dict(ret or {})

    @classmethod
    def decode(cls, buf):
        self = cls()
        for field, _wt, v in iter_fields(buf):
            if field == 1:
                self.signature = OpDefSignature.decode(v)
            elif field == 3:
                self.nodes.append(NodeDef.decode(v))
            elif field == 4:
                key, val = None, None
                for f2, _w2, v2 in iter_fields(v):
                    if f2 == 1:
                        key = bytes(v2).decode("utf-8")
                    elif f2 == 2:
                        val = bytes(v2).decode("utf-8")
                if key is not None:
                    self.ret[key] = val
        return self

    def encode(self):
        out = bytearray()
        emit_bytes(out, 1, self.signature.encode())
        for n in self.nodes:
            emit_bytes(out, 3, n.encode())
        for k, v in self.ret.items():
            entry = bytearray()
            emit_bytes(entry, 1, k.encode("utf-8"))
            emit_bytes(entry, 2, v.encode("utf-8"))
            emit_bytes(out, 4, entry)
        return bytes(out)


class GraphDef:
    """graph.proto: node=1 (repeated NodeDef), library=2
    (FunctionDefLibrary{function=1}); versions ignored."""

    def __init__(self, nodes=None, functions=None):
        self.nodes = list(nodes or [])
        self.functions = list(functions or [])   # FunctionDef list

    @classmethod
    def decode(cls, buf):
        self = cls()
        for field, _wt, v in iter_fields(buf):
            if field == 1:
                self.nodes.append(NodeDef.decode(v))
            elif field == 2:
                for f2, _w2, v2 in iter_fields(v):
                    if f2 == 1:
                        self.functions.append(FunctionDef.decode(v2))
        return self

    @classmethod
    def parse(cls, path_or_bytes):
        if isinstance(path_or_bytes, (bytes, bytearray, memoryview)):
            return cls.decode(path_or_bytes)
        with open(path_or_bytes, "rb") as f:
            return cls.decode(f.read())

    def encode(self):
        out = bytearray()
        for node in self.nodes:
            emit_bytes(out, 1, node.encode())
        if self.functions:
            lib = bytearray()
            for fn in self.functions:
                emit_bytes(lib, 1, fn.encode())
            emit_bytes(out, 2, lib)
        return bytes(out)

    def save(self, path):
        with open(path, "wb") as f:
            f.write(self.encode())


# ---------------------------------------------------------------------------
# fixture-building helpers (encode side)
# ---------------------------------------------------------------------------

def attr_tensor(arr):
    return AttrValue(tensor=TensorProto.from_numpy(arr))


def attr_type(np_dtype):
    return AttrValue(type=numpy_to_dtype(np_dtype))


def attr_shape(dims):
    return AttrValue(shape=TensorShapeProto(list(dims)))


def attr_i(i):
    return AttrValue(i=int(i))


def attr_b(b):
    return AttrValue(b=bool(b))


def attr_f(f):
    return AttrValue(f=float(f))


def attr_s(s):
    return AttrValue(s=s if isinstance(s, bytes) else s.encode("utf-8"))


def attr_ilist(vals):
    return AttrValue(list={"i": [int(v) for v in vals]})


def attr_func(name):
    return AttrValue(func=str(name))
