"""Keras HDF5 model import.

Reference capability: `deeplearning4j-modelimport`
`org.deeplearning4j.nn.modelimport.keras.KerasModelImport` (SURVEY.md
§2.7: ~40k LoC Java over the JavaCPP hdf5 preset; VERDICT.md round-1
missing item 1). Reads a Keras 2.x HDF5 file (the `model_config` JSON
attr + `model_weights` groups) via h5py and builds a native
MultiLayerNetwork (Sequential) or ComputationGraph (Functional) with the
trained weights installed.

Layout conventions (same conversions the reference performs):
- Conv2D kernels HWIO -> OIHW; imported conv nets take NCHW inputs.
- Recurrent inputs: Keras [N, T, C] -> DL4J NCW [N, C, T].
- The final Dense/softmax layer becomes an OutputLayer (loss inferred
  from the activation: softmax -> MCXENT, sigmoid -> XENT, else MSE) so
  the imported model is trainable, matching the reference's
  `importKerasSequentialModelAndWeights(..., enforceTrainingConfig)`.

Scope: the baseline architectures (MLP / CNN / LSTM, sequential and
functional) — Dense, Conv2D, MaxPooling2D, AveragePooling2D, Flatten,
Dropout, BatchNormalization, Activation, Embedding, LSTM, SimpleRNN,
InputLayer, concatenate/add merges.
"""

from __future__ import annotations

import json

import numpy as np

from deeplearning4j_tpu.nn import (
    ActivationLayer, BatchNormalization, Bidirectional, ComputationGraph,
    ConvolutionLayer, DenseLayer, DropoutLayer, EmbeddingSequenceLayer,
    GRU, InputType, LastTimeStep, LossFunction, LSTM, MergeVertex,
    MultiLayerNetwork, NeuralNetConfiguration, OutputLayer, RnnOutputLayer,
    SimpleRnn, SubsamplingLayer)
from deeplearning4j_tpu.nn.conf.layers import PoolingType

_ACTIVATIONS = {
    "relu": "relu", "tanh": "tanh", "sigmoid": "sigmoid",
    "softmax": "softmax", "linear": "identity", "elu": "elu",
    "selu": "selu", "softplus": "softplus", "softsign": "softsign",
    "hard_sigmoid": "hardsigmoid", "swish": "swish", "gelu": "gelu",
    "leaky_relu": "leakyrelu",
}


def _act(name):
    if name is None:
        return "identity"
    if name not in _ACTIVATIONS:
        raise ValueError(f"unsupported Keras activation: {name!r}")
    return _ACTIVATIONS[name]


def _loss_for_output(activation):
    return {"softmax": LossFunction.MCXENT,
            "sigmoid": LossFunction.XENT}.get(activation, LossFunction.MSE)


class KerasModelImport:
    """Entry points mirroring the reference class."""

    @staticmethod
    def importKerasSequentialModelAndWeights(path) -> MultiLayerNetwork:
        cfg, weights = _read_h5(path)
        if cfg["class_name"] != "Sequential":
            raise ValueError(
                f"not a Sequential model: {cfg['class_name']} "
                f"(use importKerasModelAndWeights)")
        return _build_sequential(cfg, weights)

    @staticmethod
    def importKerasModelAndWeights(path):
        cfg, weights = _read_h5(path)
        if cfg["class_name"] == "Sequential":
            return _build_sequential(cfg, weights)
        return _build_functional(cfg, weights)


# ---------------------------------------------------------------------------
# HDF5 reading
# ---------------------------------------------------------------------------

def _read_h5(path):
    import h5py

    with h5py.File(path, "r") as f:
        raw = f.attrs["model_config"]
        if isinstance(raw, bytes):
            raw = raw.decode("utf-8")
        cfg = json.loads(raw)
        weights = {}
        mw = f["model_weights"]
        for lname in mw:
            g = mw[lname]
            names = g.attrs.get("weight_names", [])
            arrs = []
            for wn in names:
                if isinstance(wn, bytes):
                    wn = wn.decode("utf-8")
                arrs.append(np.array(g[wn]))
            if arrs:
                weights[lname] = arrs
    return cfg, weights


# ---------------------------------------------------------------------------
# layer conversion
# ---------------------------------------------------------------------------

def _input_type_from_shape(shape):
    """batch_input_shape (without batch dim) -> InputType. Keras NHWC conv
    input -> convolutional(h, w, c); [T, C] -> recurrent(C, T)."""
    shape = [s for s in shape if s is not None]
    if len(shape) == 4:
        d, h, w, c = shape   # Keras NDHWC -> our NCDHW
        return InputType.convolutional3D(d, h, w, c)
    if len(shape) == 3:
        h, w, c = shape
        return InputType.convolutional(h, w, c)
    if len(shape) == 2:
        t, c = shape
        return InputType.recurrent(c, t)
    if len(shape) == 1:
        return InputType.feedForward(shape[0])
    raise ValueError(f"unsupported input shape {shape}")


def _convert_layer(class_name, kc, is_last, prev_returns_sequences):
    """One Keras layer config -> (our layer or None, activation_carryover).

    Returns None for structural layers (Flatten/InputLayer) that our
    config DSL expresses through input-type inference."""
    if class_name in ("InputLayer", "Flatten"):
        return None
    if class_name == "Dense":
        act = _act(kc.get("activation"))
        if is_last:
            return OutputLayer.Builder().nOut(kc["units"]).activation(act) \
                .lossFunction(_loss_for_output(act)) \
                .hasBias(kc.get("use_bias", True)).build()
        return DenseLayer.Builder().nOut(kc["units"]).activation(act) \
            .hasBias(kc.get("use_bias", True)).build()
    if class_name == "Conv2D":
        ks = kc["kernel_size"]
        st = kc.get("strides", (1, 1))
        b = (ConvolutionLayer.Builder().nOut(kc["filters"])
             .kernelSize(list(ks)).stride(list(st))
             .activation(_act(kc.get("activation")))
             .hasBias(kc.get("use_bias", True)))
        if kc.get("padding") == "same":
            b = b.convolutionMode("same")
        return b.build()
    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        pt = (PoolingType.MAX if class_name == "MaxPooling2D"
              else PoolingType.AVG)
        ps = kc.get("pool_size", (2, 2))
        st = kc.get("strides") or ps
        return SubsamplingLayer.Builder(poolingType=pt) \
            .kernelSize(list(ps)).stride(list(st)).build()
    if class_name == "Dropout":
        return DropoutLayer.Builder().dropOut(1.0 - kc["rate"]).build()
    if class_name == "BatchNormalization":
        return BatchNormalization.Builder() \
            .eps(kc.get("epsilon", 1e-3)) \
            .decay(kc.get("momentum", 0.99)).build()
    if class_name == "Activation":
        return ActivationLayer.Builder() \
            .activation(_act(kc.get("activation"))).build()
    if class_name == "Embedding":
        return EmbeddingSequenceLayer.Builder() \
            .nIn(kc["input_dim"]).nOut(kc["output_dim"]).build()
    if class_name in ("LSTM", "SimpleRNN", "GRU"):
        if class_name == "GRU":
            # a config MISSING these keys is a pre-TF2 Keras save whose
            # actual defaults were hard_sigmoid gates and
            # reset_after=False — don't silently assume TF2 semantics
            ract = kc.get("recurrent_activation")
            if ract != "sigmoid":
                raise ValueError(
                    f"GRU recurrent_activation={ract!r} unsupported — "
                    "the gruLayer op computes exact sigmoid gates "
                    "(Keras-2-era hard_sigmoid, the default when the "
                    "key is absent, would silently diverge); re-export "
                    "with recurrent_activation='sigmoid'")
            rnn = (GRU.Builder()
                   .nOut(kc["units"])
                   .resetAfter(kc.get("reset_after", False))
                   .activation(_act(kc.get("activation", "tanh")))
                   .build())
        elif class_name == "LSTM":
            # Keras bakes unit_forget_bias into the SAVED bias; the
            # DSL's runtime forgetGateBiasInit add must be zero or the
            # forget gate would get +1 twice
            rnn = (LSTM.Builder().nOut(kc["units"])
                   .activation(_act(kc.get("activation", "tanh")))
                   .forgetGateBiasInit(0.0).build())
        else:
            rnn = (SimpleRnn.Builder().nOut(kc["units"])
                   .activation(_act(kc.get("activation", "tanh")))
                   .build())
        if not kc.get("return_sequences", False):
            return LastTimeStep(rnn)
        return rnn
    if class_name == "Bidirectional":
        inner_spec = kc["layer"]
        inner_cn = inner_spec["class_name"]
        inner_kc = inner_spec.get("config", {})
        if inner_cn not in ("LSTM", "SimpleRNN", "GRU"):
            raise ValueError(
                f"Bidirectional wraps {inner_cn}, not a supported RNN")
        if not inner_kc.get("return_sequences", False):
            raise ValueError(
                "Bidirectional with return_sequences=False is not "
                "importable: Keras concatenates the forward layer's "
                "LAST step with the backward layer's FIRST — re-export "
                "with return_sequences=True (+ pooling) instead")
        rnn = _convert_layer(inner_cn, inner_kc, False, False)
        mode = {"concat": Bidirectional.CONCAT, "sum": Bidirectional.ADD,
                "ave": Bidirectional.AVERAGE,
                "mul": Bidirectional.MUL}.get(
                    kc.get("merge_mode", "concat"))
        if mode is None:
            raise ValueError(
                f"Bidirectional merge_mode={kc.get('merge_mode')!r} "
                "unsupported")
        return Bidirectional(rnn=rnn, mode=mode)
    if class_name in ("GlobalAveragePooling2D", "GlobalMaxPooling2D"):
        from deeplearning4j_tpu.nn import GlobalPoolingLayer

        pt = (PoolingType.AVG if "Average" in class_name
              else PoolingType.MAX)
        return GlobalPoolingLayer.Builder().poolingType(pt).build()
    if class_name == "ZeroPadding2D":
        from deeplearning4j_tpu.nn import ZeroPaddingLayer

        pad = kc.get("padding", (1, 1))
        if isinstance(pad, int):
            pads = [pad] * 4
        elif pad and isinstance(pad[0], (list, tuple)):
            # ((top, bottom), (left, right))
            pads = [pad[0][0], pad[0][1], pad[1][0], pad[1][1]]
        else:  # (sym_h, sym_w)
            pads = [pad[0], pad[0], pad[1], pad[1]]
        return ZeroPaddingLayer.Builder().padding(pads).build()
    if class_name == "UpSampling2D":
        from deeplearning4j_tpu.nn import Upsampling2D

        size = kc.get("size", (2, 2))
        return Upsampling2D.Builder().size(list(size)).build()
    if class_name == "DepthwiseConv2D":
        from deeplearning4j_tpu.nn import DepthwiseConvolution2D

        ks = kc["kernel_size"]
        st = kc.get("strides", (1, 1))
        b = (DepthwiseConvolution2D.Builder()
             .depthMultiplier(kc.get("depth_multiplier", 1))
             .kernelSize(list(ks)).stride(list(st))
             .activation(_act(kc.get("activation")))
             .hasBias(kc.get("use_bias", True)))
        if kc.get("padding") == "same":
            b = b.convolutionMode("same")
        return b.build()
    if class_name == "SeparableConv2D":
        from deeplearning4j_tpu.nn import SeparableConvolution2D

        ks = kc["kernel_size"]
        st = kc.get("strides", (1, 1))
        b = (SeparableConvolution2D.Builder().nOut(kc["filters"])
             .kernelSize(list(ks)).stride(list(st))
             .activation(_act(kc.get("activation")))
             .hasBias(kc.get("use_bias", True)))
        if kc.get("padding") == "same":
            b = b.convolutionMode("same")
        return b.build()
    if class_name == "LeakyReLU":
        alpha = kc.get("alpha", 0.3)  # Keras default slope
        return ActivationLayer.Builder() \
            .activation(f"leakyrelu:{alpha}").build()
    if class_name == "Conv1D":
        from deeplearning4j_tpu.nn import Convolution1DLayer

        if kc.get("padding") == "causal":
            raise ValueError(
                "Conv1D padding='causal' is not supported by the importer")
        b = (Convolution1DLayer.Builder().nOut(kc["filters"])
             .kernelSize(kc["kernel_size"][0])
             .stride(kc.get("strides", (1,))[0])
             .activation(_act(kc.get("activation")))
             .hasBias(kc.get("use_bias", True)))
        if kc.get("padding") == "same":
            b = b.convolutionMode("same")
        return b.build()
    if class_name == "Conv3D":
        from deeplearning4j_tpu.nn import Convolution3D

        b = (Convolution3D.Builder().nOut(kc["filters"])
             .kernelSize(list(kc["kernel_size"]))
             .stride(list(kc.get("strides", (1, 1, 1))))
             .activation(_act(kc.get("activation")))
             .hasBias(kc.get("use_bias", True)))
        if kc.get("padding") == "same":
            b = b.convolutionMode("same")
        return b.build()
    if class_name in ("MaxPooling3D", "AveragePooling3D"):
        from deeplearning4j_tpu.nn import Subsampling3DLayer

        pt = (PoolingType.MAX if class_name == "MaxPooling3D"
              else PoolingType.AVG)
        ps = kc.get("pool_size", (2, 2, 2))
        st = kc.get("strides") or ps
        b = Subsampling3DLayer.Builder(poolingType=pt) \
            .kernelSize(list(ps)).stride(list(st))
        if kc.get("padding") == "same":
            b = b.convolutionMode("same")
        return b.build()
    if class_name == "Cropping1D":
        from deeplearning4j_tpu.nn import Cropping1D

        crop = kc.get("cropping", (1, 1))
        if isinstance(crop, int):
            crop = (crop, crop)
        return Cropping1D.Builder().cropping(list(crop)).build()
    if class_name == "Cropping2D":
        from deeplearning4j_tpu.nn import Cropping2D

        crop = kc.get("cropping", ((0, 0), (0, 0)))
        if isinstance(crop, int):
            pads = [crop] * 4
        elif crop and isinstance(crop[0], (list, tuple)):
            pads = [crop[0][0], crop[0][1], crop[1][0], crop[1][1]]
        else:
            pads = [crop[0], crop[0], crop[1], crop[1]]
        return Cropping2D.Builder().cropping(pads).build()
    if class_name == "UpSampling1D":
        from deeplearning4j_tpu.nn import Upsampling1D

        return Upsampling1D.Builder().size(kc.get("size", 2)).build()
    if class_name == "UpSampling3D":
        from deeplearning4j_tpu.nn import Upsampling3D

        return Upsampling3D.Builder() \
            .size(list(kc.get("size", (2, 2, 2)))).build()
    if class_name == "RepeatVector":
        from deeplearning4j_tpu.nn import RepeatVector

        return RepeatVector.Builder().repetitionFactor(kc["n"]).build()
    if class_name == "PReLU":
        from deeplearning4j_tpu.nn import PReLULayer

        return PReLULayer.Builder().build()
    if class_name in ("GlobalAveragePooling1D", "GlobalMaxPooling1D"):
        from deeplearning4j_tpu.nn import GlobalPoolingLayer

        pt = (PoolingType.AVG if "Average" in class_name
              else PoolingType.MAX)
        return GlobalPoolingLayer.Builder().poolingType(pt).build()
    if class_name == "ELU":
        return ActivationLayer.Builder() \
            .activation(f"elu:{kc.get('alpha', 1.0)}").build()
    if class_name == "ThresholdedReLU":
        return ActivationLayer.Builder() \
            .activation(f"thresholdedrelu:{kc.get('theta', 1.0)}").build()
    raise ValueError(f"unsupported Keras layer: {class_name}")


def _keras_layers(cfg):
    layers = cfg["config"]["layers"]
    out = []
    for spec in layers:
        kc = spec.get("config", {})
        out.append((spec["class_name"], kc,
                    kc.get("name") or spec.get("name")))
    return out


def _build_sequential(cfg, weights) -> MultiLayerNetwork:
    specs = _keras_layers(cfg)
    input_type = None
    for class_name, kc, _name in specs:
        shape = kc.get("batch_input_shape")
        if shape is not None:
            input_type = _input_type_from_shape(shape[1:])
            break
    if input_type is None:
        raise ValueError("model has no input shape recorded")

    # find the index of the last WEIGHTED layer (it becomes the output)
    last_w = max(i for i, (cn, _kc, _n) in enumerate(specs)
                 if cn in ("Dense", "Conv2D", "LSTM", "SimpleRNN", "GRU",
                           "Bidirectional"))

    built = []
    names = []
    for i, (class_name, kc, name) in enumerate(specs):
        lr = _convert_layer(class_name, kc, i == last_w, False)
        if lr is None:
            continue
        built.append(lr)
        names.append(name)
    if not built:
        raise ValueError("model has no convertible layers")

    lb = (NeuralNetConfiguration.Builder().seed(12345).list())
    for lr in built:
        lb = lb.layer(lr)
    conf = lb.setInputType(input_type).build()
    net = MultiLayerNetwork(conf).init()
    _install_weights_mln(net, names, weights)
    return net


def _build_functional(cfg, weights) -> ComputationGraph:
    layers = cfg["config"]["layers"]
    inputs = [li[0] for li in cfg["config"]["input_layers"]]
    outputs = [lo[0] for lo in cfg["config"]["output_layers"]]

    gb = NeuralNetConfiguration.Builder().seed(12345).graphBuilder()
    gb = gb.addInputs(*inputs)
    input_type = None
    name_map = {}
    for spec in layers:
        cn = spec["class_name"]
        kc = spec.get("config", {})
        name = spec.get("name") or kc.get("name")
        inbound = spec.get("inbound_nodes") or []
        srcs = []
        if inbound:
            node = inbound[0]
            if isinstance(node, dict):  # keras 3 style
                node = node.get("args", [[]])[0]
            for ref in node:
                if isinstance(ref, (list, tuple)):
                    srcs.append(ref[0])
        srcs = [name_map.get(s, s) for s in srcs]
        if cn == "InputLayer":
            shape = kc.get("batch_input_shape")
            if shape is not None and input_type is None:
                input_type = _input_type_from_shape(shape[1:])
            name_map[name] = name  # identity: it's a graph input
            continue
        if cn in ("Concatenate", "Merge"):
            gb = gb.addVertex(name, MergeVertex(), *srcs)
            name_map[name] = name
            continue
        if cn == "Add":
            from deeplearning4j_tpu.nn import ElementWiseVertex

            gb = gb.addVertex(name, ElementWiseVertex("add"), *srcs)
            name_map[name] = name
            continue
        if cn == "Flatten":
            # expressed via input-type inference; alias to its source
            name_map[name] = srcs[0]
            continue
        lr = _convert_layer(cn, kc, name in outputs, False)
        gb = gb.addLayer(name, lr, *srcs)
        name_map[name] = name
    outputs = [name_map.get(o, o) for o in outputs]
    gb = gb.setOutputs(*outputs)
    if input_type is not None:
        gb = gb.setInputTypes(input_type)
    conf = gb.build()
    net = ComputationGraph(conf).init()
    _install_weights_graph(net, weights)
    return net


# ---------------------------------------------------------------------------
# weight installation
# ---------------------------------------------------------------------------

def _gru_gate_perm(a):
    """Keras GRU gate blocks [z | r | h] -> gruLayer's [r | u | c]."""
    h3 = a.shape[-1]
    if h3 % 3:
        raise ValueError(f"GRU weight last dim {h3} not divisible by 3")
    h = h3 // 3
    return np.concatenate(
        [a[..., h:2 * h], a[..., :h], a[..., 2 * h:]], axis=-1)


def _convert_weights(layer, arrs):
    """Keras weight list -> our param dict for one layer."""
    if isinstance(layer, LastTimeStep):
        return _convert_weights(layer.rnn, arrs)
    if isinstance(layer, Bidirectional):
        # Keras stores [forward weights..., backward weights...]
        half = len(arrs) // 2
        return {"fwd": _convert_weights(layer.rnn, arrs[:half]),
                "bwd": _convert_weights(layer.rnn, arrs[half:])}
    if isinstance(layer, GRU):
        out = {"W": _gru_gate_perm(arrs[0]),
               "R": _gru_gate_perm(arrs[1])}
        h = arrs[1].shape[0]
        if len(arrs) > 2:
            b = np.asarray(arrs[2])
            if b.ndim == 2:   # reset_after=True: [input_bias, rec_bias]
                out["b"] = np.concatenate(
                    [_gru_gate_perm(b[0]), _gru_gate_perm(b[1])])
            else:             # reset_after=False: input bias only
                out["b"] = _gru_gate_perm(b)
        else:
            out["b"] = np.zeros(
                (6 * h if layer.resetAfter else 3 * h,), np.float32)
        return out
    from deeplearning4j_tpu.nn import SeparableConvolution2D

    if isinstance(layer, SeparableConvolution2D):
        # Keras: depthwise (kh,kw,in,mult), pointwise (1,1,in*mult,out)
        dw = np.transpose(arrs[0], (3, 2, 0, 1))   # -> (mult,in,kh,kw)
        pw = np.transpose(arrs[1], (3, 2, 0, 1))   # -> (out,in*mult,1,1)
        out = {"dW": dw, "pW": pw}
        if len(arrs) > 2:
            out["b"] = arrs[2]
        return out
    # (DepthwiseConvolution2D falls through to the generic conv branch:
    # its (kh,kw,in,mult) kernel takes the same (3,2,0,1) transpose and
    # its bias flattening matches the op's c*mult+m output order)
    from deeplearning4j_tpu.nn import (
        Convolution1DLayer, Convolution3D, PReLULayer)

    if isinstance(layer, Convolution3D):
        w = np.transpose(arrs[0], (4, 3, 0, 1, 2))  # DHWIO -> OIDHW
        out = {"W": w}
        if len(arrs) > 1:
            out["b"] = arrs[1]
        return out
    if isinstance(layer, Convolution1DLayer):
        w = np.transpose(arrs[0], (2, 1, 0))        # KIO -> OIK
        out = {"W": w}
        if len(arrs) > 1:
            out["b"] = arrs[1]
        return out
    if isinstance(layer, PReLULayer):
        # Keras alpha carries the input shape (often with shared spatial
        # axes); ours is per-channel/per-feature
        a = np.asarray(arrs[0], np.float32)
        if a.ndim == 1:
            return {"alpha": a}
        if a.size == a.shape[-1]:
            return {"alpha": a.reshape(a.shape[-1])}
        import warnings

        warnings.warn(
            f"PReLU alpha of shape {a.shape} has unshared spatial axes; "
            f"importing the per-channel mean", stacklevel=2)
        return {"alpha": a.mean(axis=tuple(range(a.ndim - 1)))}
    if isinstance(layer, ConvolutionLayer):
        w = np.transpose(arrs[0], (3, 2, 0, 1))  # HWIO -> OIHW
        out = {"W": w}
        if len(arrs) > 1:
            out["b"] = arrs[1]
        return out
    if isinstance(layer, (LSTM,)):
        # Keras gate order i,f,c,o == ours i,f,g,o
        out = {"W": arrs[0], "R": arrs[1]}
        out["b"] = arrs[2] if len(arrs) > 2 else np.zeros(
            arrs[0].shape[1], np.float32)
        return out
    if isinstance(layer, SimpleRnn):
        out = {"W": arrs[0], "R": arrs[1]}
        out["b"] = arrs[2] if len(arrs) > 2 else np.zeros(
            arrs[0].shape[1], np.float32)
        return out
    if isinstance(layer, BatchNormalization):
        # gamma, beta, moving_mean, moving_variance
        return {"gamma": arrs[0], "beta": arrs[1],
                "_mean": arrs[2], "_var": arrs[3]}
    if isinstance(layer, EmbeddingSequenceLayer):
        return {"W": arrs[0]}
    # Dense / OutputLayer
    out = {"W": arrs[0]}
    if len(arrs) > 1:
        out["b"] = arrs[1]
    return out


def _set_params(net_set_param, layer, idx_or_name, arrs, set_state):
    conv = _convert_weights(layer, arrs)
    state = {}
    for k in ("_mean", "_var"):
        if k in conv:
            state[k.lstrip("_")] = conv.pop(k)
    for k, v in conv.items():
        if isinstance(v, dict):  # nested group (Bidirectional fwd/bwd)
            net_set_param(idx_or_name, k, {
                kk: np.asarray(vv, np.float32) for kk, vv in v.items()})
        else:
            net_set_param(idx_or_name, k, np.asarray(v, np.float32))
    if state:
        set_state(idx_or_name, state)


def _install_weights_mln(net: MultiLayerNetwork, names, weights):
    for i, (lr, name) in enumerate(zip(net.layers, names)):
        arrs = weights.get(name)
        if not arrs:
            continue

        def set_state(idx, st):
            net._states[idx] = {k: np.asarray(v, np.float32)
                                for k, v in st.items()}

        _set_params(net.setParam, lr, i, arrs, set_state)


def _install_weights_graph(net: ComputationGraph, weights):
    for name, (node, _ins) in net.conf.nodes.items():
        arrs = weights.get(name)
        if not arrs:
            continue

        def set_param(n, k, v):
            net._params[n][k] = v

        def set_state(n, st):
            net._states[n] = {k: np.asarray(v, np.float32)
                              for k, v in st.items()}

        _set_params(set_param, node, name, arrs, set_state)
