"""Sharded (pod-scale) checkpointing: per-process shard files + a JSON
manifest, restorable onto a different mesh shape or process count.

Reference capability (SURVEY.md §5 checkpoint row): the reference's
ModelSerializer gathers everything to one host file; SURVEY prescribes
"add sharded save for pod-scale params" for the TPU build. Design:

- Every process writes ONE `shard_{pid}.npz` holding the param chunks it
  owns. A chunk is one distinct shard of a `jax.Array`'s sharding; the
  owner is the lowest-id device holding that chunk, so replicated arrays
  are written exactly once and the chunk->file map is computed
  identically on every process with no communication
  (`Sharding.devices_indices_map` is a global view).
- Process 0 writes `manifest.json` (leaf names, shapes, dtypes, the
  chunk->file map, step, optional metadata) after a cross-process sync,
  so a complete manifest implies complete shard files.
- Restore assembles each requested region from the chunk files it
  overlaps: with a target sharding, `jax.make_array_from_callback`
  materializes only the chunks each process actually needs — restoring
  onto a different mesh/process count re-shards for free; without one,
  the full numpy array is assembled (single-host restore).

The checkpoint directory must be shared storage for multi-process use
(same contract as ElasticTrainer)."""

from __future__ import annotations

import json
import os
import time

import numpy as np

MANIFEST = "manifest.json"

# -- non-native dtype codec (ADVICE r5 medium) -------------------------------
# np.savez silently stores ml_dtypes arrays (bfloat16, float8_*) as raw
# void records ('V2'), which load back as void and cannot be assigned
# into a typed buffer — a checkpoint that saves cleanly but is
# unrestorable. Fix: store such arrays as a same-width uint VIEW (a
# bitcast, no copy of semantics) and view back to the manifest-recorded
# dtype on load.

_UINT_BY_ITEMSIZE = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def encode_for_npz(arr):
    """Bitcast non-native dtypes to a same-width uint for npz storage."""
    arr = np.asarray(arr)
    if arr.dtype.kind == "V" and arr.dtype.itemsize in _UINT_BY_ITEMSIZE:
        return arr.view(_UINT_BY_ITEMSIZE[arr.dtype.itemsize])
    return arr


def resolve_dtype(name):
    """np.dtype for a manifest dtype string, including ml_dtypes names
    ('bfloat16', 'float8_e4m3fn', ...) numpy itself cannot parse."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        try:
            return np.dtype(getattr(ml_dtypes, str(name)))
        except AttributeError:
            raise TypeError(f"unknown checkpoint dtype {name!r}")


def decode_npz_view(arr, dtype):
    """Undo encode_for_npz: view a stored uint array back to `dtype`."""
    if arr.dtype != dtype and dtype.kind == "V" \
            and arr.dtype.kind == "u" \
            and arr.dtype.itemsize == dtype.itemsize:
        return arr.view(dtype)
    return arr


def _sync(tag="dl4j_tpu_sharded_ckpt"):
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def _norm_index(index, shape):
    """Slice tuple -> [[start, stop], ...] (one per dim)."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        if sl.step not in (None, 1):
            raise ValueError(f"strided shard index {sl} unsupported")
        out.append([start, stop])
    return out


def _flatten_with_names(tree):
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf)
            for path, leaf in flat], treedef


def _record_checkpoint(op, t0, nbytes):
    """Checkpoint telemetry (ISSUE 1: checkpoint save/restore records
    bytes and duration); no-op when telemetry is disabled."""
    from deeplearning4j_tpu import telemetry

    if not telemetry.enabled():
        return
    reg = telemetry.get_registry()
    reg.counter("dl4j_checkpoint_total", "Checkpoints written/restored",
                ("op",)).labels(op=op).inc()
    reg.histogram("dl4j_checkpoint_seconds",
                  "Checkpoint save/restore wall time",
                  ("op",)).labels(op=op).observe(time.perf_counter() - t0)
    reg.counter("dl4j_checkpoint_bytes_total",
                "Bytes written/read by checkpoints",
                ("op",)).labels(op=op).inc(nbytes)


def extract_snapshot(tree, step=0, meta=None):
    """The device-facing half of :func:`save_sharded`: compute the
    global chunk->file map and collect THIS process's chunk payload.
    No file I/O and no cross-process sync happen here, so the result —
    a plain dict of host metadata plus (possibly still-transferring)
    array views — can be handed to a background writer thread
    (resilience ISSUE 5 async checkpointing) while the train loop moves
    on. ``write_snapshot`` commits it."""
    import jax

    pid = jax.process_index()
    named, _ = _flatten_with_names(tree)
    payload, leaves_spec = {}, {}
    for i, (name, leaf) in enumerate(named):
        key_base = f"leaf{i}"
        if isinstance(leaf, jax.Array):
            shape, dtype = leaf.shape, np.dtype(leaf.dtype)
            gmap = leaf.sharding.devices_indices_map(shape)
            owners = {}  # chunk slices (as json) -> owning device
            for dev, index in gmap.items():
                k = json.dumps(_norm_index(index, shape))
                if k not in owners or dev.id < owners[k].id:
                    owners[k] = dev
            local = {json.dumps(_norm_index(s.index, shape)):
                     s.data for s in leaf.addressable_shards}
            chunks = []
            for j, (k, dev) in enumerate(sorted(owners.items())):
                npz_key = f"{key_base}.{j}"
                chunks.append({
                    "slices": json.loads(k),
                    "file": f"shard_{dev.process_index}.npz",
                    "key": npz_key})
                if dev.process_index == pid:
                    payload[npz_key] = encode_for_npz(local[k])
        else:  # host value: single chunk owned by process 0
            arr = np.asarray(leaf)
            shape, dtype = arr.shape, arr.dtype
            npz_key = f"{key_base}.0"
            chunks = [{"slices": [[0, d] for d in shape],
                       "file": "shard_0.npz", "key": npz_key}]
            if pid == 0:
                payload[npz_key] = encode_for_npz(arr)
        leaves_spec[name] = {"shape": list(shape), "dtype": str(dtype),
                             "host": not isinstance(leaf, jax.Array),
                             "chunks": chunks}
    return {"pid": pid, "process_count": jax.process_count(),
            "payload": payload, "leaves": leaves_spec,
            "step": int(step), "meta": meta or {}}


def _write_shard(directory, snap) -> str:
    """Write this process's shard npz (tmp + replace); returns the
    committed shard path."""
    pid = snap["pid"]
    os.makedirs(directory, exist_ok=True)
    tmp = os.path.join(directory, f"shard_{pid}.tmp.npz")
    np.savez(tmp, **snap["payload"])
    shard_path = os.path.join(directory, f"shard_{pid}.npz")
    os.replace(tmp, shard_path)
    return shard_path


def _write_manifest(directory, snap, pre_commit=None):
    """Process 0's manifest commit (tmp + replace). ``pre_commit``
    runs before the rename (fault-injection seam)."""
    man = {"step": snap["step"], "process_count": snap["process_count"],
           "leaves": snap["leaves"], "meta": snap["meta"]}
    mtmp = os.path.join(directory, MANIFEST + ".tmp")
    with open(mtmp, "w") as f:
        json.dump(man, f)
    if pre_commit is not None:
        pre_commit()
    os.replace(mtmp, os.path.join(directory, MANIFEST))


def write_snapshot(directory, snap, pre_commit=None):
    """The I/O half of :func:`save_sharded`, collective-free BY
    CONSTRUCTION: shard write, then (process 0) manifest commit, with
    no cross-process sync anywhere on the path. This is the function a
    background checkpoint writer thread may call — a background
    thread's barrier would interleave with the train loop's in-step
    collectives and the processes would disagree on collective order
    (observed as gloo context-init deadlocks; PR 5). Without a barrier
    the manifest does NOT certify the other hosts' shards, so readers
    must use ``latest_agreed()`` / :func:`is_complete`, which verify
    every referenced shard file on the shared directory instead.

    The split (vs. the historical ``sync=`` flag) is deliberate: the
    dl4jlint collective-thread rule proves background threads cannot
    reach a collective, which a runtime flag cannot express."""
    t0 = time.perf_counter()
    shard_path = _write_shard(directory, snap)
    _record_checkpoint("save", t0, os.path.getsize(shard_path))
    if snap["pid"] == 0:
        _write_manifest(directory, snap, pre_commit)


def write_snapshot_synced(directory, snap, pre_commit=None):
    """Barrier-certified commit for the synchronous save path: shard
    write, all-hosts sync, manifest commit (a complete manifest then
    implies complete shard files on every host), final sync. TRAIN
    THREAD ONLY — never call from a background thread (see
    :func:`write_snapshot`)."""
    t0 = time.perf_counter()
    shard_path = _write_shard(directory, snap)
    _sync("shards_written")
    _record_checkpoint("save", t0, os.path.getsize(shard_path))
    if snap["pid"] == 0:
        _write_manifest(directory, snap, pre_commit)
    _sync("manifest_written")


def save_sharded(directory, tree, step=0, meta=None, pre_commit=None):
    """Write this process's chunks of `tree` (a pytree of jax/numpy
    arrays) under `directory`; process 0 also writes the manifest.
    ``pre_commit`` runs before the manifest rename (fault seam)."""
    write_snapshot_synced(directory, extract_snapshot(tree, step, meta),
                          pre_commit=pre_commit)


def is_complete(directory) -> bool:
    """True when `directory` holds a committed manifest AND every chunk
    file the manifest references exists — i.e. every host finished its
    shard write and the commit happened. The building block of
    ``latest_agreed()`` (resilience ISSUE 5): on shared storage a
    checkpoint directory passing this check is restorable from ANY
    host."""
    mpath = os.path.join(directory, MANIFEST)
    if not os.path.isfile(mpath):
        return False
    try:
        with open(mpath) as f:
            man = json.load(f)
    except (OSError, ValueError):
        return False
    files = {ch["file"] for spec in man.get("leaves", {}).values()
             for ch in spec["chunks"]}
    return all(os.path.isfile(os.path.join(directory, f)) for f in files)


class _ChunkReader:
    def __init__(self, directory, manifest):
        self.dir = directory
        self.man = manifest
        self._files = {}

    def _npz(self, fname):
        if fname not in self._files:
            self._files[fname] = np.load(os.path.join(self.dir, fname))
        return self._files[fname]

    def region(self, name, index=None):
        """Assemble the region `index` (slice tuple, or None for the
        whole array) of leaf `name` from its overlapping chunks."""
        spec = self.man["leaves"][name]
        shape = tuple(spec["shape"])
        dtype = resolve_dtype(spec["dtype"])
        want = _norm_index(index, shape) if index is not None else \
            [[0, d] for d in shape]
        out = np.empty([e - s for s, e in want], dtype)
        filled = 0
        for ch in spec["chunks"]:
            inter = [(max(ws, cs), min(we, ce)) for (ws, we), (cs, ce)
                     in zip(want, ch["slices"])]
            if any(s >= e for s, e in inter):
                continue
            src = decode_npz_view(self._npz(ch["file"])[ch["key"]], dtype)
            src_sl = tuple(slice(s - cs, e - cs) for (s, e), (cs, _ce)
                           in zip(inter, ch["slices"]))
            dst_sl = tuple(slice(s - ws, e - ws) for (s, e), (ws, _we)
                           in zip(inter, want))
            out[dst_sl] = src[src_sl]
            filled += int(np.prod([e - s for s, e in inter]))
        if filled < int(np.prod(out.shape)):
            raise ValueError(
                f"checkpoint chunks do not cover leaf {name!r} region "
                f"{want} — incomplete shard files?")
        return out

    def close(self):
        for f in self._files.values():
            f.close()


def load_sharded(directory, template=None, shardings=None):
    """Restore a checkpoint written by save_sharded.

    template: pytree with the same structure as the saved tree — the
      result is unflattened into that structure (leaf values unused).
      None returns a flat {name: array} dict.
    shardings: pytree of jax.sharding.Sharding (matching template
      structure), a single Sharding for all leaves, or None for plain
      numpy arrays. With shardings, each process materializes only its
      addressable chunks (pod-scale restore onto any mesh).
    Returns (tree, step, meta)."""
    import jax

    t0 = time.perf_counter()
    with open(os.path.join(directory, MANIFEST)) as f:
        man = json.load(f)
    reader = _ChunkReader(directory, man)
    names = list(man["leaves"])

    if template is not None:
        tnamed, treedef = _flatten_with_names(template)
        tnames = [n for n, _ in tnamed]
        if sorted(tnames) != sorted(names):
            missing = sorted(set(names) ^ set(tnames))
            raise ValueError(
                f"template structure does not match checkpoint "
                f"(mismatched leaves: {missing[:5]}...)")
        names = tnames  # template order
    shard_list = None
    if shardings is not None:
        if hasattr(shardings, "devices_indices_map"):  # single sharding
            shard_list = [shardings] * len(names)
        else:
            snamed, _ = _flatten_with_names(shardings)
            smap = {n: s for n, s in snamed}
            shard_list = [smap[n] for n in names]

    out = []
    for i, name in enumerate(names):
        spec = man["leaves"][name]
        shape = tuple(spec["shape"])
        if shard_list is not None and not spec.get("host"):
            arr = jax.make_array_from_callback(
                shape, shard_list[i],
                lambda idx, _n=name: reader.region(_n, idx))
        else:  # host-saved leaves come back as numpy (dtype-exact)
            arr = reader.region(name)
        out.append(arr)
    read_bytes = sum(
        os.path.getsize(os.path.join(directory, f))
        for f in reader._files)
    reader.close()
    _record_checkpoint("restore", t0, read_bytes)
    if template is not None:
        import jax as _jax

        tree = _jax.tree_util.tree_unflatten(treedef, out)
    else:
        tree = dict(zip(names, out))
    return tree, man["step"], man.get("meta", {})
