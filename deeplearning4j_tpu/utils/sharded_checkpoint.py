"""Sharded (pod-scale) checkpointing: per-process shard files + a JSON
manifest, restorable onto a different mesh shape or process count.

Reference capability (SURVEY.md §5 checkpoint row): the reference's
ModelSerializer gathers everything to one host file; SURVEY prescribes
"add sharded save for pod-scale params" for the TPU build. Design:

- Every process writes ONE `shard_{pid}.npz` holding the param chunks it
  owns. A chunk is one distinct shard of a `jax.Array`'s sharding; the
  owner is the lowest-id device holding that chunk, so replicated arrays
  are written exactly once and the chunk->file map is computed
  identically on every process with no communication
  (`Sharding.devices_indices_map` is a global view).
- Process 0 writes `manifest.json` (leaf names, shapes, dtypes, the
  chunk->file map, step, optional metadata) after a cross-process sync,
  so a complete manifest implies complete shard files.
- Restore assembles each requested region from the chunk files it
  overlaps: with a target sharding, `jax.make_array_from_callback`
  materializes only the chunks each process actually needs — restoring
  onto a different mesh/process count re-shards for free; without one,
  the full numpy array is assembled (single-host restore).

The checkpoint directory must be shared storage for multi-process use
(same contract as ElasticTrainer)."""

from __future__ import annotations

import json
import os

import numpy as np

MANIFEST = "manifest.json"


def _sync(tag="dl4j_tpu_sharded_ckpt"):
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def _norm_index(index, shape):
    """Slice tuple -> [[start, stop], ...] (one per dim)."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        if sl.step not in (None, 1):
            raise ValueError(f"strided shard index {sl} unsupported")
        out.append([start, stop])
    return out


def _flatten_with_names(tree):
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf)
            for path, leaf in flat], treedef


def save_sharded(directory, tree, step=0, meta=None):
    """Write this process's chunks of `tree` (a pytree of jax/numpy
    arrays) under `directory`; process 0 also writes the manifest."""
    import jax

    pid = jax.process_index()
    os.makedirs(directory, exist_ok=True)
    named, _ = _flatten_with_names(tree)
    payload, leaves_spec = {}, {}
    for i, (name, leaf) in enumerate(named):
        key_base = f"leaf{i}"
        if isinstance(leaf, jax.Array):
            shape, dtype = leaf.shape, np.dtype(leaf.dtype)
            gmap = leaf.sharding.devices_indices_map(shape)
            owners = {}  # chunk slices (as json) -> owning device
            for dev, index in gmap.items():
                k = json.dumps(_norm_index(index, shape))
                if k not in owners or dev.id < owners[k].id:
                    owners[k] = dev
            local = {json.dumps(_norm_index(s.index, shape)):
                     s.data for s in leaf.addressable_shards}
            chunks = []
            for j, (k, dev) in enumerate(sorted(owners.items())):
                npz_key = f"{key_base}.{j}"
                chunks.append({
                    "slices": json.loads(k),
                    "file": f"shard_{dev.process_index}.npz",
                    "key": npz_key})
                if dev.process_index == pid:
                    payload[npz_key] = np.asarray(local[k])
        else:  # host value: single chunk owned by process 0
            arr = np.asarray(leaf)
            shape, dtype = arr.shape, arr.dtype
            npz_key = f"{key_base}.0"
            chunks = [{"slices": [[0, d] for d in shape],
                       "file": "shard_0.npz", "key": npz_key}]
            if pid == 0:
                payload[npz_key] = arr
        leaves_spec[name] = {"shape": list(shape), "dtype": str(dtype),
                             "host": not isinstance(leaf, jax.Array),
                             "chunks": chunks}
    tmp = os.path.join(directory, f"shard_{pid}.tmp.npz")
    np.savez(tmp, **payload)
    os.replace(tmp, os.path.join(directory, f"shard_{pid}.npz"))
    _sync("shards_written")
    if pid == 0:
        man = {"step": int(step), "process_count": jax.process_count(),
               "leaves": leaves_spec, "meta": meta or {}}
        mtmp = os.path.join(directory, MANIFEST + ".tmp")
        with open(mtmp, "w") as f:
            json.dump(man, f)
        os.replace(mtmp, os.path.join(directory, MANIFEST))
    _sync("manifest_written")


class _ChunkReader:
    def __init__(self, directory, manifest):
        self.dir = directory
        self.man = manifest
        self._files = {}

    def _npz(self, fname):
        if fname not in self._files:
            self._files[fname] = np.load(os.path.join(self.dir, fname))
        return self._files[fname]

    def region(self, name, index=None):
        """Assemble the region `index` (slice tuple, or None for the
        whole array) of leaf `name` from its overlapping chunks."""
        spec = self.man["leaves"][name]
        shape = tuple(spec["shape"])
        dtype = np.dtype(spec["dtype"])
        want = _norm_index(index, shape) if index is not None else \
            [[0, d] for d in shape]
        out = np.empty([e - s for s, e in want], dtype)
        filled = 0
        for ch in spec["chunks"]:
            inter = [(max(ws, cs), min(we, ce)) for (ws, we), (cs, ce)
                     in zip(want, ch["slices"])]
            if any(s >= e for s, e in inter):
                continue
            src = self._npz(ch["file"])[ch["key"]]
            src_sl = tuple(slice(s - cs, e - cs) for (s, e), (cs, _ce)
                           in zip(inter, ch["slices"]))
            dst_sl = tuple(slice(s - ws, e - ws) for (s, e), (ws, _we)
                           in zip(inter, want))
            out[dst_sl] = src[src_sl]
            filled += int(np.prod([e - s for s, e in inter]))
        if filled < int(np.prod(out.shape)):
            raise ValueError(
                f"checkpoint chunks do not cover leaf {name!r} region "
                f"{want} — incomplete shard files?")
        return out

    def close(self):
        for f in self._files.values():
            f.close()


def load_sharded(directory, template=None, shardings=None):
    """Restore a checkpoint written by save_sharded.

    template: pytree with the same structure as the saved tree — the
      result is unflattened into that structure (leaf values unused).
      None returns a flat {name: array} dict.
    shardings: pytree of jax.sharding.Sharding (matching template
      structure), a single Sharding for all leaves, or None for plain
      numpy arrays. With shardings, each process materializes only its
      addressable chunks (pod-scale restore onto any mesh).
    Returns (tree, step, meta)."""
    import jax

    with open(os.path.join(directory, MANIFEST)) as f:
        man = json.load(f)
    reader = _ChunkReader(directory, man)
    names = list(man["leaves"])

    if template is not None:
        tnamed, treedef = _flatten_with_names(template)
        tnames = [n for n, _ in tnamed]
        if sorted(tnames) != sorted(names):
            missing = sorted(set(names) ^ set(tnames))
            raise ValueError(
                f"template structure does not match checkpoint "
                f"(mismatched leaves: {missing[:5]}...)")
        names = tnames  # template order
    shard_list = None
    if shardings is not None:
        if hasattr(shardings, "devices_indices_map"):  # single sharding
            shard_list = [shardings] * len(names)
        else:
            snamed, _ = _flatten_with_names(shardings)
            smap = {n: s for n, s in snamed}
            shard_list = [smap[n] for n in names]

    out = []
    for i, name in enumerate(names):
        spec = man["leaves"][name]
        shape = tuple(spec["shape"])
        if shard_list is not None and not spec.get("host"):
            arr = jax.make_array_from_callback(
                shape, shard_list[i],
                lambda idx, _n=name: reader.region(_n, idx))
        else:  # host-saved leaves come back as numpy (dtype-exact)
            arr = reader.region(name)
        out.append(arr)
    reader.close()
    if template is not None:
        import jax as _jax

        tree = _jax.tree_util.tree_unflatten(treedef, out)
    else:
        tree = dict(zip(names, out))
    return tree, man["step"], man.get("meta", {})
