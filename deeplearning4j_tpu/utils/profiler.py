"""Profiler + debug instrumentation.

Reference capability: `org.nd4j.linalg.profiler.{OpProfiler, ProfilerConfig}`
+ `PerformanceTracker` (SURVEY.md §2.3, §5 "Tracing / profiling"): per-op
wall time, NaN/Inf panic checking modes, bandwidth tracking, hooked in at
the op-executioner choke point. The TPU-native equivalent exposed here:

- ProfilerConfig: starts/stops the XLA/PJRT profiler (XPlane traces,
  TensorBoard-compatible) — the SURVEY-prescribed mapping ("PJRT/XLA
  already emits XPlane traces; expose a ProfilerConfig-shaped API").
- StepTimer: per-iteration step time + throughput (PerformanceTracker).
- nan_guard / assert_finite: NAN_PANIC / INF_PANIC modes — a finite-check
  compiled INTO the step (cheap on TPU: one all-reduce over grads) that
  raises host-side naming the first offending variable.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field

import numpy as np
import jax
import jax.numpy as jnp


@dataclass
class ProfilerConfig:
    """Trace-collection config. `checkForNaN`/`checkForInf` mirror the
    reference's ANY_PANIC modes; `trace_dir` enables XPlane traces viewable
    in TensorBoard (tensorboard --logdir <trace_dir>)."""

    trace_dir: str = "/tmp/dl4j_tpu_trace"
    checkForNaN: bool = False
    checkForInf: bool = False
    _active: bool = field(default=False, repr=False)

    def start(self):
        os.makedirs(self.trace_dir, exist_ok=True)
        jax.profiler.start_trace(self.trace_dir)
        self._active = True
        return self

    def stop(self):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
        return self.trace_dir

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def trace(self, fn, *args, **kwargs):
        """Profile one call; returns (result, trace_dir)."""
        with self:
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
        return out, self.trace_dir


class StepTimer:
    """Per-iteration timing + items/sec (reference: PerformanceTracker /
    PerformanceListener internals). Synchronizes via a scalar device read,
    which is the reliable sync on the axon platform."""

    def __init__(self, window: int = 50):
        self.window = window
        self.times: list[float] = []
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, sync_value=None) -> float:
        if sync_value is not None:
            jax.block_until_ready(sync_value)
        dt = time.perf_counter() - self._t0
        self.times.append(dt)
        if len(self.times) > self.window:
            self.times.pop(0)
        from deeplearning4j_tpu import telemetry

        if telemetry.enabled():
            # route through the shared registry (ISSUE 1) under this
            # module's own loop label — synced timings, true step time
            telemetry.get_registry().histogram(
                "dl4j_step_seconds", telemetry.STEP_HELP,
                ("loop",)).labels(loop="step_timer").observe(dt)
        return dt

    def mean_step_time(self) -> float:
        return float(np.mean(self.times)) if self.times else 0.0

    def throughput(self, items_per_step: int) -> float:
        m = self.mean_step_time()
        return items_per_step / m if m > 0 else 0.0

    def summary(self, items_per_step: int | None = None) -> dict:
        out = {"steps": len(self.times),
               "mean_step_ms": 1e3 * self.mean_step_time()}
        if items_per_step:
            out["items_per_sec"] = self.throughput(items_per_step)
        return out


def finite_flags(tree) -> jnp.ndarray:
    """Inside-jit helper: per-leaf all-finite flags, one bool per leaf
    (cheap reductions XLA fuses into the step)."""
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.stack([jnp.all(jnp.isfinite(l)) for l in leaves])


def assert_finite(tree, where: str = "gradients"):
    """Host-side check naming the first non-finite variable. Use on the
    OUTPUT of a jitted step (flags computed in-step via finite_flags stay
    on device until this reads them)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    paths = _leaf_paths(tree)
    for path, leaf in zip(paths, leaves):
        arr = np.asarray(leaf)
        if not np.all(np.isfinite(arr)):
            n_nan = int(np.isnan(arr).sum())
            n_inf = int(np.isinf(arr).sum())
            raise FloatingPointError(
                f"non-finite values in {where} at '{path}': "
                f"{n_nan} NaN, {n_inf} Inf (shape {arr.shape}). "
                f"Reference capability: OpProfiler NAN_PANIC mode.")


def _leaf_paths(tree) -> list[str]:
    paths = []
    jax.tree_util.tree_map_with_path(
        lambda p, _: paths.append(jax.tree_util.keystr(p)), tree)
    return paths


def nan_panic_check(profiler_cfg, loss, tree=None, where="parameters",
                    context=""):
    """Shared NAN_PANIC/INF_PANIC check used by the trainers' fit loops.

    No-op unless `profiler_cfg` enables checkForNaN/checkForInf (keeps the
    happy path free of a per-step device sync). On a non-finite loss,
    names the first non-finite leaf in `tree` if any, else blames the
    batch."""
    if profiler_cfg is None or not (
            getattr(profiler_cfg, "checkForNaN", False)
            or getattr(profiler_cfg, "checkForInf", False)):
        return
    lv = float(loss)
    if np.isnan(lv) or np.isinf(lv):
        if tree is not None:
            assert_finite(tree, where)
        raise FloatingPointError(
            f"non-finite loss {lv!r}{context} (NAN_PANIC mode); {where} "
            f"were finite — inspect this batch's features/labels")


def profile_step(fn, *args, trace_dir="/tmp/dl4j_tpu_trace", steps=3):
    """One-command step attribution: runs `steps` calls under the XLA
    profiler and returns the trace dir for TensorBoard."""
    cfg = ProfilerConfig(trace_dir=trace_dir)
    with cfg:
        out = None
        for _ in range(steps):
            out = fn(*args)
        jax.block_until_ready(out)
    return trace_dir
