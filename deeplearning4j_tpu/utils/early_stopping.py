"""Early stopping.

Reference capability: org.deeplearning4j.earlystopping.* (SURVEY.md §2.5):
EarlyStoppingConfiguration with epoch/score/time termination conditions, a
score calculator over a validation iterator, trainer that keeps the best
model and returns an EarlyStoppingResult."""

from __future__ import annotations

import time


# -- termination conditions --------------------------------------------------

class MaxEpochsTerminationCondition:
    def __init__(self, maxEpochs):
        self.maxEpochs = maxEpochs

    def terminate(self, epoch, score, best_epoch):
        # reference semantics: train exactly maxEpochs epochs (0-indexed
        # epoch counter checked after the epoch completes)
        return epoch + 1 >= self.maxEpochs


class ScoreImprovementEpochTerminationCondition:
    """Stop after N epochs without an improvement of at least
    minImprovement. Tracks its own best (the trainer's best ignores the
    threshold); direction is set by the trainer via `minimize`."""

    def __init__(self, maxEpochsWithNoImprovement, minImprovement=0.0):
        self.patience = maxEpochsWithNoImprovement
        self.minImprovement = minImprovement
        self.minimize = True
        self._best = None
        self._best_epoch = -1

    def terminate(self, epoch, score, best_epoch):
        if self._best is None:
            improved = True
        elif self.minimize:
            improved = (self._best - score) > self.minImprovement
        else:
            improved = (score - self._best) > self.minImprovement
        if improved:
            self._best, self._best_epoch = score, epoch
        return (epoch - self._best_epoch) > self.patience


class MaxTimeIterationTerminationCondition:
    def __init__(self, maxSeconds):
        self.maxSeconds = maxSeconds
        self._start = None

    def terminate(self, epoch, score, best_epoch):
        if self._start is None:
            self._start = time.time()
            return False
        return (time.time() - self._start) > self.maxSeconds


class MaxScoreIterationTerminationCondition:
    """Abort if the score explodes above a bound (NaN guard included)."""

    def __init__(self, maxScore):
        self.maxScore = maxScore

    def terminate(self, epoch, score, best_epoch):
        return score != score or score > self.maxScore


# -- score calculators -------------------------------------------------------

class DataSetLossCalculator:
    """Mean loss over a validation iterator (reference:
    DataSetLossCalculator). Lower is better."""

    def __init__(self, iterator, average=True):
        self.iterator = iterator
        self.average = average

    def calculateScore(self, model):
        total, n = 0.0, 0
        for ds in self.iterator:
            total += model.score(ds)
            n += 1
        return total / max(n, 1) if self.average else total

    def minimizeScore(self):
        return True


class ClassificationScoreCalculator:
    """Accuracy-based (higher better)."""

    def __init__(self, iterator):
        self.iterator = iterator

    def calculateScore(self, model):
        return model.evaluate(self.iterator).accuracy()

    def minimizeScore(self):
        return False


# -- configuration + trainer -------------------------------------------------

class EarlyStoppingConfiguration:
    class Builder:
        def __init__(self):
            self._epoch_conds = []
            self._iter_conds = []
            self._calc = None
            self._save_last = False
            self._eval_every = 1

        def epochTerminationConditions(self, *conds):
            self._epoch_conds.extend(conds)
            return self

        def iterationTerminationConditions(self, *conds):
            self._iter_conds.extend(conds)
            return self

        def scoreCalculator(self, calc):
            self._calc = calc
            return self

        def evaluateEveryNEpochs(self, n):
            self._eval_every = n
            return self

        def build(self):
            cfg = EarlyStoppingConfiguration()
            cfg.epochConditions = self._epoch_conds
            cfg.iterationConditions = self._iter_conds
            cfg.scoreCalculator = self._calc
            cfg.evaluateEveryNEpochs = self._eval_every
            return cfg


class EarlyStoppingResult:
    def __init__(self, terminationReason, terminationDetails, scoreVsEpoch,
                 bestModelEpoch, bestModelScore, totalEpochs, bestModel):
        self.terminationReason = terminationReason
        self.terminationDetails = terminationDetails
        self.scoreVsEpoch = scoreVsEpoch
        self.bestModelEpoch = bestModelEpoch
        self.bestModelScore = bestModelScore
        self.totalEpochs = totalEpochs
        self.bestModel = bestModel

    def getBestModel(self):
        return self.bestModel

    def getBestModelEpoch(self):
        return self.bestModelEpoch

    def getBestModelScore(self):
        return self.bestModelScore


class EarlyStoppingTrainer:
    """Reference: EarlyStoppingTrainer / EarlyStoppingGraphTrainer (works
    for both net kinds here since both expose fit/score/clone)."""

    def __init__(self, config: EarlyStoppingConfiguration, model,
                 trainIterator):
        self.config = config
        self.model = model
        self.trainIterator = trainIterator

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        minimize = cfg.scoreCalculator.minimizeScore()
        best_score = float("inf") if minimize else float("-inf")
        best_epoch = -1
        best_model = None
        score_vs_epoch = {}
        for c in cfg.epochConditions + cfg.iterationConditions:
            if hasattr(c, "minimize"):
                c.minimize = minimize
        epoch = 0
        reason, details = "MaxEpochs", ""
        last_score = None
        while True:
            self.model.fit(self.trainIterator, 1)
            stop = False
            if epoch % cfg.evaluateEveryNEpochs == 0:
                score = cfg.scoreCalculator.calculateScore(self.model)
                score_vs_epoch[epoch] = score
                last_score = score
                better = (score < best_score) if minimize \
                    else (score > best_score)
                if better:
                    best_score, best_epoch = score, epoch
                    best_model = self.model.clone()
            # iteration conditions (time budget, NaN/exploding score) run
            # EVERY epoch against the last known score, not only on
            # evaluation epochs
            for c in cfg.iterationConditions:
                if c.terminate(epoch, last_score if last_score is not None
                               else best_score, best_epoch):
                    reason = "IterationTerminationCondition"
                    details = type(c).__name__
                    stop = True
            # score-based epoch conditions only fire on epochs where the
            # score was actually measured (a patience condition must not
            # consume its window on unevaluated epochs); MaxEpochs has no
            # score dependency and runs every epoch
            for c in cfg.epochConditions:
                score_based = not isinstance(c, MaxEpochsTerminationCondition)
                if score_based and epoch not in score_vs_epoch:
                    continue
                if c.terminate(epoch, score_vs_epoch.get(epoch, best_score),
                               best_epoch):
                    reason = "EpochTerminationCondition"
                    details = type(c).__name__
                    stop = True
            epoch += 1
            if stop:
                break
        return EarlyStoppingResult(
            reason, details, score_vs_epoch, best_epoch, best_score, epoch,
            best_model or self.model)


EarlyStoppingGraphTrainer = EarlyStoppingTrainer
