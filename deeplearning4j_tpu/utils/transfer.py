"""Transfer learning.

Reference capability: org.deeplearning4j.nn.transferlearning.TransferLearning
(.Builder) + FineTuneConfiguration (SURVEY.md §2.5): take a trained net,
freeze feature-extractor layers, swap/replace output layers, fine-tune the
rest. Freezing here = assigning the NoOp updater to the frozen layer configs
(their gradients are still computed inside the fused step but produce zero
updates — XLA dead-code-eliminates the unused updater math)."""

from __future__ import annotations

import copy

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.configuration import MultiLayerConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.updaters import NoOp


class FineTuneConfiguration:
    class Builder:
        def __init__(self):
            self._fields = {}

        def updater(self, u):
            self._fields["updater"] = u
            return self

        def seed(self, s):
            self._fields["seed"] = s
            return self

        def l1(self, v):
            self._fields["l1"] = v
            return self

        def l2(self, v):
            self._fields["l2"] = v
            return self

        def build(self):
            cfg = FineTuneConfiguration()
            cfg.fields = self._fields
            return cfg


class TransferLearning:
    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            if not net._initialized:
                raise ValueError("source network must be initialized")
            self._src = net
            self._fine_tune = None
            self._freeze_until = None
            self._replacements: dict = {}   # layer idx -> new layer conf
            self._removed_from = None       # drop layers >= idx
            self._appended: list = []

        def fineTuneConfiguration(self, cfg: FineTuneConfiguration):
            self._fine_tune = cfg
            return self

        def setFeatureExtractor(self, layerIdx):
            """Freeze layers [0..layerIdx] inclusive."""
            self._freeze_until = layerIdx
            return self

        def nOutReplace(self, layerIdx, nOut, weightInit=None):
            old = self._src.layers[layerIdx]
            new = copy.deepcopy(old)
            new.nOut = nOut
            if weightInit is not None:
                new.weightInit = weightInit
            self._replacements[layerIdx] = new
            # the next layer's nIn must change too; clear for re-inference
            if layerIdx + 1 < len(self._src.layers):
                nxt = copy.deepcopy(self._src.layers[layerIdx + 1])
                nxt.nIn = None
                self._replacements.setdefault(layerIdx + 1, nxt)
            return self

        def removeLayersFromOutput(self, n):
            self._removed_from = len(self._src.layers) - n
            return self

        def removeOutputLayer(self):
            return self.removeLayersFromOutput(1)

        def addLayer(self, layer):
            self._appended.append(layer)
            return self

        def build(self) -> MultiLayerNetwork:
            src = self._src
            layers = [copy.deepcopy(lr) for lr in src.layers]
            n_keep = self._removed_from if self._removed_from is not None \
                else len(layers)
            layers = layers[:n_keep]
            for idx, new in self._replacements.items():
                if idx < len(layers):
                    layers[idx] = new
            layers.extend(self._appended)
            defaults = dict(src.conf.defaults)
            if self._fine_tune is not None:
                defaults.update(self._fine_tune.fields)
                # clear the overridden fields on copied layers so
                # apply_defaults refills them from the fine-tune values
                # (copied layers arrive with the OLD defaults materialized)
                for lr in layers:
                    for fld in self._fine_tune.fields:
                        if fld in lr.INHERITED:
                            setattr(lr, fld, None)
            if self._freeze_until is not None:
                for i in range(min(self._freeze_until + 1, len(layers))):
                    layers[i].updater = NoOp()
            conf = MultiLayerConfiguration(
                layers, defaults, src.conf.inputType,
                defaults.get("seed", src.conf.seed), src.conf.dataType)
            net = MultiLayerNetwork(conf)
            net.init()
            # copy weights for all kept, unreplaced layers — REAL copies:
            # the source net's next fit() donates its buffers
            copy_arr = lambda t: jax.tree_util.tree_map(  # noqa: E731
                lambda x: jnp.array(x, copy=True), t)
            for i in range(min(n_keep, len(layers))):
                if i in self._replacements:
                    continue
                if i < len(src._params):
                    net._params[i] = copy_arr(src._params[i])
                    net._states[i] = copy_arr(src._states[i])
            return net
