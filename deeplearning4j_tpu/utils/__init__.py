"""Aux utilities (reference: ModelSerializer, listeners, early stopping,
transfer learning — SURVEY.md §2.5/§5)."""

from deeplearning4j_tpu.utils.serializer import ModelSerializer  # noqa: F401
from deeplearning4j_tpu.utils.listeners import (  # noqa: F401
    CheckpointListener, CollectScoresIterationListener, EvaluativeListener,
    PerformanceListener, ScoreIterationListener, TimeIterationListener,
    TrainingListener)
from deeplearning4j_tpu.utils.early_stopping import (  # noqa: F401
    ClassificationScoreCalculator, DataSetLossCalculator,
    EarlyStoppingConfiguration, EarlyStoppingGraphTrainer,
    EarlyStoppingResult, EarlyStoppingTrainer, MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition)
from deeplearning4j_tpu.utils.transfer import (  # noqa: F401
    FineTuneConfiguration, TransferLearning)
from deeplearning4j_tpu.utils.profiler import (  # noqa: F401
    ProfilerConfig, StepTimer, assert_finite, profile_step)
