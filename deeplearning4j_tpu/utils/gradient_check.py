"""Numeric-vs-analytic gradient checking.

Reference capability: org.deeplearning4j.gradientcheck.GradientCheckUtil
(SURVEY.md §4 "Gradient checks" — the backbone of DL4J correctness): central
finite differences in fp64 against analytic gradients on tiny nets. Here the
analytic side is jax.grad of the lowered net function; fp64 is enabled
per-call via jax.enable_x64 so the check is immune to bf16/f32
matmul drift (SURVEY.md §7 "Numerics")."""

from __future__ import annotations

import numpy as np
import jax


class GradientCheckUtil:
    @staticmethod
    def checkGradients(net, features, labels, epsilon=1e-5, maxRelError=1e-3,
                       minAbsError=1e-8, subset=None, seed=0,
                       print_results=False) -> bool:
        """net: MultiLayerNetwork (initialized). Perturbs each parameter
        (or a random subset of `subset` per array) and compares
        (f(x+e)-f(x-e))/2e with the analytic gradient."""
        f = np.asarray(features, np.float64)
        l = np.asarray(labels, np.float64)

        # TPUs have no native fp64 — running the check there silently
        # degrades precision until finite differences underflow to zero.
        # Pin everything to the host CPU backend (the reference equivalently
        # runs gradient checks on the fp64-capable CPU backend).
        import contextlib

        try:
            cpu = jax.devices("cpu")[0]
        except RuntimeError:
            cpu = None
        device_scope = (jax.default_device(cpu) if cpu is not None
                        else contextlib.nullcontext())

        try:
            x64_scope = jax.enable_x64
        except AttributeError:  # removed from the jax root namespace
            from jax.experimental import enable_x64 as x64_scope

        with device_scope, x64_scope():
            # ascontiguousarray is load-bearing: XLA buffers can expose
            # non-C-contiguous layouts through np.asarray, making
            # reshape(-1) below return a COPY and perturbations silently
            # no-ops
            to64 = lambda x: np.ascontiguousarray(  # noqa: E731
                np.asarray(x, np.float64))
            params64 = jax.tree_util.tree_map(to64, net._params)
            states64 = jax.tree_util.tree_map(to64, net._states)

            def loss_fn(p):
                loss, _ = net._loss_from(p, states64, f, l, False, None)
                return loss

            analytic = jax.grad(loss_fn)(params64)
            base_loss = float(loss_fn(params64))
            if base_loss != base_loss:
                raise ValueError("loss is NaN at the test point")

            rng = np.random.default_rng(seed)
            failures = []
            total_checked = 0
            for li, p in enumerate(params64):
                for k, arr in p.items():
                    flat = arr.reshape(-1)
                    assert np.shares_memory(flat, arr), \
                        "perturbation view must alias the param array"
                    n = flat.shape[0]
                    idxs = (range(n) if subset is None or subset >= n
                            else rng.choice(n, subset, replace=False))
                    an = np.asarray(analytic[li][k], np.float64).reshape(-1)
                    for i in idxs:
                        orig = flat[i]
                        flat[i] = orig + epsilon
                        lp = float(loss_fn(params64))
                        flat[i] = orig - epsilon
                        lm = float(loss_fn(params64))
                        flat[i] = orig
                        numeric = (lp - lm) / (2 * epsilon)
                        a = an[i]
                        denom = max(abs(numeric), abs(a))
                        abs_err = abs(numeric - a)
                        rel = abs_err / denom if denom > 0 else 0.0
                        total_checked += 1
                        if rel > maxRelError and abs_err > minAbsError:
                            failures.append(
                                (li, k, int(i), float(a), float(numeric),
                                 float(rel)))
            if print_results or failures:
                print(f"gradient check: {total_checked} params checked, "
                      f"{len(failures)} failures")
                for li, k, i, a, nmr, rel in failures[:20]:
                    print(f"  layer {li} {k}[{i}]: analytic={a:.3e} "
                          f"numeric={nmr:.3e} rel={rel:.3e}")
            return not failures
