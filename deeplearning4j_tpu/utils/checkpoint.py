"""DL4J-layout checkpoint artifacts + zoo pretrained-weight plumbing.

Reference capability: org.deeplearning4j.util.ModelSerializer's zip
layout (SURVEY.md §5 checkpoint row; VERDICT.md round-1 item 10) —
`configuration.json` + `coefficients.bin` + `updaterState.bin` in one
zip, where the .bin entries are written through Java's big-endian
DataOutputStream. The reference mount has been empty in rounds 1-2
(VERDICT.md header), so byte-level verification against an actual DL4J
artifact is blocked; the layout below is therefore specified exactly in
this docstring and covered by its own reader, writer and round-trip
tests, with numpy `.npy`/`.npz` (whose spec IS independently published)
as the verifiable interchange bridge — nd4j itself reads/writes `.npy`
via Nd4j.createFromNpyFile/Nd4j.writeAsNumpy.

coefficients.bin / updaterState.bin layout (all integers big-endian):

    bytes 0-3    magic b"ND4J"
    bytes 4-7    int32 format version (1)
    byte  8      dtype code: 0 = float32, 1 = float64
    bytes 9-12   int32 rank
    then         rank x int64 shape dims
    then         raw array payload, big-endian, C order
"""

from __future__ import annotations

import io
import json
import os
import struct
import zipfile

import numpy as np


def atomic_save(path, write_fn, pre_commit=None):
    """The tmp + ``os.replace`` commit protocol every checkpoint writer
    here shares (ElasticTrainer zips, sharded shard files + manifest,
    async checkpoints): ``write_fn(tmp_path)`` produces the artifact
    under ``<path>.tmp``; the rename commits it. A crash at ANY point
    leaves either the previous committed file or a ``.tmp`` remnant —
    never a partial artifact under the real name, so ``latest()`` /
    ``latest_agreed()`` can trust whatever they find.

    ``pre_commit`` (optional callable) runs after the write but before
    the rename — the deterministic fault-injection seam (resilience
    ISSUE 5: a simulated crash *between snapshot and commit* must leave
    the tmp behind and the previous checkpoint current)."""
    tmp = str(path) + ".tmp"
    write_fn(tmp)
    if pre_commit is not None:
        pre_commit()
    os.replace(tmp, path)
    return str(path)


_MAGIC = b"ND4J"
_DTYPES = {0: ">f4", 1: ">f8"}
_CODES = {np.dtype(np.float32): 0, np.dtype(np.float64): 1}


def write_nd4j_array(arr: np.ndarray) -> bytes:
    """Serialize one array in the big-endian .bin layout above."""
    arr = np.ascontiguousarray(arr)
    code = _CODES.get(arr.dtype)
    if code is None:
        arr = arr.astype(np.float32)
        code = 0
    out = io.BytesIO()
    out.write(_MAGIC)
    out.write(struct.pack(">i", 1))
    out.write(struct.pack("B", code))
    out.write(struct.pack(">i", arr.ndim))
    for d in arr.shape:
        out.write(struct.pack(">q", d))
    out.write(arr.astype(_DTYPES[code]).tobytes())
    return out.getvalue()


def read_nd4j_array(data: bytes) -> np.ndarray:
    buf = io.BytesIO(data)
    if buf.read(4) != _MAGIC:
        raise ValueError("not an ND4J .bin array (bad magic)")
    (version,) = struct.unpack(">i", buf.read(4))
    if version != 1:
        raise ValueError(f"unsupported .bin version {version}")
    (code,) = struct.unpack("B", buf.read(1))
    (rank,) = struct.unpack(">i", buf.read(4))
    shape = [struct.unpack(">q", buf.read(8))[0] for _ in range(rank)]
    arr = np.frombuffer(buf.read(), dtype=_DTYPES[code]).reshape(shape)
    # native byte order for downstream jnp use
    return np.ascontiguousarray(arr.astype(arr.dtype.newbyteorder("=")))


class Dl4jCheckpoint:
    """Write/read the DL4J artifact shape: configuration.json +
    coefficients.bin (flat params in params() order) + updaterState.bin."""

    @staticmethod
    def save(model, path, saveUpdater: bool = True):
        import jax

        from deeplearning4j_tpu.nn.graph import ComputationGraph

        is_graph = isinstance(model, ComputationGraph)
        with zipfile.ZipFile(path, "w") as zf:
            zf.writestr("configuration.json", model.conf.to_json())
            zf.writestr("modelType", "ComputationGraph" if is_graph
                        else "MultiLayerNetwork")
            flat = model.params().toNumpy().astype(np.float32)
            zf.writestr("coefficients.bin",
                        write_nd4j_array(flat.reshape(1, -1)))
            if saveUpdater:
                leaves = jax.tree_util.tree_leaves(model._opt_states)
                if leaves:
                    upd = np.concatenate(
                        [np.asarray(l, np.float32).ravel() for l in leaves])
                else:
                    upd = np.zeros(0, np.float32)
                zf.writestr("updaterState.bin",
                            write_nd4j_array(upd.reshape(1, -1)))
                ts = {"iteration": model._iteration, "epoch": model._epoch}
                prec = getattr(model, "_prec_state", None)
                if prec:
                    # loss-scaler state (ISSUE 4): a resumed bf16_mixed
                    # run must not restart at init_scale (the warmed
                    # scale encodes everything learned about the run's
                    # gradient magnitudes)
                    ts["lossScale"] = {
                        k: float(np.asarray(jax.device_get(v)))
                        for k, v in prec.items()}
                zf.writestr("trainingState.json", json.dumps(ts))

    @staticmethod
    def load(path, loadUpdater: bool = True):
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn.conf.configuration import (
            MultiLayerConfiguration)
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with zipfile.ZipFile(path) as zf:
            mtype = zf.read("modelType").decode() \
                if "modelType" in zf.namelist() else "MultiLayerNetwork"
            conf_json = zf.read("configuration.json").decode()
            if mtype == "ComputationGraph":
                model = ComputationGraph(
                    ComputationGraphConfiguration.from_json(conf_json))
            else:
                model = MultiLayerNetwork(
                    MultiLayerConfiguration.from_json(conf_json))
            model.init()
            flat = read_nd4j_array(zf.read("coefficients.bin")).ravel()
            model.setParams(flat)
            if loadUpdater and "updaterState.bin" in zf.namelist():
                upd = read_nd4j_array(zf.read("updaterState.bin")).ravel()
                leaves, treedef = jax.tree_util.tree_flatten(
                    model._opt_states)
                pos = 0
                new_leaves = []
                for leaf in leaves:
                    n = int(np.prod(np.shape(leaf))) if np.shape(leaf) \
                        else 1
                    chunk = upd[pos:pos + n]
                    pos += n
                    new_leaves.append(
                        jnp.asarray(chunk, jnp.asarray(leaf).dtype)
                        .reshape(np.shape(leaf)))
                if pos != upd.size:
                    raise ValueError(
                        f"updaterState.bin holds {upd.size} values but the "
                        f"model's updater needs {pos}")
                model._opt_states = jax.tree_util.tree_unflatten(
                    treedef, new_leaves)
                if "trainingState.json" in zf.namelist():
                    ts = json.loads(zf.read("trainingState.json"))
                    model._iteration = ts["iteration"]
                    model._epoch = ts["epoch"]
                    if ts.get("lossScale") and getattr(
                            model, "_prec_state", None):
                        model._prec_state = {
                            k: jnp.asarray(
                                v, model._prec_state[k].dtype)
                            for k, v in ts["lossScale"].items()}
        return model


# ---------------------------------------------------------------------------
# .npy / .npz interop (nd4j: Nd4j.writeAsNumpy / Nd4j.createFromNpyFile)
# ---------------------------------------------------------------------------

def write_npy(arr, path):
    np.save(path, np.asarray(arr), allow_pickle=False)


def read_npy(path):
    return np.load(path, allow_pickle=False)


def save_params_npz(model, path):
    """Named per-layer params as a standard .npz — the portable
    pretrained-weight format initPretrained() consumes."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    named = {}
    if isinstance(model, ComputationGraph):
        items = model._params.items()
        states = model._states.items()
    else:
        items = ((str(i), p) for i, p in enumerate(model._params))
        states = ((str(i), s) for i, s in enumerate(model._states))
    for name, p in items:
        for k, v in p.items():
            named[f"p/{name}/{k}"] = np.asarray(v)
    for name, s in states:
        for k, v in s.items():
            named[f"s/{name}/{k}"] = np.asarray(v)
    np.savez(path, **named)


def load_params_npz(model, path):
    """Install named params saved by save_params_npz into a compatible
    freshly-init'd model (shape-checked)."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.graph import ComputationGraph

    data = np.load(path)
    is_graph = isinstance(model, ComputationGraph)
    for key in data.files:
        kind, name, pname = key.split("/", 2)
        arr = data[key]
        if is_graph:
            target = model._params if kind == "p" else model._states
            slot = target[name]
        else:
            target = model._params if kind == "p" else model._states
            slot = target[int(name)]
        if pname not in slot:
            raise ValueError(
                f"pretrained file has param {key} but the model's "
                f"layer {name!r} holds {sorted(slot)} — wrong weights "
                "for this architecture")
        if np.shape(slot[pname]) != arr.shape:
            raise ValueError(
                f"pretrained weight {key} has shape {arr.shape}, model "
                f"expects {np.shape(slot[pname])}")
        slot[pname] = jnp.asarray(arr)
    return model
