"""Training listeners.

Reference capability: org.deeplearning4j.optimize.listeners.* (SURVEY.md
§2.5 "Listeners", §5 observability): hooks called from the fit loop with
(model, iteration, epoch). Score reads are host-side floats the fit loop
already materialized — no extra device sync."""

from __future__ import annotations

import logging
import os
import time

log = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    def iterationDone(self, model, iteration, epoch):
        pass

    def onEpochStart(self, model):
        pass

    def onEpochEnd(self, model):
        pass


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations (reference: ScoreIterationListener)."""

    def __init__(self, printIterations=10):
        self.printIterations = printIterations
        self.scores: list = []  # (iteration, score) history

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.printIterations == 0:
            s = model.score()
            self.scores.append((iteration, s))
            log.info("Score at iteration %d is %s", iteration, s)


class PerformanceListener(TrainingListener):
    """Iterations/sec, mean step time and examples/sec (reference:
    PerformanceListener + PerformanceTracker, SURVEY.md §5 tracing row).
    Pass batchSize to also report examples/sec."""

    def __init__(self, frequency=10, reportScore=False, batchSize=None):
        self.frequency = frequency
        self.reportScore = reportScore
        self.batchSize = batchSize
        self._last_time = None
        self._last_iter = None
        self.samples: list = []  # (iteration, iters_per_sec)

    def iterationDone(self, model, iteration, epoch):
        now = time.time()
        if self._last_time is not None and \
                iteration % self.frequency == 0 and \
                iteration != self._last_iter:
            dt = now - self._last_time
            its = (iteration - self._last_iter) / dt if dt > 0 else 0.0
            self.samples.append((iteration, its))
            msg = (f"iteration {iteration}: {its:.2f} iters/sec "
                   f"({1e3 / its if its > 0 else 0:.1f} ms/step)")
            if self.batchSize:
                msg += f", {its * self.batchSize:.1f} examples/sec"
            if self.reportScore:
                msg += f", score {model.score()}"
            log.info(msg)
            from deeplearning4j_tpu import telemetry

            if telemetry.enabled() and its > 0:
                # route through the registry (ISSUE 1): iteration-to-
                # iteration wall time is the steady-state step time, so
                # feed the shared histogram under its own loop label
                reg = telemetry.get_registry()
                reg.histogram("dl4j_step_seconds", telemetry.STEP_HELP,
                              ("loop",)).labels(
                    loop="listener").observe(1.0 / its)
                if self.batchSize:
                    reg.gauge("dl4j_examples_per_second",
                              "Instantaneous training throughput",
                              ("source",)).labels(
                        source="performance_listener").set(
                            its * self.batchSize)
            self._last_time = now
            self._last_iter = iteration
        elif self._last_time is None:
            self._last_time = now
            self._last_iter = iteration

    def mean_step_ms(self) -> float:
        if not self.samples:
            return 0.0
        rates = [r for _, r in self.samples if r > 0]
        return 1e3 / (sum(rates) / len(rates)) if rates else 0.0


class CheckpointListener(TrainingListener):
    """Rotating checkpoints every N iterations/epochs (reference:
    CheckpointListener.Builder keepLast/saveEveryNIterations)."""

    def __init__(self, directory, saveEveryNIterations=None,
                 saveEveryNEpochs=None, keepLast=3, saveUpdater=True):
        self.directory = directory
        self.saveEveryNIterations = saveEveryNIterations
        self.saveEveryNEpochs = saveEveryNEpochs
        self.keepLast = keepLast
        self.saveUpdater = saveUpdater
        self._saved: list = []
        self._last_epoch = None
        os.makedirs(directory, exist_ok=True)

    def _save(self, model, tag):
        from deeplearning4j_tpu.utils.serializer import ModelSerializer

        path = os.path.join(self.directory, f"checkpoint_{tag}.zip")
        ModelSerializer.writeModel(model, path, self.saveUpdater)
        self._saved.append(path)
        while len(self._saved) > self.keepLast:
            old = self._saved.pop(0)
            if os.path.exists(old):
                os.remove(old)

    def iterationDone(self, model, iteration, epoch):
        if self.saveEveryNIterations and \
                iteration % self.saveEveryNIterations == 0:
            self._save(model, f"iter_{iteration}")
        if self.saveEveryNEpochs and epoch != self._last_epoch and \
                epoch % self.saveEveryNEpochs == 0:
            self._last_epoch = epoch
            self._save(model, f"epoch_{epoch}")

    def lastCheckpoint(self):
        return self._saved[-1] if self._saved else None


class EvaluativeListener(TrainingListener):
    """Periodic evaluation on a held-out iterator (reference:
    EvaluativeListener)."""

    def __init__(self, iterator, frequency=100):
        self.iterator = iterator
        self.frequency = frequency
        self.evaluations: list = []  # (iteration, Evaluation)

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.frequency == 0:
            ev = model.evaluate(self.iterator)
            self.evaluations.append((iteration, ev))
            log.info("Eval at iteration %d: accuracy %.4f", iteration,
                     ev.accuracy())


class TimeIterationListener(TrainingListener):
    """ETA logging (reference: TimeIterationListener)."""

    def __init__(self, totalIterations):
        self.totalIterations = totalIterations
        self._start = None

    def iterationDone(self, model, iteration, epoch):
        if self._start is None:
            self._start = time.time()
            return
        elapsed = time.time() - self._start
        rate = iteration / elapsed if elapsed > 0 else 0
        remaining = (self.totalIterations - iteration) / rate if rate else 0
        log.info("iteration %d/%d, ETA %.1fs", iteration,
                 self.totalIterations, remaining)


class CollectScoresIterationListener(TrainingListener):
    """Collect every score (reference: CollectScoresIterationListener)."""

    def __init__(self, frequency=1):
        self.frequency = frequency
        self.scores: list = []

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, model.score()))


class HealthListener(TrainingListener):
    """DL4J-style per-layer training-health listener (ISSUE 3; reference:
    the training UI's update:parameter-ratio / gradient-magnitude
    diagnostics, SURVEY.md §2.5 listeners).

    Attach with ``net.setListeners(HealthListener(policy="halt"))``:
    the fit loop's HealthMonitor then uses THIS listener's divergence
    config instead of the process default (telemetry.health.configure),
    and pushes every checked step's per-layer stats into ``history``
    for dashboards/tests — the stats themselves are computed inside the
    jitted step, so attaching this listener adds no device work.

    The monitor discovers the listener by the HEALTH_LISTENER marker
    (duck-typed to keep telemetry.health import-cycle-free)."""

    HEALTH_LISTENER = True

    def __init__(self, policy="warn", ratio_max=None, ratio_min=None,
                 check_every=1, history=200, dump_dir=None):
        from collections import deque

        from deeplearning4j_tpu.telemetry import health

        self.config = health.HealthConfig(
            policy=policy, ratio_max=ratio_max, ratio_min=ratio_min,
            check_every=check_every, dump_dir=dump_dir)
        # (step, {layer_label: {stat_name: value}}) per checked step
        self.history = deque(maxlen=history)

    def onHealthStats(self, loop, step, stats):
        self.history.append((step, stats))

    def lastStats(self) -> dict:
        """{layer_label: {grad_norm, update_norm, param_norm,
        update_param_ratio, nonfinite}} of the newest checked step."""
        return self.history[-1][1] if self.history else {}
