"""Model persistence.

Reference capability: org.deeplearning4j.util.ModelSerializer (SURVEY.md §5
"Checkpoint / resume"): a ZIP holding configuration.json + coefficients
(flat params) + updater state + optional normalizer — the same artifact
shape, so checkpoints carry config + weights + optimizer state in one file.
Params are stored as an npz of per-layer named arrays (canonical restore
source). Pass includeFlatCoefficients=True to additionally write
'coefficients.bin' — a raw little-endian float32 flat vector in
MultiLayerNetwork.params() order for DL4J-artifact-shape compatibility
(doubles the weight payload, so off by default)."""

from __future__ import annotations

import io
import json
import zipfile

import numpy as np
import jax.numpy as jnp


_SEP = "\x1f"  # unit separator: cannot appear in layer names


class ModelSerializer:
    @staticmethod
    def writeModel(model, path, saveUpdater: bool = True, normalizer=None,
                   includeFlatCoefficients: bool = False,
                   sharded: bool = False, modelType: str | None = None,
                   pre_commit=None):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        # modelType override: async-checkpoint snapshots (resilience/
        # async_ckpt.py) hand in a detached host copy of the model state
        # that is not an actual ComputationGraph instance
        is_graph = (modelType == "ComputationGraph" if modelType is not None
                    else isinstance(model, ComputationGraph))
        if sharded:
            # pod-scale path: `path` is a DIRECTORY; every process must
            # call this (each writes its own shard file). Normalizers
            # ride the manifest; flat coefficients are gather-based and
            # meaningless sharded, so unsupported here.
            if includeFlatCoefficients:
                raise ValueError(
                    "includeFlatCoefficients requires the single-file "
                    "(gathering) writeModel path")
            from deeplearning4j_tpu.utils.sharded_checkpoint import (
                save_sharded)

            tree = {"p": model._params, "s": model._states}
            if saveUpdater:
                tree["o"] = model._opt_states
            prec = getattr(model, "_prec_state", None) or None
            if saveUpdater and prec:
                # dynamic loss-scaler state rides the sharded tree too:
                # a resumed mixed-precision run must keep the warmed
                # scale (resilience bit-identical-resume contract)
                tree["prec"] = prec
            meta = {"modelType": ("ComputationGraph" if is_graph
                                  else "MultiLayerNetwork"),
                    "configuration": model.conf.to_json(),
                    "saveUpdater": bool(saveUpdater),
                    "hasPrecState": bool(saveUpdater and prec),
                    "trainingState": {"iteration": model._iteration,
                                      "epoch": model._epoch}}
            if normalizer is not None:
                meta["normalizer"] = {
                    "class": type(normalizer).__name__,
                    "state": {k: np.asarray(v).tolist()
                              for k, v in normalizer._state().items()}}
            save_sharded(path, tree, step=model._iteration, meta=meta,
                         pre_commit=pre_commit)
            return
        with zipfile.ZipFile(path, "w") as zf:
            zf.writestr("configuration.json", model.conf.to_json())
            zf.writestr("modelType",
                        "ComputationGraph" if is_graph
                        else "MultiLayerNetwork")
            if includeFlatCoefficients:
                flat = model.params().toNumpy().astype("<f4")
                zf.writestr("coefficients.bin", flat.tobytes())
            # named per-layer arrays (the canonical restore source);
            # one nesting level (Bidirectional {"fwd": {...}}) flattens
            # into a 4-part key
            def _put(named, kind, owner, pdict):
                for k, v in pdict.items():
                    if isinstance(v, dict):
                        for kk, vv in v.items():
                            named[_SEP.join((kind, owner, k, kk))] = \
                                np.asarray(vv)
                    else:
                        named[_SEP.join((kind, owner, k))] = np.asarray(v)

            named = {}
            if is_graph:
                for name, p in model._params.items():
                    _put(named, "p", name, p)
                for name, s in model._states.items():
                    _put(named, "s", name, s)
            else:
                for i, p in enumerate(model._params):
                    _put(named, "p", str(i), p)
                for i, s in enumerate(model._states):
                    _put(named, "s", str(i), s)
            # non-native dtypes (ml_dtypes bf16/fp8) would silently hit
            # npz as raw void and come back unrestorable (ADVICE r5):
            # store a same-width uint view + a dtype sidecar to view back
            from deeplearning4j_tpu.utils.sharded_checkpoint import (
                encode_for_npz)

            dtype_map = {k: str(v.dtype) for k, v in named.items()
                         if v.dtype.kind == "V"}
            if dtype_map:
                named = {k: encode_for_npz(v) for k, v in named.items()}
                zf.writestr("paramDtypes.json", json.dumps(dtype_map))
            buf = io.BytesIO()
            np.savez(buf, **named)
            zf.writestr("params.npz", buf.getvalue())
            if saveUpdater:
                import jax

                leaves, _ = jax.tree_util.tree_flatten(model._opt_states)
                uarrs = {str(i): np.asarray(l)
                         for i, l in enumerate(leaves)}
                u_dtypes = {k: str(v.dtype) for k, v in uarrs.items()
                            if v.dtype.kind == "V"}
                if u_dtypes:
                    uarrs = {k: encode_for_npz(v)
                             for k, v in uarrs.items()}
                    zf.writestr("updaterDtypes.json",
                                json.dumps(u_dtypes))
                ubuf = io.BytesIO()
                np.savez(ubuf, **uarrs)
                zf.writestr("updaterState.npz", ubuf.getvalue())
                ts = {"iteration": model._iteration, "epoch": model._epoch}
                prec = getattr(model, "_prec_state", None)
                if prec:
                    # loss-scaler state (ISSUE 4 / resilience ISSUE 5):
                    # a resumed mixed-precision run must keep the warmed
                    # dynamic scale, not restart at init_scale
                    ts["lossScale"] = {
                        k: float(np.asarray(jax.device_get(v)))
                        for k, v in prec.items()}
                zf.writestr("trainingState.json", json.dumps(ts))
            if normalizer is not None:
                nbuf = io.BytesIO()
                np.savez(nbuf, __class__=type(normalizer).__name__,
                         **normalizer._state())
                zf.writestr("normalizer.npz", nbuf.getvalue())

    @staticmethod
    def _restore(path, expect, loadUpdater):
        import jax

        from deeplearning4j_tpu.nn.conf.configuration import (
            MultiLayerConfiguration)
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with zipfile.ZipFile(path) as zf:
            mtype = zf.read("modelType").decode()
            if expect and mtype != expect:
                raise ValueError(f"model file holds a {mtype}, not {expect}")
            conf_json = zf.read("configuration.json").decode()
            if mtype == "ComputationGraph":
                model = ComputationGraph(
                    ComputationGraphConfiguration.from_json(conf_json))
            else:
                model = MultiLayerNetwork(
                    MultiLayerConfiguration.from_json(conf_json))
            model.init()
            from deeplearning4j_tpu.utils.sharded_checkpoint import (
                decode_npz_view, resolve_dtype)

            dtype_map = (json.loads(zf.read("paramDtypes.json"))
                         if "paramDtypes.json" in zf.namelist() else {})
            named = np.load(io.BytesIO(zf.read("params.npz")))
            for key in named.files:
                parts = key.split(_SEP)
                kind, idx, pname = parts[0], parts[1], parts[2]
                raw = named[key]
                if key in dtype_map:
                    raw = decode_npz_view(raw,
                                          resolve_dtype(dtype_map[key]))
                arr = jnp.asarray(raw)
                target = model._params if kind == "p" else model._states
                slot = target[idx if mtype == "ComputationGraph"
                              else int(idx)]
                if len(parts) == 4:  # nested group (Bidirectional)
                    sub = slot.get(pname)
                    if not isinstance(sub, dict):
                        sub = slot[pname] = {}
                    sub[parts[3]] = arr
                else:
                    slot[pname] = arr
            if loadUpdater and "updaterState.npz" in zf.namelist():
                proto_leaves, treedef = jax.tree_util.tree_flatten(
                    model._opt_states)
                u_dtypes = (json.loads(zf.read("updaterDtypes.json"))
                            if "updaterDtypes.json" in zf.namelist()
                            else {})
                data = np.load(io.BytesIO(zf.read("updaterState.npz")))
                leaves = [jnp.asarray(
                    decode_npz_view(data[str(i)],
                                    resolve_dtype(u_dtypes[str(i)]))
                    if str(i) in u_dtypes else data[str(i)])
                          for i in range(len(proto_leaves))]
                model._opt_states = jax.tree_util.tree_unflatten(
                    treedef, leaves)
                ts = json.loads(zf.read("trainingState.json"))
                model._iteration = ts["iteration"]
                model._epoch = ts["epoch"]
                if ts.get("lossScale") and getattr(
                        model, "_prec_state", None):
                    model._prec_state = {
                        k: jnp.asarray(v, model._prec_state[k].dtype)
                        for k, v in ts["lossScale"].items()}
        return model

    @staticmethod
    def _restore_sharded(path, expect, loadUpdater):
        import jax
        import os

        from deeplearning4j_tpu.nn.conf.configuration import (
            MultiLayerConfiguration)
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.utils.sharded_checkpoint import (
            MANIFEST, load_sharded)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with open(os.path.join(path, MANIFEST)) as f:
            meta = json.load(f)["meta"]
        if expect and meta["modelType"] != expect:
            raise ValueError(
                f"checkpoint holds a {meta['modelType']}, not {expect}")
        if meta["modelType"] == "ComputationGraph":
            model = ComputationGraph(
                ComputationGraphConfiguration.from_json(
                    meta["configuration"]))
        else:
            model = MultiLayerNetwork(
                MultiLayerConfiguration.from_json(meta["configuration"]))
        model.init()
        want_updater = loadUpdater and meta.get("saveUpdater")
        # the template must mirror the SAVED tree (incl. updater state
        # even when the caller skips it — it is dropped after load)
        template = {"p": model._params, "s": model._states}
        if meta.get("saveUpdater"):
            template["o"] = model._opt_states
        if meta.get("hasPrecState"):
            # scaler state saved; the template must mirror it even when
            # this model's policy does no scaling (dropped after load)
            template["prec"] = (model._prec_state
                                if getattr(model, "_prec_state", None)
                                else {"scale": np.float32(0),
                                      "good_steps": np.int32(0),
                                      "overflows": np.int32(0)})
        # restore each leaf with the sharding the freshly initialized
        # model gave it (re-shards from any saved topology)
        shardings = jax.tree_util.tree_map(
            lambda l: l.sharding if isinstance(l, jax.Array)
            else jax.sharding.SingleDeviceSharding(jax.devices()[0]),
            template)
        tree, _step, _ = load_sharded(path, template=template,
                                      shardings=shardings)
        model._params, model._states = tree["p"], tree["s"]
        if want_updater:
            model._opt_states = tree["o"]
            ts = meta["trainingState"]
            model._iteration = ts["iteration"]
            model._epoch = ts["epoch"]
            if meta.get("hasPrecState") and getattr(
                    model, "_prec_state", None):
                model._prec_state = {
                    k: jnp.asarray(np.asarray(v),
                                   model._prec_state[k].dtype)
                    for k, v in tree["prec"].items()}
        return model

    @staticmethod
    def restoreMultiLayerNetwork(path, loadUpdater: bool = True,
                                 sharded: bool = False):
        if sharded:
            return ModelSerializer._restore_sharded(
                path, "MultiLayerNetwork", loadUpdater)
        return ModelSerializer._restore(path, "MultiLayerNetwork",
                                        loadUpdater)

    @staticmethod
    def restoreComputationGraph(path, loadUpdater: bool = True,
                                sharded: bool = False):
        if sharded:
            return ModelSerializer._restore_sharded(
                path, "ComputationGraph", loadUpdater)
        return ModelSerializer._restore(path, "ComputationGraph", loadUpdater)

    @staticmethod
    def restoreNormalizerFromFile(path):
        from deeplearning4j_tpu.datasets.normalizers import (
            ImagePreProcessingScaler, NormalizerMinMaxScaler,
            NormalizerStandardize)

        import os
        if os.path.isdir(path):  # sharded checkpoint: meta-held
            from deeplearning4j_tpu.utils.sharded_checkpoint import (
                MANIFEST)

            with open(os.path.join(path, MANIFEST)) as f:
                meta = json.load(f)["meta"]
            nz = meta.get("normalizer")
            if nz is None:
                return None
            cls = {c.__name__: c for c in (
                NormalizerStandardize, NormalizerMinMaxScaler,
                ImagePreProcessingScaler)}[nz["class"]]
            obj = cls.__new__(cls)
            obj._load_state({k: np.asarray(v)
                             for k, v in nz["state"].items()})
            return obj
        with zipfile.ZipFile(path) as zf:
            if "normalizer.npz" not in zf.namelist():
                return None
            z = np.load(io.BytesIO(zf.read("normalizer.npz")),
                        allow_pickle=True)
            cls = {c.__name__: c for c in (
                NormalizerStandardize, NormalizerMinMaxScaler,
                ImagePreProcessingScaler)}[str(z["__class__"])]
            obj = cls.__new__(cls)
            obj._load_state(z)
            return obj

    @staticmethod
    def addNormalizerToModel(path, normalizer):
        # rewrite zip with the normalizer entry added
        with zipfile.ZipFile(path) as zf:
            entries = {n: zf.read(n) for n in zf.namelist()
                       if n != "normalizer.npz"}
        nbuf = io.BytesIO()
        np.savez(nbuf, __class__=type(normalizer).__name__,
                 **normalizer._state())
        entries["normalizer.npz"] = nbuf.getvalue()
        with zipfile.ZipFile(path, "w") as zf:
            for n, data in entries.items():
                zf.writestr(n, data)
