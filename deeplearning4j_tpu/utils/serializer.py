"""Model persistence.

Reference capability: org.deeplearning4j.util.ModelSerializer (SURVEY.md §5
"Checkpoint / resume"): a ZIP holding configuration.json + coefficients
(flat params) + updater state + optional normalizer — the same artifact
shape, so checkpoints carry config + weights + optimizer state in one file.
Params are stored as an npz of per-layer named arrays (canonical restore
source). Pass includeFlatCoefficients=True to additionally write
'coefficients.bin' — a raw little-endian float32 flat vector in
MultiLayerNetwork.params() order for DL4J-artifact-shape compatibility
(doubles the weight payload, so off by default)."""

from __future__ import annotations

import io
import json
import zipfile

import numpy as np
import jax.numpy as jnp


_SEP = "\x1f"  # unit separator: cannot appear in layer names


class ModelSerializer:
    @staticmethod
    def writeModel(model, path, saveUpdater: bool = True, normalizer=None,
                   includeFlatCoefficients: bool = False):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        is_graph = isinstance(model, ComputationGraph)
        with zipfile.ZipFile(path, "w") as zf:
            zf.writestr("configuration.json", model.conf.to_json())
            zf.writestr("modelType",
                        "ComputationGraph" if is_graph
                        else "MultiLayerNetwork")
            if includeFlatCoefficients:
                flat = model.params().toNumpy().astype("<f4")
                zf.writestr("coefficients.bin", flat.tobytes())
            # named per-layer arrays (the canonical restore source)
            named = {}
            if is_graph:
                for name, p in model._params.items():
                    for k, v in p.items():
                        named[_SEP.join(("p", name, k))] = np.asarray(v)
                for name, s in model._states.items():
                    for k, v in s.items():
                        named[_SEP.join(("s", name, k))] = np.asarray(v)
            else:
                for i, p in enumerate(model._params):
                    for k, v in p.items():
                        named[_SEP.join(("p", str(i), k))] = np.asarray(v)
                for i, s in enumerate(model._states):
                    for k, v in s.items():
                        named[_SEP.join(("s", str(i), k))] = np.asarray(v)
            buf = io.BytesIO()
            np.savez(buf, **named)
            zf.writestr("params.npz", buf.getvalue())
            if saveUpdater:
                import jax

                leaves, _ = jax.tree_util.tree_flatten(model._opt_states)
                ubuf = io.BytesIO()
                np.savez(ubuf, **{str(i): np.asarray(l)
                                  for i, l in enumerate(leaves)})
                zf.writestr("updaterState.npz", ubuf.getvalue())
                zf.writestr("trainingState.json", json.dumps({
                    "iteration": model._iteration, "epoch": model._epoch}))
            if normalizer is not None:
                nbuf = io.BytesIO()
                np.savez(nbuf, __class__=type(normalizer).__name__,
                         **normalizer._state())
                zf.writestr("normalizer.npz", nbuf.getvalue())

    @staticmethod
    def _restore(path, expect, loadUpdater):
        import jax

        from deeplearning4j_tpu.nn.conf.configuration import (
            MultiLayerConfiguration)
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with zipfile.ZipFile(path) as zf:
            mtype = zf.read("modelType").decode()
            if expect and mtype != expect:
                raise ValueError(f"model file holds a {mtype}, not {expect}")
            conf_json = zf.read("configuration.json").decode()
            if mtype == "ComputationGraph":
                model = ComputationGraph(
                    ComputationGraphConfiguration.from_json(conf_json))
            else:
                model = MultiLayerNetwork(
                    MultiLayerConfiguration.from_json(conf_json))
            model.init()
            named = np.load(io.BytesIO(zf.read("params.npz")))
            for key in named.files:
                kind, idx, pname = key.split(_SEP, 2)
                arr = jnp.asarray(named[key])
                if mtype == "ComputationGraph":
                    target = model._params if kind == "p" else model._states
                    target[idx][pname] = arr
                else:
                    target = model._params if kind == "p" else model._states
                    target[int(idx)][pname] = arr
            if loadUpdater and "updaterState.npz" in zf.namelist():
                proto_leaves, treedef = jax.tree_util.tree_flatten(
                    model._opt_states)
                data = np.load(io.BytesIO(zf.read("updaterState.npz")))
                leaves = [jnp.asarray(data[str(i)])
                          for i in range(len(proto_leaves))]
                model._opt_states = jax.tree_util.tree_unflatten(
                    treedef, leaves)
                ts = json.loads(zf.read("trainingState.json"))
                model._iteration = ts["iteration"]
                model._epoch = ts["epoch"]
        return model

    @staticmethod
    def restoreMultiLayerNetwork(path, loadUpdater: bool = True):
        return ModelSerializer._restore(path, "MultiLayerNetwork",
                                        loadUpdater)

    @staticmethod
    def restoreComputationGraph(path, loadUpdater: bool = True):
        return ModelSerializer._restore(path, "ComputationGraph", loadUpdater)

    @staticmethod
    def restoreNormalizerFromFile(path):
        from deeplearning4j_tpu.datasets.normalizers import (
            ImagePreProcessingScaler, NormalizerMinMaxScaler,
            NormalizerStandardize)

        with zipfile.ZipFile(path) as zf:
            if "normalizer.npz" not in zf.namelist():
                return None
            z = np.load(io.BytesIO(zf.read("normalizer.npz")),
                        allow_pickle=True)
            cls = {c.__name__: c for c in (
                NormalizerStandardize, NormalizerMinMaxScaler,
                ImagePreProcessingScaler)}[str(z["__class__"])]
            obj = cls.__new__(cls)
            obj._load_state(z)
            return obj

    @staticmethod
    def addNormalizerToModel(path, normalizer):
        # rewrite zip with the normalizer entry added
        with zipfile.ZipFile(path) as zf:
            entries = {n: zf.read(n) for n in zf.namelist()
                       if n != "normalizer.npz"}
        nbuf = io.BytesIO()
        np.savez(nbuf, __class__=type(normalizer).__name__,
                 **normalizer._state())
        entries["normalizer.npz"] = nbuf.getvalue()
        with zipfile.ZipFile(path, "w") as zf:
            for n, data in entries.items():
                zf.writestr(n, data)
