"""Native runtime layer: on-demand compiled C++ ETL kernels + ctypes
bindings.

Reference capability: the reference's C++ runtime tier (libnd4j host
helpers; SURVEY.md §2.1 — its ops AND its ETL loops are native). Here
the device math is XLA-compiled, so the native tier covers host ETL hot
loops (see etl.cpp). pybind11 isn't in the image, so bindings are
ctypes over an `extern "C"` surface; the .so is built with g++ on first
use and cached beside the source (rebuilt when etl.cpp changes).
`available()` reports whether the fast path is live — every call site
falls back to numpy when it isn't."""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "etl.cpp")
_LOCK = threading.Lock()
_LIB = None
_TRIED = False


def _so_path():
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:12]
    return os.path.join(_DIR, f"_etl_{tag}.so")


def _build(so):
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", so]
    subprocess.run(cmd, check=True, capture_output=True, timeout=120)


def _load():
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        try:
            so = _so_path()
            if not os.path.exists(so):
                _build(so)
            lib = ctypes.CDLL(so)
            i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
            i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
            f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
            u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
            lib.sg_pairs.restype = ctypes.c_long
            lib.sg_pairs.argtypes = [i32p, i64p, ctypes.c_int64, i32p,
                                     i32p, i32p]
            lib.csv_parse.restype = ctypes.c_long
            lib.csv_parse.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_char, f32p,
                ctypes.c_int64, ctypes.POINTER(ctypes.c_int64)]
            lib.hwc_to_chw.restype = None
            lib.hwc_to_chw.argtypes = [
                u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int, ctypes.c_float, ctypes.c_float, f32p]
            lib.resize_hwc_to_chw.restype = None
            lib.resize_hwc_to_chw.argtypes = [
                u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
                ctypes.c_float, ctypes.c_float, f32p]
            _LIB = lib
        except Exception:  # toolchain missing/failed -> numpy fallback
            _LIB = None
        return _LIB


def available() -> bool:
    return _load() is not None


def sg_pairs_flat(flat, offsets, bs):
    """sg_pairs over the flat token array + sentence offsets directly
    (no per-sentence Python list): the 10M-word-corpus path."""
    lib = _load()
    if lib is None:
        return None
    flat = np.ascontiguousarray(flat, dtype=np.int32)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    bs = np.ascontiguousarray(bs, dtype=np.int32)
    if len(flat) == 0:
        return (np.zeros(0, np.int32), np.zeros(0, np.int32))
    cap = int(2 * bs.sum())
    centers = np.empty(cap, np.int32)
    contexts = np.empty(cap, np.int32)
    n = lib.sg_pairs(flat, offsets, len(offsets) - 1, bs, centers,
                     contexts)
    return centers[:n].copy(), contexts[:n].copy()


def sg_pairs(encoded_sentences, bs):
    """Skip-gram pairs across sentences. encoded_sentences: list of int32
    arrays; bs: int32 window draws, concatenated per token. Returns
    (centers, contexts) int32 arrays, or None if the native lib is
    unavailable."""
    lib = _load()
    if lib is None:
        return None
    if not encoded_sentences:
        return (np.zeros(0, np.int32), np.zeros(0, np.int32))
    idxs = np.ascontiguousarray(np.concatenate(encoded_sentences),
                                dtype=np.int32)
    offsets = np.zeros(len(encoded_sentences) + 1, np.int64)
    np.cumsum([len(s) for s in encoded_sentences], out=offsets[1:])
    bs = np.ascontiguousarray(bs, dtype=np.int32)
    cap = int(2 * bs.sum())
    centers = np.empty(cap, np.int32)
    contexts = np.empty(cap, np.int32)
    n = lib.sg_pairs(idxs, offsets, len(encoded_sentences), bs, centers,
                     contexts)
    return centers[:n].copy(), contexts[:n].copy()


def csv_parse(text: bytes, delimiter=",") -> np.ndarray | None:
    """Parse a numeric CSV blob -> [rows, cols] float32, or None when the
    native lib is unavailable or the data isn't plain numeric CSV."""
    lib = _load()
    if lib is None:
        return None
    if isinstance(text, str):
        text = text.encode()
    delim = delimiter.encode()[:1]
    cap = max(16, text.count(delim) + text.count(b"\n") + 2)
    out = np.empty(cap, np.float32)
    cols = ctypes.c_int64(0)
    rows = lib.csv_parse(text, len(text), delimiter.encode()[:1], out,
                         cap, ctypes.byref(cols))
    if rows < 0 or cols.value == 0:
        return None
    return out[:rows * cols.value].reshape(rows, cols.value).copy()


def resize_hwc_to_chw(img_u8: np.ndarray, out_h: int, out_w: int,
                      flip_h=False, scale=1.0, shift=0.0):
    """Fused bilinear resize + [H,W,C]u8 -> [C,oh,ow]f32 + affine
    normalize in one native pass, or None when the lib is unavailable."""
    lib = _load()
    if lib is None:
        return None
    img_u8 = np.ascontiguousarray(img_u8, dtype=np.uint8)
    if img_u8.ndim == 2:
        img_u8 = img_u8[:, :, None]
    h, w, c = img_u8.shape
    if h == 0 or w == 0 or out_h <= 0 or out_w <= 0:
        return None  # callers fall back to the Python path's clear error
    dst = np.empty((c, int(out_h), int(out_w)), np.float32)
    lib.resize_hwc_to_chw(img_u8, h, w, c, int(out_h), int(out_w),
                          int(bool(flip_h)), float(scale), float(shift),
                          dst)
    return dst


def hwc_to_chw(img_u8: np.ndarray, flip_h=False, scale=1.0, shift=0.0):
    """[H,W,C] uint8 -> [C,H,W] float32 (optionally h-flipped and affine
    scaled), or None when the native lib is unavailable."""
    lib = _load()
    if lib is None:
        return None
    img_u8 = np.ascontiguousarray(img_u8, dtype=np.uint8)
    if img_u8.ndim == 2:
        img_u8 = img_u8[:, :, None]
    h, w, c = img_u8.shape
    dst = np.empty((c, h, w), np.float32)
    lib.hwc_to_chw(img_u8, h, w, c, int(bool(flip_h)), float(scale),
                   float(shift), dst)
    return dst
