// Native ETL kernels for the host-side data path.
//
// Reference capability: the reference's ETL/runtime tier is C++
// (libnd4j helpers + JavaCPP-wrapped OpenCV/datavec loops, SURVEY.md
// §2.1/§2.4). The TPU compute path here is XLA; this library covers the
// host loops that feed it — the places where a Python for-loop is the
// measured bottleneck:
//   * skip-gram training-pair generation (word2vec: per-token nested
//     window loops over the whole corpus, every epoch)
//   * CSV numeric parsing (record readers)
//   * HWC uint8 -> CHW float image conversion with flip/scale
//     (image pipeline)
// Compiled on demand by deeplearning4j_tpu/native/__init__.py with g++
// (-O3 -shared -fPIC); every caller keeps a pure-numpy fallback, so the
// framework works (slower) without a toolchain.

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// Skip-gram pairs with reference-style reduced windows.
//   idxs      concatenated sentence token ids
//   offsets   n_sent+1 prefix offsets into idxs
//   bs        per-token window draw b ~ U[1, window] (caller's rng keeps
//             determinism identical to the Python path)
//   out_*     capacity >= sum(2*bs[i]) (caller allocates the bound)
// Returns the number of pairs written.
long sg_pairs(const int32_t* idxs, const int64_t* offsets, int64_t n_sent,
              const int32_t* bs, int32_t* out_centers,
              int32_t* out_contexts) {
    long k = 0;
    for (int64_t s = 0; s < n_sent; ++s) {
        const int64_t lo = offsets[s], hi = offsets[s + 1];
        const int64_t n = hi - lo;
        for (int64_t pos = 0; pos < n; ++pos) {
            const int64_t b = bs[lo + pos];
            int64_t jlo = pos - b < 0 ? 0 : pos - b;
            int64_t jhi = pos + b + 1 > n ? n : pos + b + 1;
            const int32_t center = idxs[lo + pos];
            for (int64_t j = jlo; j < jhi; ++j) {
                if (j == pos) continue;
                out_centers[k] = center;
                out_contexts[k] = idxs[lo + j];
                ++k;
            }
        }
    }
    return k;
}

// CSV numeric parse: writes row-major floats, returns the number of rows
// (-1 on ragged rows / capacity overflow). *cols receives the column
// count of the first row. Handles \n and \r\n, skips empty lines; no
// quoting (numeric CSVs only — the Python csv reader stays the general
// path).
long csv_parse(const char* buf, int64_t len, char delim, float* out,
               int64_t max_vals, int64_t* cols) {
    int64_t k = 0;
    long rows = 0;
    int64_t row_cols = 0;
    *cols = 0;
    const char* p = buf;
    const char* end = buf + len;
    while (p < end) {
        // skip blank lines
        while (p < end && (*p == '\n' || *p == '\r')) ++p;
        if (p >= end) break;
        row_cols = 0;
        while (p < end && *p != '\n' && *p != '\r') {
            char* next = nullptr;
            float v = strtof(p, &next);
            if (next == p) return -1;  // not a number
            if (k >= max_vals) return -1;
            out[k++] = v;
            ++row_cols;
            p = next;
            if (p < end && *p == delim) ++p;
        }
        if (*cols == 0) *cols = row_cols;
        else if (row_cols != *cols) return -1;  // ragged
        ++rows;
    }
    return rows;
}

// HWC uint8 -> CHW float32, optional horizontal flip and affine
// y = x * scale + shift (the ImagePreProcessingScaler fuse).
void hwc_to_chw(const uint8_t* src, int64_t h, int64_t w, int64_t c,
                int flip_h, float scale, float shift, float* dst) {
    for (int64_t ch = 0; ch < c; ++ch) {
        float* plane = dst + ch * h * w;
        for (int64_t y = 0; y < h; ++y) {
            const uint8_t* row = src + y * w * c;
            float* drow = plane + y * w;
            if (flip_h) {
                for (int64_t x = 0; x < w; ++x)
                    drow[x] = (float)row[(w - 1 - x) * c + ch] * scale
                              + shift;
            } else {
                for (int64_t x = 0; x < w; ++x)
                    drow[x] = (float)row[x * c + ch] * scale + shift;
            }
        }
    }
}

// Fused bilinear resize + HWC->CHW + affine normalize (+optional h-flip):
// the whole NativeImageLoader.asMatrix hot path in ONE pass over the
// output (reference: NativeImageLoader wraps C++ OpenCV resize/convert,
// SURVEY.md §2.4). src is [h,w,c] uint8, dst is [c,oh,ow] float32.
void resize_hwc_to_chw(const uint8_t* src, int64_t h, int64_t w, int64_t c,
                       int64_t oh, int64_t ow, int flip_h, float scale,
                       float shift, float* dst) {
    if (h <= 0 || w <= 0 || oh <= 0 || ow <= 0 || c <= 0) return;
    // half-pixel centers, classic bilinear (OpenCV INTER_LINEAR
    // semantics — NO antialiasing; PIL's antialiased downscale differs).
    // The numpy fallback (_bilinear_resize_chw) implements the same math.
    const float sy = (float)h / (float)oh;
    const float sx = (float)w / (float)ow;
    for (int64_t y = 0; y < oh; ++y) {
        float fy = ((float)y + 0.5f) * sy - 0.5f;
        if (fy < 0) fy = 0;
        int64_t y0 = (int64_t)fy;
        if (y0 > h - 1) y0 = h - 1;
        int64_t y1 = y0 + 1 < h ? y0 + 1 : h - 1;
        const float wy = fy - (float)y0;
        for (int64_t x = 0; x < ow; ++x) {
            const int64_t xo = flip_h ? ow - 1 - x : x;
            float fx = ((float)x + 0.5f) * sx - 0.5f;
            if (fx < 0) fx = 0;
            int64_t x0 = (int64_t)fx;
            if (x0 > w - 1) x0 = w - 1;
            int64_t x1 = x0 + 1 < w ? x0 + 1 : w - 1;
            const float wx = fx - (float)x0;
            const uint8_t* p00 = src + (y0 * w + x0) * c;
            const uint8_t* p01 = src + (y0 * w + x1) * c;
            const uint8_t* p10 = src + (y1 * w + x0) * c;
            const uint8_t* p11 = src + (y1 * w + x1) * c;
            for (int64_t ch = 0; ch < c; ++ch) {
                const float top = (float)p00[ch] * (1.0f - wx)
                                  + (float)p01[ch] * wx;
                const float bot = (float)p10[ch] * (1.0f - wx)
                                  + (float)p11[ch] * wx;
                dst[ch * oh * ow + y * ow + xo] =
                    (top * (1.0f - wy) + bot * wy) * scale + shift;
            }
        }
    }
}

}  // extern "C"
