from deeplearning4j_tpu.evaluation.classification import (  # noqa: F401
    Evaluation, EvaluationBinary, ROC, ROCBinary, ROCMultiClass)
from deeplearning4j_tpu.evaluation.regression import (  # noqa: F401
    RegressionEvaluation)
from deeplearning4j_tpu.evaluation.calibration import (  # noqa: F401
    EvaluationCalibration, ReliabilityDiagram)
