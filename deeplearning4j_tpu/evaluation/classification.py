"""Classification evaluation.

Reference capability: org.nd4j.evaluation.classification.{Evaluation,
EvaluationBinary, ROC, ROCMultiClass} (SURVEY.md §2.3 "Evaluation").
Accumulation is a confusion-matrix merge per eval(labels, predictions)
call — device math is a couple of argmax/scatter ops; the stats() report
is host-side formatting.
"""

from __future__ import annotations

import numpy as np


def _to_np(x):
    a = np.asarray(x.numpy()) if hasattr(x, "numpy") else np.asarray(x)
    # upcast sub-fp32 floats (bfloat16 / float16 eval outputs) BEFORE any
    # accumulation: ROC cumsums and binary-count sums lose counts past
    # the narrow mantissa on long iterators (ISSUE 4 satellite)
    # (ml_dtypes types report numpy kind 'V'; plain float16 is 'f'/2)
    if a.dtype.itemsize < 4 and a.dtype.kind in ("f", "V"):
        a = a.astype(np.float32)
    return a


def _class_indices(arr):
    a = _to_np(arr)
    if a.ndim >= 2 and a.shape[-1] > 1:
        return np.argmax(a, axis=-1).reshape(-1)
    return a.reshape(-1).astype(np.int64)


class Evaluation:
    """Multiclass accuracy/precision/recall/F1 + confusion matrix."""

    def __init__(self, numClasses=None, labelsList=None):
        self.numClasses = numClasses
        self.labelsList = labelsList
        self._conf = None if numClasses is None else np.zeros(
            (numClasses, numClasses), np.int64)

    # -- accumulation --------------------------------------------------------
    def eval(self, labels, predictions, mask=None):
        labels = _to_np(labels)
        predictions = _to_np(predictions)
        if labels.ndim == 3:
            # [N, C, T] time series -> fold time into batch
            labels = np.moveaxis(labels, 2, 1).reshape(-1, labels.shape[1])
            predictions = np.moveaxis(predictions, 2, 1).reshape(
                -1, predictions.shape[1])
        t = _class_indices(labels)
        p = _class_indices(predictions)
        if mask is not None:
            m = _to_np(mask).reshape(-1).astype(bool)
            t, p = t[m], p[m]
        # grow past a fixed numClasses too: an out-of-range class index
        # must widen the matrix, not crash np.add.at with an IndexError
        n = max(self.numClasses or 0,
                int(max(t.max(initial=0), p.max(initial=0))) + 1)
        if self._conf is None or n > self._conf.shape[0]:
            conf = np.zeros((n, n), np.int64)
            if self._conf is not None:
                conf[: self._conf.shape[0], : self._conf.shape[1]] = self._conf
            self._conf = conf
            self.numClasses = n
        np.add.at(self._conf, (t, p), 1)
        return self

    # -- metrics -------------------------------------------------------------
    def _require(self):
        if self._conf is None:
            raise ValueError("no data accumulated; call eval() first")
        return self._conf

    def accuracy(self):
        c = self._require()
        tot = c.sum()
        return float(np.trace(c) / tot) if tot else 0.0

    def _tp(self):
        return np.diag(self._require()).astype(np.float64)

    def precision(self, cls=None):
        c = self._require()
        col = c.sum(axis=0).astype(np.float64)
        per = np.divide(self._tp(), col, out=np.zeros_like(col),
                        where=col > 0)
        return float(per[cls]) if cls is not None else float(
            per[col > 0].mean() if (col > 0).any() else 0.0)

    def recall(self, cls=None):
        c = self._require()
        row = c.sum(axis=1).astype(np.float64)
        per = np.divide(self._tp(), row, out=np.zeros_like(row),
                        where=row > 0)
        return float(per[cls]) if cls is not None else float(
            per[row > 0].mean() if (row > 0).any() else 0.0)

    def f1(self, cls=None):
        p = self.precision(cls)
        r = self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0

    def falsePositiveRate(self, cls):
        c = self._require()
        fp = c[:, cls].sum() - c[cls, cls]
        tn = c.sum() - c[cls, :].sum() - c[:, cls].sum() + c[cls, cls]
        return float(fp / (fp + tn)) if (fp + tn) else 0.0

    def confusionMatrix(self):
        return self._require().copy()

    def getNumRowCounter(self):
        return int(self._require().sum())

    def stats(self) -> str:
        c = self._require()
        n = c.shape[0]
        names = list(self.labelsList or [])
        # the matrix may have grown past the provided labels list (an
        # out-of-range class index widens it); pad names to match
        names += [str(i) for i in range(len(names), n)]
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes:    {n}",
            f" Accuracy:        {self.accuracy():.4f}",
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
            "",
            "=========================Confusion Matrix=========================",
        ]
        width = max(len(nm) for nm in names) + 2
        header = " " * width + " ".join(f"{i:>6d}" for i in range(n))
        lines.append(header)
        for i in range(n):
            row = " ".join(f"{c[i, j]:>6d}" for j in range(n))
            lines.append(f"{names[i]:<{width}}{row}")
        return "\n".join(lines)

    def __str__(self):
        return self.stats()


class EvaluationBinary:
    """Per-output independent binary evaluation (sigmoid outputs)."""

    def __init__(self, nOutputs=None, threshold=0.5):
        self.threshold = threshold
        self._tp = self._fp = self._tn = self._fn = None

    def eval(self, labels, predictions, mask=None):
        t = _to_np(labels)
        p = (_to_np(predictions) >= self.threshold).astype(np.int64)
        t = (t >= 0.5).astype(np.int64)
        if self._tp is None:
            k = t.shape[-1]
            self._tp = np.zeros(k, np.int64)
            self._fp = np.zeros(k, np.int64)
            self._tn = np.zeros(k, np.int64)
            self._fn = np.zeros(k, np.int64)
        self._tp += ((p == 1) & (t == 1)).sum(axis=0)
        self._fp += ((p == 1) & (t == 0)).sum(axis=0)
        self._tn += ((p == 0) & (t == 0)).sum(axis=0)
        self._fn += ((p == 0) & (t == 1)).sum(axis=0)
        return self

    def accuracy(self, i):
        tot = self._tp[i] + self._fp[i] + self._tn[i] + self._fn[i]
        return float((self._tp[i] + self._tn[i]) / tot) if tot else 0.0

    def precision(self, i):
        d = self._tp[i] + self._fp[i]
        return float(self._tp[i] / d) if d else 0.0

    def recall(self, i):
        d = self._tp[i] + self._fn[i]
        return float(self._tp[i] / d) if d else 0.0

    def f1(self, i):
        p, r = self.precision(i), self.recall(i)
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0

    def stats(self):
        k = len(self._tp)
        lines = ["Label  Acc     Precision  Recall   F1"]
        for i in range(k):
            lines.append(f"{i:<6d} {self.accuracy(i):<7.4f} "
                         f"{self.precision(i):<10.4f} {self.recall(i):<8.4f} "
                         f"{self.f1(i):.4f}")
        return "\n".join(lines)


class ROC:
    """Binary ROC / AUC / AUPRC with exact thresholding (thresholdSteps=0
    semantics of the reference: every distinct score is a threshold)."""

    def __init__(self, thresholdSteps=0):
        self.thresholdSteps = thresholdSteps
        self._scores = []
        self._labels = []

    def eval(self, labels, predictions, mask=None):
        lab = _to_np(labels)
        pred = _to_np(predictions)
        if lab.ndim >= 2 and lab.shape[-1] == 2:
            lab = lab[..., 1]
            pred = pred[..., 1]
        self._labels.append(lab.reshape(-1))
        self._scores.append(pred.reshape(-1))
        return self

    def _collect(self):
        y = np.concatenate(self._labels)
        s = np.concatenate(self._scores)
        return y, s

    def calculateAUC(self):
        y, s = self._collect()
        order = np.argsort(-s, kind="stable")
        y = y[order]
        tps = np.cumsum(y)
        fps = np.cumsum(1 - y)
        P, N = tps[-1], fps[-1]
        if P == 0 or N == 0:
            return 0.0
        tpr = np.concatenate([[0], tps / P])
        fpr = np.concatenate([[0], fps / N])
        return float(np.trapezoid(tpr, fpr))

    def calculateAUCPR(self):
        y, s = self._collect()
        order = np.argsort(-s, kind="stable")
        y = y[order]
        tps = np.cumsum(y)
        P = tps[-1]
        if P == 0:
            return 0.0
        prec = tps / np.arange(1, len(y) + 1)
        rec = tps / P
        return float(np.trapezoid(prec, rec))


class ROCMultiClass:
    def __init__(self, thresholdSteps=0):
        self._rocs: dict[int, ROC] = {}

    def eval(self, labels, predictions, mask=None):
        lab = _to_np(labels)
        pred = _to_np(predictions)
        for c in range(lab.shape[-1]):
            self._rocs.setdefault(c, ROC()).eval(lab[..., c], pred[..., c])
        return self

    def calculateAUC(self, cls):
        return self._rocs[cls].calculateAUC()

    def calculateAverageAUC(self):
        return float(np.mean([r.calculateAUC() for r in self._rocs.values()]))


class ROCBinary:
    """Per-output ROC for MULTI-LABEL binary outputs [N, nOut] (reference:
    org.nd4j.evaluation.classification.ROCBinary — one ROC per sigmoid
    output, vs ROC's single binary problem)."""

    def __init__(self, thresholdSteps=0):
        self.thresholdSteps = thresholdSteps
        self._rocs: dict[int, ROC] = {}

    def eval(self, labels, predictions, mask=None):
        lab = _to_np(labels)
        pred = _to_np(predictions)
        if lab.ndim == 1:
            lab = lab[:, None]
            pred = pred[:, None]
        m = None if mask is None else _to_np(mask)
        if lab.ndim == 3:
            # DL4J time series [N, nOut, T]: fold time into the batch so
            # the per-OUTPUT axis stays axis -1. A [N, T] mask folds to
            # per-example; a [N, nOut, T] mask folds to per-output.
            lab = lab.transpose(0, 2, 1).reshape(-1, lab.shape[1])
            pred = pred.transpose(0, 2, 1).reshape(-1, pred.shape[1])
            if m is not None:
                m = (m.transpose(0, 2, 1).reshape(-1, m.shape[1])
                     if m.ndim == 3 else m.reshape(-1))
        for i in range(lab.shape[-1]):
            li, pi = lab[..., i].reshape(-1), pred[..., i].reshape(-1)
            if m is not None:
                # per-output mask [N, nOut] selects its column; a
                # per-example mask [N] applies to every output
                mi = m[..., i] if m.ndim == lab.ndim else m
                keep = mi.reshape(-1) > 0
                li, pi = li[keep], pi[keep]
            self._rocs.setdefault(i, ROC(self.thresholdSteps)).eval(li, pi)
        return self

    def numLabels(self):
        return len(self._rocs)

    def calculateAUC(self, outputNum):
        return self._rocs[outputNum].calculateAUC()

    def calculateAUCPR(self, outputNum):
        return self._rocs[outputNum].calculateAUCPR()

    def calculateAverageAUC(self):
        if not self._rocs:
            return 0.0
        return float(np.mean([r.calculateAUC()
                              for r in self._rocs.values()]))

    def stats(self):
        lines = ["ROCBinary (per-output AUC / AUCPR)"]
        for i, r in sorted(self._rocs.items()):
            lines.append(f"  out {i}: AUC {r.calculateAUC():.4f}  "
                         f"AUCPR {r.calculateAUCPR():.4f}")
        return "\n".join(lines)
