"""Regression evaluation (reference: org.nd4j.evaluation.regression.
RegressionEvaluation, SURVEY.md §2.3): per-column MSE/MAE/RMSE/RSE/PC/R2
accumulated across eval() calls via sufficient statistics."""

from __future__ import annotations

import numpy as np


def _to_np(x):
    a = np.asarray(x.numpy()) if hasattr(x, "numpy") else np.asarray(x)
    # upcast sub-fp32 floats (bfloat16/float16 outputs; ml_dtypes report
    # numpy kind 'V') BEFORE the per-batch sums: squaring and summing in
    # bf16 loses MSE precision on long iterators (ISSUE 4 satellite) —
    # the cross-batch accumulators below are float64 already
    if a.dtype.itemsize < 4 and a.dtype.kind in ("f", "V"):
        a = a.astype(np.float32)
    return a


class RegressionEvaluation:
    def __init__(self, nColumns=None, columnNames=None):
        self.columnNames = columnNames
        self._n = 0
        self._sum_err2 = None   # sum (p-l)^2
        self._sum_abs = None    # sum |p-l|
        self._sum_l = None
        self._sum_l2 = None
        self._sum_p = None
        self._sum_p2 = None
        self._sum_lp = None

    def eval(self, labels, predictions, mask=None):
        l = _to_np(labels)
        p = _to_np(predictions)
        if l.ndim == 3:
            # [N, C, T] (NCW layout) -> fold time into batch, C columns
            l = np.moveaxis(l, 2, 1).reshape(-1, l.shape[1])
            p = np.moveaxis(p, 2, 1).reshape(-1, p.shape[1])
        else:
            l = l.reshape(-1, l.shape[-1])
            p = p.reshape(-1, p.shape[-1])
        if self._sum_err2 is None:
            k = l.shape[1]
            for name in ("_sum_err2", "_sum_abs", "_sum_l", "_sum_l2",
                         "_sum_p", "_sum_p2", "_sum_lp"):
                setattr(self, name, np.zeros(k))
        self._n += l.shape[0]
        self._sum_err2 += ((p - l) ** 2).sum(axis=0)
        self._sum_abs += np.abs(p - l).sum(axis=0)
        self._sum_l += l.sum(axis=0)
        self._sum_l2 += (l ** 2).sum(axis=0)
        self._sum_p += p.sum(axis=0)
        self._sum_p2 += (p ** 2).sum(axis=0)
        self._sum_lp += (l * p).sum(axis=0)
        return self

    def meanSquaredError(self, col=0):
        return float(self._sum_err2[col] / self._n)

    def meanAbsoluteError(self, col=0):
        return float(self._sum_abs[col] / self._n)

    def rootMeanSquaredError(self, col=0):
        return float(np.sqrt(self._sum_err2[col] / self._n))

    def relativeSquaredError(self, col=0):
        ss_tot = self._sum_l2[col] - self._sum_l[col] ** 2 / self._n
        return float(self._sum_err2[col] / ss_tot) if ss_tot else 0.0

    def pearsonCorrelation(self, col=0):
        n = self._n
        cov = self._sum_lp[col] - self._sum_l[col] * self._sum_p[col] / n
        vl = self._sum_l2[col] - self._sum_l[col] ** 2 / n
        vp = self._sum_p2[col] - self._sum_p[col] ** 2 / n
        d = np.sqrt(vl * vp)
        return float(cov / d) if d else 0.0

    def rSquared(self, col=0):
        return 1.0 - self.relativeSquaredError(col)

    def averageMeanSquaredError(self):
        return float((self._sum_err2 / self._n).mean())

    def averagerootMeanSquaredError(self):
        return float(np.sqrt(self._sum_err2 / self._n).mean())

    def averageMeanAbsoluteError(self):
        return float((self._sum_abs / self._n).mean())

    def stats(self):
        k = len(self._sum_err2)
        names = self.columnNames or [f"col_{i}" for i in range(k)]
        lines = ["Column    MSE        MAE        RMSE       RSE        R^2"]
        for i in range(k):
            lines.append(
                f"{names[i]:<9} {self.meanSquaredError(i):<10.5f} "
                f"{self.meanAbsoluteError(i):<10.5f} "
                f"{self.rootMeanSquaredError(i):<10.5f} "
                f"{self.relativeSquaredError(i):<10.5f} "
                f"{self.rSquared(i):.5f}")
        return "\n".join(lines)
