"""Probability-calibration evaluation.

Reference capability: org.deeplearning4j.eval.EvaluationCalibration
(SURVEY.md §2.3 evaluation row): reliability diagrams (mean predicted
probability vs observed positive fraction per bin), residual plots and
probability histograms over network outputs. Accumulation is streaming
numpy (eval per batch, merge-able), like the other evaluation classes."""

from __future__ import annotations

import numpy as np


class ReliabilityDiagram:
    def __init__(self, mean_predicted, frac_positives, counts):
        self.meanPredictedValueX = np.asarray(mean_predicted)
        self.fractionPositivesY = np.asarray(frac_positives)
        self.binCounts = np.asarray(counts)

    def getMeanPredictedValueX(self):
        return self.meanPredictedValueX

    def getFractionPositivesY(self):
        return self.fractionPositivesY


class EvaluationCalibration:
    def __init__(self, reliabilityDiagNumBins=10, histogramNumBins=50):
        self.rBins = int(reliabilityDiagNumBins)
        self.hBins = int(histogramNumBins)
        self._num_classes = None
        # per class, per reliability bin: sum(p), count, positives
        self._sum_p = None
        self._count = None
        self._pos = None
        self._prob_hist = None       # all predicted probabilities
        self._label_hist = None      # probabilities of the true class
        self._residual_hist = None   # |label - p|

    def _ensure(self, n_classes):
        if self._num_classes is None:
            self._num_classes = n_classes
            self._sum_p = np.zeros((n_classes, self.rBins))
            self._count = np.zeros((n_classes, self.rBins), np.int64)
            self._pos = np.zeros((n_classes, self.rBins), np.int64)
            self._prob_hist = np.zeros(self.hBins, np.int64)
            self._label_hist = np.zeros(self.hBins, np.int64)
            self._residual_hist = np.zeros(self.hBins, np.int64)
        elif self._num_classes != n_classes:
            raise ValueError(
                f"class count changed: {self._num_classes} -> {n_classes}")

    def eval(self, labels, predictions, mask=None):
        """labels: one-hot [N, C]; predictions: probabilities [N, C];
        mask: optional per-example [N] (0 = exclude, the padded-batch
        convention shared with the other evaluators)."""
        labels = np.asarray(labels, np.float64)
        p = np.asarray(predictions, np.float64)
        if labels.shape != p.shape or labels.ndim != 2:
            raise ValueError(f"shapes must match and be 2-D, got "
                             f"{labels.shape} vs {p.shape}")
        if mask is not None:
            keep = np.asarray(mask).reshape(-1) > 0
            labels, p = labels[keep], p[keep]
        n, c = p.shape
        self._ensure(c)
        bins = np.clip((p * self.rBins).astype(np.int64), 0, self.rBins - 1)
        is_pos = labels > 0.5
        # one scatter per accumulator over the flattened (class, bin)
        # index — no per-class Python loop
        flat = (np.arange(c)[None, :] * self.rBins + bins).ravel()
        np.add.at(self._sum_p.reshape(-1), flat, p.ravel())
        np.add.at(self._count.reshape(-1), flat, 1)
        np.add.at(self._pos.reshape(-1), flat,
                  is_pos.astype(np.int64).ravel())
        hb = np.clip((p * self.hBins).astype(np.int64), 0, self.hBins - 1)
        np.add.at(self._prob_hist, hb.ravel(), 1)
        true_p = p[is_pos]
        np.add.at(self._label_hist,
                  np.clip((true_p * self.hBins).astype(np.int64), 0,
                          self.hBins - 1), 1)
        resid = np.abs(labels - p)
        np.add.at(self._residual_hist,
                  np.clip((resid * self.hBins).astype(np.int64), 0,
                          self.hBins - 1).ravel(), 1)
        return self

    def merge(self, other: "EvaluationCalibration"):
        if other._num_classes is None:
            return self
        if (self.rBins, self.hBins) != (other.rBins, other.hBins):
            raise ValueError(
                f"bin configuration mismatch: ({self.rBins}, {self.hBins})"
                f" vs ({other.rBins}, {other.hBins})")
        self._ensure(other._num_classes)
        self._sum_p += other._sum_p
        self._count += other._count
        self._pos += other._pos
        self._prob_hist += other._prob_hist
        self._label_hist += other._label_hist
        self._residual_hist += other._residual_hist
        return self

    def getReliabilityDiagram(self, classIdx) -> ReliabilityDiagram:
        if self._num_classes is None:
            raise ValueError("no data evaluated")
        cnt = self._count[classIdx]
        nz = cnt > 0
        mean_p = np.zeros(self.rBins)
        frac = np.zeros(self.rBins)
        mean_p[nz] = self._sum_p[classIdx][nz] / cnt[nz]
        frac[nz] = self._pos[classIdx][nz] / cnt[nz]
        return ReliabilityDiagram(mean_p[nz], frac[nz], cnt[nz])

    def expectedCalibrationError(self, classIdx=None) -> float:
        """ECE = sum_b (n_b/N) |acc_b - conf_b| (macro-averaged over
        classes when classIdx is None)."""
        if self._num_classes is None:
            raise ValueError("no data evaluated")
        idxs = ([classIdx] if classIdx is not None
                else range(self._num_classes))
        eces = []
        for ci in idxs:
            cnt = self._count[ci]
            total = cnt.sum()
            if total == 0:
                continue
            nz = cnt > 0
            conf = self._sum_p[ci][nz] / cnt[nz]
            acc = self._pos[ci][nz] / cnt[nz]
            eces.append(float(np.sum(cnt[nz] / total * np.abs(acc - conf))))
        return float(np.mean(eces)) if eces else 0.0

    def getProbabilityHistogramAllClasses(self):
        return np.asarray(self._prob_hist)

    def getProbabilityHistogram(self):
        """Histogram of predicted probability for the TRUE class."""
        return np.asarray(self._label_hist)

    def getResidualPlotAllClasses(self):
        return np.asarray(self._residual_hist)

    def stats(self) -> str:
        return (f"EvaluationCalibration(classes={self._num_classes}, "
                f"ECE={self.expectedCalibrationError():.4f})")
