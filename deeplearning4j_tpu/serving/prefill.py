"""Chunked prefill: the second decode executable (ISSUE 12 tentpole a).

PR 8's `DecodeEngine` prefills prompts through the per-token step
executable — one prompt token per engine boundary, so a 2k-token
prompt pays 2k boundaries of host bookkeeping (and 2k dispatches)
before emitting anything. `ChunkedPrefill` compiles ONE more
executable with shape ``[max_slots, chunk]`` that retires prompt
tokens in fixed-size blocks: time-to-first-token drops from
O(prompt_len) boundaries to O(prompt_len / chunk), while in-flight
decodes keep streaming through the unchanged per-token executable at
every boundary (the engine runs the prefill dispatch first, then the
token step — prefilling and decoding slots interleave, Dragon-Alpha's
lean-kernel-set discipline: one block executable, not a kernel per
feature).

Bit-identity is the correctness bar, and it is held BY CONSTRUCTION:
the block executable's body is a ``lax.fori_loop`` over the SAME
masked single-token function the step executable runs (`masked_fn` on
the decode models), at the same ``[max_slots]`` shapes — position j of
a chunk computes exactly what the per-token path would have computed
at that boundary, so the engine's output for a chunked prompt equals
the offline single-request decode loop token for token (asserted for
a >=512-token prompt in tests).

Masking: ``counts[s]`` is how many of slot s's block tokens are real.
Iterations past a slot's count route their KV-pool writes to scratch
page 0 and keep RNN carries via ``jnp.where`` — an idle or decoding
slot passes through a prefill dispatch bit-unchanged, the same
invariant the token step already holds for idle slots.

The same class doubles as the SPECULATIVE VERIFIER (tentpole c): a
``[max_slots, k+1]`` block of draft tokens through `run()` returns the
target's next-token argmax at every position in one batched call —
the per-shape jit cache means chunk-prefill and verify are two
executables of one traced function (or ONE executable when
``chunk == k + 1``).
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.telemetry import compile_ledger


class ChunkedPrefill:
    """``[max_slots, width]`` block executable over a decode model's
    masked token step. One instance serves every block width (the jit
    cache keys on the block shape); the engine warms the widths it
    will use so steady state never compiles."""

    def __init__(self, model, chunk):
        import jax

        if int(chunk) < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.model = model
        self.chunk = int(chunk)
        self._jit = jax.jit(self._fn)
        # ISSUE 20: the prefill/verify executable rides the persistent
        # store like the token step (unsharded lane only; identity
        # when the store is off or the model has no program digest)
        # (the block width rides the per-signature key, not the
        # program: prefill and verify instances share store entries)
        if getattr(model, "_store_program", None) is not None:
            from deeplearning4j_tpu.serving.decode import _maybe_store

            self._jit = _maybe_store(self._jit, "decode:prefill",
                                     model, "prefill")

    def _fn(self, params, state, blocks, pos0, counts, table):
        import jax.numpy as jnp
        from jax import lax

        S, V = blocks.shape

        def body(j, carry):
            state, outs = carry
            active = j < counts
            pos = jnp.where(active, pos0 + j, 0)
            nxt, state = self.model.masked_fn(
                params, state, blocks[:, j], pos, table, active)
            outs = outs.at[:, j].set(jnp.where(active, nxt, -1))
            return state, outs

        outs0 = jnp.full((S, V), -1, jnp.int32)
        state, outs = lax.fori_loop(0, V, body, (state, outs0))
        return outs, state

    def run(self, state, blocks, pos0, counts, table, site=None):
        """Consume ``counts[s]`` tokens of ``blocks[s]`` per slot
        starting at ``pos0[s]``. Returns ``(outs, state)`` where
        ``outs[s, j]`` is the model's next-token argmax after consuming
        block token j (-1 past a slot's count) — ignored by prefill,
        consumed by speculative verify."""
        args = (self.model.params_for_step(), state,
                np.ascontiguousarray(blocks, dtype=np.int32),
                np.ascontiguousarray(pos0, dtype=np.int32),
                np.ascontiguousarray(counts, dtype=np.int32), table)
        outs, state = self._jit(*args)
        if site is not None:
            compile_ledger.note_step(site, self._jit, args, donation=())
        return np.asarray(outs), state

    def warmup(self, state, table, widths=None, site=None):
        """Compile every block width the engine will dispatch (all
        counts zero: the engine state rides through untouched except
        scratch)."""
        S = self.model.max_slots
        z = np.zeros((S,), np.int32)
        for width in (widths or (self.chunk,)):
            self.run(state, np.zeros((S, int(width)), np.int32), z, z,
                     table, site=site)
        return self
