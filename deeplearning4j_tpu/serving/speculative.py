"""Speculative decoding over the paged decode engine (ISSUE 12
tentpole c).

A small DRAFT model proposes ``k`` tokens per boundary; the TARGET
verifies all of them in ONE batched call through the same
``[max_slots, width]`` block executable chunked prefill compiled
(`serving/prefill.py`) — no verifier-specific kernel, Dragon-Alpha's
lean-kernel discipline. Greedy equivalence is exact, not sampled:
the verify outputs ``o_j`` are the target's own argmax after
consuming the fed prefix, so the engine emits ``o_0`` (always — it is
the target's answer to the real last token) and then each ``o_j``
whose draft proposal matched ``o_{j-1}``; the emitted stream is the
target-only greedy stream token for token (asserted in tests).

Rejected positions need no rollback anywhere: both lanes' KV pools
are POSITIONAL — writes past the accepted point sit above the causal
length mask until the true tokens overwrite them at the same
positions, and the draft's accepted-prefix writes are exactly right
because matching is what acceptance means.

The draft lane is a full mirror of the target's plumbing: its own
`PagedKVCache` (refcounted), its own `PrefixCache` when the engine
caches prefixes, the same chunk executable shape for prompt prefill,
and a masked single-token step so proposals for decoding slots never
touch a slot that is still prefilling.

Acceptance-rate fallback: an EWMA of the per-boundary draft
acceptance rate; when it collapses below ``min_acceptance`` the
engine falls back to plain decode (the draft lane keeps tracking
emitted tokens so its state stays alignable), probing speculation
again every ``probe_every`` boundaries.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from deeplearning4j_tpu.serving.prefill import ChunkedPrefill
from deeplearning4j_tpu.serving.prefix_cache import (
    PrefixCache, apply_admission, plan_admission)
from deeplearning4j_tpu.telemetry import flight


@dataclass
class SpeculativeConfig:
    """draft: a paged decode model (same vocab and max_slots as the
    target; typically far smaller). k: draft proposals per boundary
    (the verify block is ``k + 1`` wide). min_acceptance: EWMA
    draft-acceptance floor below which the engine falls back to plain
    decode; probe_every: boundaries between speculation probes while
    in fallback."""

    draft: object
    k: int = 4
    min_acceptance: float = 0.35
    ewma_alpha: float = 0.25
    warmup_boundaries: int = 8
    probe_every: int = 64


class SpeculativeDecoder:
    """The engine-side draft lane + acceptance bookkeeping."""

    def __init__(self, cfg: SpeculativeConfig, chunk, name,
                 prefix_cache=False):
        from deeplearning4j_tpu.serving.decode import (DecodeError,
                                                       PagedKVCache)

        model = cfg.draft
        if not getattr(model, "uses_pages", False):
            raise DecodeError(
                "speculative decoding needs a paged draft model "
                "(positional KV state is what makes rejected draft "
                "writes free to roll back)")
        if int(cfg.k) < 1:
            raise DecodeError(f"speculative k must be >= 1, got {cfg.k}")
        self.cfg = cfg
        self.model = model
        self.name = name
        self.k = int(cfg.k)
        self._kv = PagedKVCache(model.n_pages, model.page,
                                model.max_pages_per_slot,
                                model.max_slots)
        self._pcache = PrefixCache(model.page) if prefix_cache else None
        self._state = model.init_state()
        from deeplearning4j_tpu.telemetry import memledger

        # the draft lane's pinned pool bytes: health() reports them
        # beside the target's, and the engine claims them (ISSUE 14)
        self.pool_bytes = memledger.tree_bytes(self._state)
        self._block = ChunkedPrefill(model, chunk)
        self._ewma = None
        self._boundaries = 0
        self._fallback = False
        self._since_probe = 0
        # per-slot publishable chain depth: when the draft adopted a
        # SHALLOWER prefix than the target skipped, the draft pages in
        # between were never written (the mirrored prefill starts at
        # the target's adopted length) — publishing them would cache
        # garbage KV under valid keys
        self._publish_depth: dict = {}

    # -- page lane (mirrors the engine's target lane) ------------------------
    def plan(self, prompt, total_len, max_adopt):
        """Draft-lane admission plan; ``max_adopt`` caps adoption at
        the target lane's adopted depth — the draft must never adopt
        deeper than the target skips, or the engine's suffix prefill
        would write into shared draft pages."""
        return plan_admission(self._kv, self._pcache, prompt, total_len,
                              max_adopt=max_adopt)

    def admit(self, slot, total_len, plan, target_adopted=0):
        adopted = apply_admission(self._kv, self._pcache, plan, slot,
                                  total_len)
        # draft pages [adopted, target_adopted) are a HOLE: the engine
        # prefills both lanes from the target's adopted length, so
        # only the adopted prefix is publishable when it falls short
        self._publish_depth[slot] = (None if adopted >= target_adopted
                                     else adopted)
        return adopted

    def release(self, slot):
        self._kv.release(slot)
        self._publish_depth.pop(slot, None)

    def publish(self, prompt, slot):
        if self._pcache is None:
            return
        n_full = len(prompt) // self._kv.page
        depth = self._publish_depth.get(slot)
        if depth is not None:
            n_full = min(n_full, depth)
        owned = self._kv.owned(slot)
        if n_full and len(owned) >= n_full:
            self._pcache.publish(self._kv, prompt, owned[:n_full])

    def clear_prefix_cache(self):
        return (self._pcache.clear(self._kv)
                if self._pcache is not None else 0)

    # -- device calls --------------------------------------------------------
    def _table(self):
        # real copy: admit/release mutate the table while a draft
        # dispatch may still be in flight (jax can alias numpy)
        return self._kv.table.copy()

    def prefill(self, blocks, pos0, counts):
        """Mirror a target chunk-prefill dispatch on the draft lane."""
        _, self._state = self._block.run(
            self._state, blocks, pos0, counts, self._table(),
            site=f"decode:{self.name}:draft_prefill")

    def propose(self, feed, pos, active):
        """k greedy draft proposals per active slot: [S, k] int32.
        Proposal j is written into the draft pool at ``pos + j`` —
        exactly the positions verify consumes, so an accepted prefix
        leaves the draft state already correct."""
        S = feed.shape[0]
        out = np.zeros((S, self.k), np.int32)
        toks = np.ascontiguousarray(feed, np.int32)
        table = self._table()
        state = self._state
        for j in range(self.k):
            nxt, state = self.model.step_masked(
                state, toks, np.ascontiguousarray(pos + j, np.int32),
                table, active, site=f"decode:{self.name}:draft_step")
            toks = np.asarray(nxt)
            out[:, j] = toks
        self._state = state
        return out

    def track(self, tokens, pos, active):
        """Keep the draft pool in sync while the engine runs plain
        boundaries (fallback), so a later probe proposes from real
        context instead of holes."""
        _, self._state = self.model.step_masked(
            self._state, tokens, pos, self._table(), active,
            site=f"decode:{self.name}:draft_step")

    def warmup(self):
        S = self.model.max_slots
        z = np.zeros((S,), np.int32)
        off = np.zeros((S,), bool)
        self.model.step_masked(self._state, z, z, self._table(), off,
                               site=f"decode:{self.name}:draft_step")
        self._block.warmup(self._state, self._table(),
                           site=f"decode:{self.name}:draft_prefill")
        return self

    # -- acceptance / fallback ----------------------------------------------
    def observe(self, accepted, fed):
        """One slot's verify outcome: ``accepted`` of ``fed`` block
        tokens emitted. The free token (o_0) is excluded from the
        rate — it measures the DRAFT, not the verifier."""
        if fed <= 1:
            return
        rate = (accepted - 1) / (fed - 1)
        a = self.cfg.ewma_alpha
        self._ewma = rate if self._ewma is None else \
            a * rate + (1.0 - a) * self._ewma

    def boundary_done(self):
        self._boundaries += 1
        if self._boundaries < self.cfg.warmup_boundaries or \
                self._ewma is None:
            return
        collapsed = self._ewma < self.cfg.min_acceptance
        if collapsed and not self._fallback:
            flight.record("speculation_fallback", model=self.name,
                          acceptance=round(self._ewma, 4),
                          boundary=self._boundaries)
        elif self._fallback and not collapsed:
            flight.record("speculation_resume", model=self.name,
                          acceptance=round(self._ewma, 4),
                          boundary=self._boundaries)
        self._fallback = collapsed
        if collapsed:
            self._since_probe = 0

    def speculate_now(self) -> bool:
        """Whether this boundary should draft+verify (True) or run the
        plain token step (False, fallback). While fallen back, every
        ``probe_every``-th boundary speculates once to re-measure."""
        if not self._fallback:
            return True
        self._since_probe += 1
        if self._since_probe >= self.cfg.probe_every:
            self._since_probe = 0
            return True
        return False

    def health(self) -> dict:
        out = {"fallback": self._fallback,
               "acceptance_ewma": (round(self._ewma, 4)
                                   if self._ewma is not None else None),
               "boundaries": self._boundaries,
               "k": self.k,
               # the draft lane's KV pool in BYTES, not just page
               # occupancy (ISSUE 14 satellite): both lanes of
               # /healthz name their pinned device memory
               "kv_pages": {
                   "total": self._kv.n_pages,
                   "free": self._kv.free_pages,
                   "pool_bytes": self.pool_bytes,
                   "used_bytes": (self.pool_bytes // (self._kv.n_pages + 1))
                   * self._kv.used_pages}}
        if self._pcache is not None:
            out["prefix_cache"] = self._pcache.stats()
        return out
