"""ModelRegistry: named, versioned servables with bucket-ladder warmup.

Reference capability: the model-zoo/serving side of the upstream project
(DL4J models exported to production and served from Java). Here a
registry row is (name, version) -> Servable + BucketLadder; `warmup()`
AOT-compiles the ladder and `describe()` feeds the
`GET /serving/v1/models` endpoint.
"""

from __future__ import annotations

import threading
import time

from deeplearning4j_tpu.serving.buckets import BucketLadder
from deeplearning4j_tpu.serving.servable import Servable, as_servable


class ModelNotFound(KeyError):
    pass


class _Entry:
    __slots__ = ("name", "version", "servable", "ladder", "registered_at",
                 "warmed", "warmup_seconds")

    def __init__(self, name, version, servable, ladder):
        self.name = name
        self.version = int(version)
        self.servable = servable
        self.ladder = ladder
        self.registered_at = time.time()
        self.warmed = False
        self.warmup_seconds = None

    def warmup(self):
        # admission-time capacity planning (ISSUE 14): sum the ladder's
        # estimated footprint against live device headroom BEFORE the
        # first compile — a structured CapacityError instead of a
        # mid-ladder OOM after minutes of warmup (dl4j_compile_total
        # provably flat on rejection, ledger-asserted in tests). The
        # judgement is scoped to the servable's pinned device when it
        # has one (a busy neighbor device must not veto this one), and
        # skipped outright — estimate included — when no device
        # capacity is knowable (unconfigured deployments pay nothing)
        # Sharded registrations (ISSUE 19) upgrade the judgement from
        # admitting to PLACING: the headroom check scopes to the SET
        # of mesh devices — each device's share of the sharded
        # footprint against that device's own headroom, with the shard
        # layout recorded as the capacity_plan flight event and a
        # per-device breakdown in CapacityError.detail on rejection.
        from deeplearning4j_tpu.telemetry import memledger

        mesh = getattr(self.servable, "mesh", None)
        dev = (None if self.servable.device is None
               else memledger.device_label(self.servable.device))
        if memledger.capacity_known(device=None if mesh is not None
                                    else dev):
            from deeplearning4j_tpu.serving.servable import (
                estimate_warmup_bytes)

            est = estimate_warmup_bytes(self.servable, self.ladder)
            if est is not None and mesh is not None:
                from deeplearning4j_tpu.serving.sharded import (
                    mesh_shape)

                memledger.plan_capacity(
                    f"serving:{self.name}:v{self.version}",
                    est["total"],
                    detail={**est, "mesh": mesh_shape(mesh)},
                    per_device=self.servable.placement_bytes(est))
            elif est is not None:
                memledger.plan_capacity(
                    f"serving:{self.name}:v{self.version}",
                    est["total"], detail=est, device=dev)
        t0 = time.perf_counter()
        self.servable.warmup(self.ladder)
        self.warmup_seconds = time.perf_counter() - t0
        self.warmed = True
        from deeplearning4j_tpu.telemetry import flight

        flight.record("model_warmup", model=self.name,
                      version=self.version,
                      seconds=round(self.warmup_seconds, 6))
        return self

    def describe(self) -> dict:
        sv = self.servable
        d = {
            "name": self.name,
            "version": self.version,
            "type": type(sv).__name__,
            "example_shape": list(sv.example_shape),
            "dtype": str(sv.dtype),
            "ladder": self.ladder.describe(),
            "warmed": self.warmed,
            "warmed_shapes": [list(s) for s in sv.warmed_shapes],
            "warmup_seconds": self.warmup_seconds,
        }
        # quantized servables report their int8 payload + calibration
        # fidelity beside the standard row (GET /serving/v1/models)
        extra = getattr(sv, "describe_extra", None)
        if callable(extra):
            d.update(extra())
        return d


class ModelRegistry:
    """name -> {version -> entry}; lookups default to the newest
    version. Registration is idempotent per (name, version): re-register
    to replace (rolling update — in-flight requests on the old entry
    finish on the old servable)."""

    def __init__(self, ladder: BucketLadder | None = None):
        self.default_ladder = ladder or BucketLadder()
        self._models: dict[str, dict[int, _Entry]] = {}
        self._lock = threading.Lock()

    def register(self, name, model, version=1, example_shape=None,
                 dtype=None, ladder=None, input_name=None,
                 output_name=None, warmup=False) -> _Entry:
        """dtype=None infers the serving dtype from the model's
        configured dataType / precision policy (see as_servable)."""
        sv = (model if isinstance(model, Servable)
              else as_servable(model, example_shape, dtype,
                               input_name=input_name,
                               output_name=output_name))
        # names the servable's bucket executables in the ISSUE 10
        # cost-attribution gauges (dl4j_flops_per_step / _executable_bytes)
        sv.cost_label = f"{name}:v{int(version)}"
        ladder = ladder if ladder is not None else self.default_ladder
        if isinstance(ladder, (list, tuple)):
            ladder = BucketLadder(ladder)
        entry = _Entry(name, version, sv, ladder)
        with self._lock:
            replaced = self._models.get(name, {}).get(entry.version)
            self._models.setdefault(name, {})[entry.version] = entry
        if replaced is not None and replaced.servable is not sv:
            # a same-(name, version) replace retires the old servable:
            # its HBM claims go with it — BEFORE the new warmup, which
            # re-states the same ledger keys (releasing after would
            # delete the new servable's claims)
            release = getattr(replaced.servable,
                              "release_memory_claims", None)
            if callable(release):
                release()
        if warmup:
            try:
                entry.warmup()
            except Exception:
                # a rejected (or otherwise failed) warmup must not
                # leave the un-warmed entry live in the registry — the
                # next predict would lazily compile and hit exactly
                # the mid-traffic OOM the planner refused. Roll the
                # insertion back (the replaced same-version entry, if
                # any, is restored; its claims re-state on next use).
                with self._lock:
                    versions = self._models.get(name, {})
                    if versions.get(entry.version) is entry:
                        if replaced is not None:
                            versions[entry.version] = replaced
                        else:
                            del versions[entry.version]
                            if not versions:
                                self._models.pop(name, None)
                raise
        return entry

    def unregister(self, name, version=None):
        with self._lock:
            if name not in self._models:
                raise ModelNotFound(name)
            if version is None:
                dropped = list(self._models[name].values())
                del self._models[name]
            else:
                try:
                    dropped = [self._models[name][int(version)]]
                    del self._models[name][int(version)]
                except KeyError:
                    # a plain KeyError would map to HTTP 500 at the
                    # admin route; a missing version is a 404 exactly
                    # like a missing name
                    raise ModelNotFound(f"{name}:{version}") from None
                if not self._models[name]:
                    del self._models[name]
        # the dropped versions' executables are no longer served: their
        # HBM ledger claims go with them (ISSUE 14)
        for e in dropped:
            release = getattr(e.servable, "release_memory_claims", None)
            if callable(release):
                release()

    def get(self, name, version=None) -> _Entry:
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise ModelNotFound(name)
            if version is None:
                return versions[max(versions)]
            try:
                return versions[int(version)]
            except KeyError:
                raise ModelNotFound(f"{name}:{version}") from None

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._models)

    def entries(self) -> list[_Entry]:
        """Every live (name, version) entry — the health scrape's way
        to reach the servable objects (sharded /healthz section)."""
        with self._lock:
            return [e for vs in self._models.values()
                    for e in vs.values()]

    def warmup(self, name=None, version=None):
        """AOT-compile the ladder for one model (or EVERY version of
        every registered model — pinned-version traffic must not hit a
        cold executable). Compiles show up in dl4j_compile_total DURING
        this call; a warmed steady state adds none."""
        if name is not None:
            entries = [self.get(name, version)]
        else:
            with self._lock:
                entries = [e for vs in self._models.values()
                           for e in vs.values()]
        for e in entries:
            e.warmup()
        return self

    def describe(self) -> list[dict]:
        """Every (name, version) row, newest version first per name —
        the GET /serving/v1/models payload."""
        with self._lock:
            entries = [e for vs in self._models.values()
                       for e in vs.values()]
        return [e.describe() for e in
                sorted(entries, key=lambda e: (e.name, -e.version))]
