"""Inference serving subsystem (ISSUE 2 tentpole; rebuilt for real
traffic in ISSUE 8).

The repo's training side compiles once and executes many; this package
gives the INFERENCE side the same contract under concurrent traffic:

- `BucketLadder` / `buckets`: pad request batches into a fixed shape
  ladder so XLA never sees a new shape after warmup;
- `ModelRegistry`: named, versioned servables (MultiLayerNetwork,
  ComputationGraph, SameDiff, plain fns) with
  `jax.jit(...).lower().compile()` AOT warmup over the ladder;
- `DynamicBatcher`: bounded-queue worker that coalesces concurrent
  predict() calls into one padded device dispatch (max-latency flush,
  backpressure, per-request timeouts, graceful shutdown);
- `ReplicaSet` (ISSUE 8): N device-pinned copies of a model's bucket
  executables with per-replica run queues and steal-on-idle, so one
  model's throughput scales with device count instead of serializing
  through the batcher thread;
- `AdmissionController` (ISSUE 8): priority classes (high/normal/
  batch), per-model concurrency budgets, and load shedding with a
  computed Retry-After — overload degrades best-effort traffic, not
  everything;
- `DecodeEngine` (ISSUE 8): continuous (iteration-level) batching for
  autoregressive decode over a preallocated paged KV cache — new
  sequences join the in-flight batch at token boundaries, finished
  ones free their slot immediately, zero steady-state recompiles;
- `InferenceSession`: the sync/async facade, instrumented through the
  PR-1 telemetry registry (`dl4j_serving_*`);
- HTTP: `UIServer.serveModels(session)` exposes
  `POST /serving/v1/models/<name>:predict` and
  `GET /serving/v1/models` beside `/metrics`;
- `ShardedServable` / `ShardedTransformerDecodeModel` (ISSUE 19):
  GSPMD mesh-partitioned serving — params sharded per NamedSharding
  over a `parallel.mesh` device mesh, the paged KV pool sharded
  page-wise, capacity PLACED per device instead of admitted in total,
  all through the same ladder/registry/warmup/ledger path.

See docs/SERVING.md.
"""

from deeplearning4j_tpu.serving.admission import (
    AdmissionController, ShedError)
from deeplearning4j_tpu.serving.batcher import (
    DynamicBatcher, QueueFullError, ServingShutdown, ServingTimeout,
    execute_plan, run_batch)
from deeplearning4j_tpu.serving.buckets import (
    BucketLadder, DEFAULT_BATCH_BUCKETS, pad_batch, pad_rows, pad_time,
    unpad)
from deeplearning4j_tpu.serving.decode import (
    DecodeEngine, PagedKVCache, RnnDecodeModel, TransformerDecodeModel)
from deeplearning4j_tpu.serving.prefill import ChunkedPrefill
from deeplearning4j_tpu.serving.prefix_cache import PrefixCache
from deeplearning4j_tpu.serving.registry import ModelNotFound, ModelRegistry
from deeplearning4j_tpu.serving.replica import Replica, ReplicaDeath, \
    ReplicaSet
from deeplearning4j_tpu.serving.servable import (
    FnServable, GraphServable, NetworkServable, SameDiffServable, Servable,
    as_servable)
from deeplearning4j_tpu.serving.session import InferenceSession
from deeplearning4j_tpu.serving.sharded import (
    ShardedServable, ShardedTransformerDecodeModel, column_parallel_mlp,
    sharded_mlp_servable)
from deeplearning4j_tpu.serving.speculative import (
    SpeculativeConfig, SpeculativeDecoder)

__all__ = [
    "AdmissionController", "BucketLadder", "ChunkedPrefill",
    "DEFAULT_BATCH_BUCKETS",
    "DecodeEngine", "DynamicBatcher", "FnServable", "GraphServable",
    "InferenceSession", "ModelNotFound", "ModelRegistry",
    "NetworkServable", "PagedKVCache", "PrefixCache", "QueueFullError",
    "Replica",
    "ReplicaDeath", "ReplicaSet", "RnnDecodeModel", "SameDiffServable",
    "Servable", "ServingShutdown", "ServingTimeout", "ShardedServable",
    "ShardedTransformerDecodeModel", "ShedError",
    "SpeculativeConfig", "SpeculativeDecoder",
    "TransformerDecodeModel", "as_servable", "column_parallel_mlp",
    "execute_plan",
    "pad_batch", "pad_rows", "pad_time", "run_batch",
    "sharded_mlp_servable", "unpad",
]
