"""Inference serving subsystem (ISSUE 2 tentpole).

The repo's training side compiles once and executes many; this package
gives the INFERENCE side the same contract under concurrent traffic:

- `BucketLadder` / `buckets`: pad request batches into a fixed shape
  ladder so XLA never sees a new shape after warmup;
- `ModelRegistry`: named, versioned servables (MultiLayerNetwork,
  ComputationGraph, SameDiff, plain fns) with
  `jax.jit(...).lower().compile()` AOT warmup over the ladder;
- `DynamicBatcher`: bounded-queue worker that coalesces concurrent
  predict() calls into one padded device dispatch (max-latency flush,
  backpressure, per-request timeouts, graceful shutdown);
- `InferenceSession`: the sync/async facade, instrumented through the
  PR-1 telemetry registry (`dl4j_serving_*`);
- HTTP: `UIServer.serveModels(session)` exposes
  `POST /serving/v1/models/<name>:predict` and
  `GET /serving/v1/models` beside `/metrics`.

See docs/SERVING.md.
"""

from deeplearning4j_tpu.serving.batcher import (
    DynamicBatcher, QueueFullError, ServingShutdown, ServingTimeout,
    execute_plan)
from deeplearning4j_tpu.serving.buckets import (
    BucketLadder, DEFAULT_BATCH_BUCKETS, pad_batch, pad_rows, pad_time,
    unpad)
from deeplearning4j_tpu.serving.registry import ModelNotFound, ModelRegistry
from deeplearning4j_tpu.serving.servable import (
    FnServable, GraphServable, NetworkServable, SameDiffServable, Servable,
    as_servable)
from deeplearning4j_tpu.serving.session import InferenceSession

__all__ = [
    "BucketLadder", "DEFAULT_BATCH_BUCKETS", "DynamicBatcher",
    "FnServable", "GraphServable", "InferenceSession", "ModelNotFound",
    "ModelRegistry", "NetworkServable", "QueueFullError",
    "SameDiffServable", "Servable", "ServingShutdown", "ServingTimeout",
    "as_servable", "execute_plan", "pad_batch", "pad_rows", "pad_time",
    "unpad",
]
