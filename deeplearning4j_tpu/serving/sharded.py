"""GSPMD-sharded serving (ISSUE 19): models bigger than one chip.

Every prior serving path — ReplicaSet, the bucket ladder, the paged KV
decode engine — replicates per device, so the largest servable model is
one device's HBM. This module partitions the serving executables across
a ``jax.sharding.Mesh`` instead, while flowing through the SAME
BucketLadder / ModelRegistry / warmup / compile-ledger machinery:

- :class:`ShardedServable` — a :class:`~.servable.Servable` whose
  params carry per-leaf ``NamedSharding`` (GSPMD) and whose inputs are
  replicated (or batch-sharded over the ``data`` axis when the bucket
  divides). Lowering commits to the mesh, so the AOT executables ARE
  the mesh programs — all collectives live inside XLA, dispatched from
  the batcher thread like any single-device call (the host-side
  off-math-path rule from PAPERS.md: shard orchestration never rides
  the per-request path, and no collective is ever issued from a
  router/poll thread — the dl4jlint collective-thread rule can prove
  it, because the Python source contains none);

- :func:`column_parallel_mlp` — the bit-exactness construction: every
  weight is sharded on its OUTPUT dimension over the ``model`` axis
  and activations are constrained back to replicated after each
  matmul. Every reduction (matmul K-loop, layernorm, softmax) is then
  computed full-length on every device — identical operand order to
  the single-device program — so sharded serving is bit-identical
  per row to the unsharded reference, not merely close (asserted in
  tests/test_sharded_serving.py);

- :class:`ShardedTransformerDecodeModel` — the mesh-sharded
  ``PagedKVCache``: the per-page flash-attention ``fori_loop`` of
  :class:`~.decode.TransformerDecodeModel` is already ring_attention's
  block accumulation, so pages-as-shards is the natural extension —
  the device pools ``[L, n_pages+1, page, H, D]`` are sharded on the
  PAGE axis over the ``model`` axis while the host-side refcounted
  page table (and with it prefix caching and speculative decoding)
  rides unchanged on top. The online-softmax accumulation order over
  pages is sequential either way, so decode is bit-identical too.

Capacity planning is upgraded from admitting to *placing* (ISSUE 19
satellite): a sharded registration is judged per device — each
device's share of the sharded footprint against THAT device's
headroom (``memledger.plan_capacity(per_device=...)``) — and the
shard layout rides the ``capacity_plan`` flight event as the placement
decision. Rejection carries the per-device breakdown in
``CapacityError.detail["per_device"]``.

The PR-13 compile store is explicitly scoped OUT for sharded entries
(store-reject cause ``sharded_executable``): a serialized SPMD
executable bakes in its device assignment, and this module does not
yet re-bind it at load — a deserialized entry could silently pin a
different device set. ``compile_shape`` therefore always compiles and
ledgers the reject, visible in /debug/compiles forensics.

Testable on CPU: ``--xla_force_host_platform_device_count=N`` makes
the mesh, ``DL4J_DEVICE_BUDGET_BYTES`` makes per-device capacity real.
"""

from __future__ import annotations

import math

import numpy as np

from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS, MODEL_AXIS, spec_for)
from deeplearning4j_tpu.serving.decode import TransformerDecodeModel
from deeplearning4j_tpu.serving.servable import Servable

# the store-reject cause for sharded entries (documented scope-out,
# see module docstring + docs/SERVING.md)
STORE_REJECT_SHARDED = ("sharded_executable: serialized device "
                        "assignment is not re-bound at load")


def mesh_shape(mesh) -> dict:
    """{axis: size} for a mesh — the sharding description the compile
    ledger, /healthz, and the flight placement decision all share."""
    return {a: int(mesh.shape[a]) for a in mesh.axis_names}


def mesh_device_labels(mesh) -> list:
    from deeplearning4j_tpu.telemetry import memledger

    return [memledger.device_label(d) for d in mesh.devices.flat]


def _spec_divisor(mesh, spec) -> int:
    """How many ways a leaf with PartitionSpec ``spec`` splits over
    ``mesh`` — the product of the named axis sizes (a replicated leaf
    divides by 1)."""
    div = 1
    for entry in tuple(spec or ()):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for a in axes:
            if a in mesh.shape:
                div *= int(mesh.shape[a])
    return div


def per_device_tree_bytes(tree) -> dict:
    """{device_label: bytes} a placed (possibly sharded) pytree pins
    per device, exact via each array's addressable shards. Replicated
    leaves charge their full bytes to every holding device — this is
    the PHYSICAL footprint, which is what capacity is about."""
    from deeplearning4j_tpu.telemetry import memledger

    import jax

    out: dict = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            continue
        for sh in shards:
            label = memledger.device_label(sh.device)
            out[label] = out.get(label, 0) + int(sh.data.nbytes)
    return out


class ShardedServable(Servable):
    """A mesh-partitioned servable: ``fn(params, x) -> y`` lowered with
    GSPMD ``NamedSharding`` on the params and replicated (or
    batch-sharded) inputs, through the standard bucket-ladder AOT path.

    ``param_specs`` is a pytree of ``PartitionSpec`` matching
    ``params`` (default: fully replicated). ``batch_axis="data"``
    shards bucket inputs over the mesh's data axis when the bucket's
    batch dimension divides it; other buckets fall back to replicated
    inputs — either way the executable commits to the sharding, so the
    ledger's abstract signature carries it and a mesh-shape change
    classifies as ``sharding_change``.
    """

    def __init__(self, fn, params, example_shape, mesh,
                 param_specs=None, dtype=np.float32, batch_axis=None,
                 program_digest=None):
        super().__init__(example_shape, dtype)
        import jax
        from jax.sharding import PartitionSpec as P

        self.mesh = mesh
        self.params = params
        self._fn = fn
        self._jitted = jax.jit(fn)
        if param_specs is None:
            param_specs = jax.tree_util.tree_map(lambda _: P(), params)
        self.param_specs = param_specs
        self.batch_axis = batch_axis
        self._digest = program_digest

    # -- placement ----------------------------------------------------------
    def _param_shardings(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), self.param_specs,
            is_leaf=lambda s: isinstance(s, P))

    def _placed_args(self) -> tuple:
        """Params placed with their NamedShardings (identity-keyed like
        the base class), with the HBM claims split per mesh device —
        /debug/memory attributes each device's actual shard bytes
        instead of lumping the sharded tree on one label."""
        args = self._call_args()
        key = tuple(map(id, args))
        cached_key, _pinned, cached = self._placed
        if key != cached_key:
            import jax

            placed = jax.device_put(self.params,
                                    self._param_shardings())
            cached = (placed,)
            self._placed = (key, args, cached)
            from deeplearning4j_tpu.telemetry import memledger

            for label, share in sorted(
                    per_device_tree_bytes(placed).items()):
                c = memledger.claim(
                    "replica_args",
                    f"{self._ledger_site()}@{label}",
                    nbytes=share, device=label, sharded=True)
                if c is not None and c not in self._mem_claims:
                    self._mem_claims.append(c)
        return cached

    # -- subclass surface ---------------------------------------------------
    def _jit_fn(self):
        return self._jitted

    def _call_args(self):
        return (self.params,)

    def _program_digest(self):
        return self._digest

    def _batch_spec(self, shape):
        from jax.sharding import PartitionSpec as P

        if (self.batch_axis
                and self.batch_axis in self.mesh.shape
                and shape and shape[0]
                and shape[0] % int(self.mesh.shape[self.batch_axis])
                == 0):
            return spec_for(self.mesh, self.batch_axis)
        return P()

    def _input_spec(self, shape):
        import jax
        from jax.sharding import NamedSharding

        return jax.ShapeDtypeStruct(
            shape, self.dtype,
            sharding=NamedSharding(self.mesh, self._batch_spec(shape)))

    def _sharding_desc(self, shape=None) -> str:
        mesh_s = ",".join(f"{a}={n}" for a, n in
                          mesh_shape(self.mesh).items())
        if shape is None:
            in_s = self.batch_axis or "replicated"
        else:
            spec = self._batch_spec(shape)
            in_s = "replicated" if spec == type(spec)() else str(spec)
        return f"mesh({mesh_s}):in={in_s}"

    # -- compile store: scoped out with an explicit reject cause ------------
    def compile_shape(self, shape: tuple):
        """Always lower + compile: sharded entries never consult the
        persistent executable store (see STORE_REJECT_SHARDED — the
        serialized device assignment is not re-bound at load). When the
        store is otherwise enabled the skip is an explicit, ledgered
        reject, not a silent miss."""
        import time as _time

        from deeplearning4j_tpu import compilestore

        shape = tuple(shape)
        if shape in self._compiled:
            return self._compiled[shape]
        info = None
        if compilestore.enabled():
            info = {"store": "reject", "mode": "compile",
                    "reject_reason": STORE_REJECT_SHARDED}
            from deeplearning4j_tpu import telemetry

            if telemetry.enabled():
                from deeplearning4j_tpu.telemetry import flight

                flight.record("compile_store_reject",
                              site=self._ledger_site(),
                              key=None, reason=STORE_REJECT_SHARDED)
        t0 = _time.perf_counter()
        exe = self._lower_shape(shape).compile()
        self._note_compiled(shape, exe, _time.perf_counter() - t0,
                            info)
        with self._lock:
            self._compiled.setdefault(shape, exe)
        return self._compiled[shape]

    # -- placement planning -------------------------------------------------
    def placement_bytes(self, est) -> dict:
        """The shard layout the capacity planner judges: each mesh
        device's share of the warmup estimate ``est`` (from
        ``estimate_warmup_bytes``). Param leaves divide by their
        spec's mesh-axis product (a replicated leaf is physically full
        on every device); bucket input/output activations are charged
        in full — replicated inputs are the default, and the
        overcharge for batch-sharded buckets errs on the safe side."""
        import jax
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        leaves = zip(
            jax.tree_util.tree_leaves(self.params),
            jax.tree_util.tree_leaves(
                self.param_specs,
                is_leaf=lambda s: isinstance(s, P)))
        param_share = 0
        for leaf, spec in leaves:
            nbytes = getattr(leaf, "nbytes", 0)
            param_share += int(nbytes) // _spec_divisor(mesh, spec)
        bucket_bytes = sum((est.get("buckets") or {}).values())
        per_dev = param_share + bucket_bytes
        return {label: per_dev for label in mesh_device_labels(self.mesh)}

    def sharded_health(self) -> dict:
        """The /healthz ``sharded`` row for this servable: mesh shape,
        the device set, and the per-device param shard bytes once
        placed."""
        out = {"mesh": mesh_shape(self.mesh),
               "devices": mesh_device_labels(self.mesh),
               "batch_axis": self.batch_axis}
        _key, _host, cached = self._placed
        if cached is not None:
            out["params_per_device_bytes"] = per_device_tree_bytes(
                cached)
        return out


# ---------------------------------------------------------------------------
# bit-exact column-parallel builders
# ---------------------------------------------------------------------------

def _dense_params(sizes, seed):
    rng = np.random.RandomState(seed)
    layers = []
    for d_in, d_out in zip(sizes[:-1], sizes[1:]):
        scale = 1.0 / math.sqrt(d_in)
        layers.append({
            "w": (rng.randn(d_in, d_out) * scale).astype(np.float32),
            "b": np.zeros((d_out,), np.float32)})
    return {"layers": layers}


def column_parallel_mlp(mesh, sizes, seed=0):
    """A tanh MLP whose every weight is column-sharded (output dim)
    over the mesh's ``model`` axis, with activations constrained back
    to replicated after each matmul.

    Returns ``(fn, ref_fn, params, param_specs)``: ``fn`` is the
    sharded program (serve it through :class:`ShardedServable`),
    ``ref_fn`` the same math without sharding constraints (the
    single-device reference) — bit-identical per row by construction:
    every reduction runs full-length on every device, the constraints
    add only all-gathers (exact data movement, no arithmetic)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    params = _dense_params(sizes, seed)
    col = spec_for(mesh, None, MODEL_AXIS)      # [in, out] -> cols
    vec = spec_for(mesh, MODEL_AXIS)
    specs = {"layers": [{"w": col, "b": vec}
                        for _ in params["layers"]]}
    repl = NamedSharding(mesh, P())
    n_layers = len(params["layers"])

    def fn(p, x):
        h = x
        for i, lp in enumerate(p["layers"]):
            h = h @ lp["w"] + lp["b"]
            h = jax.lax.with_sharding_constraint(h, repl)
            if i + 1 < n_layers:
                h = jnp.tanh(h)
        return h

    def ref_fn(p, x):
        h = x
        for i, lp in enumerate(p["layers"]):
            h = h @ lp["w"] + lp["b"]
            if i + 1 < n_layers:
                h = jnp.tanh(h)
        return h

    return fn, ref_fn, params, specs


def sharded_mlp_servable(mesh, sizes, example_shape=None, seed=0,
                         batch_axis=None) -> ShardedServable:
    """The one-call builder the ``"sharded"`` fleet worker kind uses:
    a column-parallel MLP as a ShardedServable on ``mesh``."""
    fn, _ref, params, specs = column_parallel_mlp(mesh, sizes,
                                                  seed=seed)
    return ShardedServable(
        fn, params, example_shape or (int(sizes[0]),), mesh,
        param_specs=specs, batch_axis=batch_axis,
        program_digest=(f"sharded_mlp:{tuple(int(s) for s in sizes)}"
                        f":seed={seed}:mesh={mesh_shape(mesh)}"))


# ---------------------------------------------------------------------------
# the mesh-sharded paged KV cache
# ---------------------------------------------------------------------------

class ShardedTransformerDecodeModel(TransformerDecodeModel):
    """:class:`~.decode.TransformerDecodeModel` with the KV pools
    sharded over the mesh — pages-as-shards.

    The pools ``[L, n_pages+1, page, H, D]`` get
    ``PartitionSpec(None, "model")``: each device owns a contiguous
    block of PAGES. The per-page flash-attention ``fori_loop`` already
    accumulates page blocks with ring_attention's online softmax, so
    the page axis is the natural shard axis: the accumulation order is
    sequential over pages either way, which is what keeps sharded
    decode bit-identical to the single-device reference. The host-side
    :class:`~.decode.PagedKVCache` (refcounts, page tables, prefix
    caching, speculative adoption) never sees device layout — it
    hands out page NUMBERS — so ISSUE 12's layers ride unchanged.

    ``n_pages`` is rounded up so ``n_pages + 1`` (page 0 is scratch)
    divides the model-axis size — every device owns whole pages.
    Params are placed replicated on the mesh; the per-device footprint
    that matters (and that the engine plans + claims per device) is
    the pool share: ``pool_bytes / model_axis_size`` per device.
    """

    def __init__(self, params, n_heads, mesh, **kw):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.mesh = mesh
        super().__init__(params, n_heads, **kw)
        shard = int(mesh.shape.get(MODEL_AXIS, 1))
        self.pool_shards = shard
        rem = (self.n_pages + 1) % shard
        if rem:
            self.n_pages += shard - rem
        self._pool_sharding = NamedSharding(
            mesh, spec_for(mesh, None, MODEL_AXIS))
        self._repl_sharding = NamedSharding(mesh, P())
        # params replicated ON THE MESH (committed): a jit call mixing
        # mesh-sharded pools with uncommitted host params would re-place
        # the params per dispatch
        self.params = jax.device_put(params, self._repl_sharding)

    def init_state(self):
        import jax
        import jax.numpy as jnp

        shape = (self.n_layers, self.n_pages + 1, self.page,
                 self.n_heads, self.head_dim)
        zeros = jnp.zeros(shape, jnp.float32)
        return {"k": jax.device_put(zeros, self._pool_sharding),
                "v": jax.device_put(zeros, self._pool_sharding)}

    def _constrain_state(self, state):
        import jax

        return {k: jax.lax.with_sharding_constraint(
                    v, self._pool_sharding)
                for k, v in state.items()}

    def _fn(self, params, state, tokens, pos, table):
        nxt, new_state = super()._fn(params, state, tokens, pos,
                                     table)
        return nxt, self._constrain_state(new_state)

    def masked_fn(self, params, state, tokens, pos, table, active):
        out, new_state = super().masked_fn(params, state, tokens, pos,
                                           table, active)
        return out, self._constrain_state(new_state)

    def pool_device_bytes(self) -> dict:
        """{device_label: bytes} of the KV pools per mesh device — the
        shard layout the engine's capacity plan judges and the
        per-device ``kv_cache`` claims state. Devices that differ only
        along non-model axes hold replicas of the same page block, so
        every device's share is ``total / model_axis_size``."""
        pool = 2 * (self.n_layers * (self.n_pages + 1) * self.page
                    * self.n_heads * self.head_dim) * 4  # k+v, fp32
        per = pool // self.pool_shards
        return {label: per for label in mesh_device_labels(self.mesh)}

    def sharded_health(self) -> dict:
        return {"mesh": mesh_shape(self.mesh),
                "devices": mesh_device_labels(self.mesh),
                "pool_shards": self.pool_shards,
                "kv_pool_per_device_bytes": self.pool_device_bytes()}
