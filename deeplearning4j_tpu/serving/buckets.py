"""Shape bucketing for inference: the compile-once/execute-many contract.

XLA specializes every executable to concrete input shapes, so a serving
path that feeds raw request batches retraces on every new batch size
(SURVEY.md §7 hard part 1 — the training side solved this with
`_pad_to_bucket`; this module is the inference-side generalization).
A `BucketLadder` fixes a small set of batch sizes (and optionally padded
sequence lengths); requests are padded UP to the smallest covering
bucket, executed on a pre-compiled executable, and the padding rows are
sliced back off. Padding repeats the last real row, so every real row's
result is bit-identical to the unbatched run (row-wise networks: dense /
conv / softmax / BN-inference all compute examples independently).
"""

from __future__ import annotations

import numpy as np

# powers of two up to 32: small enough to warm quickly, dense enough that
# occupancy (real rows / bucket rows) never drops below 50%
DEFAULT_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32)


class BucketLadder:
    """An ascending set of batch-size buckets plus an optional ascending
    set of padded sequence lengths (for [N, C, T] time-series inputs)."""

    def __init__(self, batch_sizes=DEFAULT_BATCH_BUCKETS, seq_lengths=None):
        sizes = sorted(set(int(b) for b in batch_sizes))
        if not sizes or sizes[0] < 1:
            raise ValueError(f"batch buckets must be >= 1, got {batch_sizes}")
        self.batch_sizes = tuple(sizes)
        self.seq_lengths = (tuple(sorted(set(int(t) for t in seq_lengths)))
                            if seq_lengths else None)
        if self.seq_lengths and self.seq_lengths[0] < 1:
            raise ValueError(f"seq buckets must be >= 1, got {seq_lengths}")

    @property
    def max_batch(self) -> int:
        return self.batch_sizes[-1]

    def covering(self, n: int):
        """Smallest bucket >= n, or None when n exceeds the ladder."""
        for b in self.batch_sizes:
            if b >= n:
                return b
        return None

    def covering_seq(self, t: int):
        """Smallest sequence bucket >= t; lengths beyond the ladder are
        left unpadded (they compile their own executable)."""
        if not self.seq_lengths:
            return t
        for s in self.seq_lengths:
            if s >= t:
                return s
        return t

    def plan(self, n: int) -> list[int]:
        """Bucket sizes covering n rows: full max-buckets, then the
        smallest covering bucket for the tail. sum(plan) >= n always."""
        if n < 1:
            raise ValueError(f"need n >= 1, got {n}")
        out = []
        while n > self.max_batch:
            out.append(self.max_batch)
            n -= self.max_batch
        out.append(self.covering(n))
        return out

    def shapes(self, example_shape: tuple) -> list[tuple]:
        """Every warmup input shape: batch buckets x seq buckets (seq
        buckets replace the trailing time axis of 2D+ examples)."""
        example_shape = tuple(example_shape)
        variants = [example_shape]
        if self.seq_lengths and len(example_shape) >= 2:
            variants = [example_shape[:-1] + (t,) for t in self.seq_lengths]
        return [(b,) + v for b in self.batch_sizes for v in variants]

    def describe(self) -> dict:
        return {"batch_sizes": list(self.batch_sizes),
                "seq_lengths": (list(self.seq_lengths)
                                if self.seq_lengths else None)}


def pad_rows(arr: np.ndarray, bucket: int) -> np.ndarray:
    """Pad the batch axis up to `bucket` rows by repeating the last row
    (same scheme as training's `_pad_to_bucket`; repeated rows keep every
    value finite so no NaN can leak into row-independent ops)."""
    arr = np.asarray(arr)
    n = arr.shape[0]
    if n == bucket:
        return arr
    if n > bucket:
        raise ValueError(f"batch {n} exceeds bucket {bucket}")
    return np.concatenate([arr, np.repeat(arr[-1:], bucket - n, axis=0)],
                          axis=0)


def pad_time(arr: np.ndarray, t_bucket: int) -> np.ndarray:
    """Zero-pad the trailing time axis of an [N, C, T] batch up to
    t_bucket timesteps."""
    arr = np.asarray(arr)
    t = arr.shape[-1]
    if t == t_bucket:
        return arr
    if t > t_bucket:
        raise ValueError(f"sequence length {t} exceeds bucket {t_bucket}")
    pad = np.zeros(arr.shape[:-1] + (t_bucket - t,), arr.dtype)
    return np.concatenate([arr, pad], axis=-1)


def pad_batch(arr: np.ndarray, ladder: BucketLadder):
    """Pad a request batch into its covering bucket. Returns
    (padded, n_real, t_real) — slice results with `unpad(y, n_real,
    t_real)`. Batches larger than the ladder are the caller's problem
    (see BucketLadder.plan)."""
    arr = np.asarray(arr)
    n, t = arr.shape[0], arr.shape[-1] if arr.ndim >= 3 else None
    if t is not None:
        arr = pad_time(arr, ladder.covering_seq(t))
    bucket = ladder.covering(n)
    if bucket is None:
        raise ValueError(
            f"batch {n} exceeds the ladder max {ladder.max_batch}; "
            f"chunk it with ladder.plan()")
    return pad_rows(arr, bucket), n, t


def unpad(y: np.ndarray, n: int, t=None) -> np.ndarray:
    """Slice a bucketed result back to the real rows (and, for 3D
    sequence outputs, the real timesteps)."""
    y = y[:n]
    if t is not None and y.ndim >= 3 and y.shape[-1] != t:
        y = y[..., :t]
    return y
