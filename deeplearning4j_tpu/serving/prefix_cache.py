"""Cross-request prefix caching over the paged KV pool (ISSUE 12
tentpole b).

Millions of users share one system prompt, yet PR 8's engine
recomputes every request's KV pages from scratch. This module makes
`PagedKVCache` pages SHARABLE: pages become refcounted, and a
`PrefixCache` keys completed full pages by a rolling token-prefix
hash. A new request whose prompt prefix matches cached pages ADOPTS
them (refcount bump, no copy, no compute) and prefills only the
suffix — a shared 2k-token system prompt costs its chunk-prefill
boundaries exactly once per process.

Sharing discipline (the copy-on-write line):

- only FULL pages whose every position is covered by PROMPT tokens
  are ever published — a page holding generated tokens, or a partial
  page, stays private;
- adoption is capped at ``(len(prompt) - 1) // page`` full pages, so
  the adopter always feeds at least its final prompt token through
  the step executable, and every position it ever WRITES lands on a
  page it allocated itself. The divergence page — where two prompts
  share a partial page — is therefore never shared: the adopter
  re-prefills that page into its own fresh allocation (copy-on-write
  realized as recompute-on-write, which is what a paged layout makes
  cheap);
- hash chains are verified against the stored token blocks, so a
  rolling-hash collision degrades to a miss, never to wrong KV.

Page lifecycle: a slot's reservation holds one reference per page;
publishing adds the cache's own reference. A page whose only
reference is the cache (refcount == 1, no slot using it) is
RECLAIMABLE — `plan_admission` counts those pages when the free pool
alone cannot satisfy a request, which fixes the PR-8 head-of-line
wedge: a request whose need exceeds the currently-free pool but not
the pool size now evicts idle cached pages instead of blocking the
FIFO forever.

Threading: all mutation happens on the engine thread (`_admit` /
publish / `_finish`); `stats()` reads only GIL-atomic ints for the
/healthz scrape.
"""

from __future__ import annotations

from deeplearning4j_tpu.telemetry import flight


class PrefixCache:
    """Rolling-hash chain store mapping full-page token prefixes to
    resident KV pool pages."""

    def __init__(self, page, max_pages=None):
        self.page = int(page)
        # optional resident-page cap; the pool itself is the hard
        # bound (cached pages are reclaimable under admission
        # pressure, so an uncapped cache cannot wedge the pool)
        self.max_pages = max_pages if max_pages is None else int(max_pages)
        self._entries: dict = {}   # (depth, hash) -> entry dict
        self._clock = 0
        self.hits = 0              # admissions that adopted >= 1 page
        self.misses = 0

    def __len__(self):
        return len(self._entries)

    def _touch(self, key):
        self._clock += 1
        self._entries[key]["last"] = self._clock

    def _chain(self, tokens, max_depth):
        """[(key, block)] for the first ``max_depth`` full pages of
        ``tokens`` — key d chains the hash of every block before it."""
        out, h = [], 0
        for d in range(1, max_depth + 1):
            block = tuple(tokens[(d - 1) * self.page: d * self.page])
            h = hash((h, block))
            out.append(((d, h), block))
        return out

    # -- lookup / adoption ---------------------------------------------------
    def match(self, prompt, max_pages=None):
        """(pages, keys) of the longest cached chain covering full
        pages of ``prompt[:-1]`` (never the final prompt token — the
        adopter must keep one token to feed, see module docstring).
        ``max_pages`` additionally caps the depth (the speculative
        draft lane adopts at most what the target adopted)."""
        depth = (len(prompt) - 1) // self.page
        if max_pages is not None:
            depth = min(depth, int(max_pages))
        pages, keys = [], []
        for key, block in self._chain(prompt, depth):
            e = self._entries.get(key)
            if e is None or e["block"] != block:
                break
            pages.append(e["page"])
            keys.append(key)
        return pages, keys

    def touch(self, keys):
        for key in keys:
            if key in self._entries:
                self._touch(key)

    # -- publication ---------------------------------------------------------
    def publish(self, kv, prompt, pages):
        """Insert the full prompt pages of a just-prefilled slot
        (``pages`` in position order, ``len(prompt) // page`` of
        them). The cache takes its own reference on each newly-cached
        page; already-cached chains are only LRU-refreshed."""
        added = 0
        for i, (key, block) in enumerate(
                self._chain(prompt, len(prompt) // self.page)):
            if i >= len(pages):
                break
            if key in self._entries:
                self._touch(key)
                continue
            page = int(pages[i])
            if page == 0:
                continue   # scratch is never sharable
            kv.retain(page)
            self._clock += 1
            self._entries[key] = {"page": page, "block": block,
                                  "depth": key[0], "last": self._clock}
            added += 1
        if added and self.max_pages is not None and \
                len(self._entries) > self.max_pages:
            self.evict(kv, len(self._entries) - self.max_pages)
        return added

    # -- reclamation ---------------------------------------------------------
    def reclaimable(self, kv, protect=()):
        """Pages this cache could free right now: resident, not in
        ``protect``, and referenced by nobody but the cache."""
        protect = set(protect)
        return sum(1 for e in self._entries.values()
                   if e["page"] not in protect
                   and kv.refcount(e["page"]) == 1)

    def evict(self, kv, n, protect=()):
        """Free up to ``n`` pages by dropping idle entries, least-
        recently-used first (deeper chain links first on ties, so a
        chain sheds from its tail and shallow prefixes stay useful).
        Entries whose page is still slot-referenced are skipped. An
        evicted mid-chain link orphans its deeper links — they stay
        resident but unreachable, and this same LRU loop reclaims
        them on a later pass."""
        protect = set(protect)
        freed = 0
        order = sorted(self._entries.items(),
                       key=lambda kv_: (kv_[1]["last"], -kv_[1]["depth"]))
        for key, e in order:
            if freed >= n:
                break
            if e["page"] in protect or kv.refcount(e["page"]) != 1:
                continue
            del self._entries[key]
            kv.decref(e["page"])
            freed += 1
        if freed:
            flight.record("prefix_evict", pages=freed,
                          resident=len(self._entries))
        return freed

    def clear(self, kv):
        """Drop every entry (releasing the cache's references; pages
        still reserved by active slots stay allocated until their
        slot releases them)."""
        for e in self._entries.values():
            kv.decref(e["page"])
        n = len(self._entries)
        self._entries.clear()
        return n

    def stats(self):
        total = self.hits + self.misses
        return {"pages": len(self._entries),
                "hits": self.hits, "misses": self.misses,
                "hit_rate": (round(self.hits / total, 4) if total
                             else None)}


# ---------------------------------------------------------------------------
# admission planning (shared by the engine's target lane and the
# speculative draft lane, so the two cannot drift)
# ---------------------------------------------------------------------------

def plan_admission(kv, cache, prompt, total_len, max_adopt=None):
    """How the head-of-line request gets its pages, or None when it
    truly cannot (need exceeds free + reclaimable — the only case
    left where strict FIFO waits). The plan is host-side and
    side-effect free; `apply_admission` executes it."""
    need = kv.pages_for(total_len)
    pages, keys = (cache.match(prompt, max_pages=max_adopt)
                   if cache is not None else ([], []))
    fresh = need - len(pages)
    free = kv.free_pages
    if fresh <= free:
        return {"adopt": pages, "keys": keys, "evict": 0}
    if cache is None:
        return None
    if fresh <= free + cache.reclaimable(kv, protect=pages):
        return {"adopt": pages, "keys": keys, "evict": fresh - free}
    return None


def apply_admission(kv, cache, plan, slot, total_len):
    """Execute a plan for ``slot``: evict what the plan reclaimed,
    adopt the matched chain (refcount bump via reserve), allocate the
    fresh suffix pages. Returns the number of adopted pages."""
    if plan["evict"]:
        cache.evict(kv, plan["evict"], protect=plan["adopt"])
    kv.reserve(slot, total_len, adopted=plan["adopt"])
    if cache is not None and plan["keys"]:
        cache.touch(plan["keys"])
    return len(plan["adopt"])
