"""ReplicaSet: multi-replica work-stealing execution for one model.

The PR-2 serving path ran every model through ONE DynamicBatcher worker
thread — correct, but a model's throughput was capped at one device
dispatch at a time regardless of how many mesh devices sit idle
(ROADMAP item 3). Here a model's per-bucket AOT executables are CLONED
onto N distinct devices (`Servable.for_device`, one executable cache
per device) and a scheduler spreads formed batches across them:

- per-replica run queues: a submitted batch is routed to the replica
  with the least load (queued + in-flight), so a slow dispatch on one
  device doesn't head-of-line-block the others;
- steal-on-idle: a replica with an empty queue pops from the TAIL of
  the longest sibling queue (FIFO order preserved for the victim's
  head), so skewed batch sizes can't strand work behind one device;
- death containment: a batch that fails with :class:`ReplicaDeath` is
  re-queued to a surviving replica (the request futures stay live —
  work is moved, not lost) and the dead replica stops taking work;
- graceful retire(): stop accepting, drain every queue and in-flight
  dispatch, then stop the workers — the rolling-update half of the
  lifecycle, mirroring DynamicBatcher.retire().

All queues share ONE lock (the set's Condition): per-replica deques
give routing and stealing their semantics; a single mutex keeps the
lock-order rule trivially satisfiable and makes load reads consistent.
At serving batch rates (hundreds/s, not millions/s) lock contention is
noise next to a device dispatch.

int8 `QuantizedServable` replicas ride the same path: `for_device`
clones the quantized payload's executor cache exactly like an fp32
servable (the payload itself is shared, placed per device on first
use).
"""

from __future__ import annotations

import threading
import time
from collections import deque

from deeplearning4j_tpu.serving.batcher import ServingShutdown, run_batch
from deeplearning4j_tpu.telemetry import flight


class ReplicaDeath(RuntimeError):
    """Infrastructure-level replica failure (device lost, executable
    invalidated). Distinct from a model/runtime error, which terminates
    the requests: a ReplicaDeath moves the batch to a live replica."""


class _BatchTask:
    __slots__ = ("requests", "inst", "attempts")

    def __init__(self, requests, inst):
        self.requests = requests
        self.inst = inst
        self.attempts = 0


class Replica:
    """One device-pinned copy of the model plus its run queue and
    worker thread."""

    def __init__(self, rset, index, device, servable):
        self.rset = rset
        self.index = index
        self.device = device
        self.servable = servable
        self.name = f"r{index}"
        self.queue: deque = deque()
        self.inflight = 0
        self.dead = False
        self.consec_errors = 0   # circuit breaker input
        self._thread = threading.Thread(
            target=rset._worker_loop, args=(self,),
            name=f"dl4j:replica:{rset.entry.name}-{index}", daemon=True)

    def load(self) -> int:
        return len(self.queue) + self.inflight

    def start(self):
        self._thread.start()

    def join(self, timeout=None):
        self._thread.join(timeout)

    def is_alive(self) -> bool:
        return self._thread.is_alive()


class ReplicaSet:
    """N replicas of one registry entry with work-stealing dispatch.

    `devices`: explicit device list, or None to pick `n_replicas`
    distinct devices via `parallel.mesh.replica_devices` (round-robin
    when n_replicas exceeds the device count — useful on CPU). The
    DynamicBatcher owns the set when wired through
    `InferenceSession.register(..., replicas=N)`.
    """

    def __init__(self, entry, n_replicas=None, devices=None, mesh=None,
                 instruments=None, steal=True, warmup=True,
                 max_queued=None):
        from deeplearning4j_tpu.parallel.mesh import replica_devices

        if devices is None:
            devices = replica_devices(n_replicas, mesh=mesh)
        self.entry = entry
        self.steal = steal
        # total standing batches across all run queues: submit_batch
        # BLOCKS the coalescer beyond this, which backs pressure up
        # into the batcher's bounded request queue — so QueueFullError
        # (HTTP 429) keeps firing at the front door instead of work
        # piling up in unbounded deques behind it
        self.max_queued = (max_queued if max_queued is not None
                           else max(4, 2 * len(devices)))
        self._instruments_fn = (instruments if callable(instruments)
                                else lambda: instruments)
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._accepting = True
        self._closed = False
        self.replicas = [
            Replica(self, i, d, entry.servable.for_device(d))
            for i, d in enumerate(devices)]
        for r in self.replicas:
            # per-replica HBM claim names: device-sharing clones
            # (CPU round-robin oversubscription) must not collapse
            # their pinned-args claims onto one ledger key
            r.servable.mem_label = r.name
        if warmup and entry.warmed:
            # the source servable was AOT-warmed; each replica clone
            # owns a per-device executable cache and warms its own
            self.warmup()
        for r in self.replicas:
            r.start()

    # -- lifecycle -----------------------------------------------------------
    def warmup(self):
        """AOT-compile the ladder on every replica's device. Compiles
        land in dl4j_compile_total HERE; the steady state adds none."""
        for r in self.replicas:
            r.servable.warmup(self.entry.ladder)
        return self

    def retire(self, timeout=30.0):
        """Drain: stop accepting, wait for every queue and in-flight
        dispatch to finish, then stop the workers."""
        deadline = time.perf_counter() + timeout
        with self._lock:
            self._accepting = False
            while self.depth_locked() > 0 or any(
                    r.inflight for r in self.replicas):
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._work.wait(min(remaining, 0.1))
            self._closed = True
            self._work.notify_all()
        for r in self.replicas:
            r.join(max(0.0, deadline - time.perf_counter()) + 1.0)
        self._release_memory_claims()

    def close(self, timeout=5.0):
        """Fail-fast: queued batches fail with ServingShutdown.
        Idempotent — a second close finds the queues already drained."""
        with self._lock:
            self._accepting = False
            self._closed = True
            leftovers = [t for r in self.replicas
                         for t in self._drain_locked(r)]
            self._work.notify_all()
        inst = self._instruments_fn()
        for task in leftovers:
            for req in task.requests:
                req.fail(ServingShutdown("replica set closed"), inst,
                         "shutdown")
        for r in self.replicas:
            r.join(timeout)
        self._release_memory_claims()

    def _release_memory_claims(self):
        """The replica clones' pinned args and per-device executables
        die with the set: drop their HBM ledger claims (ISSUE 14)."""
        for r in self.replicas:
            release = getattr(r.servable, "release_memory_claims", None)
            if callable(release):
                release()

    def _drain_locked(self, replica):
        out = list(replica.queue)
        replica.queue.clear()
        return out

    # -- submission / introspection ------------------------------------------
    def depth_locked(self) -> int:
        return sum(len(r.queue) for r in self.replicas)

    def depth(self) -> int:
        with self._lock:
            return self.depth_locked()

    def live_replicas(self) -> list:
        return [r for r in self.replicas if not r.dead]

    def submit_batch(self, requests, inst=None):
        """Route one formed batch to the least-loaded live replica. A
        batch carrying a high-priority request goes to the HEAD of the
        run queue (and tail-stealing then migrates best-effort work
        first) — admission control bounds how MANY requests stand in
        line; this bounds WHERE the latency-sensitive ones stand."""
        self._submit(_BatchTask(list(requests),
                                inst if inst is not None
                                else self._instruments_fn()))

    def _submit(self, task):
        urgent = any(getattr(r, "priority", None) == "high"
                     for r in task.requests)
        with self._lock:
            while (not self._closed and self._accepting
                   and self.depth_locked() >= self.max_queued):
                self._work.wait(0.05)   # workers notify on completion
            if self._closed or not self._accepting:
                raise ServingShutdown(
                    f"replica set for {self.entry.name!r} closed")
            live = [r for r in self.replicas if not r.dead]
            if not live:
                raise ReplicaDeath(
                    f"no live replicas for {self.entry.name!r}")
            target = min(live, key=lambda r: (r.load(), r.index))
            if urgent:
                target.queue.appendleft(task)
            else:
                target.queue.append(task)
            self._work.notify_all()
        self._publish_load()

    def _publish_load(self):
        inst = self._instruments_fn()
        if inst is None or getattr(inst, "replica_load", None) is None:
            return
        for r in self.replicas:
            inst.replica_load(r.name).set(-1.0 if r.dead else r.load())

    # -- worker side ---------------------------------------------------------
    def _next_task_locked(self, me):
        """Own queue first (FIFO head); else steal from the tail of the
        longest sibling queue. Returns (task, victim_name_or_None) —
        a non-None victim means the task was stolen."""
        if me.queue:
            return me.queue.popleft(), None
        if self.steal:
            victims = [r for r in self.replicas if r is not me and r.queue]
            if victims:
                victim = max(victims, key=lambda r: len(r.queue))
                return victim.queue.pop(), victim.name
        return None, None

    def _worker_loop(self, me):
        try:
            while True:
                with self._lock:
                    if me.dead:   # a dead replica must not steal work
                        return
                    task, stolen = self._next_task_locked(me)
                    while task is None:
                        if self._closed or me.dead:
                            return
                        if not self._accepting and \
                                self.depth_locked() == 0:
                            return
                        self._work.wait(0.05)
                        task, stolen = self._next_task_locked(me)
                    me.inflight += 1
                try:
                    self._run_task(me, task, stolen)
                finally:
                    with self._lock:
                        me.inflight -= 1
                        self._work.notify_all()
                    self._publish_load()
        finally:
            if me.dead:
                self._on_death(me)

    # consecutive batch-level errors before a replica is declared dead:
    # a real device failure raises generic XLA errors, not ReplicaDeath
    # — without a breaker the broken replica fails batches instantly,
    # keeps a ~0 load, and least-loaded routing feeds it ALL traffic
    # while healthy siblings idle
    ERROR_BREAKER = 3

    @staticmethod
    def _task_trace(task):
        """Trace id of the first sampled request in a batch (None when
        nothing in it was sampled) — incident flight events name the
        span tree they belong to (ISSUE 10 satellite)."""
        for r in task.requests:
            ctx = getattr(r, "trace", None)
            if ctx is not None:
                return ctx.trace_id
        return None

    def _run_task(self, me, task, stolen):
        inst = task.inst
        if stolen is not None:
            if inst is not None and \
                    getattr(inst, "steals", None) is not None:
                inst.steals.inc()
            # the steal names its actors: a flight dump after an
            # incident says WHICH replica drained WHOSE queue, not
            # just that steals happened
            flight.record("steal", model=self.entry.name,
                          replica=me.name, victim=stolen,
                          batch_rows=sum(r.n for r in task.requests),
                          trace_id=self._task_trace(task))
        task.attempts += 1
        try:
            errored = run_batch(self.entry, task.requests, inst,
                                servable=me.servable, replica=me.name)
        except ReplicaDeath as e:
            me.dead = True
            flight.record("replica_death", model=self.entry.name,
                          replica=me.name, error=str(e),
                          attempt=task.attempts, reason="death",
                          trace_id=self._task_trace(task))
            self._requeue(me, task, e)
            return
        if not errored:
            me.consec_errors = 0
            return
        me.consec_errors += 1
        others_alive = any(r is not me and not r.dead and r.is_alive()
                           for r in self.replicas)
        if me.consec_errors >= self.ERROR_BREAKER and others_alive:
            # the batch's requests already failed; move the BACKLOG
            me.dead = True
            death = ReplicaDeath(
                f"replica {me.name} tripped the error breaker "
                f"({me.consec_errors} consecutive failed dispatches)")
            flight.record("replica_death", model=self.entry.name,
                          replica=me.name, error=str(death),
                          attempt=task.attempts, reason="breaker",
                          trace_id=self._task_trace(task))
            self._requeue(me, None, death)

    def _requeue(self, me, task, death):
        """Move a failed batch (and everything queued on the dead
        replica) to survivors; fail the requests only when no replica
        is left or the batch already died on every one of them.
        task=None moves just the backlog (breaker-tripped path: the
        triggering batch's requests already failed)."""
        with self._lock:
            stranded = self._drain_locked(me) + \
                ([task] if task is not None else [])
            live = [r for r in self.replicas
                    if not r.dead and r.is_alive()]
            requeued, doomed = [], []
            for t in stranded:
                if live and t.attempts < len(self.replicas):
                    target = min(live, key=lambda r: (r.load(), r.index))
                    target.queue.append(t)
                    requeued.append(t)
                else:
                    doomed.append(t)
            self._work.notify_all()
        inst = self._instruments_fn()
        for t in doomed:
            for req in t.requests:
                req.fail(death, inst, "error")
        if requeued:
            flight.record("replica_requeue", model=self.entry.name,
                          source=me.name, batches=len(requeued))

    def _on_death(self, me):
        self._publish_load()
