"""Servable adapters: one uniform `infer(batch) -> batch` face over
MultiLayerNetwork, ComputationGraph, SameDiff, and plain callables, with
shape-bucketed AOT compilation.

Warmup lowers the model's pure inference function once per ladder shape
via `jax.jit(fn).lower(...).compile()` and keeps the compiled
executables keyed by input shape. The serving hot path calls those
executables DIRECTLY — the jit dispatch cache is a separate cache, so
routing a warmed shape back through `jax.jit` would re-trace and
re-compile (measured on this jax build); going straight to the
executable is what makes "zero recompiles after warmup" a guarantee the
`dl4j_compile_total` counter can assert, not a hope.

Parameters are read from the live network at call time, never captured:
`fit()` DONATES its buffers and rebinds, so a captured reference would
go stale after interleaved training. Shapes don't change, so warmed
executables stay valid across training steps (continuous
train-and-serve).
"""

from __future__ import annotations

import threading

import numpy as np

from deeplearning4j_tpu.serving.buckets import BucketLadder


def _np(y):
    return np.asarray(y)


def _model_dtype(model) -> np.dtype:
    """The serving-boundary dtype a model's configuration implies: the
    precision policy's output dtype (== the configured dataType without
    a policy). bf16 nets previously got silently adapted to np.float32
    at the serving boundary (ISSUE 4 satellite); fp32-master mixed nets
    correctly resolve to fp32."""
    conf = getattr(model, "conf", None)
    if conf is not None and hasattr(conf, "precision_policy"):
        return np.dtype(conf.precision_policy.output_jnp)
    return np.dtype(np.float32)


class Servable:
    """Base: shape-keyed AOT executable cache + jitted fallback.

    Subclasses provide `_jit_fn()` (the jax.jit-wrapped pure function)
    and `_call_args()` (the non-input arguments, read fresh per call).

    `device` (set via :meth:`for_device`) pins the servable to one mesh
    device: executables are lowered against that device's sharding and
    the call args are placed there (cached by identity, so a training
    step that rebinds the params re-places them exactly once). This is
    what lets a ReplicaSet run N copies of one model on N devices
    without the copies sharing a dispatch queue.

    `cost_label` (set by ModelRegistry.register) names this servable in
    the ISSUE 10 cost-attribution gauges: every AOT-compiled bucket
    publishes its HLO FLOPs and executable memory footprint as
    ``dl4j_flops_per_step`` / ``dl4j_executable_bytes`` with
    ``executable="<name>:v<version>:<shape>"``.
    """

    cost_label = None

    def __init__(self, example_shape, dtype=np.float32):
        if example_shape is None:
            raise ValueError(
                "serving needs the per-example input shape (no batch "
                "axis), e.g. example_shape=(784,)")
        self.example_shape = tuple(int(d) for d in example_shape)
        self.dtype = np.dtype(dtype)
        self.device = None
        # (args identity key, HOST args, placed args): the host args
        # ride along to pin their ids — see _placed_args
        self._placed = (None, None, None)
        self._compiled = {}
        self._mem_claims = []   # HBM ledger claims this servable owns
        # distinguishes claims of device-sharing clones (a ReplicaSet
        # round-robin-oversubscribed on CPU pins one arg copy PER
        # replica — same device label must not collapse them)
        self.mem_label = None
        self._lock = threading.Lock()

    def for_device(self, device) -> "Servable":
        """A device-pinned replica of this servable: shares the model
        (params are read live through `_call_args()` like always) but
        owns its executable cache and places args/executables on
        `device`. The clone warms independently — executables are
        per-device objects."""
        import copy

        clone = copy.copy(self)
        clone.device = device
        clone._placed = (None, None, None)
        clone._compiled = {}
        clone._mem_claims = []
        clone._lock = threading.Lock()
        return clone

    def _placed_args(self) -> tuple:
        """The call args, on this servable's device when pinned. The
        placement is cached keyed on the args' object identities:
        `fit()` donates and rebinds params, so a changed identity means
        a changed value (re-place). The cache tuple also HOLDS the host
        args: without that reference, the step-N params could be
        garbage-collected and a later step's fresh pytree could land on
        a recycled address whose id() matches the cached key — and the
        replica would silently serve stale parameters."""
        args = self._call_args()
        if self.device is None:
            return args
        key = tuple(map(id, args))
        cached_key, _pinned, cached = self._placed
        if key != cached_key:
            import jax

            cached = jax.device_put(args, self.device)
            self._placed = (key, args, cached)   # one swap: thread-safe
            # HBM ledger (ISSUE 14): the pinned per-replica arg copy is
            # real device memory this replica owns; re-placement (a
            # training step rebound the params) re-states the claim
            from deeplearning4j_tpu.telemetry import memledger

            c = memledger.claim(
                "replica_args",
                f"{self._ledger_site()}@{self._mem_suffix()}",
                tree=cached, device=self.device)
            if c is not None and c not in self._mem_claims:
                self._mem_claims.append(c)
        return cached

    def _mem_suffix(self) -> str:
        from deeplearning4j_tpu.telemetry import memledger

        label = memledger._device_label(self.device)
        return label if not self.mem_label else \
            f"{label}:{self.mem_label}"

    def release_memory_claims(self):
        """Drop this servable's HBM ledger claims (executables + pinned
        replica args) — called when a replica retires or a registry
        entry is unregistered."""
        claims, self._mem_claims = self._mem_claims, []
        for c in claims:
            c.release()

    def _input_spec(self, shape):
        """ShapeDtypeStruct for one input shape, carrying the pinned
        device's sharding so lowered executables commit to it."""
        import jax

        if self.device is None:
            return jax.ShapeDtypeStruct(shape, self.dtype)
        from jax.sharding import SingleDeviceSharding

        return jax.ShapeDtypeStruct(
            shape, self.dtype, sharding=SingleDeviceSharding(self.device))

    # -- subclass surface ---------------------------------------------------
    def _jit_fn(self):
        raise NotImplementedError

    def _call_args(self) -> tuple:
        raise NotImplementedError

    def _input(self, x):
        """Adapt the raw batch into the traced function's input pytree."""
        return x

    def _output(self, y):
        """Adapt the traced function's output back to one array."""
        return _np(y)

    def _ledger_site(self) -> str:
        return self.cost_label or f"servable:{type(self).__name__}"

    def _sharding_desc(self, shape=None) -> str:
        """The sharding string the compile ledger's abstract signature
        carries: the pinned device here; a mesh description for
        ShardedServable (serving/sharded.py) — which is what makes a
        forced mesh-shape change classify as ``sharding_change``."""
        return "" if self.device is None else str(self.device)

    def _program_digest(self):
        """Digest of everything beyond the input signature that
        determines the traced program, when the adapter can state it
        (a network's configuration JSON). None means the executable
        store falls back to the lowered HLO fingerprint — always
        sound, but the warm path then pays a re-trace."""
        return None

    def _note_compiled(self, shape, exe, seconds, info=None):
        """Publish one freshly-built bucket executable: cost/memory
        attribution (ISSUE 10 — registry-named servables only, the
        gauges key on cost_label) plus a compile-ledger record with the
        eager HLO audit (ISSUE 11 — every servable: warmup is the one
        place the Compiled object is in hand). ``info`` is the
        executable-store outcome (ISSUE 13): a ``hit`` ledgers as
        ``cache_hit``, a ``reject`` as ``cache_reject``."""
        from deeplearning4j_tpu import telemetry

        if not telemetry.enabled():
            return
        from deeplearning4j_tpu.telemetry import compile_ledger, costmodel

        if self.cost_label is not None:
            label = f"{self.cost_label}:{'x'.join(str(d) for d in shape)}"
            costmodel.executable_cost(label, exe)
        info = info or {}
        compile_ledger.record_executable(
            self._ledger_site(), exe, ((shape, str(self.dtype)),),
            seconds=seconds, bucketed=True,
            sharding=self._sharding_desc(shape),
            store=info.get("store"), mode=info.get("mode", "compile"),
            fingerprint=info.get("hlo_fingerprint"))
        # HBM ledger (ISSUE 14): claim this bucket executable's
        # footprint from the real memory_analysis — temp + output +
        # code are what the executable itself pins (arguments are the
        # params/inputs, owned by their own claims) — with the full
        # breakdown in the claim meta
        from deeplearning4j_tpu.telemetry import memledger

        try:
            mem = exe.memory_analysis()
        except Exception:
            mem = None
        if mem is not None:
            parts = {kind: int(getattr(mem, attr, 0) or 0)
                     for kind, attr in
                     (("argument", "argument_size_in_bytes"),
                      ("output", "output_size_in_bytes"),
                      ("temp", "temp_size_in_bytes"),
                      ("code", "generated_code_size_in_bytes"))}
            name = (f"{self._ledger_site()}:"
                    f"{'x'.join(str(d) for d in shape)}")
            if self.device is not None:
                name += f"@{self._mem_suffix()}"
            c = memledger.claim(
                "executable", name,
                nbytes=parts["temp"] + parts["output"] + parts["code"],
                device=self.device, **parts)
            if c is not None and c not in self._mem_claims:
                self._mem_claims.append(c)

    # -- AOT warmup ---------------------------------------------------------
    def _lower_shape(self, shape):
        """Lower the inference function for one concrete input shape
        (subclasses with a different lowering arg order override)."""
        spec = self._input(self._input_spec(shape))
        return self._jit_fn().lower(*self._placed_args(), spec)

    def _store_sig(self, shape):
        from deeplearning4j_tpu.telemetry import compile_ledger

        return compile_ledger.Signature(
            args=((tuple(shape), str(self.dtype)),), donation=(),
            policy="",
            sharding=self._sharding_desc(shape))

    def compile_shape(self, shape: tuple):
        """Acquire the inference executable for one concrete input
        shape (idempotent): deserialize it from the persistent
        executable store when warm (ISSUE 13 — zero XLA compiles on a
        warm restart), else lower + compile (and commit the serialized
        result for the next process)."""
        import time as _time

        from deeplearning4j_tpu import compilestore

        shape = tuple(shape)
        if shape in self._compiled:
            return self._compiled[shape]
        t0 = _time.perf_counter()
        if compilestore.enabled():
            exe, info = compilestore.resolve(
                self._ledger_site(), lambda: self._lower_shape(shape),
                self._store_sig(shape), program=self._program_digest())
        else:
            exe, info = self._lower_shape(shape).compile(), None
        self._note_compiled(shape, exe, _time.perf_counter() - t0, info)
        with self._lock:
            self._compiled.setdefault(shape, exe)
        return self._compiled[shape]

    def estimate_shape_bytes(self, shape):
        """Pre-compile footprint estimate for one bucket shape
        (ISSUE 14 admission planner): ``(input_bytes, output_bytes)``
        via ``jax.eval_shape`` — a host-side trace, never an XLA
        compile. None when this adapter cannot be shape-evaluated (the
        planner then refuses to guess)."""
        import jax

        from deeplearning4j_tpu.telemetry import memledger

        try:
            spec = self._input(
                jax.ShapeDtypeStruct(tuple(shape), self.dtype))
            out = jax.eval_shape(self._jit_fn(), *self._call_args(),
                                 spec)
            return (memledger.tree_bytes(spec),
                    memledger.tree_bytes(out))
        except Exception:
            return None

    def warmup(self, ladder: BucketLadder) -> list[tuple]:
        """AOT-compile every ladder shape; returns the warmed shapes.
        Progress is visible in the /healthz ``compile`` section while
        the ladder is mid-warmup (ISSUE 11 satellite)."""
        from deeplearning4j_tpu.telemetry import compile_ledger

        shapes = ladder.shapes(self.example_shape)
        with compile_ledger.warmup_scope(self._ledger_site(),
                                         len(shapes)) as progress:
            for s in shapes:
                self.compile_shape(s)
                progress.step()
        return shapes

    @property
    def warmed_shapes(self) -> list[tuple]:
        return sorted(self._compiled)

    # -- hot path -----------------------------------------------------------
    def infer(self, x) -> np.ndarray:
        """Run one already-bucketed batch. Warmed shapes execute the AOT
        executable (zero compiles); unwarmed shapes fall through to the
        jitted function (compiles once, visible in dl4j_compile_total)."""
        x = np.ascontiguousarray(x, dtype=self.dtype)
        exe = self._compiled.get(x.shape)
        if exe is not None:
            y = exe(*self._placed_args(), self._input(x))
        else:
            y = self._jit_fn()(*self._placed_args(), self._input(x))
        return self._output(y)


class NetworkServable(Servable):
    """MultiLayerNetwork: reuses the network's own jitted inference
    function, so direct `net.output()` calls and serving share one jit
    cache (and produce bit-identical results)."""

    def __init__(self, net, example_shape, dtype=None):
        super().__init__(example_shape,
                         _model_dtype(net) if dtype is None else dtype)
        self.net = net

    def _jit_fn(self):
        return self.net._infer_fn(False)

    def _call_args(self):
        return (self.net._params, self.net._states)

    def _program_digest(self):
        # the configuration JSON is the full architecture (weights are
        # call args, not constants): same conf + dtype => same program
        return (f"infer:MultiLayerNetwork:{self.net.conf.to_json()}"
                f":dtype={self.dtype}")


class GraphServable(Servable):
    """ComputationGraph (single input / single output)."""

    def __init__(self, graph, example_shape, dtype=None):
        super().__init__(example_shape,
                         _model_dtype(graph) if dtype is None else dtype)
        if len(graph.conf.inputs) != 1 or len(graph.conf.outputs) != 1:
            raise ValueError(
                f"serving supports single-input/single-output graphs; "
                f"got inputs={graph.conf.inputs} "
                f"outputs={graph.conf.outputs}")
        self.graph = graph
        self._in = graph.conf.inputs[0]
        self._out = graph.conf.outputs[0]
        self._jitted = None

    def _jit_fn(self):
        if self._jitted is None:
            import jax

            g, out = self.graph, self._out

            def fn(params, states, inputs):
                params = g._cast_for_inference(params)
                env, _ = g._forward(params, states, inputs, False, None)
                return g._cast_output(env[out])

            self._jitted = jax.jit(fn)
        return self._jitted

    def _call_args(self):
        return (self.graph._params, self.graph._states)

    def _input(self, x):
        return {self._in: x}

    def _program_digest(self):
        return (f"infer:ComputationGraph:{self.graph.conf.to_json()}"
                f":in={self._in}:out={self._out}:dtype={self.dtype}")


class SameDiffServable(Servable):
    """SameDiff graph: serve one placeholder -> one output variable."""

    def __init__(self, sd, input_name, output_name, example_shape,
                 dtype=None):
        super().__init__(example_shape,
                         np.float32 if dtype is None else dtype)
        import jax

        self.sd = sd
        self.input_name = (input_name.name()
                           if hasattr(input_name, "name") else input_name)
        self.output_name = (output_name.name()
                            if hasattr(output_name, "name") else output_name)
        self._rng = jax.random.key(sd._seed)

    def _jit_fn(self):
        return self.sd._jitted((self.output_name,), False)

    def _call_args(self):
        params, consts = self.sd._split_values()
        return (params, consts, self._rng)

    def _input(self, x):
        return {self.input_name: x}

    def _output(self, y):
        return _np(y[self.output_name])

    def _lower_shape(self, shape):
        # SameDiff's traced fn takes the input dict FIRST
        params, consts, rng = self._placed_args()
        spec = self._input(self._input_spec(shape))
        return self._jit_fn().lower(spec, params, consts, rng)

    def estimate_shape_bytes(self, shape):
        import jax

        from deeplearning4j_tpu.telemetry import memledger

        try:
            spec = self._input(
                jax.ShapeDtypeStruct(tuple(shape), self.dtype))
            out = jax.eval_shape(self._jit_fn(), spec,
                                 *self._call_args())
            return (memledger.tree_bytes(spec),
                    memledger.tree_bytes(out))
        except Exception:
            return None

    def infer(self, x):
        x = np.ascontiguousarray(x, dtype=self.dtype)
        exe = self._compiled.get(x.shape)
        fn = exe if exe is not None else self._jit_fn()
        return self._output(fn(self._input(x), *self._placed_args()))


class FnServable(Servable):
    """A plain `fn(x) -> y` (jax-traceable), jitted and bucket-compiled
    like any network — the escape hatch for custom pipelines."""

    def __init__(self, fn, example_shape, dtype=None):
        super().__init__(example_shape,
                         np.float32 if dtype is None else dtype)
        import jax

        self._jitted = jax.jit(fn)

    def _jit_fn(self):
        return self._jitted

    def _call_args(self):
        return ()


def estimate_warmup_bytes(servable, ladder) -> dict | None:
    """Pre-compile footprint of a full ladder warmup (ISSUE 14
    admission planner): the servable's call-arg bytes (params, counted
    once — every bucket shares them) plus per-bucket input + output
    bytes from ``jax.eval_shape``. A deliberate *lower bound* — XLA
    temp buffers are unknowable before compile — that still catches
    the order-of-magnitude mistakes (a ladder that cannot possibly
    fit) before the first compile burns minutes and then OOMs
    mid-ladder. None when the servable cannot be shape-evaluated."""
    from deeplearning4j_tpu.telemetry import memledger

    shapes = ladder.shapes(servable.example_shape)
    buckets = {}
    total = 0
    for s in shapes:
        est = servable.estimate_shape_bytes(s)
        if est is None:
            return None
        in_b, out_b = est
        buckets["x".join(str(d) for d in s)] = in_b + out_b
        total += in_b + out_b
    try:
        param_bytes = memledger.tree_bytes(servable._call_args())
    except Exception:
        param_bytes = 0
    return {"param_bytes": param_bytes, "buckets": buckets,
            "total": total + param_bytes, "basis": "eval_shape"}


def as_servable(model, example_shape=None, dtype=None,
                input_name=None, output_name=None) -> Servable:
    """Wrap any supported model type in its Servable adapter.

    dtype=None (the default) infers the serving-boundary dtype from the
    model's configured dataType / precision policy instead of assuming
    np.float32 — a bf16 net serves bf16, a bf16_mixed net serves fp32."""
    if isinstance(model, Servable):
        return model
    kind = type(model).__name__
    if kind == "MultiLayerNetwork":
        return NetworkServable(model, example_shape, dtype)
    if kind == "ComputationGraph":
        return GraphServable(model, example_shape, dtype)
    if kind == "SameDiff":
        if input_name is None or output_name is None:
            raise ValueError(
                "SameDiff serving needs input_name= and output_name=")
        return SameDiffServable(model, input_name, output_name,
                                example_shape, dtype)
    if callable(model):
        return FnServable(model, example_shape, dtype)
    raise TypeError(f"cannot serve a {kind}")
