"""Admission control: priority classes, per-model concurrency budgets,
and load shedding (ISSUE 8 tentpole c).

The PR-2 serving path had exactly one overload defense: a bounded queue
whose QueueFullError rejected WHOEVER arrived next — under 2x overload
every caller's p99 degrades together, which is the opposite of what a
production tier wants. Admission control makes overload a POLICY:

- four priority classes — ``high`` (interactive / SLO-bound),
  ``normal`` (default), ``batch`` (best-effort backfill), and
  ``train`` (ISSUE 20: the fleet fine-tuner's steps, arbitrated
  against serving on the same host);
- a per-model concurrency budget (requests admitted and not yet
  terminal). Lower classes are capped at a FRACTION of the budget, so
  headroom is reserved: ``train`` traffic is shed first, ``batch``
  next, then ``normal``, and ``high`` keeps the full budget. Under 2x
  overload the best-effort tail absorbs the shedding and high-priority
  p99 stays near its unloaded value (the bench.py `serving_load` row
  measures exactly this; `fleet_loop` measures the train-vs-serve
  arbitration);
- shed responses carry a computed ``retry_after`` (seconds), derived
  from the recent per-request service rate and the current standing
  load — an honest backoff hint for HTTP 429 Retry-After instead of a
  constant.

The controller is intentionally approximate: one lock, integer loads,
EWMA service rate. Admission decisions are made BEFORE a request
touches the batching queue, so a shed costs ~1 µs and no queue slot.
"""

from __future__ import annotations

import threading
import time

PRIORITIES = ("high", "normal", "batch", "train")

# fraction of a model's budget each class may fill (cumulative with
# everything above it): train is shed beyond 25% standing load, batch
# beyond 50%, normal beyond 85%, high rides to the full budget — so a
# co-hosted fine-tune loop can never occupy more than a quarter of a
# serving model's budget, and is the first thing shed under load
DEFAULT_CLASS_FRACTION = {"high": 1.0, "normal": 0.85, "batch": 0.5,
                          "train": 0.25}


class ShedError(RuntimeError):
    """Request shed by admission control (HTTP 429 + Retry-After)."""

    def __init__(self, message, retry_after=0.1, priority="normal"):
        super().__init__(message)
        self.retry_after = float(retry_after)
        self.priority = priority


class _ModelBudget:
    __slots__ = ("budget", "fractions", "standing", "rate_ewma",
                 "last_done")

    def __init__(self, budget, fractions):
        self.budget = int(budget)
        self.fractions = dict(fractions)
        self.standing = 0          # admitted, not yet terminal
        self.rate_ewma = 0.0       # completions per second (EWMA)
        self.last_done = None


class Ticket:
    """One admitted request; release() exactly once when terminal (the
    session wires it to the future's done-callback)."""

    __slots__ = ("_ctrl", "model", "priority", "_released")

    def __init__(self, ctrl, model, priority):
        self._ctrl = ctrl
        self.model = model
        self.priority = priority
        self._released = False

    def release(self):
        if self._released:
            return
        self._released = True
        self._ctrl._release(self.model)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class AdmissionController:
    """Per-model budgets + priority-class shedding.

    `default_budget` applies to models without an explicit
    `set_budget`. `instruments` is a zero-arg callable returning the
    model's ServingInstruments (or None) — only used to count sheds.
    """

    def __init__(self, default_budget=64, class_fractions=None,
                 min_retry_after=0.05, max_retry_after=5.0):
        self.default_budget = int(default_budget)
        self.class_fractions = dict(class_fractions
                                    or DEFAULT_CLASS_FRACTION)
        self.min_retry_after = min_retry_after
        self.max_retry_after = max_retry_after
        self._models: dict[str, _ModelBudget] = {}
        self._lock = threading.Lock()

    def set_budget(self, model, budget, class_fractions=None):
        with self._lock:
            self._models[model] = _ModelBudget(
                budget, class_fractions or self.class_fractions)
        return self

    def describe(self) -> dict:
        with self._lock:
            return {m: {"budget": b.budget, "standing": b.standing,
                        "fractions": dict(b.fractions),
                        "service_rate": round(b.rate_ewma, 3)}
                    for m, b in self._models.items()}

    def _get(self, model) -> _ModelBudget:
        b = self._models.get(model)
        if b is None:
            b = _ModelBudget(self.default_budget, self.class_fractions)
            self._models[model] = b
        return b

    def admit(self, model, priority="normal", inst=None) -> Ticket:
        """Admit or shed. Raises ShedError with a computed retry_after
        when the request's class is over its share of the budget."""
        if priority not in self.class_fractions:
            raise ValueError(
                f"unknown priority {priority!r}; choose from "
                f"{sorted(self.class_fractions)}")
        with self._lock:
            b = self._get(model)
            cap = max(1, int(b.budget * b.fractions.get(priority, 1.0)))
            if b.standing >= cap:
                excess = b.standing - cap + 1
                retry = self._retry_after(b, excess)
                shed = ShedError(
                    f"model {model!r} over its {priority!r} budget "
                    f"({b.standing}/{cap} standing, budget "
                    f"{b.budget}); retry in {retry:.2f}s",
                    retry_after=retry, priority=priority)
                standing = b.standing
            else:
                b.standing += 1
                shed = None
        if shed is not None:
            if inst is not None:
                inst.shed(priority)
                inst.request("shed")
            # a shed is a POLICY decision: the flight recorder names
            # the model, class, standing load, and (when the request
            # was sampled) its trace id — an incident dump says who
            # was turned away, not just how many (ISSUE 10 satellite)
            from deeplearning4j_tpu.telemetry import flight, tracing

            ctx = tracing.current()
            flight.record("shed", model=model, priority=priority,
                          standing=standing,
                          retry_after=round(shed.retry_after, 4),
                          trace_id=(ctx.trace_id if ctx is not None
                                    else None))
            raise shed
        return Ticket(self, model, priority)

    def _release(self, model):
        now = time.perf_counter()
        with self._lock:
            b = self._models.get(model)
            if b is None:
                return
            b.standing = max(0, b.standing - 1)
            if b.last_done is not None:
                dt = now - b.last_done
                if dt > 0:
                    inst_rate = 1.0 / dt
                    b.rate_ewma = (inst_rate if b.rate_ewma == 0.0
                                   else 0.9 * b.rate_ewma
                                   + 0.1 * inst_rate)
            b.last_done = now

    def _retry_after(self, b, excess) -> float:
        """Seconds until `excess` standing requests should have
        drained at the recent service rate."""
        if b.rate_ewma <= 0.0:
            return self.min_retry_after
        return float(min(self.max_retry_after,
                         max(self.min_retry_after,
                             excess / b.rate_ewma)))
