"""DynamicBatcher: coalesce concurrent predict() calls into bucketed
device dispatches.

The economics (PAPERS.md "Towards High Performance Java-based Deep
Learning Frameworks", and the cuDNN paper's fixed-shape lesson): per-
request dispatch overhead dominates small-batch inference, so N
concurrent single-example requests should cost ~1 device dispatch, not
N. The worker thread drains a bounded queue, packs requests into the
smallest covering bucket (padding the remainder), executes ONE warmed
executable, and splits the result rows back to each caller's Future.

Semantics:
- max-latency flush: the first request in a batch waits at most
  `max_latency` seconds for co-travelers, then the batch executes;
- backpressure: the queue is bounded; `submit()` on a full queue raises
  QueueFullError immediately (callers see HTTP 429) instead of letting
  latency grow without bound;
- per-request timeout: a request that exceeds its deadline while still
  QUEUED fails with ServingTimeout and never reaches the device
  (outcome `timeout_queued`); one whose deadline passes DURING the
  device dispatch completes but is recorded as `timeout_execute` — the
  split tells an operator whether p99 is dying in the queue (shed
  harder / add replicas) or on the device (kernels too slow), which a
  single collapsed `timeout` outcome hid;
- graceful shutdown: close() stops the worker and fails queued requests
  with ServingShutdown rather than hanging their futures.

With an `executor` (a ReplicaSet), the worker thread becomes a pure
coalescer: formed batches are handed to the work-stealing scheduler as
BatchTasks and the padding/concat/dispatch/split runs on a replica
worker, so N devices execute N batches concurrently instead of
serializing through this thread.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from concurrent.futures import Future

import numpy as np

from deeplearning4j_tpu.serving.buckets import pad_rows, pad_time
from deeplearning4j_tpu.telemetry import flight, tracing

# process-wide request ids: every request carries one so flight-recorder
# serving summaries (ISSUE 3) correlate with client-side logs
_REQ_IDS = itertools.count(1)


class QueueFullError(RuntimeError):
    """Backpressure: the batching queue is at capacity."""


class ServingTimeout(TimeoutError):
    """The request's deadline passed before it reached the device."""


class ServingShutdown(RuntimeError):
    """The batcher shut down with this request still queued."""


class _Request:
    __slots__ = ("x", "n", "t", "future", "t_enqueue", "deadline",
                 "req_id", "model", "started", "priority", "trace",
                 "t_open", "t_formed")

    def __init__(self, x, deadline, model=None, priority="normal",
                 trace=None):
        self.x = x
        self.n = x.shape[0]
        # real trailing time length of sequence inputs: results slice
        # back to it after bucket padding
        self.t = x.shape[-1] if x.ndim >= 3 else None
        self.future = Future()
        self.t_enqueue = time.perf_counter()
        self.deadline = deadline
        self.req_id = next(_REQ_IDS)
        self.model = model
        self.started = False   # set_running already done (replica re-run)
        self.priority = priority
        # sampled-trace context captured at submit (None = unsampled):
        # rides the request across the batcher/replica threads so
        # run_batch can emit the queue/coalesce/replica-queue/execute
        # phase spans retroactively (ISSUE 10)
        self.trace = trace
        self.t_open = None     # coalescer popped this batch's head
        self.t_formed = None   # batch closed / handed to the executor

    def trace_id(self):
        return self.trace.trace_id if self.trace is not None else None

    def expired(self, now):
        return self.deadline is not None and now > self.deadline

    def summary(self, outcome, queue_s=None, **extra):
        """Flight-recorder serving summary (one ring-buffer append).
        Pass queue_s when dispatch already happened — measuring it here
        would fold the execute time into the queue wait."""
        if queue_s is None:
            queue_s = time.perf_counter() - self.t_enqueue
        if self.trace is not None:   # sampled: the event names its trace
            extra.setdefault("trace_id", self.trace.trace_id)
        flight.record("serving", req_id=self.req_id, model=self.model,
                      outcome=outcome, rows=self.n,
                      queue_s=round(queue_s, 6), **extra)

    def fail(self, exc, instruments, outcome):
        if self.started:            # already RUNNING (mid-execute fail)
            ok = not self.future.done()
        else:
            ok = self.future.set_running_or_notify_cancel()
            self.started = True
        if ok and not self.future.done():
            self.future.set_exception(exc)
        if instruments is not None:
            instruments.request(outcome)
        self.summary(outcome)


def execute_plan(entry, xs, servable=None):
    """Execute already-coalesced rows through the entry's bucketed
    executables: pad the time axis to its covering bucket ONCE, chunk
    rows by ladder.plan, pad each chunk to its bucket, run, and slice
    the padding rows back off. The ONE ladder-execution algorithm,
    shared by the batcher worker, the session's direct path, and the
    replica workers (which pass their device-pinned `servable` clone).
    Returns (y_real_rows_time_padded, device_dispatch_count,
    padded_row_count).
    """
    ladder = entry.ladder
    sv = servable if servable is not None else entry.servable
    if xs.ndim >= 3:
        xs = pad_time(xs, ladder.covering_seq(xs.shape[-1]))
    n = xs.shape[0]
    outs, n_padded, off = [], 0, 0
    plan = ladder.plan(n)
    for bucket in plan:
        take = min(bucket, n - off)
        chunk = pad_rows(xs[off:off + take], bucket)
        outs.append(sv.infer(chunk)[:take])
        off += take
        n_padded += bucket
    y = outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)
    return y, len(plan), n_padded


def _mark_running(req) -> bool:
    """set_running_or_notify_cancel, tolerant of a batch being re-run
    after a replica died mid-execute (the future is already RUNNING on
    the second attempt — only a cancelled/finished future opts out;
    the `started` flag avoids re-poking a RUNNING future, which logs a
    critical and raises)."""
    if req.started:
        return not req.future.done()
    ok = req.future.set_running_or_notify_cancel()
    req.started = True
    return ok


def run_batch(entry, batch, inst, servable=None, replica=None):
    """Run one formed batch of requests end to end: late expiry check
    (outcome `timeout_queued`), pad/concat, ladder execution, result
    split, telemetry, and the mid-execute deadline check (outcome
    `timeout_execute`). Shared by the DynamicBatcher's inline worker
    and the ReplicaSet workers. Raises ReplicaDeath through (the
    scheduler re-queues the batch); every other exception terminates
    the requests with outcome `error`. Returns True when the dispatch
    errored — the ReplicaSet's circuit breaker counts consecutive
    errors per replica."""
    from deeplearning4j_tpu.serving.replica import ReplicaDeath

    now = time.perf_counter()
    live, first_run = [], []
    for r in batch:
        if r.expired(now):
            r.fail(ServingTimeout("timed out in queue"), inst,
                   "timeout_queued")
        else:
            first = not r.started   # before _mark_running flips it
            if _mark_running(r):
                live.append(r)
                if first:
                    first_run.append(r)
            else:
                if inst is not None:
                    inst.request("rejected")  # caller cancelled
                r.summary("cancelled")
    if not live:
        return False
    total = sum(r.n for r in live)
    if inst is not None:
        # only first attempts: a batch re-run after a replica death
        # would fold the failed attempt's execute time into the
        # queue-wait histogram and skew exactly the signal the
        # timeout_queued/timeout_execute split is meant to clean up
        for r in first_run:
            inst.queue_wait.observe(now - r.t_enqueue,
                                    exemplar=r.trace_id())
    for r in first_run:
        if r.trace is None:
            continue
        # retroactive phase spans (ISSUE 10): the request's wall time
        # decomposes into queue-wait (enqueue -> the coalescer popped
        # this batch's head), coalesce (the max-latency window the
        # batch held open), and — executor mode — replica-queue (batch
        # formed -> a replica worker picked it up)
        t_open = min(r.t_open if r.t_open is not None else now, now)
        t_formed = min(r.t_formed if r.t_formed is not None else now, now)
        tracing.emit("serving.queue_wait", r.trace, r.t_enqueue,
                     max(r.t_enqueue, t_open), req_id=r.req_id)
        tracing.emit("serving.coalesce", r.trace,
                     max(r.t_enqueue, t_open), t_formed,
                     batch_rows=total)
        if replica is not None:
            tracing.emit("serving.replica_queue", r.trace, t_formed,
                         now, replica=replica)
    try:
        if live[0].t is not None:
            # sequence inputs may differ in trailing length within
            # one coalesced batch: pad each to the covering seq
            # bucket of the longest BEFORE concatenating (results
            # slice back to each request's own real length)
            t_bucket = entry.ladder.covering_seq(max(r.t for r in live))
            parts = [pad_time(r.x, t_bucket) for r in live]
        else:
            parts = [r.x for r in live]
        xs = (np.concatenate(parts, axis=0)
              if len(parts) > 1 else parts[0])
        t0 = time.perf_counter()
        y, n_dispatch, n_padded = execute_plan(entry, xs,
                                               servable=servable)
        dt = time.perf_counter() - t0
        if inst is not None:
            inst.execute.observe(
                dt, exemplar=next((r.trace_id() for r in live
                                   if r.trace is not None), None))
            inst.dispatch.inc(n_dispatch)
            inst.occupancy.set(total / max(n_padded, 1))
        for r in live:
            if r.trace is not None:
                tracing.emit("serving.execute", r.trace, t0, t0 + dt,
                             batch_rows=total, dispatches=n_dispatch,
                             **({} if replica is None
                                else {"replica": replica}))
        done_at = time.perf_counter()
        off = 0
        for r in live:
            seg = y[off:off + r.n]
            if r.t is not None and seg.ndim >= 3 and \
                    seg.shape[-1] != r.t:
                seg = seg[..., :r.t]
            off += r.n
            if r.expired(done_at):
                # deadline passed while the device was executing: the
                # caller already gave up, and the distinction from a
                # queued expiry is what names the p99 driver
                r.fail(ServingTimeout("deadline passed mid-execute"),
                       inst, "timeout_execute")
                continue
            # per-request phase durations ride the future (read by
            # session.predict(timing=) → the worker's Server-Timing
            # header, ISSUE 16 hop decomposition); stamped BEFORE
            # set_result so a waiter woken by the result sees them
            r.future.dl4j_timing = {"queue": round(now - r.t_enqueue, 6),
                                    "execute": round(dt, 6)}
            r.future.set_result(seg)
            if inst is not None:
                inst.request("ok")
            extra = {} if replica is None else {"replica": replica}
            r.summary("ok", queue_s=now - r.t_enqueue,
                      batch_rows=total, dispatches=n_dispatch,
                      execute_s=round(dt, 6), **extra)
    except ReplicaDeath:
        raise                     # scheduler re-queues; futures stay live
    except Exception as e:  # surface the device error to every caller
        # OOM forensics (ISSUE 14): an allocation failure during the
        # coalesced dispatch fails the requests with the typed
        # DeviceOomError (flight `oom` event names this seam, the
        # requested bytes, and the top HBM claims)
        from deeplearning4j_tpu.telemetry import memledger

        oom = memledger.oom_error(e, site="serving.run_batch",
                                  model=entry.name)
        if oom is not None:
            e = oom
        for r in live:
            if not r.future.done():
                r.future.set_exception(e)
            if inst is not None:
                inst.request("error")
            r.summary("error", queue_s=now - r.t_enqueue,
                      error=f"{type(e).__name__}: {e}")
        return True
    return False


_PRIO_RANK = {"high": 0, "normal": 1, "batch": 2}


class DynamicBatcher:
    """One worker thread per served model.

    `entry` is a ModelRegistry entry (servable + ladder); `instruments`
    a telemetry.ServingInstruments, a zero-arg callable returning one
    (or None) — re-resolved per use so telemetry toggled mid-flight is
    honored — or None. `executor` is an optional ReplicaSet: formed
    batches are submitted to its work-stealing scheduler instead of
    executing on this thread (the batcher owns the executor's
    lifecycle: retire/close cascade).

    The coalescing queue is a PRIORITY queue (high < normal < batch,
    FIFO within a class via the monotonic request id): under overload
    a high-priority request jumps the standing best-effort backlog
    instead of aging behind it — one of the three places the ISSUE 8
    priority story is enforced (admission budget, coalescing order,
    replica-queue placement).
    """

    _SENTINEL = object()

    def __init__(self, entry, max_latency=0.002, queue_size=256,
                 default_timeout=30.0, instruments=None, executor=None):
        self.entry = entry
        self.max_latency = float(max_latency)
        self.default_timeout = default_timeout
        self.executor = executor
        self._instruments_fn = (instruments if callable(instruments)
                                else lambda: instruments)
        self._accepting = True
        self._q: queue.Queue = queue.PriorityQueue(maxsize=queue_size)
        self._carry = None   # dequeued but didn't fit the closing batch
        self._closed = False
        # serializes submit-enqueue against close-drain: without it a
        # request enqueued between close()'s drain and the closed check
        # would never be completed nor failed
        self._submit_lock = threading.Lock()
        self._worker = threading.Thread(
            target=self._run, name=f"dl4j:batcher:coalescer-{entry.name}",
            daemon=True)
        self._worker.start()

    # -- client side --------------------------------------------------------
    def submit(self, x, timeout=None, priority="normal") -> Future:
        """Enqueue one request batch [n, ...]; returns its Future.
        Raises QueueFullError when the bounded queue is at capacity.
        `priority` rides with the request: a ReplicaSet executor places
        batches carrying high-priority requests at the HEAD of a
        replica queue (the single coalescing queue itself stays
        FIFO)."""
        x = np.asarray(x)
        if timeout is None:
            timeout = self.default_timeout
        deadline = (time.perf_counter() + timeout
                    if timeout is not None else None)
        # the caller's sampled trace context (None when unsampled or
        # telemetry disabled — zero tracer calls either way) crosses
        # to the worker/replica threads on the request itself
        req = _Request(x, deadline, model=self.entry.name,
                       priority=priority, trace=tracing.current())
        inst = self._instruments_fn()
        try:
            with self._submit_lock:
                if self._closed or not self._accepting:
                    raise ServingShutdown(
                        f"batcher for {self.entry.name!r} closed")
                self._q.put_nowait((_PRIO_RANK.get(priority, 1),
                                    req.req_id, req))
        except queue.Full:
            if inst is not None:
                inst.request("rejected")
            req.summary("rejected")
            raise QueueFullError(
                f"serving queue for {self.entry.name!r} is full "
                f"({self._q.maxsize} requests)") from None
        if inst is not None:
            inst.depth.set(self._q.qsize())
        return req.future

    def queue_depth(self) -> int:
        depth = self._q.qsize() + (1 if self._carry is not None else 0)
        if self.executor is not None:
            depth += self.executor.depth()
        return depth

    def retire(self, timeout=30.0):
        """Rolling-update shutdown: stop ACCEPTING, let the worker
        finish everything already queued, then stop. (close() is the
        fail-fast path.)"""
        with self._submit_lock:
            if self._closed:
                return
            self._accepting = False
        # rank above every priority class: drains the queue first
        self._q.put((max(_PRIO_RANK.values()) + 1, next(_REQ_IDS),
                     self._SENTINEL))
        self._worker.join(timeout)
        if self.executor is not None:
            self.executor.retire(timeout)
        self._closed = True

    def close(self, timeout=5.0):
        """Stop the worker; queued requests fail with ServingShutdown."""
        if self._closed:
            return
        self._closed = True
        self._accepting = False
        # rank below every class: the worker sees it next, fail-fast
        self._q.put((-1, next(_REQ_IDS), self._SENTINEL))
        self._worker.join(timeout)
        inst = self._instruments_fn()
        with self._submit_lock:       # no submit can enqueue after this
            leftovers = [] if self._carry is None else [self._carry]
            self._carry = None
            while True:
                try:
                    r = self._q.get_nowait()[2]
                except queue.Empty:
                    break
                if r is not self._SENTINEL:
                    leftovers.append(r)
            if self._worker.is_alive():
                # join timed out mid-dispatch and the drain may have
                # consumed the sentinel: re-arm it so the worker exits
                # instead of polling forever
                self._q.put((-1, next(_REQ_IDS), self._SENTINEL))
        for r in leftovers:
            r.fail(ServingShutdown("batcher closed"), inst, "shutdown")
        if self.executor is not None:
            self.executor.close(timeout)

    # -- worker side --------------------------------------------------------
    def _next(self, timeout):
        if self._carry is not None:
            r, self._carry = self._carry, None
            return r
        try:
            return self._q.get(timeout=timeout)[2]
        except queue.Empty:
            return None

    def _run(self):
        max_batch = self.entry.ladder.max_batch
        while True:
            head = self._next(timeout=0.1)
            if head is None:
                continue
            if head is self._SENTINEL:
                return
            t_open = time.perf_counter()   # coalescing window opens
            if self._closed:
                # graceful shutdown: in-flight work completed, queued
                # requests fail fast instead of executing
                head.fail(ServingShutdown("batcher closed"),
                          self._instruments_fn(), "shutdown")
                continue
            batch, total = [head], head.n
            flush_at = time.perf_counter() + self.max_latency
            while total < max_batch:
                wait = flush_at - time.perf_counter()
                if wait <= 0:
                    break
                nxt = self._next(timeout=wait)
                if nxt is None:
                    break
                if nxt is self._SENTINEL:
                    self._execute(batch, total, t_open)
                    return
                if nxt.expired(time.perf_counter()):
                    nxt.fail(ServingTimeout("timed out in queue"),
                             self._instruments_fn(), "timeout_queued")
                    continue
                if total + nxt.n > max_batch and nxt.n <= max_batch:
                    # would overflow the largest bucket: hold it for the
                    # next batch (oversized requests pass through and get
                    # chunked by the ladder plan)
                    self._carry = nxt
                    break
                batch.append(nxt)
                total += nxt.n
            self._execute(batch, total, t_open)

    def _execute(self, batch, total, t_open=None):
        inst = self._instruments_fn()
        if inst is not None:
            inst.depth.set(self._q.qsize())
        t_formed = time.perf_counter()
        for r in batch:
            if r.trace is not None:   # phase stamps for run_batch spans
                r.t_open = t_open
                r.t_formed = t_formed
        if self.executor is not None:
            # pure-coalescer mode: hand the formed batch to the
            # work-stealing scheduler; padding/dispatch/split runs on a
            # replica worker and this thread immediately coalesces the
            # next batch
            try:
                self.executor.submit_batch(batch, inst)
            except Exception as e:
                outcome = ("shutdown" if isinstance(e, ServingShutdown)
                           else "error")
                for r in batch:
                    r.fail(e, inst, outcome)
            return
        run_batch(self.entry, batch, inst)
