"""JSON request/response codec for the serving HTTP routes.

Kept transport-free so ui/server.py (stdlib http.server) stays a thin
dispatcher: this module turns a request body into a numpy batch, runs it
through an InferenceSession, and maps serving errors onto HTTP statuses:

    400  malformed JSON / wrong shape or dtype
    404  unknown model (or no serving session attached)
    429  queue full, or shed by admission control (the shed response
         carries a Retry-After header computed from the model's
         current service rate)
    504  request timed out before execution
    503  session shut down
    500  device/runtime error

Wire format (TF-Serving-style):

    POST /serving/v1/models/<name>:predict
    {"instances": [[...], ...]}             -> {"predictions": [[...], ...]}
    {"instances": [...], "version": 2, "timeout_ms": 100,
     "priority": "high"}                    # high | normal | batch
"""

from __future__ import annotations

import json

import numpy as np

from deeplearning4j_tpu.serving.admission import ShedError
from deeplearning4j_tpu.serving.batcher import (
    QueueFullError, ServingShutdown, ServingTimeout)
from deeplearning4j_tpu.serving.registry import ModelNotFound

PREDICT_SUFFIX = ":predict"
DECODE_SUFFIX = ":decode"
REGISTER_SUFFIX = ":register"
UNREGISTER_SUFFIX = ":unregister"
MODELS_PATH = "/serving/v1/models"


class HttpError(Exception):
    def __init__(self, status, message, headers=None):
        super().__init__(message)
        self.status = status
        self.message = message
        self.headers = dict(headers or {})


def _parse_suffix_path(path: str, suffix: str):
    if not path.startswith(MODELS_PATH + "/") or \
            not path.endswith(suffix):
        return None
    name = path[len(MODELS_PATH) + 1:-len(suffix)]
    return name or None


def parse_predict_path(path: str):
    """'/serving/v1/models/<name>:predict' -> name, or None when the
    path is not a predict route."""
    return _parse_suffix_path(path, PREDICT_SUFFIX)


def parse_decode_path(path: str):
    """'/serving/v1/models/<name>:decode' -> name, or None."""
    return _parse_suffix_path(path, DECODE_SUFFIX)


def parse_register_path(path: str):
    """'/serving/v1/models/<name>:register' -> name, or None. The
    fleet-admin seam (ISSUE 15): rollouts push spec-built model
    versions through the worker's versioned registry."""
    return _parse_suffix_path(path, REGISTER_SUFFIX)


def parse_unregister_path(path: str):
    """'/serving/v1/models/<name>:unregister' -> name, or None."""
    return _parse_suffix_path(path, UNREGISTER_SUFFIX)


def handle_register(admin, name: str, body: bytes) -> bytes:
    """POST /serving/v1/models/<name>:register — register a model
    version from a JSON spec (fleet rollouts, docs/FLEET.md):

        {"spec": {"kind": "linear", ...}, "version": 2,
         "warmup": true}
        -> {"model": ..., "version": 2, "warmed": true}
    """
    if admin is None:
        raise HttpError(404, "no fleet admin attached "
                             "(UIServer.serveFleetAdmin(admin))")
    try:
        payload = json.loads(body or b"")
    except (ValueError, UnicodeDecodeError) as e:
        raise HttpError(400, f"malformed JSON body: {e}") from None
    if not isinstance(payload, dict) or \
            not isinstance(payload.get("spec"), dict) or \
            "version" not in payload:
        raise HttpError(400, 'body must be {"spec": {...}, '
                             '"version": N}')
    try:
        entry = admin.register_spec(
            name, payload["spec"], int(payload["version"]),
            warmup=bool(payload.get("warmup", True)))
    except (ValueError, TypeError) as e:
        raise HttpError(400, str(e)) from None
    except Exception as e:
        raise HttpError(500, f"{type(e).__name__}: {e}") from None
    return json.dumps({"model": name, "version": entry.version,
                       "warmed": entry.warmed}).encode()


def handle_unregister(admin, name: str, body: bytes) -> bytes:
    """POST /serving/v1/models/<name>:unregister — retract one version
    (rollout rollback) or every version: {"version": 2} / {}."""
    if admin is None:
        raise HttpError(404, "no fleet admin attached "
                             "(UIServer.serveFleetAdmin(admin))")
    try:
        payload = json.loads(body or b"{}")
    except (ValueError, UnicodeDecodeError) as e:
        raise HttpError(400, f"malformed JSON body: {e}") from None
    version = payload.get("version") if isinstance(payload, dict) \
        else None
    try:
        admin.unregister(name, version)
    except ModelNotFound as e:
        raise HttpError(404, f"unknown model: {e}") from None
    except (ValueError, TypeError) as e:
        raise HttpError(400, str(e)) from None
    except Exception as e:
        raise HttpError(500, f"{type(e).__name__}: {e}") from None
    return json.dumps({"model": name, "unregistered": version}).encode()


def handle_decode(session, name: str, body: bytes,
                  timing=None) -> bytes:
    """POST /serving/v1/models/<name>:decode — continuous-batching
    autoregressive decode:

        {"prompt": [1, 2, 3], "max_new_tokens": 16,
         "eos_id": 0, "priority": "high"}       # eos/priority optional
        -> {"model": ..., "tokens": [...]}

    ``timing`` receives the request's ``ttft`` seconds for the
    Server-Timing header (decode rollouts judge latency on TTFT).
    """
    if session is None:
        raise HttpError(404, "no serving session attached "
                             "(UIServer.serveModels(session))")
    try:
        payload = json.loads(body or b"")
    except (ValueError, UnicodeDecodeError) as e:
        raise HttpError(400, f"malformed JSON body: {e}") from None
    if not isinstance(payload, dict) or "prompt" not in payload \
            or "max_new_tokens" not in payload:
        raise HttpError(400, 'body must be {"prompt": [...], '
                             '"max_new_tokens": N}')
    priority = payload.get("priority", "normal")
    if priority not in ("high", "normal", "batch", "train"):
        raise HttpError(400, f"priority must be high|normal|batch|"
                             f"train, got {priority!r}")
    timeout = payload.get("timeout_ms")
    try:
        timeout = float(timeout) / 1e3 if timeout is not None else None
        prompt = [int(t) for t in payload["prompt"]]
        max_new = int(payload["max_new_tokens"])
        eos_id = payload.get("eos_id")
        eos_id = int(eos_id) if eos_id is not None else None
    except (TypeError, ValueError) as e:
        raise HttpError(400, f"bad decode parameters: {e}") from None
    try:
        tokens = session.decode(name, prompt, max_new, eos_id=eos_id,
                                timeout=timeout, priority=priority,
                                timing=timing)
    except ModelNotFound as e:
        raise HttpError(404, f"unknown decoder: {e}") from None
    except ShedError as e:
        raise HttpError(
            429, str(e),
            headers={"Retry-After": f"{max(e.retry_after, 0.001):.3f}"},
        ) from None
    except (ServingTimeout, TimeoutError) as e:
        raise HttpError(504, f"timed out: {e}") from None
    except ServingShutdown as e:
        raise HttpError(503, str(e)) from None
    except QueueFullError as e:
        raise HttpError(429, str(e)) from None
    except ValueError as e:
        raise HttpError(400, str(e)) from None
    except Exception as e:
        from deeplearning4j_tpu.serving.decode import (DecodeError,
                                                       DecodeShutdown)

        if isinstance(e, DecodeShutdown):
            raise HttpError(503, str(e)) from None
        if isinstance(e, DecodeError):   # limits: too long for the pool
            raise HttpError(400, str(e)) from None
        raise HttpError(500, f"{type(e).__name__}: {e}") from None
    return json.dumps({"model": name, "tokens": tokens}).encode()


def handle_models(session) -> bytes:
    """GET /serving/v1/models payload."""
    if session is None:
        raise HttpError(404, "no serving session attached "
                             "(UIServer.serveModels(session))")
    return json.dumps({"models": session.models()}).encode()


def handle_predict(session, name: str, body: bytes,
                   timing=None) -> bytes:
    """``timing`` (a dict) receives the request's queue/execute seconds
    so the transport can answer with a Server-Timing header (ISSUE 16
    hop decomposition)."""
    if session is None:
        raise HttpError(404, "no serving session attached "
                             "(UIServer.serveModels(session))")
    try:
        payload = json.loads(body or b"")
    except (ValueError, UnicodeDecodeError) as e:
        raise HttpError(400, f"malformed JSON body: {e}") from None
    if not isinstance(payload, dict) or "instances" not in payload:
        raise HttpError(400, 'body must be {"instances": [...]}')
    timeout = payload.get("timeout_ms")
    try:
        timeout = float(timeout) / 1e3 if timeout is not None else None
    except (TypeError, ValueError):
        raise HttpError(400, f"timeout_ms must be a number, "
                             f"got {timeout!r}") from None
    version = payload.get("version")
    priority = payload.get("priority", "normal")
    if priority not in ("high", "normal", "batch", "train"):
        raise HttpError(400, f"priority must be high|normal|batch|"
                             f"train, got {priority!r}")
    try:
        entry = session.registry.get(name, version)
        x = np.asarray(payload["instances"],
                       dtype=entry.servable.dtype)
        y = session.predict(name, x, timeout=timeout, version=version,
                            priority=priority, timing=timing)
    except ModelNotFound as e:
        raise HttpError(404, f"unknown model: {e}") from None
    except ShedError as e:
        # overload policy, not backpressure accident: tell the client
        # WHEN to come back (admission computed it from the model's
        # recent service rate)
        raise HttpError(
            429, str(e),
            headers={"Retry-After": f"{max(e.retry_after, 0.001):.3f}"},
        ) from None
    except QueueFullError as e:
        raise HttpError(429, str(e)) from None
    except (ServingTimeout, TimeoutError) as e:
        raise HttpError(504, f"timed out: {e}") from None
    except ServingShutdown as e:
        raise HttpError(503, str(e)) from None
    except ValueError as e:
        raise HttpError(400, str(e)) from None
    except Exception as e:
        raise HttpError(500, f"{type(e).__name__}: {e}") from None
    return json.dumps({"model": name, "version": entry.version,
                       "predictions": np.asarray(y).tolist()}).encode()


def error_body(exc: HttpError) -> bytes:
    return json.dumps({"error": exc.message, "status": exc.status}).encode()
