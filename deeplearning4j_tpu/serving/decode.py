"""Continuous (iteration-level) batching for autoregressive decode
(ISSUE 8 tentpole b).

The PR-2 serving path batches at REQUEST granularity: a batch executes
start-to-finish, so a 5-token completion waits for the 200-token one it
shares a batch with, and a request arriving mid-batch waits for the
whole batch to drain. Token streams need iteration-level batching (the
Orca/vLLM scheduling insight): the device executes ONE token step for
every in-flight sequence per iteration, new sequences join the batch at
any token boundary, and finished sequences free their slot immediately.

Fixed shapes everywhere: the step function is compiled ONCE for
``[max_slots]`` token vectors and a preallocated paged KV pool — joins
and leaves change the CONTENT of slots, never a shape, so the steady
state adds nothing to ``dl4j_compile_total`` (the PR-2 contract,
asserted in tests).

The KV cache is PAGED (`PagedKVCache`): a pool of fixed-size
``[page]``-token blocks with a per-slot page table. A joining sequence
reserves ``ceil(total_len / page)`` pages up front (no mid-flight
eviction), a leaving one returns them; page 0 is a scratch page that
idle slots write into so the step function stays branch-free. The
blocked attention accumulation — iterate over pages, carry flash-style
online-softmax ``(m, l, o)`` — is `parallel/ring_attention.py`'s ring
body with pages in place of ring ranks (and no collectives: a decode
replica is single-device; the engine thread must stay collective-free
per the dl4jlint collective-thread rule).

Two shipped models:

- `RnnDecodeModel`: wraps a real `MultiLayerNetwork` with recurrent
  layers — slot state is the per-slot ``{h, c}`` carry rows (the
  repo's `rnnTimeStep` streaming state, batched over slots). Params
  are read live from the net: train-and-serve keeps working.
- `TransformerDecodeModel`: causal decode-only transformer over the
  paged KV pool, mirroring `models/bert.py`'s post-LN block so
  `from_bert()` can lift a trained BERT encoder's weights into a
  token-stream servable (tied LM head).

Per-sequence determinism: every op along a slot's compute path is
row-wise (LSTM carries, masked paged attention, layer norm, argmax),
so a sequence's tokens are BIT-IDENTICAL whether it decodes alone or
wedged between strangers — asserted by tests, and the property that
makes continuous batching safe to enable by default.
"""

from __future__ import annotations

import math
import queue as _queue
import threading
import time
from concurrent.futures import Future

import numpy as np

from deeplearning4j_tpu.telemetry import compile_ledger, flight, tracing


class DecodeError(RuntimeError):
    pass


class DecodeShutdown(RuntimeError):
    """Engine closed with this request still pending."""


# ---------------------------------------------------------------------------
# paged KV bookkeeping (host side)
# ---------------------------------------------------------------------------

class PagedKVCache:
    """Host-side page accounting for a preallocated device KV pool.

    `n_pages` counts the usable pool (page 0 is reserved scratch for
    idle slots, so the device pool must hold ``n_pages + 1`` pages).
    Allocation is all-up-front per sequence: `reserve()` either grants
    every page the sequence can ever touch or refuses — admission
    control at the slot boundary instead of mid-decode eviction.

    Pages are REFCOUNTED (ISSUE 12): a slot's reservation holds one
    reference per page, and the cross-request `PrefixCache`
    (serving/prefix_cache.py) holds its own reference on pages it has
    published. A page returns to the free pool only when its last
    reference drops — so a finished request's shared-prefix pages
    stay resident for the next request to adopt, and `release()`
    after `clear()`-ing the cache provably returns the pool to fully
    free (the leak assertion in tests)."""

    def __init__(self, n_pages, page, max_pages_per_slot, max_slots):
        if page < 1 or n_pages < 1:
            raise ValueError(f"need page >= 1 and n_pages >= 1, got "
                             f"page={page} n_pages={n_pages}")
        self.page = int(page)
        self.n_pages = int(n_pages)
        self.max_pages_per_slot = int(max_pages_per_slot)
        # page 0 = scratch; usable pages are 1..n_pages
        self._free = list(range(self.n_pages, 0, -1))
        self.table = np.zeros((max_slots, self.max_pages_per_slot),
                              np.int32)
        self._owned: dict[int, list[int]] = {}
        self._ref: dict[int, int] = {}

    def pages_for(self, total_len: int) -> int:
        return math.ceil(total_len / self.page)

    def can_reserve(self, total_len: int) -> bool:
        need = self.pages_for(total_len)
        return need <= len(self._free) and \
            need <= self.max_pages_per_slot

    def reserve(self, slot: int, total_len: int, adopted=()):
        """Grant every page ``slot`` can ever touch: ``adopted`` pages
        (shared, refcount bumped — the prefix-cache hit) fill the
        leading table entries in position order, fresh pages cover the
        suffix. Refuses rather than partially grants."""
        need = self.pages_for(total_len)
        adopted = list(adopted)
        if need > self.max_pages_per_slot:
            raise DecodeError(
                f"sequence of {total_len} tokens needs {need} pages > "
                f"max_pages_per_slot={self.max_pages_per_slot}")
        if len(adopted) > need:
            raise DecodeError(
                f"adopting {len(adopted)} pages for a {need}-page "
                f"sequence")
        fresh_need = need - len(adopted)
        if fresh_need > len(self._free):
            raise DecodeError(
                f"KV pool exhausted: need {fresh_need} fresh pages, "
                f"{len(self._free)} free")
        if 0 in adopted:
            raise DecodeError("scratch page 0 is never sharable")
        fresh = [self._free.pop() for _ in range(fresh_need)]
        pages = adopted + fresh
        for p in pages:
            self._ref[p] = self._ref.get(p, 0) + 1
        self._owned[slot] = pages
        self.table[slot, :] = 0
        self.table[slot, :need] = pages
        return pages

    def release(self, slot: int):
        pages = self._owned.pop(slot, [])
        for p in reversed(pages):
            self.decref(p)
        self.table[slot, :] = 0

    def retain(self, page: int):
        """An extra reference (the prefix cache publishing a page)."""
        if page == 0:
            raise DecodeError("scratch page 0 is never sharable")
        self._ref[page] = self._ref.get(page, 0) + 1

    def decref(self, page: int) -> bool:
        """Drop one reference; the page returns to the free pool when
        nobody holds it anymore. Returns True when freed."""
        n = self._ref.get(page, 0) - 1
        if n > 0:
            self._ref[page] = n
            return False
        self._ref.pop(page, None)
        self._free.append(page)
        return True

    def refcount(self, page: int) -> int:
        return self._ref.get(page, 0)

    def owned(self, slot: int) -> list:
        """The slot's page list in position order (adopted prefix
        first) — what the prefix cache publishes from."""
        return list(self._owned.get(slot, ()))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.n_pages - len(self._free)


def _boundary_error(e, site, what):
    """The engine-boundary failure an affected request sees: a typed
    DeviceOomError (plus a flight ``oom`` event naming the site and the
    top HBM claims) when the dispatch died on an allocation, else the
    usual RuntimeError wrapper."""
    from deeplearning4j_tpu.telemetry import memledger

    err = memledger.oom_error(e, site=site)
    if err is not None:
        return err
    return RuntimeError(f"{what}: {type(e).__name__}: {e}")


def _pool_bytes_estimate(model):
    """Bytes a decode model's state (KV pool / carries) will pin, via
    ``jax.eval_shape`` over ``init_state`` — a host-side trace, nothing
    allocated yet. None when the model cannot be shape-evaluated (the
    ISSUE 14 planner then refuses to guess)."""
    import jax

    from deeplearning4j_tpu.telemetry import memledger

    try:
        return memledger.tree_bytes(jax.eval_shape(model.init_state))
    except Exception:
        return None


# ---------------------------------------------------------------------------
# decode models
# ---------------------------------------------------------------------------

def _maybe_store(jitted, site, model, lane):
    """Route a decode-model jit through the PR-13 persistent executable
    store (ISSUE 20 satellite: the remaining cold-start gap). Warm
    engine construction then deserializes every step/prefill/verify
    executable instead of compiling — ledger-asserted zero XLA
    compiles. The sharded lane stays scoped out (ISSUE 19: serialized
    SPMD executables bake in a device assignment), and the wrapper is
    the identity when the store is off."""
    from deeplearning4j_tpu import compilestore

    if getattr(model, "mesh", None) is not None:
        return jitted
    if not compilestore.enabled():
        return jitted
    return compilestore.StoredJit(
        jitted, site, program=f"{model._store_program()}:{lane}",
        donation=())


class RnnDecodeModel:
    """Token-step decode over a MultiLayerNetwork with recurrent
    layers (the graves_lstm char-RNN workload as a token stream).

    Slot state = the network's streaming rnn carry, batched over
    ``max_slots`` rows; one engine iteration feeds every slot its next
    token id as a one-hot [S, nIn, 1] timestep through the net's own
    `_forward` — the same math `rnnTimeStep` runs, so a served stream
    matches an offline `rnnTimeStep` loop bit for bit. Params are read
    live from the net at every step (never captured)."""

    uses_pages = False
    page = None

    def __init__(self, net, max_slots=8, vocab=None):
        import jax

        net._check_init()
        self.net = net
        self.max_slots = int(max_slots)
        self._rec = set(net._recurrent_indices(forbid_bidirectional=True))
        if not self._rec:
            raise DecodeError("RnnDecodeModel needs at least one "
                              "recurrent layer")
        self.n_in = net.layers[0].nIn
        self.vocab = int(vocab) if vocab is not None else int(self.n_in)
        self._dtype = net.conf.dtype
        self._jit_step = _maybe_store(jax.jit(self._fn),
                                      "decode:step", self, "step")
        self._jit_masked = _maybe_store(jax.jit(self.masked_fn),
                                        "decode:step", self, "masked")
        # slot is a TRACED scalar: one reset executable serves every
        # slot (a static slot arg would compile per slot index and
        # break the zero-steady-state-recompiles contract)
        self._jit_reset = _maybe_store(jax.jit(self._reset_fn),
                                       "decode:reset", self, "reset")

    def _store_program(self):
        """Store program digest (the servable.py idiom): the math is a
        pure function of the net's conf plus the engine geometry, so
        identical digests guarantee identical lowered programs and a
        warm process never pays a fingerprint re-trace."""
        return (f"decode:RnnDecodeModel:{self.net.conf.to_json()}"
                f":slots={self.max_slots}:vocab={self.vocab}")

    # state: the full per-layer states list with recurrent carries
    # seeded to [max_slots] rows
    def init_state(self):
        return self.net._seed_rnn_states(self.net._states,
                                         self.max_slots)

    def _fn(self, params, state, tokens, pos, table):
        import jax
        import jax.numpy as jnp

        x = jax.nn.one_hot(tokens, self.n_in,
                           dtype=self._dtype)[:, :, None]
        y, new_state = self.net._forward(params, state, x, False, None)
        logits = y[:, :, 0].astype(jnp.float32)
        nxt = jnp.argmax(logits[:, :self.vocab], axis=-1) \
            .astype(jnp.int32)
        return nxt, new_state

    def masked_fn(self, params, state, tokens, pos, table, active):
        """The step math gated per slot: inactive rows keep their
        recurrent carries bitwise (``jnp.where`` on the carry rows).
        Active rows compute exactly ``_fn`` — the chunk-prefill loop
        body (serving/prefill.py) composes this, which is what makes
        chunked prefill bit-identical to the per-token path."""
        import jax.numpy as jnp

        nxt, new_state = self._fn(params, state, tokens, pos, table)
        out = list(new_state)
        for i in self._rec:
            out[i] = {
                k: jnp.where(
                    active.reshape((-1,) + (1,) * (v.ndim - 1)),
                    v, state[i][k])
                for k, v in new_state[i].items()}
        return jnp.where(active, nxt, -1), out

    def _reset_fn(self, state, slot):
        import jax.numpy as jnp

        out = list(state)
        for i in self._rec:
            out[i] = {k: v.at[slot].set(jnp.zeros_like(v[slot]))
                      for k, v in state[i].items()}
        return out

    def params_for_step(self):
        # read live from the net at every dispatch (never captured)
        return self.net._params

    def step(self, state, tokens, pos, table, site=None):
        args = (self.net._params, state, tokens, pos, table)
        out = self._jit_step(*args)
        if site is not None:
            compile_ledger.note_step(site, self._jit_step, args,
                                     donation=())
        return out

    def step_masked(self, state, tokens, pos, table, active, site=None):
        args = (self.net._params, state, tokens, pos, table,
                np.ascontiguousarray(active, dtype=bool))
        out = self._jit_masked(*args)
        if site is not None:
            compile_ledger.note_step(site, self._jit_masked, args,
                                     donation=())
        return out

    def reset_slot(self, state, slot):
        return self._jit_reset(state, np.int32(slot))


class TransformerDecodeModel:
    """Causal single-token decode over a paged KV pool.

    Mirrors `models/bert.py`'s post-LN encoder block (qkv/out/ln1/ffn/
    ln2 naming, gelu FFN, tied LM head), so `from_bert()` serves a
    trained encoder's weights as a token stream. Attention per slot
    iterates its OWN page-table pages with the flash-style online
    softmax carried from `ring_attention._ring_attention_local` (pages
    play the role of ring ranks; no collectives — replicas are
    single-device)."""

    uses_pages = True

    def __init__(self, params, n_heads, max_slots=8, page=16,
                 max_pages_per_slot=8, n_pages=None, eps=1e-12):
        import jax

        self.params = params
        self.n_heads = int(n_heads)
        hidden = int(np.asarray(params["tok_emb"]).shape[1])
        if hidden % self.n_heads:
            raise DecodeError(f"hidden {hidden} not divisible by "
                              f"{n_heads} heads")
        self.hidden = hidden
        self.head_dim = hidden // self.n_heads
        self.vocab = int(np.asarray(params["tok_emb"]).shape[0])
        self.max_len = int(np.asarray(params["pos_emb"]).shape[0])
        self.max_slots = int(max_slots)
        self.page = int(page)
        self.max_pages_per_slot = int(max_pages_per_slot)
        self.n_pages = (int(n_pages) if n_pages is not None
                        else max_slots * max_pages_per_slot)
        self.eps = eps
        self.n_layers = len(params["layers"])
        self._jit_step = _maybe_store(jax.jit(self._fn),
                                      "decode:step", self, "step")
        self._jit_masked = _maybe_store(jax.jit(self.masked_fn),
                                        "decode:step", self, "masked")

    def _store_program(self):
        """Store program digest: the transformer step is determined by
        the structural geometry below (param SHAPES ride in the
        per-signature key, and the values never shape the program)."""
        return (f"decode:TransformerDecodeModel:L={self.n_layers}"
                f":heads={self.n_heads}:hidden={self.hidden}"
                f":vocab={self.vocab}:max_len={self.max_len}"
                f":slots={self.max_slots}:page={self.page}"
                f":pages={self.n_pages}"
                f":pps={self.max_pages_per_slot}:eps={self.eps}")

    @classmethod
    def from_bert(cls, params, cfg, **kw):
        """Lift a `models/bert.py` param tree into a decode servable
        (cfg: BertConfig — supplies head count)."""
        kw.setdefault("page", 16)
        return cls(params, n_heads=cfg.num_heads,
                   eps=cfg.layer_norm_eps, **kw)

    @classmethod
    def init(cls, vocab=64, hidden=32, n_layers=2, n_heads=2,
             max_len=128, seed=0, **kw):
        """Standalone random init (bert-style param naming)."""
        from deeplearning4j_tpu.models.bert import (BertConfig,
                                                    init_params)
        import jax

        cfg = BertConfig(vocab_size=vocab, hidden=hidden,
                         num_layers=n_layers, num_heads=n_heads,
                         ffn=4 * hidden, max_len=max_len)
        params = init_params(cfg, jax.random.key(seed))
        return cls(params, n_heads=n_heads, **kw)

    # pools: [L, n_pages + 1, page, H, D]; page 0 is scratch
    def init_state(self):
        import jax.numpy as jnp

        shape = (self.n_layers, self.n_pages + 1, self.page,
                 self.n_heads, self.head_dim)
        return {"k": jnp.zeros(shape, jnp.float32),
                "v": jnp.zeros(shape, jnp.float32)}

    def _paged_attention(self, q, kpool, vpool, table, pos):
        """q [S,H,D] against this slot's pages. Blockwise online
        softmax over the page axis — ring_attention's accumulation with
        pages instead of ring ranks; masked pages contribute exactly
        zero, so a slot's output never depends on its neighbors."""
        import jax.numpy as jnp
        from jax import lax

        s_, h_, d_ = q.shape
        scale = 1.0 / math.sqrt(d_)
        page = self.page

        def body(i, carry):
            m, l, o = carry
            kb = kpool[table[:, i]]                  # [S, page, H, D]
            vb = vpool[table[:, i]]
            s = jnp.einsum("shd,sphd->shp", q, kb) * scale
            k_pos = i * page + jnp.arange(page)      # this block's slots
            mask = k_pos[None, :] <= pos[:, None]    # causal + length
            s = jnp.where(mask[:, None, :], s, -jnp.inf)
            blk_max = jnp.max(s, axis=-1)            # [S, H]
            new_m = jnp.maximum(m, blk_max)
            new_m_safe = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
            p = jnp.exp(s - new_m_safe[..., None])
            p = jnp.where(jnp.isfinite(s), p, 0.0)
            corr = jnp.exp(
                jnp.where(jnp.isfinite(m), m - new_m_safe, -jnp.inf))
            corr = jnp.where(jnp.isfinite(m), corr, 0.0)
            new_l = l * corr + jnp.sum(p, axis=-1)
            new_o = o * corr[..., None] + jnp.einsum("shp,sphd->shd",
                                                     p, vb)
            return new_m, new_l, new_o

        m0 = jnp.full((s_, h_), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((s_, h_), jnp.float32)
        o0 = jnp.zeros((s_, h_, d_), jnp.float32)
        m, l, o = lax.fori_loop(0, self.max_pages_per_slot, body,
                                (m0, l0, o0))
        return o / jnp.maximum(l, 1e-30)[..., None]

    def _fn(self, params, state, tokens, pos, table):
        import jax.numpy as jnp

        S = self.max_slots
        pidx = table[jnp.arange(S), pos // self.page]   # [S] write page
        return self._apply(params, state, tokens, pos, table, pidx)

    def masked_fn(self, params, state, tokens, pos, table, active):
        """The step math with inactive slots routed to scratch: their
        pool writes land on page 0 and their outputs are -1, while an
        active row computes bit-exactly what ``_fn`` computes (same
        [S]-shaped row-wise math) — the property the chunk-prefill /
        verify block executable (serving/prefill.py) is built on."""
        import jax.numpy as jnp

        S = self.max_slots
        pos = jnp.where(active, pos, 0)
        pidx = jnp.where(active,
                         table[jnp.arange(S), pos // self.page], 0)
        nxt, new_state = self._apply(params, state, tokens, pos, table,
                                     pidx)
        return jnp.where(active, nxt, -1), new_state

    def _apply(self, params, state, tokens, pos, table, pidx):
        import jax
        import jax.numpy as jnp

        S = self.max_slots
        nh, hd = self.n_heads, self.head_dim
        ln = lambda x, p: _layer_norm(x, p["g"], p["b"], self.eps)  # noqa: E731
        h = params["tok_emb"][tokens] + params["pos_emb"][pos]
        h = ln(h, params["emb_ln"])
        off = pos % self.page
        new_k, new_v = [], []
        for li, lp in enumerate(params["layers"]):
            qkv = h @ lp["qkv_w"] + lp["qkv_b"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(S, nh, hd)
            k = k.reshape(S, nh, hd)
            v = v.reshape(S, nh, hd)
            kpool = state["k"][li].at[pidx, off].set(k)
            vpool = state["v"][li].at[pidx, off].set(v)
            new_k.append(kpool)
            new_v.append(vpool)
            att = self._paged_attention(q, kpool, vpool, table, pos)
            att = att.reshape(S, nh * hd) @ lp["out_w"] + lp["out_b"]
            h = ln(h + att, lp["ln1"])
            ffn = jax.nn.gelu(h @ lp["ffn_in_w"] + lp["ffn_in_b"])
            ffn = ffn @ lp["ffn_out_w"] + lp["ffn_out_b"]
            h = ln(h + ffn, lp["ln2"])
        logits = h @ params["tok_emb"].T + params["mlm_bias"]
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        new_state = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
        return nxt, new_state

    def params_for_step(self):
        return self.params

    def step(self, state, tokens, pos, table, site=None):
        args = (self.params, state, tokens, pos, table)
        out = self._jit_step(*args)
        if site is not None:
            compile_ledger.note_step(site, self._jit_step, args,
                                     donation=())
        return out

    def step_masked(self, state, tokens, pos, table, active, site=None):
        args = (self.params, state, tokens, pos, table,
                np.ascontiguousarray(active, dtype=bool))
        out = self._jit_masked(*args)
        if site is not None:
            compile_ledger.note_step(site, self._jit_masked, args,
                                     donation=())
        return out

    def reset_slot(self, state, slot):
        # stale page contents are unreachable once the page table drops
        # them (the length mask covers in-page staleness): no wipe
        return state


def _layer_norm(x, g, b, eps):
    import jax
    import jax.numpy as jnp

    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class _DecodeRequest:
    __slots__ = ("prompt", "max_new", "eos_id", "future", "stream",
                 "slot", "ptr", "generated", "t_submit", "req_id",
                 "trace", "spans_emitted", "t_suppressed",
                 "ttft_boundaries", "published", "t_first")
    _END = object()

    def __init__(self, prompt, max_new, eos_id, req_id):
        self.prompt = [int(t) for t in prompt]
        if not self.prompt:
            raise ValueError("decode needs at least one prompt token")
        self.max_new = int(max_new)
        self.eos_id = eos_id
        self.future: Future = Future()
        self.stream: _queue.Queue = _queue.Queue()
        self.slot = None
        self.ptr = 0            # next prompt position to feed
        self.generated: list[int] = []
        self.t_submit = time.perf_counter()
        self.req_id = req_id
        # sampled-trace context captured at submit (None = unsampled):
        # the engine thread emits per-token-boundary child spans to it
        self.trace = tracing.current()
        self.spans_emitted = 0     # per-boundary spans so far
        self.t_suppressed = None   # first boundary past the span cap
        # TTFT accounting (ISSUE 12): engine boundaries this request
        # rode before its first token — the number chunked prefill
        # and prefix adoption exist to shrink
        self.ttft_boundaries = 0
        self.published = False     # prompt pages in the prefix cache
        self.t_first = None        # wall time of the first token

    def tokens(self, timeout=None):
        """Generator of tokens as they decode (terminates with the
        sequence; raises if the engine failed the request)."""
        while True:
            item = self.stream.get(timeout=timeout)
            if item is self._END:
                exc = self.future.exception()
                if exc is not None:
                    raise exc
                return
            yield item

    def result(self, timeout=None) -> list:
        return self.future.result(timeout=timeout)


class DecodeEngine:
    """Continuous batcher: one worker thread advancing every in-flight
    sequence one token per iteration.

    - `submit(prompt, max_new_tokens)` joins at the next token
      boundary if a slot (and, for paged models, enough KV pages) is
      free, else waits in the pending queue;
    - prompt PREFILL runs through the same step executable, one token
      per iteration — a joining sequence interleaves with in-flight
      decodes from its first token (no separate prefill executable,
      no second compiled shape);
    - a finished sequence (max_new reached or eos) frees its slot and
      pages at the SAME token boundary, and the next pending request
      takes them over immediately;
    - `warmup()` runs one throwaway step + slot reset so every
      executable exists before traffic; after it, `dl4j_compile_total`
      stays flat (asserted in tests).

    ISSUE 12 layers (all default-off, composable):

    - ``chunk=N``: chunked prefill — prompts retire in N-token blocks
      through a second ``[max_slots, N]`` executable at each boundary
      (serving/prefill.py), cutting TTFT boundaries from
      O(prompt_len) to O(prompt_len / N) while decoding slots keep
      streaming; bit-identical to the per-token path by construction;
    - ``prefix_cache=True``: completed full prompt pages are
      refcounted and published under a rolling token-prefix hash
      (serving/prefix_cache.py); a request with a matching prefix
      adopts the pages and prefills only its suffix. Admission counts
      cache-idle pages as reclaimable — the PR-8 head-of-line wedge
      fix;
    - ``speculative=SpeculativeConfig(draft, k)``: a draft model
      proposes k tokens per boundary, verified in one call through
      the block executable, with acceptance-EWMA fallback to plain
      decode (serving/speculative.py). Greedy output is identical to
      target-only decode.
    """

    def __init__(self, model, name="decode", pending_size=64,
                 max_new_limit=1024, instruments=None,
                 wedge_timeout=30.0, chunk=None, prefix_cache=False,
                 speculative=None, backlog_timeout=120.0):
        self.model = model
        self.name = name
        # /healthz wedge detection (ISSUE 10 satellite): with sequences
        # in flight, a token boundary is expected at least this often —
        # an engine stuck inside one step longer than this reports the
        # decoder section "degraded" (still 200)
        self.wedge_timeout = float(wedge_timeout)
        self._last_boundary = None
        # hard per-request generation cap, enforced for EVERY model:
        # paged models are also bounded by max_len/pool, but a
        # page-less RNN model has no natural ceiling — without this an
        # HTTP client asking for 10**6 tokens wedges a slot for hours
        self.max_new_limit = int(max_new_limit)
        self._instruments_fn = (instruments if callable(instruments)
                                else lambda: instruments)
        self._pending: _queue.Queue = _queue.Queue(maxsize=pending_size)
        self._waiting: list = []   # engine-side FIFO (page head-block)
        self._active: dict[int, _DecodeRequest] = {}
        self._free_slots = list(range(model.max_slots - 1, -1, -1))
        # admission-time capacity planning (ISSUE 14): validate the KV
        # pool bytes against live device headroom BEFORE allocating it
        # — a structured CapacityError beats an opaque mid-init OOM.
        # eval_shape is a host-side trace: nothing is allocated yet;
        # both it and the plan are skipped when no device capacity is
        # knowable (the engine allocates on the default device, so
        # that is the device the judgement scopes to)
        from deeplearning4j_tpu.telemetry import memledger

        self._plan_device = memledger.device_label()
        # a mesh-sharded model (serving/sharded.py) is planned as a
        # PLACEMENT: each mesh device's pool share against that
        # device's own headroom — the whole point of sharding the pool
        # is that the total never has to fit one device
        self._sharded_mesh = getattr(model, "mesh", None)
        if getattr(model, "uses_pages", False) and \
                self._sharded_mesh is not None and \
                memledger.capacity_known():
            pool_est = _pool_bytes_estimate(model)
            if pool_est is not None:
                memledger.plan_capacity(
                    f"decode:{name}:kv", pool_est,
                    detail={"lane": "target", "pages": model.n_pages,
                            "page": model.page,
                            "slots": model.max_slots,
                            "pool_shards": getattr(
                                model, "pool_shards", None)},
                    per_device=model.pool_device_bytes())
        elif getattr(model, "uses_pages", False) and \
                memledger.capacity_known(device=self._plan_device):
            pool_est = _pool_bytes_estimate(model)
            if pool_est is not None:
                memledger.plan_capacity(
                    f"decode:{name}:kv", pool_est,
                    detail={"lane": "target", "pages": model.n_pages,
                            "page": model.page,
                            "slots": model.max_slots},
                    device=self._plan_device)
        try:
            self._state = model.init_state()
        except Exception as e:
            memledger.raise_if_oom(e, site=f"decode:{name}:kv",
                                   lane="target")
            raise
        self._kv = None
        self._pool_bytes = memledger.tree_bytes(self._state)
        self._mem_claim = None   # registered at the END of __init__
        if getattr(model, "uses_pages", False):
            self._kv = PagedKVCache(model.n_pages, model.page,
                                    model.max_pages_per_slot,
                                    model.max_slots)
        self._table = (self._kv.table if self._kv is not None
                       else np.zeros((model.max_slots, 1), np.int32))
        # -- decode v2 layers (ISSUE 12), all default-off ------------------
        self._spec = None
        self._draft_mem_claim = None
        if speculative is not None:
            from deeplearning4j_tpu.serving.speculative import (
                SpeculativeConfig, SpeculativeDecoder)

            cfg = (speculative if isinstance(speculative,
                                             SpeculativeConfig)
                   else SpeculativeConfig(draft=speculative))
            if self._kv is None:
                raise DecodeError("speculative decoding needs a paged "
                                  "target model (the verifier rides "
                                  "the block executable over the "
                                  "paged pool)")
            if getattr(cfg.draft, "vocab", None) != model.vocab:
                raise DecodeError(
                    f"draft vocab {getattr(cfg.draft, 'vocab', None)} "
                    f"!= target vocab {model.vocab}")
            if cfg.draft.max_slots != model.max_slots:
                raise DecodeError(
                    f"draft max_slots {cfg.draft.max_slots} != target "
                    f"max_slots {model.max_slots}")
            # the draft lane mirrors the target's page accounting:
            # equal page size keeps adoption depths in one unit, and a
            # pool at least as roomy keeps every submit-side limit
            # check (which consults only the target) valid for the
            # draft too — a smaller draft pool would re-introduce the
            # head-of-line wedge on the mirror lane
            if cfg.draft.page != model.page:
                raise DecodeError(
                    f"draft page {cfg.draft.page} != target page "
                    f"{model.page}")
            if cfg.draft.max_pages_per_slot < model.max_pages_per_slot \
                    or cfg.draft.n_pages < model.n_pages:
                raise DecodeError(
                    f"draft pool (max_pages_per_slot="
                    f"{cfg.draft.max_pages_per_slot}, n_pages="
                    f"{cfg.draft.n_pages}) smaller than the target's "
                    f"({model.max_pages_per_slot}, {model.n_pages})")
            if chunk is None:
                # verify width doubles as the prefill block: ONE block
                # executable total (the lean-kernel default)
                chunk = cfg.k + 1
            # the draft lane's mirror pool is validated and claimed
            # exactly like the target's (ISSUE 14)
            if memledger.capacity_known(device=self._plan_device):
                draft_est = _pool_bytes_estimate(cfg.draft)
                if draft_est is not None:
                    memledger.plan_capacity(
                        f"decode:{name}:kv", draft_est,
                        detail={"lane": "draft",
                                "pages": cfg.draft.n_pages,
                                "page": cfg.draft.page,
                                "slots": cfg.draft.max_slots},
                        device=self._plan_device)
            try:
                self._spec = SpeculativeDecoder(
                    cfg, chunk, name, prefix_cache=bool(prefix_cache))
            except Exception as e:
                memledger.raise_if_oom(e, site=f"decode:{name}:kv",
                                       lane="draft")
                raise
        self._block = None
        if chunk is not None:
            from deeplearning4j_tpu.serving.prefill import ChunkedPrefill

            self._block = ChunkedPrefill(model, chunk)
        self._pcache = None
        if prefix_cache:
            from deeplearning4j_tpu.serving.prefix_cache import (
                PrefixCache)

            if self._kv is None:
                raise DecodeError("prefix caching needs a paged model "
                                  "(KV pages are what gets shared)")
            self._pcache = (prefix_cache if isinstance(prefix_cache,
                                                       PrefixCache)
                            else PrefixCache(self._kv.page))
        self.backlog_timeout = float(backlog_timeout)
        # duck-typed models (tests, foreign adapters) may predate the
        # ledger-site kwarg on step() — detect once, not per boundary
        import inspect

        try:
            self._step_takes_site = "site" in inspect.signature(
                model.step).parameters
        except (TypeError, ValueError):
            self._step_takes_site = False
        self._closed = False
        self._warmed = False
        self._ids = 0
        # HBM ledger claims registered LAST (ISSUE 14): any validation
        # raise above must not leak a claim for an engine that never
        # existed — the pools are only pinned once this line is reached.
        # A mesh-sharded pool (ISSUE 19) splits its claim per device —
        # one `name:target@<device>` row per mesh device so
        # /debug/memory attributes each device's actual share, instead
        # of one total that no single device holds
        self._shard_mem_claims = []
        if self._sharded_mesh is not None and \
                callable(getattr(model, "pool_device_bytes", None)):
            for label, share in sorted(
                    model.pool_device_bytes().items()):
                self._shard_mem_claims.append(memledger.claim(
                    "kv_cache", f"{name}:target@{label}",
                    nbytes=share, device=label, sharded=True,
                    slots=model.max_slots,
                    pages=getattr(model, "n_pages", None)))
        else:
            self._mem_claim = memledger.claim(
                "kv_cache", f"{name}:target", nbytes=self._pool_bytes,
                slots=model.max_slots,
                pages=getattr(model, "n_pages", None))
        if self._spec is not None:
            self._draft_mem_claim = memledger.claim(
                "kv_cache", f"{name}:draft",
                nbytes=self._spec.pool_bytes,
                slots=self._spec.model.max_slots,
                pages=self._spec.model.n_pages)
        # serializes submit(): the capacity check and the req-id
        # counter both race under concurrent HTTP handler threads
        self._submit_lock = threading.Lock()
        self._wake = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name=f"dl4j:decode:engine-{name}", daemon=True)
        self._thread.start()

    # -- client side ---------------------------------------------------------
    def submit(self, prompt, max_new_tokens, eos_id=None,
               timeout=None) -> _DecodeRequest:
        if self._closed:
            raise DecodeShutdown(f"decode engine {self.name!r} closed")
        if int(max_new_tokens) > self.max_new_limit:
            raise DecodeError(
                f"max_new_tokens={max_new_tokens} exceeds the "
                f"engine's limit of {self.max_new_limit} "
                f"(max_new_limit=)")
        total = len(list(prompt)) + int(max_new_tokens)
        max_len = getattr(self.model, "max_len", None)
        if max_len is not None and total > max_len:
            raise DecodeError(
                f"prompt + max_new_tokens = {total} exceeds the "
                f"model's max_len {max_len}")
        if self._kv is not None:
            need = self._kv.pages_for(total)
            # validate against BOTH per-slot max and the pool total:
            # a request that could never reserve would head-block the
            # strict-FIFO waiting line forever
            limit = min(self.model.max_pages_per_slot,
                        self._kv.n_pages)
            if need > limit:
                raise DecodeError(
                    f"sequence of {total} tokens needs {need} KV "
                    f"pages > the engine's limit of {limit} "
                    f"(max_pages_per_slot="
                    f"{self.model.max_pages_per_slot}, pool="
                    f"{self._kv.n_pages})")
        with self._submit_lock:
            # backpressure bound spans the submit queue AND the
            # engine's head-blocking FIFO (requests parked waiting for
            # KV pages) — without counting _waiting, the engine
            # draining the queue each token boundary would make
            # pending_size meaningless
            if self._pending.qsize() + len(self._waiting) >= \
                    self._pending.maxsize:
                from deeplearning4j_tpu.serving.batcher import (
                    QueueFullError)

                raise QueueFullError(
                    f"decode pending queue for {self.name!r} full "
                    f"({self._pending.maxsize} waiting)")
            self._ids += 1
            req = _DecodeRequest(prompt, max_new_tokens, eos_id,
                                 self._ids)
            self._pending.put_nowait(req)
        self._wake.set()
        return req

    def decode(self, prompt, max_new_tokens, eos_id=None,
               timeout=None) -> list:
        """Synchronous decode: the generated token ids."""
        return self.submit(prompt, max_new_tokens,
                           eos_id=eos_id).result(timeout=timeout)

    def warmup(self):
        """Compile the full executable set with throwaway iterations,
        leaving the engine state untouched (slot 0's carry is re-reset
        afterwards; block warmups run with all counts zero). Every
        executable lands in the compile ledger under a
        ``decode:<name>:*`` site, so the zero-steady-state-recompile
        invariant is ledger-assertable for the whole set: token step +
        chunk prefill + verify + draft step + draft prefill (tests)."""
        if compile_ledger.enabled():
            # the jax.monitoring hook installs on first registry use;
            # without it the warmup compiles below would never be
            # attributed to their decode:* ledger sites
            from deeplearning4j_tpu.telemetry import (registry
                                                      as _registry)

            _registry.get_registry()
        state = self.model.reset_slot(self._state, 0)
        tokens = np.zeros((self.model.max_slots,), np.int32)
        pos = np.zeros((self.model.max_slots,), np.int32)
        # a REAL copy, not ascontiguousarray (which aliases an
        # already-contiguous table): admission mutates the table
        # between boundaries, and jax may zero-copy numpy inputs
        table = self._table.copy()
        self._model_step(state, tokens, pos, table)
        if self._block is not None:
            self._block.warmup(self._state, table,
                               site=f"decode:{self.name}:prefill")
            if self._spec is not None and \
                    self._spec.k + 1 != self._block.chunk:
                self._block.warmup(self._state, table,
                                   widths=(self._spec.k + 1,),
                                   site=f"decode:{self.name}:verify")
        if self._spec is not None:
            self._spec.warmup()
        self._state = self.model.reset_slot(self._state, 0)
        self._warmed = True
        return self

    @property
    def active_slots(self) -> int:
        return len(self._active)

    def _backlog_age(self):
        """Age of the oldest request still waiting for its first token
        (queued, head-blocked, or mid-prefill) — the chunked-prefill
        backlog signal for /healthz."""
        oldest = None
        for req in list(self._waiting):
            if oldest is None or req.t_submit < oldest:
                oldest = req.t_submit
        for req in list(self._active.values()):
            if not req.generated and (oldest is None
                                      or req.t_submit < oldest):
                oldest = req.t_submit
        return (time.perf_counter() - oldest) if oldest is not None \
            else None

    def health(self) -> dict:
        """Liveness detail for /healthz: active/waiting counts plus
        wedge detection — sequences in flight but no token boundary
        for longer than ``wedge_timeout`` means a slot is stuck inside
        a device step (or the engine thread died mid-decode). ISSUE 12
        adds prefix-cache occupancy/hit-rate, the prefill backlog age
        (degraded past ``backlog_timeout`` — boundaries may be
        advancing while a starved request never reaches its first
        token), KV-page occupancy, and speculation state — all
        degraded-not-503, the PR-9 contract."""
        active = len(self._active)
        last = self._last_boundary
        age = (time.monotonic() - last) if last is not None else None
        wedged = bool(active and age is not None
                      and age > self.wedge_timeout)
        backlog = self._backlog_age()
        starved = bool(backlog is not None
                       and backlog > self.backlog_timeout)
        out = {"active": active,
               "waiting": self._pending.qsize() + len(self._waiting),
               "boundary_age_seconds": (round(age, 3)
                                        if age is not None else None),
               "wedged": wedged,
               "degraded": (wedged or starved
                            or not self._thread.is_alive())}
        if self._block is not None:
            out["prefill"] = {
                "chunk": self._block.chunk,
                "backlog": sum(
                    1 for r in list(self._active.values())
                    if not r.generated) + len(self._waiting),
                "oldest_age_seconds": (round(backlog, 3)
                                       if backlog is not None
                                       else None),
                "starved": starved}
        if self._kv is not None:
            # the pool in BYTES beside page occupancy (ISSUE 14
            # satellite): the device pool holds n_pages + 1 pages
            # (page 0 = scratch), so per-page bytes divide by that
            per_page = self._pool_bytes // (self._kv.n_pages + 1)
            out["kv_pages"] = {"total": self._kv.n_pages,
                               "free": self._kv.free_pages,
                               "occupancy": round(
                                   self._kv.used_pages
                                   / self._kv.n_pages, 4),
                               "pool_bytes": self._pool_bytes,
                               "used_bytes": per_page
                               * self._kv.used_pages}
            if self._sharded_mesh is not None and \
                    callable(getattr(self.model,
                                     "pool_device_bytes", None)):
                out["kv_pages"]["per_device_bytes"] = \
                    self.model.pool_device_bytes()
        if self._sharded_mesh is not None and \
                callable(getattr(self.model, "sharded_health", None)):
            out["sharded"] = self.model.sharded_health()
        if self._pcache is not None:
            out["prefix_cache"] = self._pcache.stats()
        if self._spec is not None:
            out["speculative"] = self._spec.health()
        return out

    def close(self, timeout=5.0):
        self._closed = True
        self._wake.set()
        self._thread.join(timeout)
        # the pools die with the engine: release their HBM claims
        if self._mem_claim is not None:
            self._mem_claim.release()
        for c in self._shard_mem_claims:
            c.release()
        self._shard_mem_claims = []
        if self._draft_mem_claim is not None:
            self._draft_mem_claim.release()
        # fail everything still pending or active
        leftovers = list(self._active.values()) + list(self._waiting)
        self._active.clear()
        self._waiting = []
        while True:
            try:
                leftovers.append(self._pending.get_nowait())
            except _queue.Empty:
                break
        for req in leftovers:
            if not req.future.done():
                req.future.set_exception(
                    DecodeShutdown("decode engine closed"))
            req.stream.put(_DecodeRequest._END)

    # -- engine side ---------------------------------------------------------
    def _page_plan(self, req):
        """Admission plan for the head-of-line request, or None when
        it must wait. Consults the prefix cache twice over (ISSUE 12
        satellite: the PR-8 head-of-line wedge): matched pages are
        ADOPTED instead of reserved, and pages held only by the cache
        (refcount==1, idle) count as reclaimable — a request that fits
        the pool no longer blocks the FIFO just because idle cached
        pages are sitting on the free list's budget."""
        from deeplearning4j_tpu.serving.prefix_cache import (
            plan_admission)

        total = len(req.prompt) + req.max_new
        plan = plan_admission(self._kv, self._pcache, req.prompt, total)
        if plan is None:
            return None
        if self._spec is not None:
            # the draft lane must never adopt DEEPER than the target
            # skips (the suffix prefill would write into shared draft
            # pages); shallower is fine — quality cost only
            dplan = self._spec.plan(req.prompt, total,
                                    max_adopt=len(plan["adopt"]))
            if dplan is None:
                return None
            return plan, dplan
        return plan, None

    def _admit(self):
        """Move pending requests into free slots at this token
        boundary. The submit queue drains into an engine-private FIFO
        first, so a request that can't get its KV pages yet
        head-blocks (fairness) without races against submit()."""
        from deeplearning4j_tpu.serving.prefix_cache import (
            apply_admission)

        while True:
            try:
                self._waiting.append(self._pending.get_nowait())
            except _queue.Empty:
                break
        admitted = 0
        while self._free_slots and self._waiting:
            req = self._waiting[0]
            plan = None
            if self._kv is not None:
                plan = self._page_plan(req)
                if plan is None:
                    break   # head-of-line waits for pages: strict FIFO
            self._waiting.pop(0)
            slot = self._free_slots.pop()
            req.slot = slot
            adopted = 0
            if self._kv is not None:
                tplan, dplan = plan
                total = len(req.prompt) + req.max_new
                try:
                    adopted = apply_admission(self._kv, self._pcache,
                                              tplan, slot, total)
                    if dplan is not None:
                        self._spec.admit(slot, total, dplan,
                                         target_adopted=adopted)
                except Exception as e:
                    # defensive: a lane-accounting failure must fail
                    # THIS request, never the engine thread (a dead
                    # loop wedges every queued request silently)
                    self._kv.release(slot)
                    if self._spec is not None:
                        self._spec.release(slot)
                    self._free_slots.append(slot)
                    req.slot = None
                    if not req.future.done():
                        req.future.set_exception(DecodeError(
                            f"admission failed: "
                            f"{type(e).__name__}: {e}"))
                    req.stream.put(_DecodeRequest._END)
                    continue
                if adopted:
                    # the adopted pages already hold this prefix's KV:
                    # prefill starts at the suffix (>= 1 prompt token
                    # always remains — match() never covers the last)
                    req.ptr = adopted * self._kv.page
                if self._pcache is not None:
                    inst = self._instruments_fn()
                    if adopted:
                        self._pcache.hits += 1
                        if inst is not None:
                            inst.prefix_hits.inc()
                    else:
                        self._pcache.misses += 1
                        if inst is not None:
                            inst.prefix_misses.inc()
            self._state = self.model.reset_slot(self._state, slot)
            self._active[slot] = req
            admitted += 1
            if req.trace is not None:
                # submit -> slot join: the decode analog of queue-wait
                tracing.emit("decode.queue", req.trace, req.t_submit,
                             time.perf_counter(), slot=slot,
                             req_id=req.req_id)
            flight.record("decode_join", model=self.name,
                          req_id=req.req_id, slot=slot,
                          prompt=len(req.prompt), max_new=req.max_new,
                          adopted_pages=adopted)
        return admitted

    # per-request ceiling on per-boundary spans; the remainder folds
    # into one aggregate decode.tokens span at finish
    boundary_span_cap = 64

    def _finish(self, req, error=None):
        slot = req.slot
        if req.trace is not None and req.t_suppressed is not None:
            tracing.emit("decode.tokens", req.trace, req.t_suppressed,
                         time.perf_counter(), slot=slot,
                         boundaries=(len(req.prompt) + len(req.generated)
                                     - 1 - req.spans_emitted))
        self._active.pop(slot, None)
        if self._kv is not None:
            self._kv.release(slot)
        if self._spec is not None:
            self._spec.release(slot)
        self._free_slots.append(slot)
        if error is not None:
            if not req.future.done():
                req.future.set_exception(error)
        elif not req.future.done():
            req.future.set_result(list(req.generated))
        req.stream.put(_DecodeRequest._END)
        flight.record("decode_leave", model=self.name,
                      req_id=req.req_id, slot=slot,
                      generated=len(req.generated),
                      seconds=round(time.perf_counter() - req.t_submit,
                                    6))

    def _model_step(self, state, tokens, pos, table):
        if self._step_takes_site:
            return self.model.step(state, tokens, pos, table,
                                   site=f"decode:{self.name}:step")
        return self.model.step(state, tokens, pos, table)

    def clear_prefix_cache(self):
        """Drop every cached prefix chain (both lanes), releasing the
        cache's page references — after every request has finished,
        the pool provably returns to fully free (the leak test)."""
        n = 0
        if self._pcache is not None and self._kv is not None:
            n = self._pcache.clear(self._kv)
        if self._spec is not None:
            n += self._spec.clear_prefix_cache()
        return n

    def _publish(self, req, slot):
        """Put the request's full prompt pages into the prefix cache —
        once, at the boundary where its prompt is fully written."""
        if self._pcache is None or req.published or \
                req.ptr < len(req.prompt):
            return
        req.published = True
        n_full = len(req.prompt) // self._kv.page
        if not n_full:
            return
        owned = self._kv.owned(slot)
        if len(owned) >= n_full:
            self._pcache.publish(self._kv, req.prompt, owned[:n_full])
        if self._spec is not None:
            self._spec.publish(req.prompt, slot)

    def _emit_token(self, req, tok, inst):
        """Append one generated token, stream it, observe TTFT on the
        first. Returns True when the request just finished."""
        req.generated.append(tok)
        req.stream.put(tok)
        if req.t_first is None:
            req.t_first = time.perf_counter()
            if inst is not None:
                inst.ttft.observe(req.t_first - req.t_submit)
        return (len(req.generated) >= req.max_new
                or (req.eos_id is not None and tok == req.eos_id))

    def _prefill_boundary(self, inst) -> bool:
        """Boundary phase 1 (ISSUE 12 tentpole a): retire up to
        ``chunk`` prompt tokens per prefilling slot through the block
        executable — always leaving the final prompt token for the
        emitting phase, so first-token emission stays on the
        per-token/verify path. Returns False when the dispatch failed
        (every request was failed, skip phase 2)."""
        todo = {s: r for s, r in list(self._active.items())
                if r.ptr < len(r.prompt) - 1}
        if not todo:
            return True
        S = self.model.max_slots
        C = self._block.chunk
        blocks = np.zeros((S, C), np.int32)
        pos0 = np.zeros((S,), np.int32)
        counts = np.zeros((S,), np.int32)
        for slot, req in todo.items():
            n = min(C, len(req.prompt) - 1 - req.ptr)
            blocks[slot, :n] = req.prompt[req.ptr:req.ptr + n]
            pos0[slot] = req.ptr
            counts[slot] = n
        # a REAL copy, not ascontiguousarray (which aliases an
        # already-contiguous table): admission mutates the table
        # between boundaries, and jax may zero-copy numpy inputs
        table = self._table.copy()
        t_b0 = time.perf_counter()
        try:
            _, self._state = self._block.run(
                self._state, blocks, pos0, counts, table,
                site=f"decode:{self.name}:prefill")
            if self._spec is not None:
                self._spec.prefill(blocks, pos0, counts)
        except Exception as e:
            # OOM forensics (ISSUE 14): a device allocation failure at
            # this boundary fails the requests with the typed error
            err = _boundary_error(e, f"decode:{self.name}:prefill",
                                  "chunk prefill failed")
            for req in list(self._active.values()):
                self._finish(req, error=err)
            return False
        t_b1 = time.perf_counter()
        self._last_boundary = time.monotonic()
        for slot, req in todo.items():
            if self._active.get(slot) is not req:
                continue
            req.ptr += int(counts[slot])
            if req.trace is not None and \
                    req.spans_emitted < self.boundary_span_cap:
                req.spans_emitted += 1
                tracing.emit("decode.prefill_chunk", req.trace, t_b0,
                             t_b1, slot=slot,
                             tokens=int(counts[slot]), pos=req.ptr)
        return True

    def _step_boundary(self, inst):
        """One per-token boundary through the step executable — the
        PR-8 path, semantics unchanged: every active slot advances one
        token (prefilling slots feed their next prompt token)."""
        S = self.model.max_slots
        tokens = np.zeros((S,), np.int32)
        pos = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        # snapshot: close() may clear _active concurrently
        for slot, req in list(self._active.items()):
            if req.ptr < len(req.prompt):
                tokens[slot] = req.prompt[req.ptr]
            else:
                tokens[slot] = req.generated[-1]
            pos[slot] = req.ptr
            active[slot] = True
        # a REAL copy, not ascontiguousarray (which aliases an
        # already-contiguous table): admission mutates the table
        # between boundaries, and jax may zero-copy numpy inputs
        table = self._table.copy()
        t_b0 = time.perf_counter()
        try:
            nxt, self._state = self._model_step(self._state, tokens,
                                                pos, table)
            nxt = np.asarray(nxt)
            if self._spec is not None:
                # fallback boundaries keep the draft pool in sync so
                # a later speculation probe proposes from real context
                self._spec.track(tokens, pos, active)
        except Exception as e:
            err = _boundary_error(e, f"decode:{self.name}:step",
                                  "decode step failed")
            for req in list(self._active.values()):
                self._finish(req, error=err)
            return
        t_b1 = time.perf_counter()
        self._last_boundary = time.monotonic()
        n_decoded = 0
        for slot, req in list(self._active.items()):
            prefilling = req.ptr + 1 < len(req.prompt)
            if req.trace is not None:
                # one child span per token boundary this sequence
                # took part in (ISSUE 10): prefill and decode
                # interleave through the same executable, and the
                # span name says which phase this boundary was.
                # Capped per request: a near-max_new generation
                # would otherwise evict every concurrent trace
                # (including its own early spans) from the bounded
                # ring — boundaries past the cap aggregate into
                # one decode.tokens span at finish.
                if req.spans_emitted < self.boundary_span_cap:
                    req.spans_emitted += 1
                    tracing.emit(
                        "decode.prefill" if prefilling
                        else "decode.token",
                        req.trace, t_b0, t_b1, slot=slot,
                        pos=req.ptr)
                elif req.t_suppressed is None:
                    req.t_suppressed = t_b0
            req.ptr += 1
            self._publish(req, slot)
            if req.ptr < len(req.prompt):
                continue            # still prefilling
            tok = int(nxt[slot])
            done = self._emit_token(req, tok, inst)
            n_decoded += 1
            if self._spec is not None and inst is not None:
                inst.accepted("fallback", 1)
            if done:
                self._finish(req)
        if inst is not None:
            inst.tokens.inc(n_decoded)

    def _speculative_boundary(self, inst):
        """Boundary phase 2, speculative (ISSUE 12 tentpole c): the
        draft proposes k tokens per decoding slot, the target verifies
        the whole block in ONE call through the chunk executable, and
        the accepted prefix (plus the verifier's own next token — the
        free one) is emitted. Greedy-identical to plain decode, up to
        k+1 tokens per boundary."""
        S = self.model.max_slots
        ready = {s: r for s, r in list(self._active.items())
                 if r.ptr >= len(r.prompt) - 1}
        if not ready:       # everyone still prefilling: plain boundary
            self._step_boundary(inst)
            return
        V = self._spec.k + 1
        feed = np.zeros((S,), np.int32)
        pos = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        for slot, req in ready.items():
            feed[slot] = (req.prompt[req.ptr]
                          if req.ptr < len(req.prompt)
                          else req.generated[-1])
            pos[slot] = req.ptr
            active[slot] = True
        # a REAL copy, not ascontiguousarray (which aliases an
        # already-contiguous table): admission mutates the table
        # between boundaries, and jax may zero-copy numpy inputs
        table = self._table.copy()
        t_b0 = time.perf_counter()
        try:
            drafts = self._spec.propose(feed, pos, active)
            blocks = np.zeros((S, V), np.int32)
            counts = np.zeros((S,), np.int32)
            for slot, req in ready.items():
                c = min(V, req.max_new - len(req.generated))
                blocks[slot, 0] = feed[slot]
                if c > 1:
                    blocks[slot, 1:c] = drafts[slot, :c - 1]
                counts[slot] = c
            outs, self._state = self._block.run(
                self._state, blocks, pos, counts, table,
                site=f"decode:{self.name}:verify")
        except Exception as e:
            err = _boundary_error(e, f"decode:{self.name}:verify",
                                  "speculative decode failed")
            for req in list(self._active.values()):
                self._finish(req, error=err)
            return
        t_b1 = time.perf_counter()
        self._last_boundary = time.monotonic()
        n_decoded = 0
        for slot, req in ready.items():
            if self._active.get(slot) is not req:
                continue
            c = int(counts[slot])
            if c < 1:
                continue
            # o_0 is the target's answer to the real last token (always
            # valid); each later o_j is valid iff the draft proposal fed
            # at j matched o_{j-1} — the greedy acceptance rule
            m = 1
            while m < c and \
                    int(blocks[slot, m]) == int(outs[slot, m - 1]):
                m += 1
            self._spec.observe(m, c)
            if inst is not None:
                inst.accepted("accepted", m)
                if c > m:
                    inst.accepted("rejected", c - m)
            if req.trace is not None and \
                    req.spans_emitted < self.boundary_span_cap:
                req.spans_emitted += 1
                tracing.emit("decode.speculate", req.trace, t_b0, t_b1,
                             slot=slot, drafted=c - 1, accepted=m,
                             pos=req.ptr)
            # rejected positions were written past the accepted point
            # in both pools — above the causal mask until the true
            # tokens overwrite those same positions (no rollback)
            req.ptr += m
            self._publish(req, slot)
            done = False
            for j in range(m):
                done = self._emit_token(req, int(outs[slot, j]), inst)
                n_decoded += 1
                if done:
                    break
            if done:
                self._finish(req)
        self._spec.boundary_done()
        if inst is not None:
            inst.tokens.inc(n_decoded)

    def _loop(self):
        while not self._closed:
            self._admit()
            if not self._active:
                self._last_boundary = None   # idle: nothing to wedge
                self._wake.wait(0.05)
                self._wake.clear()
                continue
            self._last_boundary = time.monotonic()
            for req in list(self._active.values()):
                if not req.generated:
                    req.ttft_boundaries += 1
            inst = self._instruments_fn()
            if self._block is not None and \
                    not self._prefill_boundary(inst):
                continue
            if self._spec is not None and any(
                    r.ptr >= len(r.prompt) - 1
                    for r in list(self._active.values())) \
                    and self._spec.speculate_now():
                self._speculative_boundary(inst)
            else:
                self._step_boundary(inst)
            if inst is not None:
                inst.slots.set(len(self._active))
                if self._kv is not None:
                    inst.kv_occupancy.set(
                        self._kv.used_pages / max(1, self._kv.n_pages))