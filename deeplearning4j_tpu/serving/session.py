"""InferenceSession: the one-call serving facade.

    from deeplearning4j_tpu.serving import InferenceSession

    session = InferenceSession()
    session.register("mnist", net, example_shape=(784,), warmup=True)
    y = session.predict("mnist", x)            # sync, batched, bucketed
    f = session.predict_async("mnist", x)      # concurrent callers coalesce

Every model gets its own DynamicBatcher (worker thread) created lazily
on first predict; `batching=False` (or per-call `batched=False`) runs
the caller's thread straight through the bucketed servable — same
padding, no queue — which is what evaluation loops and single-tenant
batch jobs want. Telemetry (`dl4j_serving_*`) records through the PR-1
MetricsRegistry either way.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import TimeoutError as _FutureTimeout

import numpy as np

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.telemetry import tracing
from deeplearning4j_tpu.serving.batcher import (
    DynamicBatcher, ServingTimeout, execute_plan)
from deeplearning4j_tpu.serving.buckets import BucketLadder, unpad
from deeplearning4j_tpu.serving.registry import (ModelNotFound,
                                                 ModelRegistry)


class InferenceSession:
    def __init__(self, registry: ModelRegistry | None = None,
                 max_latency=0.002, queue_size=256, default_timeout=30.0,
                 batching=True, admission=None):
        self.registry = registry or ModelRegistry()
        self.max_latency = max_latency
        self.queue_size = queue_size
        self.default_timeout = default_timeout
        self.batching = batching
        self.admission = admission   # AdmissionController or None
        self._batchers: dict[str, DynamicBatcher] = {}
        self._replica_spec: dict = {}  # (name, version) -> (n, devices)
        self._decoders: dict = {}      # name -> DecodeEngine
        self._instruments: dict = {}   # per-model bundle, built once
        self._lock = threading.Lock()
        self._closed = False
        # a session exists to compile-and-serve: touching the
        # executable store now starts its code-epoch sweep in the
        # background, off the first warmup's timed path (no-op when
        # the store is unconfigured)
        from deeplearning4j_tpu import compilestore

        compilestore.get_store()

    # -- registry passthrough ------------------------------------------------
    def register(self, name, model, replicas=None, devices=None, **kw):
        """See ModelRegistry.register. Re-registering retires the
        model's old batchers: new predicts bind the new entry while
        already-queued requests finish on the old servable (rolling
        update).

        `replicas=N` (or an explicit `devices` list) executes this
        model through a work-stealing ReplicaSet: N device-pinned
        copies of the bucket executables with per-replica run queues
        (see serving/replica.py). The batcher thread then only
        coalesces; dispatches run on the replica workers."""
        entry = self.registry.register(name, model, **kw)
        with self._lock:
            stale = [k for k in self._batchers if k[0] == name]
            dropped = [self._batchers.pop(k) for k in stale]
            # specs of superseded versions leak across rolling updates
            # otherwise — sweep every spec for this name first
            for k in [k for k in self._replica_spec if k[0] == name]:
                del self._replica_spec[k]
            if replicas is not None or devices is not None:
                self._replica_spec[(name, entry.version)] = (replicas,
                                                             devices)
        for b in dropped:
            b.retire()
        if entry.warmed and (name, entry.version) in self._replica_spec:
            # build the ReplicaSet (and its N-replica ladder warmup)
            # NOW, not lazily under the session lock on the first
            # predict — ready() must keep meaning "no cold compile in
            # any request's latency path"
            self._batcher(name, entry)
        from deeplearning4j_tpu.telemetry import flight

        flight.record("model_registered", model=name,
                      version=entry.version, warmed=entry.warmed,
                      replicas=replicas)
        return entry

    def register_decoder(self, name, model, warmup=True, **kw):
        """Attach a continuous-batching DecodeEngine under `name`
        (POST /serving/v1/models/<name>:decode). `model` is a
        DecodeModel (RnnDecodeModel / TransformerDecodeModel) or an
        already-built DecodeEngine. Engine kwargs pass through —
        ``chunk=64`` (chunked prefill), ``prefix_cache=True``,
        ``speculative=SpeculativeConfig(draft, k)`` (ISSUE 12)."""
        from deeplearning4j_tpu.serving.decode import DecodeEngine

        if isinstance(model, DecodeEngine):
            engine = model
        else:
            engine = DecodeEngine(model, name=name,
                                  instruments=lambda: self._inst(name),
                                  **kw)
        if warmup and not engine._warmed:
            engine.warmup()
        with self._lock:
            old = self._decoders.get(name)
            self._decoders[name] = engine
        if old is not None and old is not engine:
            old.close()
        from deeplearning4j_tpu.telemetry import flight

        flight.record("decoder_registered", model=name,
                      slots=engine.model.max_slots)
        return engine

    def unregister_decoder(self, name):
        """Detach (and close) the decode engine under `name` — the
        retract half of a decode-path rollout (ISSUE 20). Raises
        ModelNotFound when no such decoder exists, matching the
        versioned registry's unregister contract."""
        with self._lock:
            engine = self._decoders.pop(name, None)
        if engine is None:
            raise ModelNotFound(name)
        engine.close()
        from deeplearning4j_tpu.telemetry import flight

        flight.record("decoder_unregistered", model=name)

    def decoder(self, name):
        engine = self._decoders.get(name)
        if engine is None:
            raise ModelNotFound(name)
        return engine

    def decode(self, name, prompt, max_new_tokens, eos_id=None,
               timeout=None, priority="normal", timing=None):
        """Generated token ids for one prompt through the continuous
        batcher (admission-controlled like predict). ``timing`` (a
        dict) receives the request's ``ttft`` seconds so the transport
        can answer with a Server-Timing header — decode-path rollouts
        judge canaries on time-to-first-token (ISSUE 20)."""
        if self._closed:
            raise RuntimeError("session closed")
        engine = self.decoder(name)
        ticket = None
        if self.admission is not None:
            ticket = self._admit_traced(name, priority)
        try:
            req = engine.submit(prompt, max_new_tokens, eos_id=eos_id)
            if ticket is not None:
                # bind as a default: the variable is nulled on the next
                # line, and a late-bound closure would call None.release
                req.future.add_done_callback(
                    lambda f, t=ticket: t.release())
                ticket = None
            try:
                tokens = req.result(timeout=timeout)
            except _FutureTimeout:
                # same normalization as predict(): pre-3.11 the futures
                # TimeoutError is NOT the builtin, and the HTTP 504
                # mapping keys on one exception type
                raise ServingTimeout(
                    f"decode on {name!r} timed out after {timeout}s"
                ) from None
            if timing is not None:
                # read AFTER the result: t_first is written by the
                # engine thread at first-token emission
                timing["ttft"] = ((req.t_first or time.perf_counter())
                                  - req.t_submit)
            return tokens
        finally:
            if ticket is not None:
                ticket.release()

    def ready(self) -> bool:
        """Readiness for /healthz: every registered model's bucket
        ladder is AOT-warmed (no cold-compile stall on first traffic)."""
        models = self.registry.describe()
        return all(m["warmed"] for m in models) if models else True

    def warmup(self, name=None, version=None):
        self.registry.warmup(name, version)
        return self

    def models(self):
        return self.registry.describe()

    # -- predict -------------------------------------------------------------
    def _inst(self, name):
        """Per-model ServingInstruments: None whenever telemetry is
        disabled (the flag is re-checked on every call so toggling
        mid-flight is honored); the bound bundle itself is built once."""
        if not telemetry.enabled():
            return None
        inst = self._instruments.get(name)
        if inst is None:
            inst = telemetry.serving_instruments(name)
            self._instruments[name] = inst
        return inst

    def _admit_traced(self, name, priority):
        """admission.admit with a span on the sampled path: the ticket
        decision is the first hop of the request's span tree (sheds
        raise and the span records status=error, naming the 429)."""
        ctx = tracing.current()
        if ctx is None:
            return self.admission.admit(name, priority,
                                        inst=self._inst(name))
        import time as _time

        t0 = _time.perf_counter()
        try:
            ticket = self.admission.admit(name, priority,
                                          inst=self._inst(name))
        except Exception as e:
            tracing.emit("serving.admission", ctx, t0,
                         _time.perf_counter(), status="error",
                         priority=priority,
                         error=f"{type(e).__name__}: {e}")
            raise
        tracing.emit("serving.admission", ctx, t0, _time.perf_counter(),
                     priority=priority)
        return ticket

    def _batcher(self, name, entry) -> DynamicBatcher:
        """One batcher per served (name, version): pinned-version
        requests coalesce among themselves, never across versions.
        Models registered with replicas= get a ReplicaSet executor."""
        key = (name, entry.version)
        b = self._batchers.get(key)
        if b is None:
            with self._lock:
                b = self._batchers.get(key)
                if b is None:
                    executor = None
                    spec = self._replica_spec.get(key)
                    if spec is not None:
                        from deeplearning4j_tpu.serving.replica import (
                            ReplicaSet)

                        n, devices = spec
                        executor = ReplicaSet(
                            entry, n_replicas=n, devices=devices,
                            instruments=lambda: self._inst(name))
                    b = DynamicBatcher(
                        entry,
                        max_latency=self.max_latency,
                        queue_size=self.queue_size,
                        default_timeout=self.default_timeout,
                        instruments=lambda: self._inst(name),
                        executor=executor)
                    self._batchers[key] = b
        return b

    def _prep(self, name, features, version=None):
        entry = self.registry.get(name, version)
        shape = entry.servable.example_shape
        x = np.asarray(features)
        single = x.ndim == len(shape)
        if single:
            x = x[None]
        got = tuple(x.shape[1:])
        # sequence models ([N, C, T]) may vary the trailing time axis —
        # it pads to a seq bucket; every other axis must match exactly
        ok = (got[:-1] == shape[:-1] if x.ndim >= 3 and len(got) == len(shape)
              else got == shape)
        if not ok:
            raise ValueError(
                f"model {name!r} expects examples of shape {shape}, "
                f"got {got}")
        return entry, x, single

    def predict_async(self, name, features, timeout=None, version=None,
                      priority="normal"):
        """Future of the prediction batch. Concurrent callers of the
        same model (and version) coalesce into shared device
        dispatches. With an AdmissionController attached, the request
        is admitted (or shed with ShedError -> HTTP 429) BEFORE it
        takes a queue slot; the admission ticket releases when the
        future goes terminal."""
        if self._closed:
            raise RuntimeError("session closed")
        entry, x, single = self._prep(name, features, version)
        ticket = None
        if self.admission is not None:
            ticket = self._admit_traced(name, priority)
        try:
            future = self._batcher(name, entry).submit(
                x, timeout=timeout, priority=priority)
        except Exception:
            if ticket is not None:
                ticket.release()
            raise
        if ticket is not None:
            future.add_done_callback(lambda f: ticket.release())
        if not single:
            return future
        from concurrent.futures import Future

        out = Future()
        out.set_running_or_notify_cancel()

        def _done(f):
            e = f.exception()
            if e is not None:
                out.set_exception(e)
            else:
                timing = getattr(f, "dl4j_timing", None)
                if timing is not None:   # before set_result: see batcher
                    out.dl4j_timing = timing
                out.set_result(f.result()[0])

        future.add_done_callback(_done)
        return out

    def predict(self, name, features, timeout=None, batched=None,
                version=None, priority="normal", timing=None):
        """Synchronous predict. `batched=False` bypasses the queue and
        runs the bucketed servable on the calling thread. ``timing``
        (a dict) is filled with the request's queue/execute seconds —
        the already-captured per-request phases, surfaced so the HTTP
        layer can return them in a Server-Timing header (ISSUE 16 hop
        decomposition) without touching the registry."""
        if timeout is None:
            timeout = self.default_timeout
        use_batcher = self.batching if batched is None else batched
        if not use_batcher:
            return self._direct(name, features, version,
                                priority=priority, timing=timing)
        t0 = time.perf_counter()
        future = self.predict_async(name, features, timeout=timeout,
                                    version=version, priority=priority)
        budget = (None if timeout is None
                  else max(0.0, timeout - (time.perf_counter() - t0)) + 0.25)
        try:
            out = future.result(timeout=budget)
            if timing is not None:
                timing.update(getattr(future, "dl4j_timing", None) or {})
            return out
        except _FutureTimeout:
            # concurrent.futures.TimeoutError is NOT the builtin
            # TimeoutError before py3.11 — normalize so callers (and the
            # HTTP 504 mapping) see one exception type
            raise ServingTimeout(
                f"request to {name!r} timed out after {timeout}s"
            ) from None

    def _direct(self, name, features, version=None, priority="normal",
                timing=None):
        entry, x, single = self._prep(name, features, version)
        inst = self._inst(name)
        if self.admission is not None:
            with self.admission.admit(name, priority, inst=inst):
                return self._direct_run(entry, x, single, inst, timing)
        return self._direct_run(entry, x, single, inst, timing)

    def _direct_run(self, entry, x, single, inst, timing=None):
        t = x.shape[-1] if x.ndim >= 3 else None
        t0 = time.perf_counter()
        try:
            y, n_dispatch, _ = execute_plan(entry, x)
        except Exception:
            if inst is not None:
                inst.request("error")
            raise
        dt = time.perf_counter() - t0
        if timing is not None:   # unbatched: no queue phase by design
            timing.update({"queue": 0.0, "execute": round(dt, 6)})
        if inst is not None:
            inst.execute.observe(dt)
            inst.dispatch.inc(n_dispatch)
            inst.request("ok")
        y = unpad(y, y.shape[0], t)
        return y[0] if single else y

    # -- introspection / lifecycle -------------------------------------------
    def health_details(self) -> dict:
        """Replica-set and decode-engine liveness for /healthz
        (ISSUE 10 satellite): a dead replica or a decode slot wedged
        past its deadline marks the matching section degraded —
        reported as status "degraded", still HTTP 200 (capacity is
        reduced, traffic still flows)."""
        with self._lock:
            batchers = dict(self._batchers)
            decoders = dict(self._decoders)
        out: dict = {}
        replica_sets = {}
        for (name, version), b in batchers.items():
            if b.executor is None:
                continue
            reps = b.executor.replicas
            dead = [r.name for r in reps if r.dead]
            replica_sets[f"{name}:v{version}"] = {
                "replicas": len(reps), "live": len(reps) - len(dead),
                "dead": dead, "degraded": bool(dead)}
        if replica_sets:
            out["replica_sets"] = replica_sets
        decs = {}
        for name, engine in decoders.items():
            try:
                decs[name] = engine.health()
            except Exception:  # a closing engine must not break healthz
                continue
        if decs:
            out["decoders"] = decs
        # mesh-sharded servables and decoders (ISSUE 19): one entry per
        # sharded unit — mesh shape, device set, per-device bytes —
        # so an operator sees WHERE a big model landed, not just that
        # it is up
        sharded = {}
        for e in self.registry.entries():
            sh = getattr(e.servable, "sharded_health", None)
            if callable(sh):
                try:
                    sharded[f"{e.name}:v{e.version}"] = sh()
                except Exception:
                    continue
        for name, engine in decoders.items():
            sh = getattr(getattr(engine, "model", None),
                         "sharded_health", None)
            if callable(sh):
                try:
                    sharded[f"decode:{name}"] = sh()
                except Exception:
                    continue
        if sharded:
            out["sharded"] = sharded
        return out

    def stats(self) -> dict:
        with self._lock:
            out = {}
            for (name, version), b in self._batchers.items():
                row = {"queue_depth": b.queue_depth()}
                if b.executor is not None:
                    row["replicas"] = {
                        r.name: {"device": str(r.device),
                                 "load": r.load(), "dead": r.dead}
                        for r in b.executor.replicas}
                out[f"{name}:v{version}"] = row
            if self.admission is not None:
                out["admission"] = self.admission.describe()
            return out

    def close(self):
        self._closed = True
        with self._lock:
            batchers, self._batchers = list(self._batchers.values()), {}
            decoders, self._decoders = list(self._decoders.values()), {}
        for b in batchers:
            b.close()
        for d in decoders:
            d.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
