from deeplearning4j_tpu.graph.deepwalk import (  # noqa: F401
    DeepWalk, Graph, RandomWalkIterator)
