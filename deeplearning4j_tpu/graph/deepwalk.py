"""DeepWalk graph embeddings.

Reference capability: deeplearning4j-graph org.deeplearning4j.graph.models
.deepwalk.DeepWalk (SURVEY.md §2.7): uniform random walks over a graph,
embedded by skip-gram. Walk generation is host-side; the skip-gram step is
the same batched device op as Word2Vec (the reference instead runs its own
hierarchical-softmax loop)."""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import CollectionSentenceIterator
from deeplearning4j_tpu.nlp.word2vec import Word2Vec


class Graph:
    """Simple undirected/directed graph keyed by int vertex ids
    (reference: org.deeplearning4j.graph.graph.Graph)."""

    def __init__(self, numVertices, directed=False):
        self.n = int(numVertices)
        self.directed = directed
        self.adj: list[list[int]] = [[] for _ in range(self.n)]

    def addEdge(self, a, b):
        self.adj[a].append(b)
        if not self.directed:
            self.adj[b].append(a)

    def getConnectedVertices(self, v):
        return list(self.adj[v])

    def numVertices(self):
        return self.n


class RandomWalkIterator:
    """Uniform random walks of fixed length from every vertex
    (reference: org.deeplearning4j.graph.iterator.RandomWalkIterator)."""

    def __init__(self, graph: Graph, walkLength: int, seed=0,
                 walksPerVertex: int = 1):
        self.graph = graph
        self.walkLength = walkLength
        self.seed = seed
        self.walksPerVertex = walksPerVertex

    def walks(self):
        rng = np.random.default_rng(self.seed)
        for _ in range(self.walksPerVertex):
            for start in range(self.graph.n):
                walk = [start]
                cur = start
                for _ in range(self.walkLength - 1):
                    nbrs = self.graph.adj[cur]
                    if not nbrs:
                        break
                    cur = int(nbrs[rng.integers(len(nbrs))])
                    walk.append(cur)
                yield walk


class DeepWalk:
    class Builder:
        def __init__(self):
            self._kw = dict(vectorSize=64, windowSize=4, learningRate=0.01,
                            seed=0, epochs=3, negative=5, batchSize=128)
            self._walk_len = 20
            self._walks_per_vertex = 4

        def vectorSize(self, n):
            self._kw["vectorSize"] = n
            return self

        def windowSize(self, n):
            self._kw["windowSize"] = n
            return self

        def learningRate(self, lr):
            self._kw["learningRate"] = lr
            return self

        def seed(self, s):
            self._kw["seed"] = s
            return self

        def epochs(self, n):
            self._kw["epochs"] = n
            return self

        def walkLength(self, n):
            self._walk_len = n
            return self

        def walksPerVertex(self, n):
            self._walks_per_vertex = n
            return self

        def build(self):
            dw = DeepWalk()
            dw.cfg = dict(self._kw)
            dw.walk_len = self._walk_len
            dw.walks_per_vertex = self._walks_per_vertex
            return dw

    def __init__(self):
        self.cfg = {}
        self.walk_len = 20
        self.walks_per_vertex = 4
        self._w2v: Word2Vec | None = None

    def fit(self, graph: Graph):
        it = RandomWalkIterator(graph, self.walk_len, self.cfg["seed"],
                                self.walks_per_vertex)
        sentences = [" ".join(str(v) for v in walk) for walk in it.walks()]
        self._w2v = (Word2Vec.Builder()
                     .minWordFrequency(1)
                     .layerSize(self.cfg["vectorSize"])
                     .windowSize(self.cfg["windowSize"])
                     .learningRate(self.cfg["learningRate"])
                     .negativeSampling(self.cfg["negative"])
                     .epochs(self.cfg["epochs"])
                     .seed(self.cfg["seed"])
                     .batchSize(self.cfg["batchSize"])
                     .sampling(0)
                     .iterate(CollectionSentenceIterator(sentences))
                     .build().fit())
        return self

    def getVertexVector(self, v) -> np.ndarray:
        return self._w2v.getWordVector(str(v))

    def similarity(self, a, b) -> float:
        return self._w2v.similarity(str(a), str(b))

    def verticesNearest(self, v, n=5):
        return [int(w) for w in self._w2v.wordsNearest(str(v), n)]
