"""Gradient updaters with DL4J semantics (reference:
org.nd4j.linalg.learning.{SgdUpdater, AdamUpdater, NesterovsUpdater, ...} and
config classes org.nd4j.linalg.learning.config.* — SURVEY.md §2.3).

Each updater is a config object with:
  init_state(params)                      -> state pytree
  apply(grads, state, params, step)       -> (updates, new_state)
where `updates` is what gets SUBTRACTED from params. All math is jnp
tree_maps, so the whole optimizer fuses into the compiled train step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.optimize.schedules import resolve_lr

_tm = jax.tree_util.tree_map


class IUpdater:
    """Base: holds learningRate (float / schedule / callable)."""

    def __init__(self, learningRate=0.1):
        self.learningRate = learningRate

    def lr(self, step):
        return resolve_lr(self.learningRate, step)

    def init_state(self, params):
        return ()

    def apply(self, grads, state, params, step):
        raise NotImplementedError

    def apply_mixed(self, grads, state, params, step):
        """Master-dtype guard for mixed-precision training (ISSUE 4):
        force each gradient leaf to its parameter's dtype before the
        updater math, so Adam/SGD moments and the update itself stay in
        the MASTER dtype (fp32 under bf16_mixed) even if a compute-dtype
        gradient leaks through (e.g. a custom layer whose backward
        returns bf16 cotangents directly). Identity when dtypes already
        match — the normal case, since the compute cast's transpose
        upcasts cotangents at the master boundary."""
        grads = _tm(
            lambda g, p: g.astype(p.dtype) if g.dtype != p.dtype else g,
            grads, params)
        return self.apply(grads, state, params, step)

    def to_json(self):
        d = {"@class": type(self).__name__}
        for k, v in self.__dict__.items():
            if hasattr(v, "to_json"):
                v = v.to_json()
            d[k] = v
        return d

    @staticmethod
    def from_json(d):
        return updater_from_config(d)


class NoOp(IUpdater):
    def __init__(self):
        super().__init__(0.0)

    def apply(self, grads, state, params, step):
        return _tm(jnp.zeros_like, grads), state


class Sgd(IUpdater):
    DEFAULT_SGD_LR = 1e-3

    def __init__(self, learningRate=DEFAULT_SGD_LR):
        super().__init__(learningRate)

    def apply(self, grads, state, params, step):
        lr = self.lr(step)
        return _tm(lambda g: lr * g, grads), state


class Nesterovs(IUpdater):
    """Nesterov momentum, DL4J formulation (NesterovsUpdater):
    v' = mu*v - lr*g;  update = -(mu*v' - lr*g) i.e. params += mu*v' - lr*g."""

    DEFAULT_NESTEROV_MOMENTUM = 0.9

    def __init__(self, learningRate=0.1, momentum=DEFAULT_NESTEROV_MOMENTUM):
        super().__init__(learningRate)
        self.momentum = momentum

    def init_state(self, params):
        return {"v": _tm(jnp.zeros_like, params)}

    def apply(self, grads, state, params, step):
        lr = self.lr(step)
        mu = self.momentum
        v_new = _tm(lambda v, g: mu * v - lr * g, state["v"], grads)
        updates = _tm(lambda vn, g: -(mu * vn - lr * g), v_new, grads)
        return updates, {"v": v_new}


class AdaGrad(IUpdater):
    DEFAULT_ADAGRAD_EPSILON = 1e-6

    def __init__(self, learningRate=0.1, epsilon=DEFAULT_ADAGRAD_EPSILON):
        super().__init__(learningRate)
        self.epsilon = epsilon

    def init_state(self, params):
        return {"h": _tm(jnp.zeros_like, params)}

    def apply(self, grads, state, params, step):
        lr = self.lr(step)
        h = _tm(lambda h, g: h + g * g, state["h"], grads)
        updates = _tm(
            lambda g, h: lr * g / (jnp.sqrt(h) + self.epsilon), grads, h
        )
        return updates, {"h": h}


class RmsProp(IUpdater):
    DEFAULT_RMSPROP_RMSDECAY = 0.95
    DEFAULT_RMSPROP_EPSILON = 1e-8

    def __init__(self, learningRate=0.1, rmsDecay=DEFAULT_RMSPROP_RMSDECAY,
                 epsilon=DEFAULT_RMSPROP_EPSILON):
        super().__init__(learningRate)
        self.rmsDecay = rmsDecay
        self.epsilon = epsilon

    def init_state(self, params):
        return {"g2": _tm(jnp.zeros_like, params)}

    def apply(self, grads, state, params, step):
        lr = self.lr(step)
        d = self.rmsDecay
        g2 = _tm(lambda a, g: d * a + (1 - d) * g * g, state["g2"], grads)
        updates = _tm(
            lambda g, a: lr * g / (jnp.sqrt(a + self.epsilon)), grads, g2
        )
        return updates, {"g2": g2}


class AdaDelta(IUpdater):
    DEFAULT_ADADELTA_RHO = 0.95
    DEFAULT_ADADELTA_EPSILON = 1e-6

    def __init__(self, rho=DEFAULT_ADADELTA_RHO, epsilon=DEFAULT_ADADELTA_EPSILON):
        super().__init__(1.0)  # AdaDelta has no lr
        self.rho = rho
        self.epsilon = epsilon

    def init_state(self, params):
        z = _tm(jnp.zeros_like, params)
        return {"msg": z, "msdx": _tm(jnp.zeros_like, params)}

    def apply(self, grads, state, params, step):
        rho, eps = self.rho, self.epsilon
        msg = _tm(lambda a, g: rho * a + (1 - rho) * g * g, state["msg"], grads)
        updates = _tm(
            lambda g, a, dx: g * jnp.sqrt(dx + eps) / jnp.sqrt(a + eps),
            grads, msg, state["msdx"],
        )
        msdx = _tm(
            lambda a, u: rho * a + (1 - rho) * u * u, state["msdx"], updates
        )
        return updates, {"msg": msg, "msdx": msdx}


class Adam(IUpdater):
    DEFAULT_ADAM_LEARNING_RATE = 1e-3
    DEFAULT_ADAM_BETA1 = 0.9
    DEFAULT_ADAM_BETA2 = 0.999
    DEFAULT_ADAM_EPSILON = 1e-8

    def __init__(self, learningRate=DEFAULT_ADAM_LEARNING_RATE,
                 beta1=DEFAULT_ADAM_BETA1, beta2=DEFAULT_ADAM_BETA2,
                 epsilon=DEFAULT_ADAM_EPSILON):
        super().__init__(learningRate)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def init_state(self, params):
        return {
            "m": _tm(jnp.zeros_like, params),
            "v": _tm(jnp.zeros_like, params),
        }

    def _moments(self, grads, state):
        b1, b2 = self.beta1, self.beta2
        m = _tm(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        v = _tm(lambda v, g: b2 * v + (1 - b2) * g * g, state["v"], grads)
        return m, v

    def apply(self, grads, state, params, step):
        lr = self.lr(step)
        t = step + 1
        m, v = self._moments(grads, state)
        bc = jnp.sqrt(1.0 - self.beta2**t) / (1.0 - self.beta1**t)
        updates = _tm(
            lambda m_, v_: lr * bc * m_ / (jnp.sqrt(v_) + self.epsilon), m, v
        )
        return updates, {"m": m, "v": v}


class AdamW(Adam):
    """Adam with decoupled weight decay (capability beyond the reference's
    updater set; standard for BERT-class training)."""

    def __init__(self, learningRate=1e-3, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, weightDecay=0.01):
        super().__init__(learningRate, beta1, beta2, epsilon)
        self.weightDecay = weightDecay

    def apply(self, grads, state, params, step):
        updates, new_state = super().apply(grads, state, params, step)
        lr = self.lr(step)
        wd = self.weightDecay
        updates = _tm(lambda u, p: u + lr * wd * p, updates, params)
        return updates, new_state


class AMSGrad(Adam):
    def init_state(self, params):
        s = super().init_state(params)
        s["vhat"] = _tm(jnp.zeros_like, params)
        return s

    def apply(self, grads, state, params, step):
        lr = self.lr(step)
        t = step + 1
        m, v = self._moments(grads, state)
        vhat = _tm(jnp.maximum, state["vhat"], v)
        bc = jnp.sqrt(1.0 - self.beta2**t) / (1.0 - self.beta1**t)
        updates = _tm(
            lambda m_, vh: lr * bc * m_ / (jnp.sqrt(vh) + self.epsilon), m, vhat
        )
        return updates, {"m": m, "v": v, "vhat": vhat}


class AdaMax(Adam):
    def apply(self, grads, state, params, step):
        lr = self.lr(step)
        t = step + 1
        b1 = self.beta1
        m = _tm(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        u = _tm(
            lambda v, g: jnp.maximum(self.beta2 * v, jnp.abs(g)),
            state["v"], grads,
        )
        updates = _tm(
            lambda m_, u_: lr / (1 - b1**t) * m_ / (u_ + self.epsilon), m, u
        )
        return updates, {"m": m, "v": u}


class Nadam(Adam):
    def apply(self, grads, state, params, step):
        lr = self.lr(step)
        t = step + 1
        b1, b2 = self.beta1, self.beta2
        m, v = self._moments(grads, state)
        mhat = _tm(
            lambda m_, g: b1 * m_ / (1 - b1**t) + (1 - b1) * g / (1 - b1**t),
            m, grads,
        )
        vhat = _tm(lambda v_: v_ / (1 - b2**t), v)
        updates = _tm(
            lambda mh, vh: lr * mh / (jnp.sqrt(vh) + self.epsilon), mhat, vhat
        )
        return updates, {"m": m, "v": v}


_REGISTRY = {
    c.__name__: c
    for c in [NoOp, Sgd, Nesterovs, AdaGrad, RmsProp, AdaDelta, Adam, AdamW,
              AMSGrad, AdaMax, Nadam]
}


def updater_from_config(d):
    """Inverse of IUpdater.to_json."""
    if isinstance(d, IUpdater):
        return d
    d = dict(d)
    cls = _REGISTRY[d.pop("@class")]
    lr = d.pop("learningRate", None)
    if isinstance(lr, dict):  # serialized schedule (possibly nested)
        from deeplearning4j_tpu.optimize.schedules import schedule_from_json

        lr = schedule_from_json(lr)
    obj = cls.__new__(cls)
    IUpdater.__init__(obj, lr if lr is not None else 0.1)
    for k, v in d.items():
        setattr(obj, k, v)
    return obj
