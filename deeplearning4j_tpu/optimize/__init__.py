"""Optimizers/updaters (reference: org.nd4j.linalg.learning.* updaters +
org.nd4j.linalg.learning.config.* and org.nd4j.linalg.schedule —
SURVEY.md §2.3 "Updaters/optimizers").

TPU-first: each updater is a pure pytree transform `(grads, state, params,
step) -> (updates, state)` so the whole update fuses into the jitted train
step (reference applied updaters as separate vectorized ops over the flat
param view; here XLA fuses them into the backward pass).
"""

from deeplearning4j_tpu.optimize.updaters import (
    Sgd,
    Adam,
    AdamW,
    AdaMax,
    Nadam,
    AMSGrad,
    Nesterovs,
    AdaGrad,
    AdaDelta,
    RmsProp,
    NoOp,
    updater_from_config,
)
from deeplearning4j_tpu.optimize.schedules import (
    FixedSchedule,
    ExponentialSchedule,
    InverseSchedule,
    PolySchedule,
    SigmoidSchedule,
    StepSchedule,
    MapSchedule,
    RampSchedule,
    CycleSchedule,
)

__all__ = [
    "Sgd", "Adam", "AdamW", "AdaMax", "Nadam", "AMSGrad", "Nesterovs",
    "AdaGrad", "AdaDelta", "RmsProp", "NoOp", "updater_from_config",
    "FixedSchedule", "ExponentialSchedule", "InverseSchedule", "PolySchedule",
    "SigmoidSchedule", "StepSchedule", "MapSchedule", "RampSchedule",
    "CycleSchedule",
]
