"""Learning-rate schedules (reference: org.nd4j.linalg.schedule.ISchedule
implementations — SURVEY.md §2.3). Pure functions of the integer step so they
trace cleanly inside a jitted train step."""

from __future__ import annotations

import jax.numpy as jnp


class ISchedule:
    def valueAt(self, iteration, epoch=0):
        raise NotImplementedError

    def __call__(self, step):
        return self.valueAt(step)

    def to_json(self):
        d = {"@class": type(self).__name__}
        for k, v in self.__dict__.items():
            d[k] = v.to_json() if isinstance(v, ISchedule) else v
        return d


class FixedSchedule(ISchedule):
    def __init__(self, value: float):
        self.value = value

    def valueAt(self, iteration, epoch=0):
        return self.value


class ExponentialSchedule(ISchedule):
    def __init__(self, initialValue: float, gamma: float):
        self.initialValue = initialValue
        self.gamma = gamma

    def valueAt(self, iteration, epoch=0):
        return self.initialValue * jnp.power(self.gamma, iteration)


class InverseSchedule(ISchedule):
    def __init__(self, initialValue: float, gamma: float, power: float):
        self.initialValue = initialValue
        self.gamma = gamma
        self.power = power

    def valueAt(self, iteration, epoch=0):
        return self.initialValue / jnp.power(1.0 + self.gamma * iteration, self.power)


class PolySchedule(ISchedule):
    def __init__(self, initialValue: float, power: float, maxIter: int):
        self.initialValue = initialValue
        self.power = power
        self.maxIter = maxIter

    def valueAt(self, iteration, epoch=0):
        frac = jnp.minimum(iteration / self.maxIter, 1.0)
        return self.initialValue * jnp.power(1.0 - frac, self.power)


class SigmoidSchedule(ISchedule):
    def __init__(self, initialValue: float, gamma: float, stepSize: int):
        self.initialValue = initialValue
        self.gamma = gamma
        self.stepSize = stepSize

    def valueAt(self, iteration, epoch=0):
        return self.initialValue / (
            1.0 + jnp.exp(self.gamma * (iteration - self.stepSize))
        )


class StepSchedule(ISchedule):
    def __init__(self, initialValue: float, decayRate: float, step: float):
        self.initialValue = initialValue
        self.decayRate = decayRate
        self.step = step

    def valueAt(self, iteration, epoch=0):
        return self.initialValue * jnp.power(
            self.decayRate, jnp.floor(iteration / self.step)
        )


class MapSchedule(ISchedule):
    """Piecewise-constant: {iteration: value}. First key must be 0."""

    def __init__(self, values: dict):
        self.values = dict(sorted((int(k), float(v)) for k, v in values.items()))

    def valueAt(self, iteration, epoch=0):
        keys = jnp.asarray(list(self.values.keys()))
        vals = jnp.asarray(list(self.values.values()))
        idx = jnp.sum(keys <= iteration) - 1
        return vals[idx]


class RampSchedule(ISchedule):
    """Linear warmup from 0 to the wrapped schedule over numIter steps."""

    def __init__(self, baseSchedule: ISchedule, numIter: int):
        self.baseSchedule = baseSchedule
        self.numIter = numIter

    def valueAt(self, iteration, epoch=0):
        ramp = jnp.minimum((iteration + 1.0) / self.numIter, 1.0)
        return ramp * self.baseSchedule.valueAt(iteration, epoch)


class CycleSchedule(ISchedule):
    """1cycle-style: ramp up then down, with a final annihilation phase."""

    def __init__(self, initialLearningRate, maxLearningRate, cycleLength,
                 annealingLength=None, annealingDecay=0.1):
        self.initialLearningRate = initialLearningRate
        self.maxLearningRate = maxLearningRate
        self.cycleLength = cycleLength
        self.annealingLength = annealingLength or max(cycleLength // 10, 1)
        self.annealingDecay = annealingDecay

    def valueAt(self, iteration, epoch=0):
        half = (self.cycleLength - self.annealingLength) / 2.0
        it = jnp.asarray(iteration, dtype=jnp.float32)
        up = self.initialLearningRate + (
            self.maxLearningRate - self.initialLearningRate
        ) * (it / half)
        down = self.maxLearningRate - (
            self.maxLearningRate - self.initialLearningRate
        ) * ((it - half) / half)
        anneal_start = 2 * half
        anneal = self.initialLearningRate * jnp.power(
            self.annealingDecay,
            (it - anneal_start) / jnp.maximum(self.annealingLength, 1),
        )
        return jnp.where(it < half, up, jnp.where(it < anneal_start, down, anneal))


def schedule_from_json(d) -> ISchedule:
    import sys

    d = dict(d)
    cls = getattr(sys.modules[__name__], d.pop("@class"))
    kwargs = {
        k: schedule_from_json(v) if isinstance(v, dict) and "@class" in v else v
        for k, v in d.items()
    }
    return cls(**kwargs)


def resolve_lr(lr, step):
    """lr may be a float, an ISchedule, or a callable(step)."""
    if isinstance(lr, ISchedule):
        return lr.valueAt(step)
    if callable(lr):
        return lr(step)
    return lr
