"""Typed runtime environment configuration.

Reference capability: tier-2 config — `Nd4jEnvironment` / ND4J system
properties and the scattered XLA/platform flags (SURVEY.md §5 "Config /
flag system": "tier 2 becomes XLA/PJRT flags behind one typed config
class"). Round 1 set these inline per entry point (conftest.py,
__graft_entry__.py), which is exactly the scatter that broke the driver's
multichip check (VERDICT.md weak item 1) — this module is the one place
that owns platform selection, virtual device counts, matmul precision and
debug toggles.

Usage (must run BEFORE the first jax backend touch for platform changes):

    from deeplearning4j_tpu.runtime import RuntimeConfig
    RuntimeConfig(platform="cpu", host_device_count=8).apply()
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


@dataclass
class RuntimeConfig:
    """One typed view of every runtime/XLA knob the framework touches.

    platform: "cpu" | "tpu" | None (None = jax default resolution)
    host_device_count: virtual CPU device count (the in-process multi-chip
        simulation; SURVEY.md §4 implication 3)
    matmul_precision: "default" | "high" | "highest" — "highest" forces
        full fp32 MXU passes (needed by the gradient-check harness,
        SURVEY.md §7 "Numerics")
    deterministic: force deterministic op lowering where available
    debug_nans / debug_infs: jax-level NaN/Inf panic (reference:
        OpProfiler NAN_PANIC / INF_PANIC, SURVEY.md §2.3)
    disable_jit: run ops eagerly for debugging (reference: the synchronous
        debug mode, SURVEY.md §5 "Race detection")
    extra_xla_flags: appended verbatim to XLA_FLAGS
    """

    platform: str | None = None
    host_device_count: int | None = None
    matmul_precision: str | None = None
    deterministic: bool = False
    debug_nans: bool = False
    debug_infs: bool = False
    disable_jit: bool = False
    extra_xla_flags: list[str] = field(default_factory=list)

    def apply(self) -> "RuntimeConfig":
        flags = os.environ.get("XLA_FLAGS", "")
        parts = [f for f in flags.split() if f]
        if self.host_device_count is not None:
            parts = [p for p in parts
                     if "xla_force_host_platform_device_count" not in p]
            parts.append("--xla_force_host_platform_device_count="
                         f"{self.host_device_count}")
        for f in self.extra_xla_flags:
            if f not in parts:
                parts.append(f)
        if parts:
            os.environ["XLA_FLAGS"] = " ".join(parts)

        import jax

        # jax may be pre-imported (.pth hook) -> env vars are latched;
        # jax.config.update works until the backend initializes
        if self.platform is not None:
            try:
                jax.config.update("jax_platforms", self.platform)
            except RuntimeError as e:  # backend already up
                raise RuntimeError(
                    "RuntimeConfig.apply() must run before the first "
                    "device access (jax backend already initialized)"
                ) from e
        if self.matmul_precision is not None:
            jax.config.update("jax_default_matmul_precision",
                              self.matmul_precision)
        if self.debug_nans:
            jax.config.update("jax_debug_nans", True)
        if self.debug_infs:
            jax.config.update("jax_debug_infs", True)
        if self.disable_jit:
            jax.config.update("jax_disable_jit", True)
        return self

    @staticmethod
    def cpu_mesh(n_devices: int = 8,
                 matmul_precision: str = "highest") -> "RuntimeConfig":
        """The in-process multi-chip simulation used by tests and the
        driver's dryrun: n virtual CPU devices, full-precision matmuls."""
        return RuntimeConfig(platform="cpu", host_device_count=n_devices,
                             matmul_precision=matmul_precision)

    @staticmethod
    def environment() -> dict:
        """Runtime environment dump (reference: Nd4jEnvironment /
        Nd4j.getExecutioner().getEnvironmentInformation())."""
        import jax

        devs = jax.devices()
        return {
            "backend": jax.default_backend(),
            "device_count": len(devs),
            "devices": [str(d) for d in devs],
            "process_count": jax.process_count(),
            "jax_version": jax.__version__,
            "xla_flags": os.environ.get("XLA_FLAGS", ""),
        }
