"""Backend discovery / selection seam.

Reference capability: org.nd4j.linalg.factory.Nd4jBackend.load() —
classpath-scanned backend priority selection between nd4j-native and
nd4j-cuda (SURVEY.md §2.2 "Backend discovery"). Here the backends are
jax platforms (TPU via the PJRT plugin, CPU fallback); discovery is one
place that enumerates what is actually loadable and picks by priority,
instead of each call site poking at jax.devices() ad hoc (the scatter
VERDICT.md round 1 flagged as the cause of the failed multichip check).

Selection can be forced with the DL4J_TPU_BACKEND env var ("tpu"/"cpu")
— the analog of ND4J's priority system properties.
"""

from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass(frozen=True)
class Backend:
    """One loadable execution backend."""

    name: str              # "tpu" | "cpu"
    platform: str          # jax platform string ("tpu"/"axon"/"cpu")
    priority: int          # higher wins (reference: backend priority)
    device_count: int

    def isAvailable(self):
        return self.device_count > 0


class Nd4jBackend:
    """Reference: Nd4jBackend.load() — pick the highest-priority
    available backend exactly once per process."""

    _loaded: Backend | None = None
    _forced: dict = {}

    #: accelerator platforms, probed in order, all mapped to name "tpu"
    TPU_PLATFORMS = ("tpu", "axon")

    @classmethod
    def _discover(cls) -> list[Backend]:
        import jax

        found = []
        for plat in cls.TPU_PLATFORMS:
            try:
                devs = jax.devices(plat)
            except RuntimeError:
                continue
            if devs:
                found.append(Backend("tpu", plat, 100, len(devs)))
                break
        try:
            cpus = jax.devices("cpu")
        except RuntimeError:
            cpus = []
        if cpus:
            found.append(Backend("cpu", "cpu", 0, len(cpus)))
        return found

    @classmethod
    def availableBackends(cls) -> list[Backend]:
        return sorted(cls._discover(), key=lambda b: -b.priority)

    @classmethod
    def load(cls, force: str | None = None) -> Backend:
        """Highest-priority available backend (memoized). `force` or the
        DL4J_TPU_BACKEND env var pin a specific backend name; an
        unavailable forced backend raises instead of silently falling
        back (reference: NoAvailableBackendException)."""
        force = force or os.environ.get("DL4J_TPU_BACKEND")
        if force is not None:
            name = str(force).lower()
            if name in cls._forced:
                return cls._forced[name]
            backends = cls.availableBackends()
            for b in backends:
                if b.name == name:
                    cls._forced[name] = b
                    return b
            raise RuntimeError(
                f"backend {force!r} requested but not available (found: "
                f"{[b.name for b in backends]})")
        if cls._loaded is None:
            backends = cls.availableBackends()
            if not backends:
                raise RuntimeError("no jax backend available")
            cls._loaded = backends[0]
        return cls._loaded

    @classmethod
    def devices(cls, force: str | None = None):
        import jax

        return jax.devices(cls.load(force).platform)

    @classmethod
    def reset(cls):
        """Testing hook: forget the memoized selections."""
        cls._loaded = None
        cls._forced = {}
