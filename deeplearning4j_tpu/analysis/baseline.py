"""dl4jlint baseline: triaged pre-existing findings, checked in.

The baseline is the escape hatch that lets the analyzer run with zero
tolerance in tier-1 from day one: every finding is either fixed,
inline-suppressed at the site, or listed here WITH a one-line reason.
``--baseline-update`` rewrites the file from the current findings,
preserving reasons for keys that survive; new entries get reason
"TODO: triage" so an un-reviewed regeneration is visible in diff.
"""

from __future__ import annotations

import json
import os


class Baseline:
    def __init__(self, entries=None, path=None):
        # key -> entry dict {key, rule, file, reason}
        self.entries = {e["key"]: dict(e) for e in (entries or [])}
        self.path = path

    @classmethod
    def load(cls, path):
        if not os.path.exists(path):
            return cls(path=path)
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        return cls(data.get("findings", []), path=path)

    def matches(self, finding) -> bool:
        return finding.key() in self.entries

    def split(self, findings):
        """(new, baselined, stale_keys): findings not in the baseline,
        findings covered by it, and baseline keys no longer produced
        (fixed code — prune them with --baseline-update)."""
        new, covered, seen = [], [], set()
        for f in findings:
            if self.matches(f):
                covered.append(f)
                seen.add(f.key())
            else:
                new.append(f)
        stale = [k for k in self.entries if k not in seen]
        return new, covered, stale

    def update_from(self, findings, restrict_to_rules=None):
        """Rewrite entries from ``findings``. With ``restrict_to_rules``
        (a set of rule names — the CLI passes it for ``--rules`` subset
        runs), entries of rules NOT in the set are kept untouched: a
        partial run must not wipe other rules' triage."""
        if restrict_to_rules is None:
            fresh = {}
        else:
            fresh = {k: e for k, e in self.entries.items()
                     if e.get("rule") not in restrict_to_rules}
        for f in findings:
            k = f.key()
            old = self.entries.get(k)
            fresh[k] = {
                "key": k,
                "rule": f.rule,
                "file": f.file,
                "reason": (old or {}).get("reason", "TODO: triage"),
            }
        self.entries = fresh

    def save(self, path=None):
        path = path or self.path
        data = {
            "version": 1,
            "comment": ("Triaged pre-existing dl4jlint findings. Every "
                        "entry needs a one-line reason; regenerate with "
                        "tools/dl4jlint.py --baseline-update (reasons "
                        "are preserved for surviving keys)."),
            "findings": sorted(self.entries.values(),
                               key=lambda e: (e["rule"], e["file"],
                                              e["key"])),
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2, sort_keys=False)
            f.write("\n")
