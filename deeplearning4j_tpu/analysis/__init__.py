"""dl4jlint: AST-level static analysis of the repo's own invariants
(ISSUE 7) plus the runtime lock witness.

Entry points:
  analyze(paths, ...)        -> Report            (runner.py)
  all_rules()                -> {name: Rule}      (core.py)
  Baseline.load(path)        -> Baseline          (baseline.py)
  witness.install()/WitnessLock                   (witness.py)

CLI: tools/dl4jlint.py. Rule catalog: docs/STATIC_ANALYSIS.md.
"""

from deeplearning4j_tpu.analysis.core import (  # noqa: F401
    Finding, Rule, Severity, all_rules, register)
from deeplearning4j_tpu.analysis.baseline import Baseline  # noqa: F401
from deeplearning4j_tpu.analysis.runner import (  # noqa: F401
    Report, analyze, run_rules)
from deeplearning4j_tpu.analysis.model import (  # noqa: F401
    Module, Project, load_project)

__all__ = ["Finding", "Rule", "Severity", "all_rules", "register",
           "Baseline", "Report", "analyze", "run_rules", "Module",
           "Project", "load_project"]
