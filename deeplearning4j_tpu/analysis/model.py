"""dl4jlint source model: parsed modules, scopes, call sites.

One ``Module`` per file: the AST plus the derived tables every rule
needs — function/class scopes with dotted qualnames, call sites with
resolved attribute chains, the import alias map, a node->parent map,
and the ``# dl4jlint: disable=`` suppression index. ``Project`` is the
set of modules under analysis plus shared config (analysis root, docs
text for the metric-drift rule).
"""

from __future__ import annotations

import ast
import os
import re
import sys


_SUPPRESS = re.compile(r"#\s*dl4jlint:\s*disable=([\w,\-]+)")


def call_chain(func_node):
    """The dotted name chain of a call target: ``a.b.c(...)`` ->
    ("a","b","c"); ``f(...)`` -> ("f",). None for computed targets
    (subscripts resolve through their value: ``self._fns[k](...)`` ->
    ("self","_fns","[]"))."""
    parts = []
    node = func_node
    while True:
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Name):
            parts.append(node.id)
            return tuple(reversed(parts))
        elif isinstance(node, ast.Subscript):
            parts.append("[]")
            node = node.value
        elif isinstance(node, ast.Call):
            # chained call like jax.jit(f)(x): resolve through the
            # inner call's target
            parts.append("()")
            node = node.func
        else:
            return None


def keyword(call, name):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


class FunctionInfo:
    """One function/method scope."""

    __slots__ = ("node", "qualname", "module", "class_name", "calls")

    def __init__(self, node, qualname, module, class_name):
        self.node = node
        self.qualname = qualname      # "Class.method" / "fn.inner"
        self.module = module
        self.class_name = class_name  # enclosing class or None
        # [(chain tuple|None, Call node)] in source order
        self.calls = []


class Module:
    """Parsed file + derived tables."""

    def __init__(self, path, root):
        self.path = str(path)
        self.rel = os.path.relpath(self.path, root).replace(os.sep, "/")
        with open(self.path, "r", encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=self.path)
        # module name relative to the package tree, for import
        # resolution: "deeplearning4j_tpu.serving.batcher"
        self.modname = self.rel[:-3].replace("/", ".") \
            if self.rel.endswith(".py") else self.rel.replace("/", ".")
        self.is_pkg = self.modname.endswith(".__init__") or \
            self.modname == "__init__"
        if self.modname.endswith(".__init__"):
            self.modname = self.modname[: -len(".__init__")]

        self.parent: dict = {}          # ast node -> parent node
        self.functions: dict = {}       # qualname -> FunctionInfo
        self.classes: dict = {}         # class name -> ClassDef node
        self.imports: dict = {}         # local alias -> dotted module/obj
        self.suppressed: dict = {}      # lineno -> set of rule names
        self._index()

    # -- construction --------------------------------------------------------
    def _index(self):
        for i, line in enumerate(self.lines, 1):
            m = _SUPPRESS.search(line)
            if m:
                self.suppressed[i] = {r.strip() for r in
                                      m.group(1).split(",") if r.strip()}

        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node

        self._walk_scope(self.tree, prefix="", class_name=None)
        self._node_fn = {id(info.node): info
                         for info in self.functions.values()}

        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.imports[alias.asname or
                                 alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_import_base(node)
                if base is None:
                    continue
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = \
                        f"{base}.{alias.name}"

    def _resolve_import_base(self, node):
        """The absolute dotted module an ImportFrom names — relative
        imports (``from .registry import X``) resolve against THIS
        module's package, so the call graph can't suffix-match the
        wrong module on basename collisions (serving/registry vs
        telemetry/registry)."""
        if not node.level:
            return node.module  # absolute (None never occurs here)
        parts = self.modname.split(".")
        if not self.is_pkg:      # drop the module's own name first
            parts = parts[:-1]
        keep = len(parts) - (node.level - 1)  # extra levels drop one
        if keep <= 0:                         # package each
            return None          # beyond the analysis root
        parts = parts[:keep]
        base = ".".join(parts)
        return f"{base}.{node.module}" if node.module else base

    def _walk_scope(self, node, prefix, class_name):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                info = FunctionInfo(child, qual, self, class_name)
                self.functions[qual] = info
                self._collect_calls(child, info)
                self._walk_scope(child, prefix=qual + ".",
                                 class_name=class_name)
            elif isinstance(child, ast.ClassDef):
                self.classes[child.name] = child
                self._walk_scope(child, prefix=f"{prefix}{child.name}.",
                                 class_name=child.name)
            else:
                self._walk_scope(child, prefix=prefix,
                                 class_name=class_name)

    def _collect_calls(self, fn_node, info):
        # calls lexically inside this def, EXCLUDING nested defs (those
        # get their own FunctionInfo)
        def visit(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    continue
                if isinstance(child, ast.Call):
                    info.calls.append((call_chain(child.func), child))
                visit(child)
        visit(fn_node)

    # -- queries -------------------------------------------------------------
    def enclosing_function(self, node):
        """The FunctionInfo whose def lexically contains ``node`` (the
        innermost one), or None at module level."""
        cur = node
        while cur is not None:
            info = self._node_fn.get(id(cur))
            if info is not None:
                return info
            cur = self.parent.get(cur)
        return None

    def scope_name(self, node) -> str:
        info = self.enclosing_function(node)
        return info.qualname if info is not None else "<module>"

    def is_suppressed(self, rule, node) -> bool:
        """True when the node's line, any enclosing def's line, or a
        module-wide directive (line 1/2) carries
        ``# dl4jlint: disable=<rule>`` (or ``=all``)."""
        lines = {getattr(node, "lineno", 0)}
        cur = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                lines.add(cur.lineno)
                # decorators push the def line down; the directive is
                # usually written on the decorator line
                for dec in cur.decorator_list:
                    lines.add(dec.lineno)
            cur = self.parent.get(cur)
        lines.update((1, 2))
        for ln in lines:
            rules = self.suppressed.get(ln)
            if rules and (rule in rules or "all" in rules):
                return True
        return False


class Project:
    """All modules under analysis + shared config.

    config keys used by rules:
      docs_text           OBSERVABILITY.md text for metric-drift and
                          route-drift (auto-loaded from
                          <root>/docs/OBSERVABILITY.md when present)
      serving_docs_text   SERVING.md text for route-drift (auto-loaded
                          from <root>/docs/SERVING.md when present)
    """

    def __init__(self, modules, root, config=None):
        self.modules = list(modules)
        self.root = str(root)
        self.config = dict(config or {})
        self.by_rel = {m.rel: m for m in self.modules}
        self.by_modname = {m.modname: m for m in self.modules}
        self._callgraph = None

    @property
    def callgraph(self):
        if self._callgraph is None:
            from deeplearning4j_tpu.analysis.callgraph import CallGraph
            self._callgraph = CallGraph(self)
        return self._callgraph


def collect_py_files(paths):
    """Expand files/directories into a sorted .py file list (skipping
    __pycache__)."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        out.append(os.path.join(dirpath, f))
        elif p.endswith(".py"):
            out.append(p)
    return sorted(set(out))


def load_project(paths, root=None, config=None) -> Project:
    files = collect_py_files(paths)
    if root is None:
        root = os.path.commonpath([os.path.abspath(f) for f in files]) \
            if files else os.getcwd()
        if os.path.isfile(root):
            root = os.path.dirname(root)
    modules = []
    for f in files:
        try:
            modules.append(Module(f, root))
        except SyntaxError as e:  # broken file IS a finding, not a crash
            print(f"dl4jlint: syntax error in {f}: {e}",
                  file=sys.stderr)
    project = Project(modules, root, config)
    for key, name in (("docs_text", "OBSERVABILITY.md"),
                      ("serving_docs_text", "SERVING.md")):
        if key not in project.config:
            docs = os.path.join(root, "docs", name)
            if os.path.exists(docs):
                with open(docs, "r", encoding="utf-8") as f:
                    project.config[key] = f.read()
    return project
