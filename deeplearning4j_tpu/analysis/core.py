"""dl4jlint core: findings, severities, the rule registry (ISSUE 7).

The invariants PRs 1-6 established — no collectives from background
threads, zero registry calls when telemetry is disabled, no host sync
inside jitted step functions, tmp+os.replace checkpoint commits, lock
discipline — previously lived in reviewers' heads and scattered runtime
tests. Each rule here encodes one of them as an AST-level check so a
violation fails tier-1 *before* it becomes a gloo deadlock or a
non-resumable checkpoint. See docs/STATIC_ANALYSIS.md for the rule
catalog and the PR-history incident each rule descends from.
"""

from __future__ import annotations

import re


class Severity:
    """Ordered severity levels. ERROR findings are bugs (the invariant
    is violated); WARN findings are hygiene debt that has caused bugs
    before; INFO is advisory."""

    INFO = "INFO"
    WARN = "WARN"
    ERROR = "ERROR"

    _ORDER = {INFO: 0, WARN: 1, ERROR: 2}

    @classmethod
    def rank(cls, sev) -> int:
        return cls._ORDER[sev]


class Finding:
    """One rule violation anchored to file:line.

    ``key()`` is the baseline identity: rule + file + enclosing scope +
    a digit-stripped message fingerprint — deliberately NOT the line
    number, so unrelated edits above a triaged finding don't invalidate
    the baseline entry."""

    __slots__ = ("rule", "severity", "file", "line", "scope", "message",
                 "_node")

    def __init__(self, rule, severity, file, line, message,
                 scope="<module>"):
        self._node = None  # AST anchor for inline-suppression lookup
        self.rule = rule
        self.severity = severity
        self.file = file          # path relative to the analysis root
        self.line = int(line)
        self.scope = scope        # enclosing function qualname
        self.message = message

    def fingerprint(self) -> str:
        # digits collapse so argnum/line references inside the message
        # stay stable across unrelated churn
        return re.sub(r"\d+", "N", self.message)[:160]

    def key(self) -> str:
        return f"{self.rule}::{self.file}::{self.scope}::" \
               f"{self.fingerprint()}"

    def render(self) -> str:
        return (f"{self.file}:{self.line}: [{self.severity}] "
                f"{self.rule}: {self.message} (in {self.scope})")

    def __repr__(self):
        return f"<Finding {self.render()}>"


class Rule:
    """Base class. Subclasses set ``name`` / ``severity`` /
    ``description`` and override ``check_module`` (per-file rules)
    and/or ``check_project`` (cross-module rules that need the call
    graph or the whole lock graph)."""

    name = "abstract"
    severity = Severity.ERROR
    description = ""

    def check_module(self, module, project):
        return ()

    def check_project(self, project):
        return ()

    def finding(self, module, node, message, scope=None,
                severity=None, line=None):
        """Build a Finding anchored to an AST node (enables inline
        suppression via the node's enclosing def lines). ``node`` may
        be None when there is no AST anchor (pass ``line``)."""
        if line is None:
            line = getattr(node, "lineno", 0)
        if scope is None:
            scope = module.scope_name(node) if node is not None \
                else "<module>"
        f = Finding(self.name, severity or self.severity,
                    module.rel, line, message, scope)
        f._node = node
        return f


_RULES: dict = {}


def register(cls):
    """Class decorator adding a Rule subclass to the registry."""
    inst = cls()
    if inst.name in _RULES:
        raise ValueError(f"duplicate rule name {inst.name!r}")
    _RULES[inst.name] = inst
    return cls


def all_rules() -> dict:
    """{name: rule instance}, importing the rule modules on first use."""
    from deeplearning4j_tpu.analysis import rules  # noqa: F401 registers
    return dict(_RULES)
