"""dl4jlint runner: parse -> rules -> suppressions -> baseline."""

from __future__ import annotations

from deeplearning4j_tpu.analysis.core import Severity, all_rules
from deeplearning4j_tpu.analysis.model import load_project


class Report:
    """Outcome of one analysis run."""

    def __init__(self, project, findings, baseline=None,
                 suppressed_count=0):
        self.project = project
        self.baseline = baseline
        self.suppressed_count = suppressed_count
        if baseline is not None:
            self.new, self.baselined, self.stale_keys = \
                baseline.split(findings)
        else:
            self.new, self.baselined, self.stale_keys = \
                list(findings), [], []
        self.all_findings = list(findings)

    @property
    def ok(self) -> bool:
        return not self.new

    def render(self, show_baselined=False) -> str:
        out = []
        for f in sorted(self.new,
                        key=lambda f: (-Severity.rank(f.severity),
                                       f.file, f.line)):
            out.append(f.render())
        if show_baselined:
            for f in sorted(self.baselined,
                            key=lambda f: (f.file, f.line)):
                out.append(f"[baselined] {f.render()}")
        return "\n".join(out)


def run_rules(project, rules=None):
    """All findings (pre-baseline), inline suppressions applied.
    Returns (findings, suppressed_count)."""
    rules = rules if rules is not None else all_rules()
    findings, suppressed = [], 0
    by_rel = project.by_rel
    for rule in rules.values():
        produced = []
        for mod in project.modules:
            produced.extend(rule.check_module(mod, project))
        produced.extend(rule.check_project(project))
        for f in produced:
            mod = by_rel.get(f.file)
            node = getattr(f, "_node", None)
            if mod is not None and node is not None and \
                    mod.is_suppressed(f.rule, node):
                suppressed += 1
                continue
            if mod is not None and node is None:
                # findings without an anchored node: honor a line-level
                # or module-level directive
                rules_at = mod.suppressed.get(f.line, set()) | \
                    mod.suppressed.get(1, set()) | \
                    mod.suppressed.get(2, set())
                if f.rule in rules_at or "all" in rules_at:
                    suppressed += 1
                    continue
            findings.append(f)
    return findings, suppressed


def analyze(paths, root=None, baseline=None, rules=None, config=None):
    """Full pipeline; returns a Report."""
    project = load_project(paths, root=root, config=config)
    findings, suppressed = run_rules(project, rules=rules)
    return Report(project, findings, baseline=baseline,
                  suppressed_count=suppressed)
