"""atomic-commit: checkpoint artifacts commit via tmp + os.replace.

Contract (PR 5, utils/checkpoint.atomic_save): a crash at ANY point
leaves either the previous committed file or a ``.tmp`` remnant —
never a partial artifact under the real name — so ``latest()`` /
``latest_agreed()`` can trust whatever they find. The PR-5 elastic GC
satellite existed because one writer leaked ``.tmp`` files; a writer
that skips the protocol entirely is worse: a torn file under the real
name poisons auto-resume.

Detection: direct write calls (``open(path, "w"/"wb")``,
``zipfile.ZipFile(path, "w")``, ``np.savez*``, ``shutil.copy*``,
``.write_text``/``.write_bytes``, ``json.dump`` target opens) where the
*path expression* looks checkpoint-ish (mentions ckpt/checkpoint/
manifest/shard) — flagged unless the path goes through a tmp name or
the enclosing function participates in the protocol (calls
``atomic_save`` or ``os.replace``/``os.rename``).
"""

from __future__ import annotations

import ast
import re

from deeplearning4j_tpu.analysis.core import Rule, Severity, register
from deeplearning4j_tpu.analysis.model import call_chain

# "store_path"/".xc"/"executable_store" extend the protocol to the
# ISSUE 13 persistent executable store: a torn serialized executable
# under its real name would be deserialized by the next warm restart
# (the payload hash rejects it, but the commit protocol is what keeps
# the PREVIOUS good entry in place)
_CKPT_PATH = re.compile(
    r"ckpt|checkpoint|manifest|shard_|store_path|executable_store|\.xc\b",
    re.IGNORECASE)
_TMPISH = re.compile(r"tmp|temp", re.IGNORECASE)
_PROTOCOL = {"atomic_save", "replace", "rename"}


def _unparse(node) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


def _write_path_arg(chain, call):
    """The path expression of a direct-write call, else None."""
    last = chain[-1]
    if last == "open" and call.args:
        if len(call.args) >= 2 and isinstance(call.args[1],
                                              ast.Constant):
            mode = str(call.args[1].value)
            if "w" not in mode and "a" not in mode and "x" not in mode:
                return None
        elif len(call.args) < 2:
            return None  # read mode by default
        return call.args[0]
    if last == "ZipFile" and len(call.args) >= 2:
        if isinstance(call.args[1], ast.Constant) and \
                "w" in str(call.args[1].value):
            return call.args[0]
        return None
    if last in ("savez", "savez_compressed", "save") and call.args:
        return call.args[0]
    if last in ("copy", "copyfile", "copy2", "move") and \
            len(call.args) >= 2:
        return call.args[1]
    if last in ("write_text", "write_bytes") and len(chain) >= 2:
        return call.func.value if isinstance(call.func,
                                             ast.Attribute) else None
    return None


@register
class AtomicCommitRule(Rule):
    name = "atomic-commit"
    severity = Severity.ERROR
    description = ("direct write to a checkpoint path bypassing the "
                   "tmp + os.replace commit protocol "
                   "(utils/checkpoint.atomic_save) — a crash can "
                   "expose a torn artifact to auto-resume")

    def check_module(self, mod, project):
        for info in mod.functions.values():
            in_protocol = any(
                chain and chain[-1] in _PROTOCOL
                for chain, _ in info.calls)
            if in_protocol:
                continue
            for chain, call in info.calls:
                if not chain:
                    continue
                path_arg = _write_path_arg(chain, call)
                if path_arg is None:
                    continue
                text = _unparse(path_arg)
                if not _CKPT_PATH.search(text):
                    continue
                if _TMPISH.search(text):
                    continue  # writing the tmp half of the protocol
                yield self.finding(
                    mod, call,
                    f"direct write to checkpoint path "
                    f"({text[:60]!r}) without atomic_save/os.replace "
                    f"— commit via tmp + rename so a crash never "
                    f"exposes a partial artifact",
                    scope=info.qualname)
