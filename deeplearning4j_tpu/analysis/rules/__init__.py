"""dl4jlint rule modules — importing this package registers every
rule with the core registry. One module per rule; each docstring names
the PR-history incident the rule descends from (catalog:
docs/STATIC_ANALYSIS.md)."""

from deeplearning4j_tpu.analysis.rules import (  # noqa: F401
    atomic_commit,
    collectives,
    donation,
    jit_purity,
    lock_order,
    metric_drift,
    route_drift,
    telemetry_gate,
    threads,
)
