"""donation-use-after: donated buffers must not be read after the call.

Incident (PR 5, async_ckpt.py): the checkpoint snapshot had to be a
jitted device-side *clone* precisely because every trainer step donates
``params``/``opt_state`` (``donate_argnums=(0, 1, ...)``) — reading a
donated buffer after the donated call returns garbage (or raises on
TPU, silently "works" on CPU until it doesn't). The safe idiom is
rebinding in the same statement: ``params, state = step(params,
state)``; this rule flags a donated argument name that is *read again*
after the call without that rebinding.

Limits (documented in docs/STATIC_ANALYSIS.md): analysis is per
function and statement-ordered by line; a read that only happens on
the next loop iteration (line above the call) is not seen — the
rebinding idiom makes that case safe in practice anyway.
"""

from __future__ import annotations

import ast

from deeplearning4j_tpu.analysis.core import Rule, Severity, register
from deeplearning4j_tpu.analysis.callgraph import _flat_targets
from deeplearning4j_tpu.analysis.model import call_chain, keyword


def _donated_argnums(call):
    """The donate_argnums tuple of a jax.jit/pjit call, else None."""
    chain = call_chain(call.func)
    if not chain or chain[-1] not in ("jit", "pjit"):
        return None
    kw = keyword(call, "donate_argnums")
    if kw is None:
        return None
    nums = []
    for node in ast.walk(kw):
        if isinstance(node, ast.Constant) and isinstance(node.value,
                                                         int):
            nums.append(node.value)
    return tuple(nums) or None


def _record_targets(node, nums, out):
    for t in _flat_targets(node):
        if isinstance(t, ast.Name):
            out[t.id] = nums
        elif isinstance(t, ast.Attribute):
            out[t.attr] = nums
        elif isinstance(t, ast.Subscript) and \
                isinstance(t.value, ast.Attribute):
            out[t.value.attr] = nums


def donation_builders(mod):
    """{builder short name: argnums} for functions whose body returns
    a donated jit — the prevailing idiom here is ``def _make_step():
    ... return jax.jit(step, donate_argnums=(0, 1))`` with the alias
    established at the CALLER (``self._fit = self._make_step()``)."""
    out = {}
    for info in mod.functions.values():
        local_jits = {}
        nums_returned = None
        for node in ast.walk(info.node):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                nums = _donated_argnums(node.value)
                if nums is not None:
                    for t in _flat_targets(node):
                        if isinstance(t, ast.Name):
                            local_jits[t.id] = nums
            elif isinstance(node, ast.Return) and node.value is not None:
                if isinstance(node.value, ast.Call):
                    nums_returned = _donated_argnums(node.value) or \
                        nums_returned
                elif isinstance(node.value, ast.Name):
                    nums_returned = local_jits.get(node.value.id) or \
                        nums_returned
        if nums_returned is not None:
            out[info.qualname.rsplit(".", 1)[-1]] = nums_returned
    return out


def donated_aliases(mod):
    """{name: argnums} for names/attrs bound to a donated jit — either
    directly (``X = jax.jit(f, donate_argnums=...)``; X a bare name, a
    self-attribute, or a subscripted self-attribute
    ``self._fns[k] = ...``) or via a builder call
    (``self._fit = self._make_step()``)."""
    out = {}
    builders = donation_builders(mod)
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Assign) and
                isinstance(node.value, ast.Call)):
            continue
        nums = _donated_argnums(node.value)
        if nums is None:
            callee = _call_name(node.value.func)
            nums = builders.get(callee)
        if nums is None:
            continue
        _record_targets(node, nums, out)
    return out


def _call_name(func_node):
    """Matchable alias name of a call target."""
    chain = call_chain(func_node)
    if not chain:
        return None
    # self._fns[k](...) -> chain ends "[]": use the attr before it
    if chain[-1] == "[]" and len(chain) >= 2:
        return chain[-2]
    return chain[-1]


@register
class DonationUseAfterRule(Rule):
    name = "donation-use-after"
    severity = Severity.ERROR
    description = ("an argument passed at a donate_argnums position is "
                   "read after the donated call without rebinding — "
                   "donated device buffers are invalidated")

    def check_module(self, mod, project):
        aliases = donated_aliases(mod)
        if not aliases:
            return
        for info in mod.functions.values():
            yield from self._check_function(mod, info, aliases)

    def _check_function(self, mod, info, aliases):
        fn = info.node
        for chain, call in info.calls:
            name = _call_name(call.func)
            if name not in aliases:
                continue
            stmt = self._enclosing_stmt(mod, call)
            if stmt is None:
                continue
            rebound = {t.id for t in _flat_targets(stmt)
                       if isinstance(t, ast.Name)}
            for pos in aliases[name]:
                if pos >= len(call.args):
                    continue
                arg = call.args[pos]
                if not isinstance(arg, ast.Name):
                    continue
                if arg.id in rebound:
                    continue  # params, s = step(params, s): safe idiom
                bad = self._read_after(fn, arg.id, stmt, call)
                if bad is not None:
                    yield self.finding(
                        mod, bad,
                        f"'{arg.id}' is donated (argnum {pos}) to "
                        f"'{name}' at line {call.lineno} and read "
                        f"again afterwards — rebind it from the call's "
                        f"results or pass a copy",
                        scope=info.qualname)

    def _enclosing_stmt(self, mod, node):
        cur = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = mod.parent.get(cur)
        return cur

    def _read_after(self, fn, name, stmt, call):
        """First Load of ``name`` after the call statement that happens
        before any re-Store, else None."""
        after = getattr(stmt, "end_lineno", stmt.lineno)
        first_store = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id == name and \
                    isinstance(node.ctx, ast.Store) and \
                    node.lineno > after:
                if first_store is None or node.lineno < first_store:
                    first_store = node.lineno
        best = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id == name and \
                    isinstance(node.ctx, ast.Load) and \
                    node.lineno > after:
                if first_store is not None and \
                        node.lineno > first_store:
                    continue
                if best is None or node.lineno < best.lineno:
                    best = node
        return best
