"""jit-purity: no host sync / wall clock / host RNG inside jitted code.

Incidents: the bit-identical kill-and-resume contract (PR 5) dies the
moment a jitted step consults ``time.time()`` or ``np.random`` — the
resumed replay diverges; and a ``float()``/``.item()``/
``.block_until_ready()`` on a traced value forces a host sync that
stalls the dispatch pipeline the PR-6 prefetcher exists to keep full
(PR 1 measured the seed's 100k-dispatch import stall from exactly this
class). ``np.asarray`` on a traced value silently falls back to host
numpy — the op leaves the device.

Scope: functions passed to ``jax.jit``/``pjit``/``shard_map``/
``jax.pmap`` (positionally, as ``fun=``, or via decorator, incl.
``@partial(jax.jit, ...)``) and ``lax.scan``/``while_loop``/``fori_loop``
body functions. Sync-class calls (``float``/``int``/``.item``/
``np.asarray``/``.block_until_ready``) are only flagged on *tainted*
expressions — values derived from the jitted function's own parameters
— so casting a closure constant stays legal. Wall clock and host RNG
are flagged unconditionally.
"""

from __future__ import annotations

import ast

from deeplearning4j_tpu.analysis.core import Rule, Severity, register
from deeplearning4j_tpu.analysis.model import call_chain, keyword

_WRAPPERS = {"jit", "pjit", "shard_map", "pmap"}
# control-flow primitives -> positions of their function-valued args
_BODY_TAKERS = {"scan": (0,), "while_loop": (0, 1), "fori_loop": (2,),
                "cond": (1, 2), "checkpoint": (0,), "remat": (0,)}
_NUMPY_ROOTS = {"np", "numpy", "onp"}
_SYNC_METHODS = {"item", "block_until_ready", "tolist", "copy_to_host"}


def jit_root_functions(mod, graph):
    """FunctionInfos whose bodies become jitted/staged computations."""
    roots = {}

    def add(fn_expr, at_node):
        if isinstance(fn_expr, ast.Name):
            info = graph._resolve_local_name(mod, at_node, fn_expr.id)
            if info is not None:
                roots[id(info)] = info

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            chain = call_chain(node.func)
            if not chain:
                continue
            last = chain[-1]
            if last in _WRAPPERS:
                add(node.args[0] if node.args else keyword(node, "fun"),
                    node)
            elif last in _BODY_TAKERS:
                for pos in _BODY_TAKERS[last]:
                    if pos < len(node.args):
                        add(node.args[pos], node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                dchain = None
                if isinstance(dec, ast.Call):
                    dchain = call_chain(dec.func)
                    if dchain and dchain[-1] == "partial" and dec.args:
                        dchain = call_chain(dec.args[0])
                else:
                    dchain = call_chain(dec)
                if dchain and dchain[-1] in _WRAPPERS:
                    for info in mod.functions.values():
                        if info.node is node:
                            roots[id(info)] = info
    return list(roots.values())


def _taint_set(fn_node):
    """Names derived from the function's parameters, by one forward
    pass in statement order (loops are not iterated to fixpoint — the
    rebinding idiom ``x = f(x)`` keeps taint anyway)."""
    args = fn_node.args
    tainted = {a.arg for a in
               list(args.posonlyargs) + list(args.args) +
               list(args.kwonlyargs)}
    if args.vararg:
        tainted.add(args.vararg.arg)
    if args.kwarg:
        tainted.add(args.kwarg.arg)
    tainted.discard("self")

    def expr_tainted(expr):
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in tainted:
                return True
        return False

    for stmt in ast.walk(fn_node):
        if isinstance(stmt, ast.Assign) and expr_tainted(stmt.value):
            for t in stmt.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        tainted.add(n.id)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)) and \
                stmt.value is not None and expr_tainted(stmt.value):
            if isinstance(stmt.target, ast.Name):
                tainted.add(stmt.target.id)
    return tainted, expr_tainted


@register
class JitPurityRule(Rule):
    name = "jit-purity"
    severity = Severity.ERROR
    description = ("host sync (float/.item/np.asarray/"
                   ".block_until_ready), wall clock, or host RNG "
                   "inside a jitted/scan body — breaks dispatch "
                   "pipelining and bit-identical resume")

    def check_module(self, mod, project):
        graph = project.callgraph
        for root in jit_root_functions(mod, graph):
            yield from self._check_root(mod, root)

    def _check_root(self, mod, root):
        tainted, expr_tainted = _taint_set(root.node)
        scope = root.qualname
        for node in ast.walk(root.node):
            if not isinstance(node, ast.Call):
                continue
            chain = call_chain(node.func)
            if not chain:
                # computed call target; only flag method syncs below
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _SYNC_METHODS:
                    chain = ("?", node.func.attr)
                else:
                    continue
            last = chain[-1]
            msg = None
            if chain[0] == "time" and len(chain) == 2:
                msg = (f"wall clock '{'.'.join(chain)}' inside jitted "
                       f"code — nondeterministic across resume replay")
            elif chain[0] == "random" and len(chain) == 2:
                msg = (f"host RNG '{'.'.join(chain)}' inside jitted "
                       f"code — use jax.random with a threaded key")
            elif len(chain) >= 2 and chain[0] in _NUMPY_ROOTS and \
                    chain[1] == "random":
                msg = (f"host RNG '{'.'.join(chain)}' inside jitted "
                       f"code — use jax.random with a threaded key")
            elif len(chain) >= 2 and last in _SYNC_METHODS:
                # obj.item() / arr.block_until_ready(): sync when obj
                # is traced; 'items' (dict) is a different name
                base = node.func.value if isinstance(
                    node.func, ast.Attribute) else None
                if last == "block_until_ready" or (
                        base is not None and expr_tainted(base)):
                    msg = (f".{last}() on a traced value inside jitted "
                           f"code — forces a host sync")
            elif len(chain) == 2 and chain[0] in _NUMPY_ROOTS and \
                    last in ("asarray", "array"):
                if node.args and expr_tainted(node.args[0]):
                    msg = (f"'{'.'.join(chain)}' on a traced value "
                           f"inside jitted code — silently leaves the "
                           f"device")
            elif chain == ("float",) or chain == ("int",) or \
                    chain == ("bool",):
                if node.args and expr_tainted(node.args[0]):
                    msg = (f"'{last}()' on a traced value inside "
                           f"jitted code — forces a host sync (and "
                           f"fails under jit)")
            if msg is not None:
                yield self.finding(mod, node, msg, scope=scope)
