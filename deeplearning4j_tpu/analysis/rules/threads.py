"""thread-hygiene: explicit daemon=, stored threads get joined, and
every package thread is named.

Incidents: the PR-5/6 review-fix lists are a catalog of thread
lifecycle bugs (the batcher re-arming its own shutdown sentinel after
a timed-out join, the prefetcher producer leaking into the next fit,
supervisor watchdog shutdown races). Three cheap invariants prevent
the recurring half: (a) every ``threading.Thread`` states ``daemon=``
explicitly — an implicit non-daemon worker turns a crashed test into a
hung process; (b) a thread stored on ``self`` is joined somewhere in
its class (``close``/``stop``/``shutdown``/``retire``/``join`` path) —
otherwise shutdown is fire-and-forget and errors are never surfaced;
(c) every thread states ``name=`` (ISSUE 18: the
``dl4j:<subsystem>:<role>`` convention) — an unnamed ``Thread-N``
cannot be attributed by the continuous wall-clock profiler's
thread-name parse or by a native thread dump.
"""

from __future__ import annotations

import ast

from deeplearning4j_tpu.analysis.core import Rule, Severity, register
from deeplearning4j_tpu.analysis.model import call_chain, keyword


@register
class ThreadHygieneRule(Rule):
    name = "thread-hygiene"
    severity = Severity.WARN
    description = ("threading.Thread without explicit daemon=, a "
                   "self-stored thread never joined anywhere in its "
                   "class, or an unnamed package thread (profiler/"
                   "thread-dump attribution needs name=)")

    def check_module(self, mod, project):
        # class name -> set of attr names .join()ed anywhere in it;
        # local aliases count: `t = self._thread; t.join()` joins
        # _thread (the prefetcher's drain-then-join idiom)
        joined: dict = {}
        daemon_attr_set: dict = {}
        name_attr_set: dict = {}
        for info in mod.functions.values():
            cls = info.class_name
            if cls is None:
                continue
            aliases = self._self_attr_aliases(info.node)
            for chain, call in info.calls:
                if chain and chain[-1] == "join" and len(chain) >= 2:
                    name = chain[-2]
                    joined.setdefault(cls, set()).add(name)
                    for attr in aliases.get(name, ()):
                        joined[cls].add(attr)
        # `t.daemon = True` / `t.name = "..."` after construction also
        # satisfy (a) / (c)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            t.attr in ("daemon", "name"):
                        base = call_chain(t.value)
                        if base:
                            dest = (daemon_attr_set
                                    if t.attr == "daemon"
                                    else name_attr_set)
                            dest.setdefault(
                                mod.scope_name(node), set()).add(
                                    base[-1])

        for info in mod.functions.values():
            for chain, call in info.calls:
                if not chain or chain[-1] != "Thread":
                    continue
                if len(chain) == 2 and chain[0] not in ("threading",):
                    continue  # SomeClass.Thread / other libs
                yield from self._check_thread(mod, info, call, joined,
                                              daemon_attr_set,
                                              name_attr_set)

    def _check_thread(self, mod, info, call, joined, daemon_attr_set,
                      name_attr_set):
        stmt = self._enclosing_stmt(mod, call)
        target_names = self._assign_names(stmt)
        if keyword(call, "daemon") is None:
            set_later = daemon_attr_set.get(info.qualname, set())
            if not (target_names & set_later):
                yield self.finding(
                    mod, call,
                    "threading.Thread without explicit daemon= — an "
                    "implicit non-daemon worker hangs process exit on "
                    "a crash; state the lifecycle intent",
                    scope=info.qualname)
        # (c) unnamed package thread (ISSUE 18): samples and native
        # thread dumps see an anonymous Thread-N
        if keyword(call, "name") is None:
            named_later = name_attr_set.get(info.qualname, set())
            if not (target_names & named_later):
                yield self.finding(
                    mod, call,
                    "unnamed package thread — the continuous profiler "
                    "and native thread dumps cannot attribute an "
                    "anonymous Thread-N; pass "
                    "name='dl4j:<subsystem>:<role>'",
                    scope=info.qualname)
        # (b) stored on self and never joined in the class
        self_attrs = self._self_attrs(stmt)
        cls = info.class_name
        if cls is not None:
            cls_joined = joined.get(cls, set())
            for attr in self_attrs:
                if attr not in cls_joined:
                    yield self.finding(
                        mod, call,
                        f"thread stored as self.{attr} is never "
                        f".join()ed in class {cls} — shutdown is "
                        f"fire-and-forget and worker errors are never "
                        f"surfaced; join it in close()/stop()",
                        scope=info.qualname)

    def _enclosing_stmt(self, mod, node):
        cur = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = mod.parent.get(cur)
        return cur

    def _assign_names(self, stmt):
        names = set()
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        return names

    def _self_attr_aliases(self, fn_node):
        """{local_name: {self attrs it aliases}} from assignments like
        ``t = self._thread`` / ``t, q = self._thread, self._queue``."""
        aliases: dict = {}

        def pair(target, value):
            if isinstance(target, ast.Name) and \
                    isinstance(value, ast.Attribute) and \
                    isinstance(value.value, ast.Name) and \
                    value.value.id == "self":
                aliases.setdefault(target.id, set()).add(value.attr)

        for node in ast.walk(fn_node):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if isinstance(t, (ast.Tuple, ast.List)) and \
                        isinstance(node.value, (ast.Tuple, ast.List)) \
                        and len(t.elts) == len(node.value.elts):
                    for te, ve in zip(t.elts, node.value.elts):
                        pair(te, ve)
                else:
                    pair(t, node.value)
        return aliases

    def _self_attrs(self, stmt):
        attrs = set()
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    attrs.add(t.attr)
        return attrs
