"""telemetry-gate: ``telemetry.disable()`` must mean zero registry
calls.

Contract (PR 1, re-asserted every PR since): ``telemetry.disable()``
compiles observability OUT — the disabled step path performs *zero*
registry calls (tested with a counting stub in test_health.py). The
idiom is either the ``*_instruments()`` factories (which return None
when disabled, so the hot loop guards on the bundle) or an explicit
``if telemetry.enabled():`` before ``get_registry()``.

This rule flags a ``get_registry()`` call in a function (outside
``telemetry/`` itself and the analyzer) that contains no
``enabled()``/``enable()`` check — the class of drift that silently
re-introduces per-step registry overhead on the disabled path.
"""

from __future__ import annotations

from deeplearning4j_tpu.analysis.core import Rule, Severity, register

_GATES = {"enabled", "enable", "loop_instruments", "etl_instruments",
          "serving_instruments"}
_EXEMPT_PREFIXES = ("telemetry/", "analysis/")


@register
class TelemetryGateRule(Rule):
    name = "telemetry-gate"
    severity = Severity.ERROR
    description = ("get_registry() in a function with no enabled() "
                   "check — breaks the zero-registry-calls-when-"
                   "disabled contract (PR 1)")

    def check_module(self, mod, project):
        rel = mod.rel
        if any(p in rel for p in _EXEMPT_PREFIXES):
            return
        for info in mod.functions.values():
            gated = any(chain and chain[-1] in _GATES
                        for chain, _ in info.calls)
            if gated:
                continue
            for chain, call in info.calls:
                if chain and chain[-1] == "get_registry":
                    yield self.finding(
                        mod, call,
                        "get_registry() without an enabled() gate in "
                        "the same function — the disabled telemetry "
                        "path must make zero registry calls",
                        scope=info.qualname)
