"""telemetry-gate: ``telemetry.disable()`` must mean zero registry —
and zero tracer — calls.

Contract (PR 1, re-asserted every PR since; extended to the tracer in
ISSUE 10): ``telemetry.disable()`` compiles observability OUT — the
disabled step/request path performs *zero* registry calls (counting
stub in test_health.py) and *zero* tracer-object calls (counting stub
in test_tracing.py). The idiom is either the ``*_instruments()``
factories (None when disabled, so the hot loop guards on the bundle),
an explicit ``if telemetry.enabled():`` before ``get_registry()``, or
— for spans — the high-level ``tracing`` helpers (``start_trace`` /
``trace_or_span`` / ``span`` / ``emit`` / ``current``), which sample
and gate internally and hand back None/NULL contexts the hot path
guards on.

This rule flags a raw ``get_registry()``, ``get_tracer()``,
``get_memledger()`` (ISSUE 14: the HBM ownership ledger's raw handle),
``get_sampler()``, ``get_evaluator()`` (ISSUE 16: the time-series
sampler's and SLO evaluator's raw handles), or ``get_profiler()``
(ISSUE 18: the continuous wall-clock profiler's raw handle — the
sampler thread must not exist while disabled) call in a function
(outside
``telemetry/`` itself and the analyzer) that contains no
``enabled()``/sampler-gate check — the class of drift that silently
re-introduces per-step observability overhead on the disabled path.
"""

from __future__ import annotations

from deeplearning4j_tpu.analysis.core import Rule, Severity, register

# per-emitter gate sets: a tracing-helper call must NOT count as a
# gate for a raw registry emission (or vice versa) — "span" in
# particular also names telemetry.span, a pure TraceAnnotation that
# gates nothing, so it appears in neither set
_REGISTRY_GATES = {"enabled", "enable", "loop_instruments",
                   "etl_instruments", "serving_instruments",
                   # ISSUE 15: the fleet router's bundle factory gates
                   # internally (None when disabled) like the others
                   "fleet_instruments"}
_TRACER_GATES = {"enabled", "enable",
                 # tracer-side gates (ISSUE 10): each samples/gates
                 # internally and returns a None/NULL handle the
                 # caller guards on
                 "start_trace", "trace_or_span", "current",
                 "current_ids", "sample_interval"}
# memledger gates (ISSUE 14): `claim()`/`claim_for_owner()` gate
# internally (None when disabled — the registrars' idiom); the
# error/planner surfaces (raise_if_oom / oom_error / plan_capacity)
# are error-path or admission-time, never steady-state emission, so
# they gate too. NOT in the set: bare generic names like `release` —
# `lock.release()` is pervasive in this codebase and would silently
# un-flag real ungated emissions (gates match on the final call name)
_MEMLEDGER_GATES = {"enabled", "enable", "claim", "claim_for_owner",
                    "raise_if_oom", "oom_error", "plan_capacity",
                    "release_prefix"}
# time-series sampler gates (ISSUE 16): `sample_now()` gates
# internally (None + zero registry calls when disabled) and is the
# only registry-touching entry point; `configure`/`start`/`on_sample`
# are setup-time, never per-request emission. The read-only query
# surface (`describe`/`rate`/`quantile`) is deliberately NOT a gate:
# reads are free of registry calls, but a raw get_sampler() next to
# them in a hot path still deserves the enabled() idiom
_TIMESERIES_GATES = {"enabled", "enable", "sample_now", "configure",
                     "start", "on_sample"}
# SLO evaluator gates (ISSUE 16): `evaluate()` gates internally (None
# + zero registry/flight calls when disabled); `declare`/`remove` are
# setup-time; `slo_instruments` is the bundle factory (None when
# disabled) matching every other *_instruments
_SLO_GATES = {"enabled", "enable", "evaluate", "declare", "remove",
              "slo_instruments"}
# continuous-profiler gates (ISSUE 18): `sample_now()` gates
# internally (None + zero registry calls + zero frame walks when
# disabled), `start()` refuses to spawn the sampler thread while
# disabled, `configure`/`register_thread` are setup-time
_PROFILER_GATES = {"enabled", "enable", "configure", "start",
                   "sample_now", "register_thread"}
_EMITTER_GATES = {"get_registry": _REGISTRY_GATES,
                  "get_tracer": _TRACER_GATES,
                  "get_memledger": _MEMLEDGER_GATES,
                  "get_sampler": _TIMESERIES_GATES,
                  "get_evaluator": _SLO_GATES,
                  "get_profiler": _PROFILER_GATES}
_EXEMPT_PREFIXES = ("telemetry/", "analysis/")


@register
class TelemetryGateRule(Rule):
    name = "telemetry-gate"
    severity = Severity.ERROR
    description = ("get_registry()/get_tracer()/get_memledger()/"
                   "get_sampler()/get_evaluator()/get_profiler() in a "
                   "function with no enabled()/sampler gate — breaks "
                   "the zero-observability-calls-when-disabled "
                   "contract (PR 1, PR 10, PR 14, PR 16, PR 17)")

    def check_module(self, mod, project):
        rel = mod.rel
        if any(p in rel for p in _EXEMPT_PREFIXES):
            return
        for info in mod.functions.values():
            called = {chain[-1] for chain, _ in info.calls if chain}
            for chain, call in info.calls:
                emitter = chain[-1] if chain else None
                if emitter not in _EMITTER_GATES:
                    continue
                if called & _EMITTER_GATES[emitter]:
                    continue   # gated for THIS emitter kind
                yield self.finding(
                    mod, call,
                    f"{emitter}() without an enabled()/sampler "
                    "gate in the same function — the disabled "
                    "telemetry path must make zero registry and "
                    "zero tracer calls",
                    scope=info.qualname)
