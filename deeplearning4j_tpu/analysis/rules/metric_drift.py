"""metric-drift: every registered metric uses dl4j_ and is documented.

Origin: tools/check_metrics.py (PR 3 satellite), absorbed here as a
rule so the whole invariant set runs as ONE tier-1 analyzer pass. The
old CLI remains as a thin shim over this module. The contract is
unchanged: every literal ``.counter("...")`` / ``.gauge`` /
``.histogram`` registration must (a) use the ``dl4j_`` prefix and (b)
appear in docs/OBSERVABILITY.md — otherwise dashboards and alert rules
silently drift from the code (cross-link: docs/OBSERVABILITY.md
"Metric-name drift").
"""

from __future__ import annotations

import ast
import re

from deeplearning4j_tpu.analysis.core import Rule, Severity, register
from deeplearning4j_tpu.analysis.model import call_chain

_REGISTRATION_METHODS = {"counter", "gauge", "histogram"}


def registered_metrics(mod):
    """[(name, Call node)] for literal metric registrations."""
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = call_chain(node.func)
        if not chain or chain[-1] not in _REGISTRATION_METHODS:
            continue
        if len(chain) < 2:
            continue  # bare gauge(...): not a registry method call
        if node.args and isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            out.append((node.args[0].value, node))
    return out


def _name_problems(name, docs_text, where=None):
    """The two drift checks, shared by the rule and the shim so they
    cannot diverge. Whole-name docs match: plain substring would let
    ``dl4j_step`` hide behind a documented ``dl4j_step_seconds``."""
    loc = f" ({where})" if where else ""
    out = []
    if not name.startswith("dl4j_"):
        out.append(f"metric {name!r}{loc} does not use the dl4j_ "
                   f"prefix")
    if not re.search(re.escape(name) + r"(?![\w])", docs_text):
        out.append(f"metric {name!r}{loc} is not documented in "
                   f"docs/OBSERVABILITY.md")
    return out


def drift_problems(names, docs_text):
    """The shim-compatible pure check: {name: [files]} + docs text ->
    human-readable problem strings (the historical check_metrics.check
    contract, used by tools/check_metrics.py and test_health.py)."""
    problems = []
    for name, files in sorted(names.items()):
        problems.extend(_name_problems(
            name, docs_text, where=", ".join(sorted(set(files)))))
    return problems


def collect_metric_names(project) -> dict:
    """{metric_name: [files]} across the project (AST-based successor
    of the old regex scan)."""
    names: dict = {}
    for mod in project.modules:
        for name, _node in registered_metrics(mod):
            names.setdefault(name, []).append(mod.rel)
    return names


@register
class MetricDriftRule(Rule):
    name = "metric-drift"
    severity = Severity.ERROR
    description = ("registered metric name without the dl4j_ prefix or "
                   "missing from docs/OBSERVABILITY.md (absorbed "
                   "tools/check_metrics.py)")

    def check_module(self, mod, project):
        docs_text = project.config.get("docs_text", "")
        for name, node in registered_metrics(mod):
            for message in _name_problems(name, docs_text):
                yield self.finding(mod, node, message)
