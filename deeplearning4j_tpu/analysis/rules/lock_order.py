"""lock-order: the static lock-acquisition graph must be acyclic.

Incident class: 16 modules use locks (serving batcher/registry/
session, resilience writer condvars, telemetry registry/health/flight,
datasets transform) and the PR-6 review found a queued-barrier
deadlock in exactly this shape — two subsystems each holding their own
lock while calling into the other. A cycle in the *static* acquisition
graph (lock A held while a path acquires B, and elsewhere B held while
a path acquires A) is a deadlock waiting for the right interleaving.

Lock identity is per declaration site: ``self._x = threading.Lock()``
in class C of module M -> ``M.C._x``; module-level ``_lock =
threading.Lock()`` -> ``M._lock``. ``Condition`` counts (it owns a
lock). Edges come from (a) lexical nesting of ``with`` blocks and (b)
calls made while holding a lock, resolved through the project call
graph to the callee's transitively-acquired locks. Non-reentrant
``Lock`` re-acquired on a path from its own holder is flagged too
(self-deadlock, no interleaving needed).

The runtime half is analysis/witness.py: an instrumented Lock wrapper
(activated by the lock_witness fixture under the slow multi-thread
tests) that records ACTUAL acquisition orders and fails on inversion —
catching orders the static over/under-approximation cannot see.
"""

from __future__ import annotations

import ast

from deeplearning4j_tpu.analysis.core import Rule, Severity, register
from deeplearning4j_tpu.analysis.model import call_chain

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "rlock",
               "Semaphore": "lock", "BoundedSemaphore": "lock"}


def declared_locks(mod):
    """{(class_or_None, attr_or_name): (lock_id, kind)} for every
    ``threading.Lock()``-style declaration in the module."""
    out = {}
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Assign) and
                isinstance(node.value, ast.Call)):
            continue
        chain = call_chain(node.value.func)
        if not chain or chain[-1] not in _LOCK_CTORS:
            continue
        if len(chain) >= 2 and chain[-2] not in ("threading",
                                                 "_thread"):
            continue
        kind = _LOCK_CTORS[chain[-1]]
        info = mod.enclosing_function(node)
        cls = info.class_name if info is not None else None
        for t in node.targets:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and \
                    t.value.id in ("self", "cls"):
                lock_id = f"{mod.modname}.{cls}.{t.attr}" if cls \
                    else f"{mod.modname}.{t.attr}"
                out[(cls, t.attr)] = (lock_id, kind)
            elif isinstance(t, ast.Name) and info is None:
                out[(None, t.id)] = (f"{mod.modname}.{t.id}", kind)
    return out


def _lock_ref(mod, locks, info, expr):
    """Resolve a with-context / .acquire() receiver expression to a
    declared lock id, else None."""
    chain = call_chain(expr)
    if not chain:
        return None
    cls = info.class_name if info is not None else None
    if len(chain) == 2 and chain[0] in ("self", "cls"):
        hit = locks.get((cls, chain[1]))
        return hit
    if len(chain) == 1:
        return locks.get((None, chain[0]))
    return None


class _FnLocks:
    """Per-function lock facts: ordered (held_set, acquired_lock,
    node) events from lexical with-nesting, plus calls made while
    holding locks."""

    def __init__(self):
        self.acquires = []     # (frozenset(held), lock_id, kind, node)
        self.calls_holding = []  # (frozenset(held), chain, call node)
        self.all_acquired = set()


def _scan_function(mod, locks, info):
    facts = _FnLocks()

    def ref_of(expr):
        hit = _lock_ref(mod, locks, info, expr)
        return hit

    def visit(node, held):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            new_held = held
            if isinstance(child, ast.With):
                acquired_here = []
                for item in child.items:
                    hit = ref_of(item.context_expr)
                    if hit is not None:
                        lock_id, kind = hit
                        facts.acquires.append(
                            (frozenset(held + acquired_here), lock_id,
                             kind, child))
                        acquired_here.append(lock_id)
                        facts.all_acquired.add(lock_id)
                new_held = held + acquired_here
            elif isinstance(child, ast.Call):
                chain = call_chain(child.func)
                if chain and chain[-1] == "acquire" and len(chain) >= 2:
                    hit = ref_of(child.func.value)
                    if hit is not None:
                        lock_id, kind = hit
                        facts.acquires.append(
                            (frozenset(held), lock_id, kind, child))
                        facts.all_acquired.add(lock_id)
                        # conservatively: held for the rest of the fn
                        held = held + [lock_id]
                        new_held = held
                elif chain and held:
                    facts.calls_holding.append(
                        (frozenset(held), chain, child))
            visit(child, new_held)

    visit(info.node, [])
    return facts


@register
class LockOrderRule(Rule):
    name = "lock-order"
    severity = Severity.ERROR
    description = ("cycle in the static lock-acquisition graph (lock A "
                   "held while acquiring B, and elsewhere B while A) — "
                   "a deadlock awaiting the right interleaving; or a "
                   "non-reentrant Lock re-acquired under itself")

    def check_project(self, project):
        graph = project.callgraph
        mod_locks = {m.rel: declared_locks(m) for m in project.modules}
        facts = {}
        for mod in project.modules:
            for info in mod.functions.values():
                facts[id(info)] = _scan_function(
                    mod, mod_locks[mod.rel], info)

        # transitive lock set per function (fixpoint over call graph)
        trans = {k: set(f.all_acquired) for k, f in facts.items()}
        infos = {id(info): info
                 for m in project.modules
                 for info in m.functions.values()}
        for _ in range(12):
            changed = False
            for key, info in infos.items():
                cur = trans[key]
                before = len(cur)
                for callee in graph.callees(info):
                    cur |= trans.get(id(callee), set())
                if len(cur) != before:
                    changed = True
            if not changed:
                break

        # edges: held -> acquired (direct + via calls)
        edges: dict = {}   # (a, b) -> (module, node, via)
        kinds: dict = {}

        def add_edge(a, b, mod, node, via):
            edges.setdefault((a, b), (mod, node, via))

        for mod in project.modules:
            for info in mod.functions.values():
                f = facts[id(info)]
                for held, lock_id, kind, node in f.acquires:
                    kinds[lock_id] = kind
                    for h in held:
                        add_edge(h, lock_id, mod, node,
                                 info.qualname)
                for held, chain, call in f.calls_holding:
                    callee = graph.resolve_call(mod, info, chain, call)
                    if callee is None:
                        continue
                    for b in trans.get(id(callee), ()):
                        for h in held:
                            add_edge(
                                h, b, mod, call,
                                f"{info.qualname} -> "
                                f"{callee.qualname}")

        yield from self._report(edges, kinds, project)

    def _report(self, edges, kinds, project):
        # self-deadlock: non-reentrant lock under itself
        reported = set()
        adj: dict = {}
        for (a, b), (mod, node, via) in edges.items():
            if a == b:
                if kinds.get(a) == "lock" and a not in reported:
                    reported.add(a)
                    yield self.finding(
                        mod, node,
                        f"non-reentrant lock '{a}' can be re-acquired "
                        f"while already held (via {via}) — "
                        f"self-deadlock, no interleaving needed",
                        )
                continue
            adj.setdefault(a, []).append(b)

        # inversion pairs (2-cycles) and longer cycles via DFS
        seen_pairs = set()
        for (a, b) in list(edges):
            if a == b or (b, a) not in edges:
                continue
            pair = tuple(sorted((a, b)))
            if pair in seen_pairs:
                continue
            seen_pairs.add(pair)
            mod, node, via = edges[(a, b)]
            _, _, via2 = edges[(b, a)]
            yield self.finding(
                mod, node,
                f"lock-order inversion: '{a}' held while acquiring "
                f"'{b}' (via {via}) but elsewhere '{b}' is held while "
                f"acquiring '{a}' (via {via2}) — deadlock under the "
                f"right interleaving")

        # longer cycles (3+) not already covered by a 2-cycle pair
        for cycle in self._cycles(adj):
            if len(cycle) < 3:
                continue
            if any(tuple(sorted((cycle[i], cycle[(i + 1) % len(cycle)])))
                   in seen_pairs for i in range(len(cycle))):
                continue
            a, b = cycle[0], cycle[1]
            mod, node, via = edges[(a, b)]
            yield self.finding(
                mod, node,
                f"lock-order cycle: {' -> '.join(cycle + [cycle[0]])} "
                f"— deadlock under the right interleaving")

    def _cycles(self, adj, limit=20):
        """Bounded simple-cycle enumeration (Johnson-lite DFS)."""
        out = []
        nodes = sorted(adj)
        for start in nodes:
            stack = [(start, [start])]
            while stack and len(out) < limit:
                cur, path = stack.pop()
                for nxt in adj.get(cur, ()):
                    if nxt == start and len(path) > 1:
                        out.append(path[:])
                    elif nxt not in path and nxt > start and \
                            len(path) < 6:
                        stack.append((nxt, path + [nxt]))
        return out
