"""collective-thread: no collectives reachable from background threads.

Incident (PR 5, async_ckpt.py): the async checkpoint writer originally
issued a multi-host barrier from its background thread; gloo serializes
collective context initialization, so the barrier interleaved with the
training step's in-step psums and deadlocked the pod — "found the hard
way" per the module docstring. The invariant since: background threads
(``threading.Thread`` targets, ``concurrent.futures`` submissions)
must never reach ``psum``/``pmean``/``all_gather``/barrier-class
primitives; multi-host agreement happens at *read* time
(``latest_agreed``) instead.

Detection: build the project call graph, then BFS from every thread
entry point to any function whose body directly invokes a collective.
A jitted alias (``self._fn = jax.jit(step)``) counts as calling
``step`` — the collective executes at call time of the compiled fn.
"""

from __future__ import annotations

import ast

from deeplearning4j_tpu.analysis.core import Rule, Severity, register
from deeplearning4j_tpu.analysis.model import call_chain, keyword

COLLECTIVE_NAMES = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "psum_scatter", "sync_global_devices",
    "process_allgather", "broadcast_one_to_all",
}
# bare "barrier" is too generic for attribute calls in general, but a
# *distributed* barrier is exactly the PR-5 deadlock — match it only
# when the chain mentions a distributed-ish root
_BARRIER_ROOTS = {"multihost_utils", "distributed", "dist", "mesh"}


def _is_collective(chain) -> bool:
    if not chain:
        return False
    last = chain[-1]
    if last in COLLECTIVE_NAMES:
        return True
    if last == "barrier" and any(p in _BARRIER_ROOTS for p in chain):
        return True
    return False


def thread_entries(mod, graph):
    """[(Call node creating the thread/submission, entry FunctionInfo)]
    for ``threading.Thread(target=...)`` and ``<executor>.submit(f)``."""
    out = []
    for info in mod.functions.values():
        for chain, call in info.calls:
            if not chain:
                continue
            target = None
            if chain[-1] == "Thread":
                target = keyword(call, "target")
            elif chain[-1] == "submit" and len(chain) >= 2 and call.args:
                # Queue.put etc. don't use .submit; executors do
                target = call.args[0]
            if target is None:
                continue
            tchain = call_chain(target) if not isinstance(
                target, ast.Lambda) else None
            entry = None
            if tchain:
                entry = graph.resolve_call(mod, info, tchain, call)
            elif isinstance(target, ast.Lambda):
                continue  # lambdas: no body-level resolution; skip
            if entry is not None:
                out.append((call, entry))
    return out


def _directly_collective(info):
    for chain, _call in info.calls:
        if _is_collective(chain):
            return True
    return False


@register
class CollectiveThreadRule(Rule):
    name = "collective-thread"
    severity = Severity.ERROR
    description = ("collective primitives (psum/pmean/all_gather/"
                   "barrier) reachable from a background thread target "
                   "or executor submission — the PR-5 gloo deadlock "
                   "class")

    def check_project(self, project):
        graph = project.callgraph
        for mod in project.modules:
            for call, entry in thread_entries(mod, graph):
                path = graph.find_path(entry, _directly_collective)
                if path is None:
                    continue
                names = " -> ".join(p.qualname for p in path)
                yield self.finding(
                    mod, call,
                    f"background thread entry '{entry.qualname}' "
                    f"reaches a collective: {names}; collectives from "
                    f"background threads deadlock gloo context init "
                    f"(PR-5 async-writer incident)")
