"""route-drift: every /debug/* and /serving/* HTTP route is documented.

Origin (ISSUE 11 satellite): the metric-drift rule keeps dashboards
honest, but the debug/serving ROUTE surface had no equivalent — PR 9
added ``/debug/traces`` and PR 11 adds ``/debug/compiles`` +
``/debug/hlo/<key>``, and an undocumented route is an endpoint
operators cannot find during an incident. This rule finds every route
literal the UI server's handlers actually dispatch on (string
constants compared against ``self.path`` or passed to a
``path.startswith(...)`` check) and requires each ``/debug/...`` /
``/serving/...`` route to appear in docs/OBSERVABILITY.md or
docs/SERVING.md (cross-link: docs/OBSERVABILITY.md "Route drift").

ISSUE 18 extends the surface in two ways: the bare ``/debug`` index
route counts as a route (operators' route discovery endpoint — it
must be documented like everything it lists), and in a module that
defines a ``*DEBUG_ROUTES`` index table, every dispatched ``/debug``
route must appear in that table — a handler added without an index
entry is invisible to the one endpoint built to make routes findable.
"""

from __future__ import annotations

import ast
import re

from deeplearning4j_tpu.analysis.core import Rule, Severity, register
from deeplearning4j_tpu.analysis.model import call_chain

_ROUTE_RE = re.compile(r"^/(debug|serving)(/|$|\?)")


def _mentions_path(node) -> bool:
    """Does this expression reference something called ``path``
    (``self.path``, ``self.path.rstrip(...)``, a bare ``path`` arg)?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "path":
            return True
        if isinstance(sub, ast.Name) and sub.id == "path":
            return True
    return False


def dispatched_routes(mod):
    """[(route, node)] for literal routes the module dispatches on:
    ``<path expr> == "/route"`` comparisons and
    ``<path expr>.startswith("/route")`` calls."""
    out = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Compare):
            parts = [node.left] + list(node.comparators)
            if not any(_mentions_path(p) for p in parts):
                continue
            for p in parts:
                if isinstance(p, ast.Constant) and \
                        isinstance(p.value, str) and \
                        _ROUTE_RE.match(p.value):
                    out.append((p.value, p))
        elif isinstance(node, ast.Call):
            chain = call_chain(node.func)
            if not chain or chain[-1] != "startswith" or \
                    not _mentions_path(node.func):
                continue
            for arg in node.args:
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str) and \
                        _ROUTE_RE.match(arg.value):
                    out.append((arg.value, arg))
    return out


def index_routes(mod):
    """Route strings listed in the module's ``*DEBUG_ROUTES`` index
    table(s) (the GET /debug payload), or None when the module defines
    no index table."""
    found = None
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign):
            continue
        targets = [t.id for t in node.targets
                   if isinstance(t, ast.Name)]
        if not any(t.endswith("DEBUG_ROUTES") for t in targets):
            continue
        found = set() if found is None else found
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Constant) and \
                    isinstance(sub.value, str) and \
                    sub.value.startswith("/"):
                found.add(sub.value)
    return found


@register
class RouteDriftRule(Rule):
    name = "route-drift"
    severity = Severity.ERROR
    description = ("/debug/* or /serving/* route dispatched by an HTTP "
                   "handler but missing from docs/OBSERVABILITY.md and "
                   "docs/SERVING.md (ISSUE 11 satellite)")

    def check_module(self, mod, project):
        docs = (project.config.get("docs_text", "") + "\n"
                + project.config.get("serving_docs_text", ""))
        index = index_routes(mod)
        for route, node in dispatched_routes(mod):
            base = route.rstrip("?").rstrip("/") or route
            # substring match: "/debug/hlo/" is documented as
            # "/debug/hlo/<key>", query-string variants as their base
            if route not in docs and base not in docs:
                yield self.finding(
                    mod, node,
                    f"route {route!r} is dispatched here but "
                    f"documented in neither docs/OBSERVABILITY.md nor "
                    f"docs/SERVING.md")
            # index coverage (ISSUE 18): a module with a /debug index
            # table must list every /debug route it dispatches
            if index is None or not base.startswith("/debug"):
                continue
            # an entry covers a dispatch literal when they normalize
            # to the same route: "<key>"-style placeholders and
            # trailing slashes stripped ("/debug/hlo/<key>" covers the
            # "/debug/hlo/" startswith dispatch) — deliberately exact
            # beyond that, so the bare "/debug" index entry cannot
            # blanket-cover every /debug/* route
            if not any(entry.split("<")[0].rstrip("/") == base
                       for entry in index):
                yield self.finding(
                    mod, node,
                    f"route {route!r} is dispatched here but missing "
                    f"from this module's DEBUG_ROUTES index table "
                    f"(GET /debug would not list it)")
