"""Runtime lock witness: the dynamic half of the lock-order rule.

The static rule (rules/lock_order.py) sees the graph it can resolve;
this wrapper sees the orders that ACTUALLY happen. A ``WitnessLock``
records, per thread, the stack of witnessed locks held; acquiring B
while holding A registers the edge A->B with a code location. If the
reverse edge B->A was ever witnessed, that is an inversion — two
threads running those two paths concurrently can deadlock — and the
witness records it (or raises in ``strict`` mode).

Activation: ``install()`` monkeypatches ``threading.Lock`` /
``threading.RLock`` with factories that return witnessed locks ONLY
when constructed from code under this package (caller-frame check) —
stdlib internals (queue.Queue, Condition's inner lock) keep real
locks. The ``lock_witness`` pytest fixture (tests/conftest.py)
installs it for every ``slow``-marked test and fails the test on any
recorded inversion.
"""

from __future__ import annotations

import os
import sys
import threading

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the genuine constructors, captured before any install() patches the
# threading module — WitnessLock's own inner lock and the witness's
# graph lock must never route back through the factory
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock


def _caller_site(root: str | None = None) -> str:
    """Nearest frame OUTSIDE this module. Call depth varies between
    .acquire() and the with-statement path, and lock construction may
    go through install()'s factories (also in this file) — walking past
    every witness.py frame lands on the real user site either way."""
    f = sys._getframe(1)
    here = os.path.abspath(__file__)
    while f is not None and \
            os.path.abspath(f.f_code.co_filename) == here:
        f = f.f_back
    if f is None:
        return "<unknown>"
    fname = os.path.abspath(f.f_code.co_filename)
    root = root or _PKG_DIR
    if fname.startswith(root + os.sep):
        # full package-relative path: names are lock CLASSES, so any
        # aliasing silences edges between the aliased locks (the
        # same-class skip in note_acquire). One parent dir is not
        # enough — serving/api/handlers.py and clustering/api/
        # handlers.py would collapse.
        label = os.path.relpath(fname, root)
    else:
        # outside the witnessed tree (tests, scripts): keep the parent
        # dir so ui/server.py and clustering/server.py stay distinct
        label = os.path.join(os.path.basename(os.path.dirname(fname)),
                             os.path.basename(fname))
    return f"{label}:{f.f_lineno}"


class Inversion:
    __slots__ = ("first", "second")

    def __init__(self, first, second):
        self.first = first    # (a, b, site) edge seen earlier
        self.second = second  # (b, a, site) edge that inverted it

    def render(self) -> str:
        (a, b, s1), (b2, a2, s2) = self.first, self.second
        return (f"lock-order inversion: {a} -> {b} at {s1} vs "
                f"{b2} -> {a2} at {s2}")


class LockOrderViolation(RuntimeError):
    pass


class LockWitness:
    """Shared recorder: the order graph + inversions.

    Identity is two-level, lockdep-style: the per-thread held stack
    tracks lock OBJECTS (so only re-acquiring the same RLock counts as
    re-entry), while the order graph is keyed by NAME — the lock's
    lockdep-style class: the explicit name, or the construction site
    for auto-named locks. Class keying catches an A-class/B-class
    inversion even when threads touch different instances, and keeps
    the graph bounded by the number of construction sites when code
    churns fresh locks in a loop (a per-instance key would grow
    order/inversions forever there). Known blind spot, same as
    lockdep's: edges between two instances of ONE class are never
    recorded, so an AB/BA inversion between two locks minted at the
    same site goes unseen — the alternative would false-positive on
    legal hierarchical same-class nesting (shard locks taken in index
    order)."""

    def __init__(self, strict=False, pkg_root=None):
        self.strict = strict
        # root that site labels are made relative to; install() points
        # it at the patched package_dir so auto-names never alias
        self.pkg_root = os.path.abspath(pkg_root or _PKG_DIR)
        self._graph_lock = _REAL_LOCK()  # guards order/inversions
        self.order: dict = {}        # (a, b) -> first-seen site str
        self.inversions: list = []
        self._inv_seen: set = set()  # (a, b) pairs already reported
        self._tls = threading.local()

    # -- per-thread held stack ----------------------------------------------
    def _held(self):
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def note_acquire(self, lock):
        name = lock.name
        held = self._held()
        if any(h is lock for h in held):  # RLock re-entry: no new edges
            held.append(lock)
            return
        if held:  # frame walk + graph lock only when edges can form
            site = _caller_site(self.pkg_root)
            with self._graph_lock:
                for prev in {h.name for h in held}:
                    if prev == name:
                        # sibling instance of the same lock class: a
                        # self-edge would flag every nested same-site
                        # pair, and hierarchical same-class nesting is
                        # legal
                        continue
                    edge = (prev, name)
                    if edge not in self.order:
                        self.order[edge] = site
                    rev = (name, prev)
                    if rev in self.order:
                        inv = Inversion((name, prev, self.order[rev]),
                                        (prev, name, site))
                        # record each inverted pair once, UNORDERED key
                        # (both directions are the same defect) — a soak
                        # loop hitting the same inversion 10k times must
                        # not grow the report unboundedly
                        pair = (prev, name) if prev < name else (name, prev)
                        if pair not in self._inv_seen:
                            self._inv_seen.add(pair)
                            self.inversions.append(inv)
                        if self.strict:
                            held.append(lock)  # keep the stack truthful
                            raise LockOrderViolation(inv.render())
        held.append(lock)

    def note_release(self, lock):
        held = self._held()
        # remove the most recent acquisition of this lock object
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                break

    def format_inversions(self) -> str:
        return "\n".join(i.render() for i in self.inversions)


class WitnessLock:
    """Drop-in for threading.Lock/RLock that reports to a witness."""

    def __init__(self, witness, name=None, reentrant=False):
        self._witness = witness
        self._inner = _REAL_RLOCK() if reentrant else _REAL_LOCK()
        if name is None:
            # frame-walk, not _getframe(1): when built via install()'s
            # factories the immediate caller is the factory itself and
            # every lock would share one name, silencing all edges
            name = _caller_site(witness.pkg_root)
        # lockdep-style class: explicit name, or construction site
        self.name = name

    def acquire(self, blocking=True, timeout=-1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            try:
                self._witness.note_acquire(self)
            except BaseException:
                # strict-mode LockOrderViolation: the raise must not
                # leave the inner lock held (the caller's with-block
                # never runs, so release would never come)
                self._witness.note_release(self)
                self._inner.release()
                raise
        return got

    def release(self):
        self._witness.note_release(self)
        self._inner.release()

    def locked(self):
        inner_locked = getattr(self._inner, "locked", None)
        if inner_locked is not None:
            return inner_locked()
        # RLock grows .locked() only in Python 3.12 — probe with a
        # non-blocking acquire (held-by-self reports unlocked, matching
        # RLock's reacquirability)
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition(lock) compatibility
    def _at_fork_reinit(self):
        self._inner._at_fork_reinit()


_installed = None  # (witness, real_Lock, real_RLock)


def install(strict=False, package_dir=None) -> LockWitness:
    """Patch threading.Lock/RLock so locks constructed from code under
    ``package_dir`` (default: this package) are witnessed. Returns the
    witness; call uninstall() to restore."""
    global _installed
    if _installed is not None:
        raise RuntimeError("lock witness already installed")
    pkg = os.path.abspath(package_dir or _PKG_DIR)
    here = os.path.abspath(__file__)
    witness = LockWitness(strict=strict, pkg_root=pkg)
    real_lock, real_rlock = threading.Lock, threading.RLock

    def _from_pkg() -> bool:
        f = sys._getframe(2)
        fname = os.path.abspath(f.f_code.co_filename)
        # os.sep-anchored, matching _caller_site's relpath check: a bare
        # prefix would witness a sibling dir like <pkg>_extras but label
        # its locks with the out-of-tree scheme, re-opening aliasing
        return fname.startswith(pkg + os.sep) and fname != here

    def lock_factory():
        if _from_pkg():
            return WitnessLock(witness, reentrant=False)
        return real_lock()

    def rlock_factory():
        if _from_pkg():
            return WitnessLock(witness, reentrant=True)
        return real_rlock()

    threading.Lock = lock_factory
    threading.RLock = rlock_factory
    _installed = (witness, real_lock, real_rlock)
    return witness


def uninstall():
    global _installed
    if _installed is None:
        return None
    witness, real_lock, real_rlock = _installed
    threading.Lock = real_lock
    threading.RLock = real_rlock
    _installed = None
    return witness
