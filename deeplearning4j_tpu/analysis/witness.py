"""Runtime lock witness: the dynamic half of the lock-order rule.

The static rule (rules/lock_order.py) sees the graph it can resolve;
this wrapper sees the orders that ACTUALLY happen. A ``WitnessLock``
records, per thread, the stack of witnessed locks held; acquiring B
while holding A registers the edge A->B with a code location. If the
reverse edge B->A was ever witnessed, that is an inversion — two
threads running those two paths concurrently can deadlock — and the
witness records it (or raises in ``strict`` mode).

Activation: ``install()`` monkeypatches ``threading.Lock`` /
``threading.RLock`` with factories that return witnessed locks ONLY
when constructed from code under this package (caller-frame check) —
stdlib internals (queue.Queue, Condition's inner lock) keep real
locks. The ``lock_witness`` pytest fixture (tests/conftest.py)
installs it for every ``slow``-marked test and fails the test on any
recorded inversion.
"""

from __future__ import annotations

import os
import sys
import threading

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the genuine constructors, captured before any install() patches the
# threading module — WitnessLock's own inner lock and the witness's
# graph lock must never route back through the factory
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock


class Inversion:
    __slots__ = ("first", "second")

    def __init__(self, first, second):
        self.first = first    # (a, b, site) edge seen earlier
        self.second = second  # (b, a, site) edge that inverted it

    def render(self) -> str:
        (a, b, s1), (b2, a2, s2) = self.first, self.second
        return (f"lock-order inversion: {a} -> {b} at {s1} vs "
                f"{b2} -> {a2} at {s2}")


class LockOrderViolation(RuntimeError):
    pass


class LockWitness:
    """Shared recorder: the order graph + inversions."""

    def __init__(self, strict=False):
        self.strict = strict
        self._graph_lock = _REAL_LOCK()  # guards order/inversions
        self.order: dict = {}        # (a, b) -> first-seen site str
        self.inversions: list = []
        self._tls = threading.local()

    # -- per-thread held stack ----------------------------------------------
    def _held(self):
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def note_acquire(self, name):
        site = self._caller_site()
        held = self._held()
        if name in held:        # RLock re-entry: no new edges
            held.append(name)
            return
        with self._graph_lock:
            for prev in set(held):
                edge = (prev, name)
                if edge not in self.order:
                    self.order[edge] = site
                rev = (name, prev)
                if rev in self.order:
                    inv = Inversion((name, prev, self.order[rev]),
                                    (prev, name, site))
                    self.inversions.append(inv)
                    if self.strict:
                        held.append(name)  # keep the stack truthful
                        raise LockOrderViolation(inv.render())
        held.append(name)

    def note_release(self, name):
        held = self._held()
        if name in held:
            # remove the most recent acquisition of this lock
            for i in range(len(held) - 1, -1, -1):
                if held[i] == name:
                    del held[i]
                    break

    @staticmethod
    def _caller_site() -> str:
        # nearest frame outside this module (call depth varies between
        # .acquire() and the with-statement __enter__ path)
        f = sys._getframe(1)
        here = os.path.abspath(__file__)
        while f is not None and \
                os.path.abspath(f.f_code.co_filename) == here:
            f = f.f_back
        if f is None:
            return "<unknown>"
        return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"

    def format_inversions(self) -> str:
        return "\n".join(i.render() for i in self.inversions)


class WitnessLock:
    """Drop-in for threading.Lock/RLock that reports to a witness."""

    def __init__(self, witness, name=None, reentrant=False):
        self._witness = witness
        self._inner = _REAL_RLOCK() if reentrant else _REAL_LOCK()
        if name is None:
            f = sys._getframe(1)
            name = (f"{os.path.basename(f.f_code.co_filename)}:"
                    f"{f.f_lineno}")
        self.name = name

    def acquire(self, blocking=True, timeout=-1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            try:
                self._witness.note_acquire(self.name)
            except BaseException:
                # strict-mode LockOrderViolation: the raise must not
                # leave the inner lock held (the caller's with-block
                # never runs, so release would never come)
                self._witness.note_release(self.name)
                self._inner.release()
                raise
        return got

    def release(self):
        self._witness.note_release(self.name)
        self._inner.release()

    def locked(self):
        inner_locked = getattr(self._inner, "locked", None)
        if inner_locked is not None:
            return inner_locked()
        # RLock grows .locked() only in Python 3.12 — probe with a
        # non-blocking acquire (held-by-self reports unlocked, matching
        # RLock's reacquirability)
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition(lock) compatibility
    def _at_fork_reinit(self):
        self._inner._at_fork_reinit()


_installed = None  # (witness, real_Lock, real_RLock)


def install(strict=False, package_dir=None) -> LockWitness:
    """Patch threading.Lock/RLock so locks constructed from code under
    ``package_dir`` (default: this package) are witnessed. Returns the
    witness; call uninstall() to restore."""
    global _installed
    if _installed is not None:
        raise RuntimeError("lock witness already installed")
    pkg = os.path.abspath(package_dir or _PKG_DIR)
    here = os.path.abspath(__file__)
    witness = LockWitness(strict=strict)
    real_lock, real_rlock = threading.Lock, threading.RLock

    def _from_pkg() -> bool:
        f = sys._getframe(2)
        fname = os.path.abspath(f.f_code.co_filename)
        return fname.startswith(pkg) and fname != here

    def lock_factory():
        if _from_pkg():
            return WitnessLock(witness, reentrant=False)
        return real_lock()

    def rlock_factory():
        if _from_pkg():
            return WitnessLock(witness, reentrant=True)
        return real_rlock()

    threading.Lock = lock_factory
    threading.RLock = rlock_factory
    _installed = (witness, real_lock, real_rlock)
    return witness


def uninstall():
    global _installed
    if _installed is None:
        return None
    witness, real_lock, real_rlock = _installed
    threading.Lock = real_lock
    threading.RLock = real_rlock
    _installed = None
    return witness
