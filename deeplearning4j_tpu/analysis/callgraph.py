"""dl4jlint project call graph (conservative, name-resolution based).

Resolution is deliberately narrow — a wrong edge in the collective or
lock-order rule becomes a false ERROR, so we only resolve what we can
justify:

  f(...)            -> enclosing scopes, then module top level, then an
                       explicit ``from X import f`` of a project module
  self.m(...)       -> method m of the lexically enclosing class
  cls.m/Class.m(...)-> method m of that class when defined in-project
  mod.f(...)        -> top-level f of the project module imported as mod
  self._fn(...)     -> where ``self._fn = jax.jit(step, ...)`` (or
                       shard_map) was recorded in the same class, the
                       edge goes to ``step`` — a jitted alias executes
                       the wrapped body at *call* time, which is exactly
                       what the collective rule must see.

Unresolvable calls (stdlib, dynamic dispatch) produce no edge.
"""

from __future__ import annotations

import ast

from deeplearning4j_tpu.analysis.model import call_chain, keyword

JIT_WRAPPERS = {"jit", "pjit", "shard_map", "pmap"}


def _flat_targets(stmt):
    """Assignment target expressions, tuples flattened."""
    targets = []
    if isinstance(stmt, ast.Assign):
        raw = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        raw = [stmt.target]
    else:
        return targets
    stack = list(raw)
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        else:
            targets.append(t)
    return targets


def wrapped_function(call):
    """For ``jax.jit(f, ...)`` / ``shard_map(f, ...)`` return the name
    node of f (first positional or fun=), else None."""
    chain = call_chain(call.func)
    if not chain or chain[-1] not in JIT_WRAPPERS:
        return None
    fn = call.args[0] if call.args else keyword(call, "fun")
    return fn


class CallGraph:
    def __init__(self, project):
        self.project = project
        # (module.rel, qualname) -> FunctionInfo
        self.functions = {}
        for mod in project.modules:
            for info in mod.functions.values():
                self.functions[(mod.rel, info.qualname)] = info
        # per module: local alias -> target FunctionInfo for jitted
        # assignments (name or self-attr), e.g. "_step_fn" -> step
        self.jit_aliases = {}
        for mod in project.modules:
            self.jit_aliases[mod.rel] = self._jit_aliases(mod)
        # edges: FunctionInfo id -> [FunctionInfo]
        self._edges = {}

    # -- jitted alias table --------------------------------------------------
    def _jit_aliases(self, mod) -> dict:
        aliases = {}
        builders = self._jit_builders(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            fn = wrapped_function(node.value)
            target_fn = None
            if fn is not None and isinstance(fn, ast.Name):
                target_fn = self._resolve_local_name(mod, node.value,
                                                     fn.id)
            elif fn is None:
                # `self._fit = self._make_step()` builder idiom: route
                # the alias to the builder — its body contains the
                # jitted step (callees() follows the jit wrapper), so
                # reachability through the stored executable is kept
                chain = call_chain(node.value.func)
                if chain and chain[-1] in builders:
                    target_fn = builders[chain[-1]]
            if target_fn is None:
                continue
            for t in _flat_targets(node):
                if isinstance(t, ast.Name):
                    aliases[t.id] = target_fn
                elif isinstance(t, ast.Attribute):
                    aliases[t.attr] = target_fn
                elif isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Attribute):
                    aliases[t.value.attr] = target_fn
        return aliases

    def _jit_builders(self, mod) -> dict:
        """{short name: FunctionInfo} for functions returning a jit
        wrapper call (directly or via a local bound to one)."""
        out = {}
        for info in mod.functions.values():
            local_jits = set()
            returns_jit = False
            for node in ast.walk(info.node):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and \
                        wrapped_function(node.value) is not None:
                    for t in _flat_targets(node):
                        if isinstance(t, ast.Name):
                            local_jits.add(t.id)
                elif isinstance(node, ast.Return) and \
                        node.value is not None:
                    if isinstance(node.value, ast.Call) and \
                            wrapped_function(node.value) is not None:
                        returns_jit = True
                    elif isinstance(node.value, ast.Name) and \
                            node.value.id in local_jits:
                        returns_jit = True
            if returns_jit:
                out[info.qualname.rsplit(".", 1)[-1]] = info
        return out

    # -- resolution ----------------------------------------------------------
    def _resolve_local_name(self, mod, at_node, name):
        """A bare name: enclosing function scopes (nested defs), then
        module top level, then project imports."""
        info = mod.enclosing_function(at_node)
        prefix = info.qualname + "." if info is not None else ""
        while True:
            cand = mod.functions.get(prefix + name)
            if cand is not None:
                return cand
            if not prefix:
                break
            # pop one scope level:  a.b.c. -> a.b.
            prefix = prefix[:-1]
            prefix = prefix[: prefix.rfind(".") + 1] \
                if "." in prefix else ""
        # class-level sibling: a method calling another method by bare
        # name doesn't resolve (that's self.m); skip to imports
        imported = mod.imports.get(name)
        if imported:
            return self._resolve_dotted(imported)
        return None

    def _resolve_dotted(self, dotted):
        """'pkg.mod.fn' -> FunctionInfo when pkg.mod is in-project."""
        if "." not in dotted:
            return None
        modpath, fname = dotted.rsplit(".", 1)
        target = self._project_module(modpath)
        if target is None:
            return None
        return target.functions.get(fname)

    def _project_module(self, dotted):
        by = self.project.by_modname
        if dotted in by:
            return by[dotted]
        for name, mod in by.items():  # suffix match: analysis root may
            if dotted.endswith("." + name) or \
                    name.endswith("." + dotted):  # sit below the package
                return mod
        return None

    def resolve_call(self, mod, info, chain, call):
        """FunctionInfo for a call site, or None."""
        if not chain or chain[-1] in ("()", "[]"):
            return None
        aliases = self.jit_aliases.get(mod.rel, {})
        if len(chain) == 1:
            name = chain[0]
            if name in aliases:
                return aliases[name]
            return self._resolve_local_name(mod, call, name)
        root, meth = chain[0], chain[-1]
        if len(chain) == 2 and root in ("self", "cls"):
            if meth in aliases:
                return aliases[meth]
            cls = info.class_name if info else None
            if cls:
                cand = mod.functions.get(f"{cls}.{meth}")
                if cand is not None:
                    return cand
            return None
        if len(chain) >= 2 and chain[-2] == "self" or \
                (len(chain) == 2 and root in mod.classes):
            # self.attr.m() beyond jit aliases: unresolved;
            # ClassName.m(): resolve in that class
            if len(chain) == 2 and root in mod.classes:
                return mod.functions.get(f"{root}.{meth}")
            if meth in aliases:
                return aliases[meth]
            return None
        if len(chain) == 2:
            imported = mod.imports.get(root)
            if imported:
                target = self._project_module(imported)
                if target is not None:
                    return target.functions.get(meth)
                return self._resolve_dotted(f"{imported}.{meth}")
        return None

    # -- edges / reachability ------------------------------------------------
    def callees(self, info):
        key = id(info)
        if key not in self._edges:
            out = []
            for chain, call in info.calls:
                target = self.resolve_call(info.module, info, chain,
                                           call)
                if target is not None:
                    out.append(target)
                fn = call.args and wrapped_function(call)
                if fn is not None and isinstance(fn, ast.Name):
                    # directly-invoked jit wrapper: jax.jit(f)(x)
                    t = self._resolve_local_name(info.module, call,
                                                 fn.id)
                    if t is not None:
                        out.append(t)
            self._edges[key] = out
        return self._edges[key]

    def find_path(self, start, predicate, max_depth=25):
        """BFS from FunctionInfo ``start``; returns the qualname path
        [start..target] to the first function satisfying
        ``predicate(info)``, else None."""
        if predicate(start):
            return [start]
        seen = {id(start)}
        frontier = [[start]]
        for _ in range(max_depth):
            nxt = []
            for path in frontier:
                for callee in self.callees(path[-1]):
                    if id(callee) in seen:
                        continue
                    seen.add(id(callee))
                    new = path + [callee]
                    if predicate(callee):
                        return new
                    nxt.append(new)
            if not nxt:
                return None
            frontier = nxt
        return None
