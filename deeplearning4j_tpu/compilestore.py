"""Persistent executable store: serialized AOT artifacts for zero-compile
restarts (ISSUE 13 tentpole).

Every process start pays the full XLA ladder again: a serving replica
re-compiles every bucket of every registered model before ``ready()``,
and a Supervisor resume re-compiles the train step it was running one
crash earlier. This module makes "an executable is compiled once per
signature per machine, ever" the invariant instead:

- **content-addressed entries**: a compiled executable is serialized
  (``jax.experimental.serialize_executable``) and committed under a
  key derived from everything that determines the program — the
  PR-11 compile-ledger abstract signature (per-leaf shapes/dtypes,
  donation, sharding, precision/health policy label), a *program
  digest* (the model's configuration JSON where the caller has one,
  else the lowered HLO fingerprint), the package **code epoch** (a
  digest of this package's own sources — a code change can never
  serve a stale program), the jax version, the backend platform +
  device kind, and the process XLA flags;
- **atomic commits**: entries are written through the shared
  ``utils/checkpoint.atomic_save`` tmp + ``os.replace`` protocol, so a
  crash mid-write leaves a ``.tmp`` remnant, never a torn entry;
- **reject, never serve wrong**: an entry whose magic / header /
  payload hash / machine identity does not check out is deleted and
  the site falls back to compile-and-overwrite — a mismatched or
  truncated artifact is never loaded;
- **LRU size cap**: reads bump the entry mtime; ``put`` evicts the
  oldest entries past ``max_bytes``.

Two consumption seams:

- :func:`resolve` — the AOT seam (``Servable.compile_shape`` and the
  coldstart tool): give it a lower-thunk and a signature, get back a
  loaded executable plus ``{"store": hit|miss|reject|off, "mode":
  compile|deserialize}`` info for the ledger's ``cache_hit`` /
  ``cache_reject`` forensics;
- :class:`StoredJit` — the train-step seam: wraps the jitted step the
  fit/graph/sharded loops build, resolves each new argument signature
  through the store, and dispatches the loaded executable directly
  (the jit dispatch cache is a separate cache — see servable.py). A
  warm restart's first step deserializes in milliseconds instead of
  recompiling in seconds, which is what lets the Supervisor watchdog
  shrink its post-resume grace.

The store is OFF unless pointed at a directory — ``configure(root=...)``
or ``DL4J_EXECUTABLE_STORE=/path`` — so default-configured processes
(and the existing test matrix) see byte-identical behavior. Multi-host
processes keep it off: serialized SPMD executables bake in a device
assignment this module does not yet reconcile across process sets.
Mesh-sharded servables (ISSUE 19) are scoped out for the same reason
even single-process: ``ShardedServable.compile_shape`` never consults
the store and ledgers ``store="reject"`` with an explicit cause
(``serving.sharded.STORE_REJECT_SHARDED``) plus a
``compile_store_reject`` flight event — visible refusal, not silent
bypass.

Telemetry: each resolve observes ``dl4j_compile_seconds{mode}`` and the
ledger grows matching ``cache_hit`` / ``cache_reject`` causes;
``GET /debug/compiles`` serves :func:`describe` as its ``store``
section. All emission is gated on the telemetry master switch — the
store itself (disk cache) keeps working with telemetry disabled, it
just stops narrating.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time

from deeplearning4j_tpu.telemetry import registry as _registry

ENV_ROOT = "DL4J_EXECUTABLE_STORE"
ENV_EPOCH = "DL4J_STORE_CODE_EPOCH"

_MAGIC = b"DL4JXC01"
_FORMAT = 1
_SUFFIX = ".xc"
DEFAULT_MAX_BYTES = 2 << 30   # 2 GiB of serialized executables

SECONDS_HELP = ("Executable acquisition seconds by mode: a real XLA "
                "backend compile vs a deserialize from the persistent "
                "executable store")

_state = {"store": None, "configured": False}
_lock = threading.Lock()
_epoch_lock = threading.Lock()
_code_epoch = None


def configure(root=None, max_bytes=None, enabled=None):
    """Point the process at a store directory (or disable with
    ``enabled=False``). ``root=None`` keeps the current/env root."""
    with _lock:
        store = _state["store"]
        if enabled is False:
            _state["store"] = None
            _state["configured"] = True
            return None
        if root is not None:
            store = ExecutableStore(root, max_bytes=max_bytes
                                    if max_bytes is not None
                                    else DEFAULT_MAX_BYTES)
        elif store is not None and max_bytes is not None:
            store.max_bytes = int(max_bytes)
        _state["store"] = store
        _state["configured"] = True
        return store


def get_store():
    """The process store, or None when unconfigured. First ask checks
    the ``DL4J_EXECUTABLE_STORE`` env seam."""
    store = _state["store"]
    if store is None and not _state["configured"]:
        with _lock:
            if _state["store"] is None and not _state["configured"]:
                root = os.environ.get(ENV_ROOT)
                if root:
                    _state["store"] = ExecutableStore(root)
                _state["configured"] = True
            store = _state["store"]
    return store


def _prewarm_epoch():
    """Start the code-epoch stat sweep on a background thread: on slow
    container filesystems it costs tens of ms, and computing it while
    the caller is still initializing jax keeps it off the first
    resolve's timed path. code_epoch() itself stays the source of
    truth (idempotent; the GIL makes the global publish safe)."""
    if _code_epoch is None:
        threading.Thread(target=code_epoch, daemon=True,
                         name="dl4j:train:store-epoch").start()


def enabled() -> bool:
    """Store is live: configured AND single-process (serialized SPMD
    executables bake in a device assignment; multi-host reconciliation
    is future work — documented in docs/SERVING.md)."""
    if get_store() is None:
        return False
    try:
        import jax

        return jax.process_count() == 1
    except Exception:
        return False


def is_warm(sites=None) -> bool:
    """True when the store holds at least one entry — the Supervisor's
    hint that a resume will deserialize instead of recompile. With
    ``sites``, only entries whose recorded site starts with one of the
    given names count (a shared store full of OTHER jobs' serving
    ladders must not promise a train-step hit)."""
    store = get_store()
    if store is None or not enabled():
        return False
    if sites is None:
        return bool(store.entry_count())
    return any(s.startswith(tuple(sites)) for s in store.sites())


def describe() -> dict:
    """The /debug/compiles ``store`` section: hit/reject/put counters,
    entries and bytes on disk; ``{"enabled": False}`` when off."""
    store = get_store()
    if store is None:
        return {"enabled": False}
    d = store.describe()
    d["enabled"] = enabled()
    return d


def code_epoch() -> str:
    """Digest of this package's own .py sources — (path, size,
    mtime_ns) per file, not contents, so the first resolve costs one
    stat sweep (~ms), not a full read+hash of the tree. A changed
    layer/step implementation changes every key, so a stale executable
    compiled from old code can never be served for new code; a mere
    re-checkout that bumps mtimes costs a spurious miss, which
    compile-and-overwrite self-heals. Overridable via
    ``DL4J_STORE_CODE_EPOCH`` (pinned deployments that version their
    store directory out of band)."""
    global _code_epoch
    if _code_epoch is not None:
        return _code_epoch
    with _epoch_lock:   # prewarm thread + first resolve: sweep once
        if _code_epoch is not None:
            return _code_epoch
        pinned = os.environ.get(ENV_EPOCH)
        if pinned:
            _code_epoch = pinned
            return _code_epoch
        h = hashlib.sha256()
        pkg = os.path.dirname(os.path.abspath(__file__))
        for dirpath, dirnames, filenames in sorted(os.walk(pkg)):
            dirnames.sort()
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                h.update(f"{os.path.relpath(path, pkg)}:{st.st_size}:"
                         f"{st.st_mtime_ns}".encode())
        _code_epoch = h.hexdigest()[:16]
        return _code_epoch


_machine_key = None


def machine_key() -> dict:
    """Everything about THIS process that changes what XLA emits for
    the same program: jax version, backend platform, device kind, and
    the process XLA flags. Computed once — none of it changes within
    a process."""
    global _machine_key
    if _machine_key is None:
        import jax

        dev = jax.devices()[0]
        _machine_key = {
            "jax": jax.__version__,
            "backend": jax.default_backend(),
            "device_kind": getattr(dev, "device_kind", str(dev)),
            "xla_flags": os.environ.get("XLA_FLAGS", ""),
            "epoch": code_epoch(),
        }
    return _machine_key


def entry_key(sig, program) -> str:
    """Content address for one executable: machine identity + program
    digest + abstract signature, canonically serialized and hashed."""
    ident = {
        "machine": machine_key(),
        "program": str(program),
        "args": [[list(s), d] for s, d in sig.args],
        "donation": list(sig.donation),
        "policy": sig.policy,
        "sharding": sig.sharding,
    }
    blob = json.dumps(ident, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


class StoreReject(Exception):
    """A store entry failed validation (bad magic/header/hash/machine)
    and was removed; the caller compiles and overwrites."""


class ExecutableStore:
    """Disk half of the store: validated entry files under
    ``root/<key[:2]>/<key>.xc``, atomic commits, LRU eviction. All
    methods are host-side; nothing here touches a device."""

    def __init__(self, root, max_bytes: int = DEFAULT_MAX_BYTES):
        self.root = str(root)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self.stats = {"hits": 0, "misses": 0, "rejects": 0, "puts": 0,
                      "evictions": 0, "put_failures": 0}
        os.makedirs(self.root, exist_ok=True)
        _prewarm_epoch()

    def _store_path(self, key: str) -> str:
        return os.path.join(self.root, key[:2], key + _SUFFIX)

    def _count(self, stat):
        with self._lock:
            self.stats[stat] += 1

    # -- entries -------------------------------------------------------------
    def get(self, key: str):
        """(header, payload) for a valid entry; None on miss; raises
        :class:`StoreReject` after deleting a corrupt/stale entry —
        mismatched artifacts are never returned."""
        path = self._store_path(key)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            self._count("misses")
            return None
        try:
            header, payload = self._validate(key, raw)
        except StoreReject:
            self._count("rejects")
            try:
                os.remove(path)
            except OSError:
                pass
            raise
        self._count("hits")
        try:
            os.utime(path)   # LRU: reads refresh the entry
        except OSError:
            pass
        return header, payload

    def _validate(self, key, raw):
        if len(raw) < len(_MAGIC) + 4 or not raw.startswith(_MAGIC):
            raise StoreReject("bad magic")
        hlen = int.from_bytes(raw[8:12], "big")
        if len(raw) < 12 + hlen:
            raise StoreReject("truncated header")
        try:
            header = json.loads(raw[12:12 + hlen])
        except ValueError as e:
            raise StoreReject(f"unparseable header: {e}") from None
        if header.get("format") != _FORMAT:
            raise StoreReject(f"format {header.get('format')}")
        if header.get("key") != key:
            raise StoreReject("key mismatch")
        if header.get("machine") != machine_key():
            # stale: another jax/backend/code epoch wrote this key
            # (possible only via hash collision or a moved store dir)
            raise StoreReject("machine identity mismatch")
        payload = raw[12 + hlen:]
        if len(payload) != header.get("payload_len"):
            raise StoreReject("truncated payload")
        digest = hashlib.sha256(payload).hexdigest()
        if digest != header.get("payload_sha256"):
            raise StoreReject("payload hash mismatch")
        return header, payload

    def put(self, key: str, payload: bytes, site: str = "",
            fingerprint=None, signature=None):
        """Commit one serialized executable (tmp + os.replace via the
        shared atomic_save seam), then evict past the size cap."""
        from deeplearning4j_tpu.utils.checkpoint import atomic_save

        header = {
            "format": _FORMAT,
            "key": key,
            "machine": machine_key(),
            "site": site,
            "hlo_fingerprint": fingerprint,
            "signature": signature,
            "payload_len": len(payload),
            "payload_sha256": hashlib.sha256(payload).hexdigest(),
            "created": round(time.time(), 3),
        }
        head = json.dumps(header, sort_keys=True).encode()
        blob = _MAGIC + len(head).to_bytes(4, "big") + head + payload
        path = self._store_path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)

        def write(tmp):
            with open(tmp, "wb") as f:
                f.write(blob)

        atomic_save(path, write)
        self._count("puts")
        self._evict()
        return path

    # -- maintenance ---------------------------------------------------------
    def _entries(self):
        """[(path, mtime, bytes)] for every committed entry file."""
        out = []
        for dirpath, _, filenames in os.walk(self.root):
            for fn in filenames:
                if not fn.endswith(_SUFFIX):
                    continue
                path = os.path.join(dirpath, fn)
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                out.append((path, st.st_mtime, st.st_size))
        return out

    def _evict(self):
        entries = self._entries()
        total = sum(b for _, _, b in entries)
        if total <= self.max_bytes:
            return
        for path, _, size in sorted(entries, key=lambda e: e[1]):
            if total <= self.max_bytes:
                break
            try:
                os.remove(path)
            except OSError:
                continue
            total -= size
            self._count("evictions")

    def entry_count(self) -> int:
        return len(self._entries())

    def sites(self) -> set:
        """Recorded sites of every entry, from header bytes only (no
        payload reads/validation — is_warm is a hint, not a promise;
        a keyed get() still rejects anything invalid)."""
        out = set()
        for path, _, _ in self._entries():
            try:
                with open(path, "rb") as f:
                    head = f.read(12)
                    if not head.startswith(_MAGIC):
                        continue
                    hlen = int.from_bytes(head[8:12], "big")
                    if hlen > (1 << 20):
                        continue
                    header = json.loads(f.read(hlen))
            except (OSError, ValueError):
                continue
            out.add(str(header.get("site", "")))
        return out

    def total_bytes(self) -> int:
        return sum(b for _, _, b in self._entries())

    def contents(self) -> list:
        """Header summaries of every valid entry, newest first (the
        coldstart tool's report; corrupt entries are listed as such
        without being deleted — only a keyed read rejects)."""
        rows = []
        for path, mtime, size in sorted(self._entries(),
                                        key=lambda e: -e[1]):
            key = os.path.basename(path)[:-len(_SUFFIX)]
            row = {"key": key, "bytes": size,
                   "mtime": round(mtime, 3)}
            try:
                with open(path, "rb") as f:
                    raw = f.read()
                header, _ = self._validate(key, raw)
                row.update(site=header.get("site"),
                           hlo_fingerprint=header.get("hlo_fingerprint"),
                           created=header.get("created"))
            except (StoreReject, OSError) as e:
                row["invalid"] = str(e)
            rows.append(row)
        return rows

    def describe(self) -> dict:
        with self._lock:
            stats = dict(self.stats)
        return {
            "root": self.root,
            "entries": self.entry_count(),
            "bytes_on_disk": self.total_bytes(),
            "max_bytes": self.max_bytes,
            **stats,
        }

    def clear(self):
        for path, _, _ in self._entries():
            try:
                os.remove(path)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# resolution: one seam shared by the serving AOT path and StoredJit
# ---------------------------------------------------------------------------

def _observe_seconds(mode, seconds):
    if not _registry.enabled():
        return
    try:
        fam = _registry.get_registry().histogram(
            "dl4j_compile_seconds", SECONDS_HELP, ("mode",))
        fam.local = True   # per-host compile history: scrape-only
        fam.labels(mode=mode).observe(seconds)
    except Exception:
        pass   # stub registries must not break a compile site


def _serialize(compiled) -> bytes:
    from jax.experimental import serialize_executable as se

    payload, in_tree, out_tree = se.serialize(compiled)
    return pickle.dumps((payload, in_tree, out_tree),
                        protocol=pickle.HIGHEST_PROTOCOL)


def _deserialize(payload: bytes):
    from jax.experimental import serialize_executable as se

    return se.deserialize_and_load(*pickle.loads(payload))


def resolve(site, lower_thunk, sig, program=None):
    """Acquire the executable for ``sig`` at ``site``: deserialize a
    validated store entry when one exists, else compile (through
    ``lower_thunk()``) and commit the serialized result.

    ``program`` is the caller's digest of everything that determines
    the traced program beyond the signature (a model's configuration
    JSON + adapter label). Callers without one pass None: the lowered
    module's HLO fingerprint is used instead — always sound, but the
    warm path then pays a re-trace per executable.

    Returns ``(executable, info)`` with info keys ``store``
    (hit|miss|reject), ``mode`` (compile|deserialize), ``seconds``,
    ``key``, and ``hlo_fingerprint`` when known."""
    from deeplearning4j_tpu.telemetry import flight, hlo_audit

    store = get_store()
    if store is None or not enabled():
        t0 = time.perf_counter()
        exe = lower_thunk().compile()
        seconds = time.perf_counter() - t0
        _observe_seconds("compile", seconds)
        return exe, {"store": "off", "mode": "compile",
                     "seconds": seconds, "key": None,
                     "hlo_fingerprint": None}
    lowered = None
    fingerprint = None
    if program is None:
        lowered = lower_thunk()
        fingerprint = hlo_audit.fingerprint(lowered.as_text())
        program = f"hlo:{fingerprint}"
    key = entry_key(sig, program)
    info = {"store": "miss", "mode": "compile", "key": key,
            "hlo_fingerprint": fingerprint}
    entry = None
    try:
        entry = store.get(key)
    except StoreReject as e:
        info["store"] = "reject"
        info["reject_reason"] = str(e)
        flight.record("compile_store_reject", site=site, key=key,
                      reason=str(e))
    if entry is not None:
        header, payload = entry
        t0 = time.perf_counter()
        try:
            exe = _deserialize(payload)
        except Exception as e:
            # an unloadable payload is a reject like any other: drop
            # the entry, compile, overwrite. get() already counted the
            # validated read as a hit — reclassify it, so one event is
            # one stat and hits+misses+rejects reconciles with resolves
            info["store"] = "reject"
            info["reject_reason"] = f"deserialize: {type(e).__name__}"
            flight.record("compile_store_reject", site=site, key=key,
                          reason=info["reject_reason"])
            try:
                os.remove(store._store_path(key))
            except OSError:
                pass
            with store._lock:
                store.stats["hits"] -= 1
                store.stats["rejects"] += 1
        else:
            seconds = time.perf_counter() - t0
            info.update(store="hit", mode="deserialize",
                        seconds=seconds,
                        hlo_fingerprint=header.get("hlo_fingerprint"))
            _observe_seconds("deserialize", seconds)
            return exe, info
    if lowered is None:
        lowered = lower_thunk()
        fingerprint = hlo_audit.fingerprint(lowered.as_text())
        info["hlo_fingerprint"] = fingerprint
    t0 = time.perf_counter()
    exe = lowered.compile()
    seconds = time.perf_counter() - t0
    info["seconds"] = seconds
    _observe_seconds("compile", seconds)
    try:
        store.put(key, _serialize(exe), site=site,
                  fingerprint=info["hlo_fingerprint"],
                  signature={"n_args": len(sig.args),
                             "policy": sig.policy,
                             "sharding": sig.sharding})
    except Exception as e:
        # a full disk / unserializable executable must not break the
        # compile path — the site just stays cold-start-expensive
        store._count("put_failures")
        flight.record("compile_store_put_failure", site=site, key=key,
                      error=f"{type(e).__name__}: {e}")
    return exe, info


# ---------------------------------------------------------------------------
# StoredJit: the train-step seam
# ---------------------------------------------------------------------------

def _args_signature(args, donation, policy):
    """The compile-ledger Signature of a concrete argument pytree
    (single-device step sites: sharding rides in the machine key)."""
    from deeplearning4j_tpu.telemetry import compile_ledger

    return compile_ledger.signature_of(args, donation=donation,
                                       policy=policy)


class _ResolvedStep:
    """One resolved (signature -> executable) slot. ``own_first`` marks
    a DESERIALIZED executable with donation whose first call must
    deep-clone the donated args into fresh XLA-owned buffers first:
    jax's in-process ``Compiled`` call path copies a donated input
    whose buffer is host-borrowed (zero-copied numpy — exactly what a
    checkpoint-restored ``setParams`` produces on CPU), but the
    ``deserialize_and_load`` call path does not, and donating a
    borrowed buffer through it corrupts the shared backing store
    (observed as a segfault on the SECOND step after a resume). After
    the first call every chained arg is this executable's own output
    — an XLA-owned buffer — so the clone runs exactly once."""

    __slots__ = ("exe", "cloner", "donation", "own_first")

    def __init__(self, exe, cloner, donation, own_first):
        self.exe = exe
        self.cloner = cloner
        self.donation = donation
        self.own_first = own_first

    def __call__(self, *args):
        if self.own_first:
            if self.cloner is not None:
                owned = self.cloner(*(args[i] for i in self.donation))
                args = list(args)
                for j, i in enumerate(self.donation):
                    args[i] = owned[j]
                args = tuple(args)
            out = self.exe(*args)
            # disarm only AFTER a successful call: a transient raise
            # here must leave the clone armed for the caller's retry,
            # or the retry would donate the borrowed originals
            self.own_first = False
            return out
        return self.exe(*args)


class StoredJit:
    """Wraps one jitted step function; per-signature dispatch goes to
    an AOT executable resolved through the store (the jit dispatch
    cache cannot be pre-seeded — see servable.py). The steady-state
    cost is one leaf walk to key the signature; resolution happens
    once per signature per process.

    Exposes ``lower`` (delegated) so the costmodel/ledger seams that
    receive this object keep working unchanged."""

    def __init__(self, jitted, site, program=None, policy=None,
                 donation=(0, 1, 2)):
        self._jitted = jitted
        self._site = site
        self._program = program
        self._policy = policy
        self._donation = tuple(donation or ())
        self._exes = {}
        self._last = None
        self._resolve_lock = threading.Lock()

    def lower(self, *args, **kw):
        return self._jitted.lower(*args, **kw)

    def __call__(self, *args):
        import jax

        leaves = jax.tree_util.tree_leaves(args)
        key = tuple(
            (tuple(getattr(x, "shape", ())),
             str(getattr(x, "dtype", type(x).__name__)))
            for x in leaves)
        last = self._last
        if last is not None and last[0] == key:
            return last[1](*args)
        slot = self._exes.get(key)
        if slot is None:
            slot = self._resolve(key, args)
        self._last = (key, slot)
        return slot(*args)

    def _clone_exe(self, args):
        """The donated-subtree deep-clone executable, itself resolved
        through the store (its own entry is written on the COLD path
        too, so a warm restart needs zero compiles even for the
        clone)."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.telemetry import compile_ledger

        donated = tuple(args[i] for i in self._donation)
        cloner = jax.jit(
            lambda *t: jax.tree_util.tree_map(jnp.copy, t))
        sig = compile_ledger.signature_of(donated, donation=(),
                                          policy="own-clone")
        exe, _ = resolve(f"{self._site}:own",
                         lambda: cloner.lower(*donated), sig,
                         program="own-clone:v1")
        return exe

    def _resolve(self, key, args):
        from deeplearning4j_tpu.telemetry import compile_ledger

        with self._resolve_lock:
            slot = self._exes.get(key)
            if slot is not None:
                return slot
            sig = compile_ledger.Signature(
                args=key, donation=self._donation,
                policy=str(self._policy or ""), sharding="")
            exe, info = resolve(
                self._site, lambda: self._jitted.lower(*args), sig,
                program=self._program)
            cloner = None
            if self._donation:
                try:
                    cloner = self._clone_exe(args)
                except Exception:
                    cloner = None
                if cloner is None and info["mode"] == "deserialize":
                    # no clone executable means the deserialized
                    # executable cannot be called safely with donation
                    # (see _ResolvedStep): fall back to a real compile
                    # — slower, never wrong
                    exe = self._jitted.lower(*args).compile()
                    info = dict(info, store="miss", mode="compile")
            if info["store"] in ("hit", "reject"):
                # hit: no backend compile fired, so the fit loop's
                # note_step will not record — the ledger entry (cause
                # cache_hit) is written here. reject: a compile DID
                # fire; claim its thread-local seconds here so the
                # loop's note_step cannot double-record it under a
                # classify cause — one ledger record per event
                compile_ledger.note_store(
                    self._site, self, args, sig, store=info["store"],
                    mode=info["mode"], seconds=info.get("seconds"),
                    fingerprint=info.get("hlo_fingerprint"))
            slot = _ResolvedStep(
                exe, cloner, self._donation,
                own_first=bool(self._donation)
                and info["mode"] == "deserialize")
            self._exes[key] = slot
            return slot
