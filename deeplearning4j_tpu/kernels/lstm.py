"""Fused LSTM recurrence as an in-repo Pallas TPU kernel.

Why a custom kernel (SURVEY.md §7: "Pallas-style custom kernels enter as
XLA custom-calls if/when generic HLO can't hit MFU targets"): the
XLA-lowered lax.scan recurrence measures ~80-155 us PER SEQUENTIAL STEP
on v5e (tools/probe_lstm.py) while the step's actual work — one
[N,H]x[H,4H] MXU matmul plus elementwise gates — rooflines at single-
digit microseconds. The scan pays per-iteration HBM round-trips for the
carried h/c; this kernel keeps h, c and R resident in VMEM across ALL
timesteps (the cuDNN-LSTM design; reference analog: libnd4j's cudnn
platform helper for lstmLayer, SURVEY.md §2.1 platform-helper tier) and
runs the whole recurrence in ONE kernel launch. Slope-timed A/B on the
char-RNN bench config (b1024, T=100, H=256, r4): 13.3 ms/step vs the
scan lowering's 24.4 — a 1.83x win (the r3 "1.23x" figure carried the
tunnel's per-launch RTT in both numerators).

Scope: the recurrence only. The input projection xw = x @ W + b (with
forgetBias folded into the f-gate columns) stays OUTSIDE — it is one
large MXU matmul XLA already runs at high efficiency.

Gradients: jax.custom_vjp with a reverse-sweep Pallas kernel (BPTT):
the forward saves post-activation gates and cell states; the backward
walks time in reverse via index maps, carrying dh/dc in VMEM and
accumulating dR on-chip. dxw flows back into the outer graph, which
differentiates the hoisted projection automatically.

Layouts: xw [T, N, 4H] f32, R [H, 4H] f32, h0/c0 [N, H] f32 ->
(hs [T, N, H], hT, cT). Gate packing i,f,g,o (DL4J order).
Constraints: f32, H % 128 == 0, N % 8 == 0 (MXU/VPU tiling); callers
fall back to the lax.scan path otherwise (`lstm_seq_available`).
`interpret=True` runs the same kernels on CPU — the parity tests in
tests/test_kernels.py use it, and TPU-gated tests cover the compiled
path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # Pallas TPU backend; interpret=True also runs on CPU for tests
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False


_VMEM_BUDGET = 90 * 1024 * 1024


def lstm_seq_available(n, h, dtype) -> bool:
    if not (_PALLAS_OK and jnp.dtype(dtype) == jnp.float32
            and h % 128 == 0 and n % 8 == 0):
        return False
    # the backward kernel's worst-case resident VMEM: R + dR scratch +
    # dR output block (H x 4H each) plus the per-step N-blocks (several
    # N x 4H / N x H buffers, double-buffered) — fall back to the scan
    # path rather than die in the Mosaic compiler on big-H configs
    weights = 3 * (h * 4 * h * 4)
    blocks = 6 * (n * 4 * h * 4) + 12 * (n * h * 4)
    return weights + blocks < _VMEM_BUDGET


def _dotT_rhs(a, b):
    """a @ b.T without materializing the transpose."""
    return jax.lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _dotT_lhs(a, b):
    """a.T @ b without materializing the transpose."""
    return jax.lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_body(xw_ref, r_ref, h_scr, c_scr):
    hsz = h_scr.shape[1]
    z = xw_ref[0] + jnp.dot(h_scr[:], r_ref[:],
                            preferred_element_type=jnp.float32)
    i = jax.nn.sigmoid(z[:, :hsz])
    f = jax.nn.sigmoid(z[:, hsz:2 * hsz])
    g = jnp.tanh(z[:, 2 * hsz:3 * hsz])
    o = jax.nn.sigmoid(z[:, 3 * hsz:])
    c = f * c_scr[:] + i * g
    h = o * jnp.tanh(c)
    return i, f, g, o, c, h


def _fwd_kernel(xw_ref, r_ref, h0_ref, c0_ref,
                hs_ref, gates_ref, cs_ref,
                h_scr, c_scr):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    i, f, g, o, c, h = _fwd_body(xw_ref, r_ref, h_scr, c_scr)
    gates_ref[0] = jnp.concatenate([i, f, g, o], axis=1)
    cs_ref[0] = c
    hs_ref[0] = h
    h_scr[:] = h
    c_scr[:] = c


def _fwd_infer_kernel(xw_ref, r_ref, h0_ref, c0_ref,
                      hs_ref, hT_ref, cT_ref,
                      h_scr, c_scr):
    """Inference variant: no gate/cell residuals hit HBM (dead outputs
    of a pallas custom call are NOT DCE'd by XLA, so the primal must
    simply not emit them)."""
    t = pl.program_id(0)
    t_total = pl.num_programs(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    _i, _f, _g, _o, c, h = _fwd_body(xw_ref, r_ref, h_scr, c_scr)
    hs_ref[0] = h
    h_scr[:] = h
    c_scr[:] = c

    @pl.when(t == t_total - 1)
    def _():
        hT_ref[:] = h
        cT_ref[:] = c


def _fwd_call(xw, r, h0, c0, interpret, save_residuals=True):
    t, n, four_h = xw.shape
    hsz = four_h // 4
    in_specs = [
        pl.BlockSpec((1, n, four_h), lambda i: (i, 0, 0)),
        pl.BlockSpec((hsz, four_h), lambda i: (0, 0)),
        pl.BlockSpec((n, hsz), lambda i: (0, 0)),
        pl.BlockSpec((n, hsz), lambda i: (0, 0)),
    ]
    params = None if interpret else pltpu.CompilerParams(
        vmem_limit_bytes=100 * 1024 * 1024)
    if save_residuals:
        return pl.pallas_call(
            _fwd_kernel,
            grid=(t,),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, n, hsz), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, n, four_h), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, n, hsz), lambda i: (i, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((t, n, hsz), jnp.float32),
                jax.ShapeDtypeStruct((t, n, four_h), jnp.float32),
                jax.ShapeDtypeStruct((t, n, hsz), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((n, hsz), jnp.float32),
                pltpu.VMEM((n, hsz), jnp.float32),
            ],
            compiler_params=params,
            interpret=interpret,
        )(xw, r, h0, c0)
    return pl.pallas_call(
        _fwd_infer_kernel,
        grid=(t,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, n, hsz), lambda i: (i, 0, 0)),
            pl.BlockSpec((n, hsz), lambda i: (0, 0)),
            pl.BlockSpec((n, hsz), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, n, hsz), jnp.float32),
            jax.ShapeDtypeStruct((n, hsz), jnp.float32),
            jax.ShapeDtypeStruct((n, hsz), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((n, hsz), jnp.float32),
            pltpu.VMEM((n, hsz), jnp.float32),
        ],
        compiler_params=params,
        interpret=interpret,
    )(xw, r, h0, c0)


# ---------------------------------------------------------------------------
# backward (reverse time sweep; grid index ti walks t = T-1-ti)
# ---------------------------------------------------------------------------

def _bwd_kernel(dhs_ref, gates_ref, cs_ref, cprev_ref, hprev_ref, r_ref,
                h0_ref, c0_ref, dhT_ref, dcT_ref,
                dxw_ref, dr_ref, dh0_ref, dc0_ref,
                dh_scr, dc_scr, dr_scr):
    ti = pl.program_id(0)
    t_total = pl.num_programs(0)
    hsz = dh_scr.shape[1]
    is_first_step = ti == t_total - 1   # t == 0 in forward time

    @pl.when(ti == 0)
    def _():
        dh_scr[:] = dhT_ref[:]
        dc_scr[:] = dcT_ref[:]
        dr_scr[:] = jnp.zeros_like(dr_scr)

    gates = gates_ref[0]
    i = gates[:, :hsz]
    f = gates[:, hsz:2 * hsz]
    g = gates[:, 2 * hsz:3 * hsz]
    o = gates[:, 3 * hsz:]
    c = cs_ref[0]
    # c_{t-1}/h_{t-1}: shifted views of cs/hs (clamped at t=0; replaced
    # by the true initial state there)
    first = jnp.where(is_first_step, jnp.float32(1.0), jnp.float32(0.0))
    c_prev = first * c0_ref[:] + (1.0 - first) * cprev_ref[0]
    h_prev = first * h0_ref[:] + (1.0 - first) * hprev_ref[0]

    tc = jnp.tanh(c)
    dh = dhs_ref[0] + dh_scr[:]
    do = dh * tc
    dc = dc_scr[:] + dh * o * (1.0 - tc * tc)
    di = dc * g
    df = dc * c_prev
    dg = dc * i
    dz = jnp.concatenate([
        di * i * (1.0 - i),
        df * f * (1.0 - f),
        dg * (1.0 - g * g),
        do * o * (1.0 - o),
    ], axis=1)
    dxw_ref[0] = dz
    dh_scr[:] = _dotT_rhs(dz, r_ref[:])          # dz @ R^T
    dc_scr[:] = dc * f
    dr_scr[:] = dr_scr[:] + _dotT_lhs(h_prev, dz)  # h_{t-1}^T @ dz

    @pl.when(is_first_step)
    def _():
        dr_ref[:] = dr_scr[:]
        dh0_ref[:] = dh_scr[:]
        dc0_ref[:] = dc_scr[:]


def _bwd_call(t, n, hsz, interpret, dhs, gates, cs, hs, r, h0, c0,
              dhT, dcT):
    four_h = 4 * hsz
    rev = lambda i: (t - 1 - i, 0, 0)            # noqa: E731
    rev_prev = lambda i: (jnp.maximum(t - 2 - i, 0), 0, 0)  # noqa: E731
    return pl.pallas_call(
        _bwd_kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, n, hsz), rev),        # dhs
            pl.BlockSpec((1, n, four_h), rev),     # gates
            pl.BlockSpec((1, n, hsz), rev),        # cs
            pl.BlockSpec((1, n, hsz), rev_prev),   # cs shifted (c_{t-1})
            pl.BlockSpec((1, n, hsz), rev_prev),   # hs shifted (h_{t-1})
            pl.BlockSpec((hsz, four_h), lambda i: (0, 0)),
            pl.BlockSpec((n, hsz), lambda i: (0, 0)),   # h0
            pl.BlockSpec((n, hsz), lambda i: (0, 0)),   # c0
            pl.BlockSpec((n, hsz), lambda i: (0, 0)),   # dhT
            pl.BlockSpec((n, hsz), lambda i: (0, 0)),   # dcT
        ],
        out_specs=[
            pl.BlockSpec((1, n, four_h), rev),     # dxw
            pl.BlockSpec((hsz, four_h), lambda i: (0, 0)),
            pl.BlockSpec((n, hsz), lambda i: (0, 0)),
            pl.BlockSpec((n, hsz), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, n, four_h), jnp.float32),
            jax.ShapeDtypeStruct((hsz, four_h), jnp.float32),
            jax.ShapeDtypeStruct((n, hsz), jnp.float32),
            jax.ShapeDtypeStruct((n, hsz), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((n, hsz), jnp.float32),
            pltpu.VMEM((n, hsz), jnp.float32),
            pltpu.VMEM((hsz, four_h), jnp.float32),
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(dhs, gates, cs, cs, hs, r, h0, c0, dhT, dcT)


# ---------------------------------------------------------------------------
# custom-vjp wrapper
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def lstm_seq(xw, r, h0, c0, interpret=False):
    """Full LSTM recurrence: xw [T,N,4H] (input projections, biases and
    forgetBias pre-folded), R [H,4H], h0/c0 [N,H] -> (hs [T,N,H], hT,
    cT)."""
    # inference primal: no gate/cell residuals are written to HBM
    hs, hT, cT = _fwd_call(xw, r, h0, c0, interpret,
                           save_residuals=False)
    return hs, hT, cT


def _lstm_seq_fwd(xw, r, h0, c0, interpret):
    hs, gates, cs = _fwd_call(xw, r, h0, c0, interpret)
    return (hs, hs[-1], cs[-1]), (gates, cs, hs, r, h0, c0)


def _lstm_seq_bwd(interpret, res, cts):
    gates, cs, hs, r, h0, c0 = res
    dhs, dhT, dcT = cts
    t, n, hsz = dhs.shape
    dxw, dr, dh0, dc0 = _bwd_call(
        t, n, hsz, interpret, dhs, gates, cs, hs, r, h0, c0,
        dhT, dcT)
    return dxw, dr, dh0, dc0


lstm_seq.defvjp(_lstm_seq_fwd, _lstm_seq_bwd)
