"""In-repo Pallas TPU kernels — the custom-call tier SURVEY.md §7
reserves for ops where generic XLA lowering demonstrably misses
(reference analog: libnd4j's platform-helper kernels, e.g. the cuDNN
LSTM path). Each kernel ships with an XLA fallback and parity tests."""

from deeplearning4j_tpu.kernels.lstm import (  # noqa: F401
    lstm_seq, lstm_seq_available)
