"""Fused GRU recurrence as an in-repo Pallas TPU kernel.

Same design as kernels/lstm.py (VMEM-resident carry + recurrent weights,
one launch for the whole sequence, custom-VJP reverse-sweep backward) —
the libnd4j gruCell packing: gates r,u then candidate c; input and
recurrent biases SEPARATE (b = [rb_input | rb_recurrent] is split by the
caller; this kernel takes the recurrent half explicitly because it
contributes inside the recurrence).

Math per step (matching autodiff/ops.py _gru_cell):
    rz   = h @ R + rb                      [N, 3H]
    r, u = sigmoid(xw_ru + rz_ru)          (first 2H columns)
    cand = tanh(xw_c + r * rz_c)           (last H columns)
    h'   = u * h + (1 - u) * cand

Residuals saved for backward: ru [T,N,2H], cand [T,N,H], rz_c [T,N,H].
Backward returns (dxw, dR, drb, dh0).

Constraints mirror the LSTM kernel: f32, H % 128 == 0, N % 8 == 0,
VMEM-bounded; callers fall back to the lax.scan lowering otherwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False

from deeplearning4j_tpu.kernels.lstm import _VMEM_BUDGET, _dotT_lhs, _dotT_rhs


def gru_seq_available(n, h, dtype) -> bool:
    if not (_PALLAS_OK and jnp.dtype(dtype) == jnp.float32
            and h % 128 == 0 and n % 8 == 0):
        return False
    weights = 3 * (h * 3 * h * 4)
    blocks = 6 * (n * 3 * h * 4) + 12 * (n * h * 4)
    return weights + blocks < _VMEM_BUDGET


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _step(xw_t, rz, hsz, h_prev):
    ru = jax.nn.sigmoid(xw_t[:, :2 * hsz] + rz[:, :2 * hsz])
    rz_c = rz[:, 2 * hsz:]
    cand = jnp.tanh(xw_t[:, 2 * hsz:] + ru[:, :hsz] * rz_c)
    u = ru[:, hsz:]
    h = u * h_prev + (1.0 - u) * cand
    return ru, rz_c, cand, h


def _fwd_kernel(xw_ref, r_ref, rb_ref, h0_ref,
                hs_ref, ru_ref, rzc_ref, cand_ref,
                h_scr):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:]

    hsz = h_scr.shape[1]
    rz = jnp.dot(h_scr[:], r_ref[:],
                 preferred_element_type=jnp.float32) + rb_ref[0]
    ru, rz_c, cand, h = _step(xw_ref[0], rz, hsz, h_scr[:])
    ru_ref[0] = ru
    rzc_ref[0] = rz_c
    cand_ref[0] = cand
    hs_ref[0] = h
    h_scr[:] = h


def _fwd_infer_kernel(xw_ref, r_ref, rb_ref, h0_ref,
                      hs_ref, hT_ref, h_scr):
    t = pl.program_id(0)
    t_total = pl.num_programs(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:]

    hsz = h_scr.shape[1]
    rz = jnp.dot(h_scr[:], r_ref[:],
                 preferred_element_type=jnp.float32) + rb_ref[0]
    _ru, _rzc, _cand, h = _step(xw_ref[0], rz, hsz, h_scr[:])
    hs_ref[0] = h
    h_scr[:] = h

    @pl.when(t == t_total - 1)
    def _():
        hT_ref[:] = h


def _fwd_call(xw, r, rb, h0, interpret, save_residuals=True):
    t, n, three_h = xw.shape
    hsz = three_h // 3
    rb2 = rb.reshape(1, three_h)
    in_specs = [
        pl.BlockSpec((1, n, three_h), lambda i: (i, 0, 0)),
        pl.BlockSpec((hsz, three_h), lambda i: (0, 0)),
        pl.BlockSpec((1, three_h), lambda i: (0, 0)),
        pl.BlockSpec((n, hsz), lambda i: (0, 0)),
    ]
    params = None if interpret else pltpu.CompilerParams(
        vmem_limit_bytes=100 * 1024 * 1024)
    if save_residuals:
        return pl.pallas_call(
            _fwd_kernel,
            grid=(t,),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, n, hsz), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, n, 2 * hsz), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, n, hsz), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, n, hsz), lambda i: (i, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((t, n, hsz), jnp.float32),
                jax.ShapeDtypeStruct((t, n, 2 * hsz), jnp.float32),
                jax.ShapeDtypeStruct((t, n, hsz), jnp.float32),
                jax.ShapeDtypeStruct((t, n, hsz), jnp.float32),
            ],
            scratch_shapes=[pltpu.VMEM((n, hsz), jnp.float32)],
            compiler_params=params,
            interpret=interpret,
        )(xw, r, rb2, h0)
    return pl.pallas_call(
        _fwd_infer_kernel,
        grid=(t,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, n, hsz), lambda i: (i, 0, 0)),
            pl.BlockSpec((n, hsz), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, n, hsz), jnp.float32),
            jax.ShapeDtypeStruct((n, hsz), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((n, hsz), jnp.float32)],
        compiler_params=params,
        interpret=interpret,
    )(xw, r, rb2, h0)


# ---------------------------------------------------------------------------
# backward (reverse sweep)
# ---------------------------------------------------------------------------

def _bwd_kernel(dhs_ref, ru_ref, rzc_ref, cand_ref, hprev_ref, r_ref,
                h0_ref, dhT_ref,
                dxw_ref, dr_ref, drb_ref, dh0_ref,
                dh_scr, dr_scr, drb_scr):
    ti = pl.program_id(0)
    t_total = pl.num_programs(0)
    hsz = dh_scr.shape[1]
    is_first_step = ti == t_total - 1   # forward t == 0

    @pl.when(ti == 0)
    def _():
        dh_scr[:] = dhT_ref[:]
        dr_scr[:] = jnp.zeros_like(dr_scr)
        drb_scr[:] = jnp.zeros_like(drb_scr)

    ru = ru_ref[0]
    rgate = ru[:, :hsz]
    u = ru[:, hsz:]
    rz_c = rzc_ref[0]
    cand = cand_ref[0]
    first = jnp.where(is_first_step, jnp.float32(1.0), jnp.float32(0.0))
    h_prev = first * h0_ref[:] + (1.0 - first) * hprev_ref[0]

    dh = dhs_ref[0] + dh_scr[:]
    dcand = dh * (1.0 - u)
    du = dh * (h_prev - cand)
    dh_carry = dh * u
    dc_pre = dcand * (1.0 - cand * cand)
    drgate = dc_pre * rz_c
    drz_c = dc_pre * rgate
    dru_r = drgate * rgate * (1.0 - rgate)
    dru_u = du * u * (1.0 - u)
    dz = jnp.concatenate([dru_r, dru_u, dc_pre], axis=1)    # input side
    drz = jnp.concatenate([dru_r, dru_u, drz_c], axis=1)    # recurrent
    dxw_ref[0] = dz
    dh_scr[:] = dh_carry + _dotT_rhs(drz, r_ref[:])
    dr_scr[:] = dr_scr[:] + _dotT_lhs(h_prev, drz)
    drb_scr[:] = drb_scr[:] + jnp.sum(drz, axis=0, keepdims=True)

    @pl.when(is_first_step)
    def _():
        dr_ref[:] = dr_scr[:]
        drb_ref[:] = drb_scr[:]
        dh0_ref[:] = dh_scr[:]


def _bwd_call(t, n, hsz, interpret, dhs, ru, rzc, cand, hs, r, h0, dhT):
    three_h = 3 * hsz
    rev = lambda i: (t - 1 - i, 0, 0)            # noqa: E731
    rev_prev = lambda i: (jnp.maximum(t - 2 - i, 0), 0, 0)  # noqa: E731
    return pl.pallas_call(
        _bwd_kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, n, hsz), rev),          # dhs
            pl.BlockSpec((1, n, 2 * hsz), rev),      # ru
            pl.BlockSpec((1, n, hsz), rev),          # rz_c
            pl.BlockSpec((1, n, hsz), rev),          # cand
            pl.BlockSpec((1, n, hsz), rev_prev),     # h_{t-1}
            pl.BlockSpec((hsz, three_h), lambda i: (0, 0)),
            pl.BlockSpec((n, hsz), lambda i: (0, 0)),    # h0
            pl.BlockSpec((n, hsz), lambda i: (0, 0)),    # dhT
        ],
        out_specs=[
            pl.BlockSpec((1, n, three_h), rev),      # dxw
            pl.BlockSpec((hsz, three_h), lambda i: (0, 0)),
            pl.BlockSpec((1, three_h), lambda i: (0, 0)),
            pl.BlockSpec((n, hsz), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, n, three_h), jnp.float32),
            jax.ShapeDtypeStruct((hsz, three_h), jnp.float32),
            jax.ShapeDtypeStruct((1, three_h), jnp.float32),
            jax.ShapeDtypeStruct((n, hsz), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((n, hsz), jnp.float32),
            pltpu.VMEM((hsz, three_h), jnp.float32),
            pltpu.VMEM((1, three_h), jnp.float32),
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(dhs, ru, rzc, cand, hs, r, h0, dhT)


# ---------------------------------------------------------------------------
# custom-vjp wrapper
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def gru_seq(xw, r, rb, h0, interpret=False):
    """Full GRU recurrence: xw [T,N,3H] (input projection + input bias
    pre-added), R [H,3H], rb [3H] recurrent bias, h0 [N,H] ->
    (hs [T,N,H], hT)."""
    hs, hT = _fwd_call(xw, r, rb, h0, interpret, save_residuals=False)
    return hs, hT


def _gru_seq_fwd(xw, r, rb, h0, interpret):
    hs, ru, rzc, cand = _fwd_call(xw, r, rb, h0, interpret,
                                  save_residuals=True)
    return (hs, hs[-1]), (ru, rzc, cand, hs, r, h0)


def _gru_seq_bwd(interpret, res, cts):
    ru, rzc, cand, hs, r, h0 = res
    dhs, dhT = cts
    t, n, hsz = dhs.shape
    dxw, dr, drb, dh0 = _bwd_call(t, n, hsz, interpret, dhs, ru, rzc,
                                  cand, hs, r, h0, dhT)
    return dxw, dr, drb.reshape(-1), dh0


gru_seq.defvjp(_gru_seq_fwd, _gru_seq_bwd)
