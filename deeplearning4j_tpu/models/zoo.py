"""Model zoo: canned architectures.

Reference capability: deeplearning4j-zoo org.deeplearning4j.zoo.model.*
(SURVEY.md §2.7): ZooModel.init() returns a ready network. Pretrained
weight download is environment-gated (no egress here); initPretrained
raises with a clear message instead.

Configs follow the reference's published architectures (LeNet, SimpleCNN,
AlexNet, VGG16, Darknet19, ResNet50); all lower to single jitted XLA steps
like any other net."""

from __future__ import annotations

from deeplearning4j_tpu.nn import (
    ActivationLayer, BatchNormalization, ComputationGraph, ConvolutionLayer,
    ConvolutionMode, Deconvolution2D, DenseLayer, DropoutLayer,
    ElementWiseVertex, GlobalPoolingLayer, InputType,
    LocalResponseNormalization, LossLayer, LSTM, MergeVertex,
    MultiLayerNetwork,
    NeuralNetConfiguration, OutputLayer, PoolingType, RnnOutputLayer,
    SeparableConvolution2D, SubsamplingLayer, WeightInit)
from deeplearning4j_tpu.optimize.updaters import Adam, Nesterovs


def _expected_num_params(conf) -> int:
    """Parameter count of a configuration WITHOUT materializing weights
    (jax.eval_shape traces init_params abstractly)."""
    import math

    import jax

    from deeplearning4j_tpu.nn.conf.graph_conf import (
        ComputationGraphConfiguration)

    if isinstance(conf, ComputationGraphConfiguration):
        inits = [node.init_params for node, _ in conf.nodes.values()
                 if hasattr(node, "init_params")]
    else:
        inits = [lr.init_params for lr in conf.layers]
    key = jax.random.key(0)
    total = 0
    for init in inits:
        shapes = jax.eval_shape(lambda k, f=init: f(k, conf.dtype), key)
        total += sum(math.prod(s.shape)
                     for s in jax.tree_util.tree_leaves(shapes))
    return total


class ZooModel:
    def init(self):
        raise NotImplementedError

    def initPretrained(self, weightsFile=None):
        """Reference: ZooModel.initPretrained() downloads + checksums a
        weight file, then loads it. No egress here, so the weight file
        must already be local: a Dl4jCheckpoint zip, a ModelSerializer
        zip, or a save_params_npz .npz of named layer params."""
        if weightsFile is None:
            raise ValueError(
                "no network access in this environment: pass "
                "initPretrained(weightsFile=...) pointing at a local "
                "checkpoint zip or params .npz")
        path = str(weightsFile)
        if path.endswith(".npz"):
            from deeplearning4j_tpu.utils.checkpoint import load_params_npz

            return load_params_npz(self.init(), path)
        import zipfile

        with zipfile.ZipFile(path) as zf:
            names = set(zf.namelist())
        if "coefficients.bin" in names:
            from deeplearning4j_tpu.utils.checkpoint import Dl4jCheckpoint

            loaded = Dl4jCheckpoint.load(path)
        else:
            from deeplearning4j_tpu.utils.serializer import ModelSerializer

            loaded = ModelSerializer._restore(path, None, loadUpdater=False)
        # the zip rebuilds from its own configuration.json — reject a
        # checkpoint for a different architecture instead of silently
        # returning whatever network the file holds. The expected count
        # comes from eval_shape (abstract init: no weights materialized)
        # when the model exposes conf(); small models without one pay a
        # real init.
        if hasattr(self, "conf"):
            expected = _expected_num_params(self.conf())
        else:
            expected = self.init().numParams()
        if loaded.numParams() != expected:
            raise ValueError(
                f"checkpoint {path!r} holds a "
                f"{loaded.numParams()}-param model, but "
                f"{type(self).__name__} has {expected} params "
                "— wrong weights for this zoo model")
        return loaded

    def metaData(self):
        return {"name": type(self).__name__}


class LeNet(ZooModel):
    """Reference: zoo.model.LeNet (the LeNet-MNIST baseline,
    BASELINE.json configs[0])."""

    def __init__(self, numClasses=10, seed=123, inputShape=(1, 28, 28),
                 updater=None):
        self.numClasses = numClasses
        self.seed = seed
        self.inputShape = inputShape
        self.updater = updater or Adam(1e-3)

    def conf(self):
        c, h, w = self.inputShape
        return (NeuralNetConfiguration.Builder().seed(self.seed)
                .updater(self.updater).weightInit(WeightInit.XAVIER)
                .list()
                .layer(ConvolutionLayer.Builder().nOut(20).kernelSize([5, 5])
                       .stride([1, 1]).activation("relu").build())
                .layer(SubsamplingLayer.Builder(poolingType=PoolingType.MAX)
                       .kernelSize([2, 2]).stride([2, 2]).build())
                .layer(ConvolutionLayer.Builder().nOut(50).kernelSize([5, 5])
                       .stride([1, 1]).activation("relu").build())
                .layer(SubsamplingLayer.Builder(poolingType=PoolingType.MAX)
                       .kernelSize([2, 2]).stride([2, 2]).build())
                .layer(DenseLayer.Builder().nOut(500).activation("relu")
                       .build())
                .layer(OutputLayer.Builder().nOut(self.numClasses)
                       .activation("softmax").lossFunction("mcxent").build())
                .setInputType(InputType.convolutionalFlat(h, w, c))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


class SimpleCNN(ZooModel):
    """Reference: zoo.model.SimpleCNN."""

    def __init__(self, numClasses=10, seed=123, inputShape=(3, 48, 48)):
        self.numClasses = numClasses
        self.seed = seed
        self.inputShape = inputShape

    def init(self) -> MultiLayerNetwork:
        c, h, w = self.inputShape
        conf = (NeuralNetConfiguration.Builder().seed(self.seed)
                .updater(Adam(1e-3)).weightInit(WeightInit.RELU)
                .list()
                .layer(ConvolutionLayer.Builder().nOut(16)
                       .kernelSize([3, 3])
                       .convolutionMode(ConvolutionMode.SAME)
                       .activation("relu").build())
                .layer(BatchNormalization.Builder().build())
                .layer(ConvolutionLayer.Builder().nOut(16)
                       .kernelSize([3, 3])
                       .convolutionMode(ConvolutionMode.SAME)
                       .activation("relu").build())
                .layer(SubsamplingLayer.Builder().kernelSize([2, 2])
                       .stride([2, 2]).build())
                .layer(ConvolutionLayer.Builder().nOut(32)
                       .kernelSize([3, 3])
                       .convolutionMode(ConvolutionMode.SAME)
                       .activation("relu").build())
                .layer(BatchNormalization.Builder().build())
                .layer(SubsamplingLayer.Builder().kernelSize([2, 2])
                       .stride([2, 2]).build())
                .layer(GlobalPoolingLayer.Builder().build())
                .layer(DropoutLayer.Builder().dropOut(0.5).build())
                .layer(OutputLayer.Builder().nOut(self.numClasses)
                       .activation("softmax").lossFunction("mcxent").build())
                .setInputType(InputType.convolutional(h, w, c))
                .build())
        return MultiLayerNetwork(conf).init()


class AlexNet(ZooModel):
    """Reference: zoo.model.AlexNet (LRN + grouped-conv-free variant)."""

    def __init__(self, numClasses=1000, seed=123, inputShape=(3, 224, 224)):
        self.numClasses = numClasses
        self.seed = seed
        self.inputShape = inputShape

    def init(self) -> MultiLayerNetwork:
        c, h, w = self.inputShape
        conf = (NeuralNetConfiguration.Builder().seed(self.seed)
                .updater(Nesterovs(1e-2, 0.9)).weightInit(WeightInit.RELU)
                .list()
                .layer(ConvolutionLayer.Builder().nOut(96)
                       .kernelSize([11, 11]).stride([4, 4])
                       .activation("relu").build())
                .layer(LocalResponseNormalization.Builder().build())
                .layer(SubsamplingLayer.Builder().kernelSize([3, 3])
                       .stride([2, 2]).build())
                .layer(ConvolutionLayer.Builder().nOut(256)
                       .kernelSize([5, 5]).padding([2, 2])
                       .activation("relu").build())
                .layer(LocalResponseNormalization.Builder().build())
                .layer(SubsamplingLayer.Builder().kernelSize([3, 3])
                       .stride([2, 2]).build())
                .layer(ConvolutionLayer.Builder().nOut(384)
                       .kernelSize([3, 3]).padding([1, 1])
                       .activation("relu").build())
                .layer(ConvolutionLayer.Builder().nOut(384)
                       .kernelSize([3, 3]).padding([1, 1])
                       .activation("relu").build())
                .layer(ConvolutionLayer.Builder().nOut(256)
                       .kernelSize([3, 3]).padding([1, 1])
                       .activation("relu").build())
                .layer(SubsamplingLayer.Builder().kernelSize([3, 3])
                       .stride([2, 2]).build())
                .layer(DenseLayer.Builder().nOut(4096).activation("relu")
                       .dropOut(0.5).build())
                .layer(DenseLayer.Builder().nOut(4096).activation("relu")
                       .dropOut(0.5).build())
                .layer(OutputLayer.Builder().nOut(self.numClasses)
                       .activation("softmax").lossFunction("mcxent").build())
                .setInputType(InputType.convolutional(h, w, c))
                .build())
        return MultiLayerNetwork(conf).init()


class VGG16(ZooModel):
    """Reference: zoo.model.VGG16. BLOCKS = (channels, conv-repeats) per
    pooled stage; VGG19 overrides it."""

    BLOCKS = ((64, 2), (128, 2), (256, 3), (512, 3), (512, 3))

    def __init__(self, numClasses=1000, seed=123, inputShape=(3, 224, 224)):
        self.numClasses = numClasses
        self.seed = seed
        self.inputShape = inputShape

    def init(self) -> MultiLayerNetwork:
        c, h, w = self.inputShape
        b = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Nesterovs(1e-2, 0.9)).weightInit(WeightInit.RELU)
             .list())

        def conv(n):
            return (ConvolutionLayer.Builder().nOut(n).kernelSize([3, 3])
                    .convolutionMode(ConvolutionMode.SAME)
                    .activation("relu").build())

        def pool():
            return (SubsamplingLayer.Builder().kernelSize([2, 2])
                    .stride([2, 2]).build())

        for n, reps in self.BLOCKS:
            for _ in range(reps):
                b = b.layer(conv(n))
            b = b.layer(pool())
        conf = (b
                .layer(DenseLayer.Builder().nOut(4096).activation("relu")
                       .dropOut(0.5).build())
                .layer(DenseLayer.Builder().nOut(4096).activation("relu")
                       .dropOut(0.5).build())
                .layer(OutputLayer.Builder().nOut(self.numClasses)
                       .activation("softmax").lossFunction("mcxent").build())
                .setInputType(InputType.convolutional(h, w, c))
                .build())
        return MultiLayerNetwork(conf).init()


class Darknet19(ZooModel):
    """Reference: zoo.model.Darknet19."""

    def __init__(self, numClasses=1000, seed=123, inputShape=(3, 224, 224)):
        self.numClasses = numClasses
        self.seed = seed
        self.inputShape = inputShape

    def init(self) -> MultiLayerNetwork:
        c, h, w = self.inputShape
        b = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weightInit(WeightInit.RELU).list())

        def conv(n, k):
            return (ConvolutionLayer.Builder().nOut(n).kernelSize([k, k])
                    .convolutionMode(ConvolutionMode.SAME)
                    .activation("leakyrelu").build())

        def bn():
            return BatchNormalization.Builder().build()

        def pool():
            return (SubsamplingLayer.Builder().kernelSize([2, 2])
                    .stride([2, 2]).build())

        plan = [(32, 3), "P", (64, 3), "P", (128, 3), (64, 1), (128, 3),
                "P", (256, 3), (128, 1), (256, 3), "P", (512, 3), (256, 1),
                (512, 3), (256, 1), (512, 3), "P", (1024, 3), (512, 1),
                (1024, 3), (512, 1), (1024, 3)]
        for item in plan:
            if item == "P":
                b = b.layer(pool())
            else:
                n, k = item
                b = b.layer(conv(n, k)).layer(bn())
        conf = (b.layer(ConvolutionLayer.Builder()
                        .nOut(self.numClasses).kernelSize([1, 1])
                        .convolutionMode(ConvolutionMode.SAME)
                        .activation("identity").build())
                .layer(GlobalPoolingLayer.Builder().build())
                .layer(LossLayer(lossFunction="mcxent",
                                 activation="softmax"))
                .setInputType(InputType.convolutional(h, w, c))
                .build())
        return MultiLayerNetwork(conf).init()


class ResNet50(ZooModel):
    """Reference: zoo.model.ResNet50 (the data-parallel throughput
    baseline, BASELINE.json configs[1]) — built as a ComputationGraph of
    bottleneck blocks with identity/projection shortcuts."""

    def __init__(self, numClasses=1000, seed=123, inputShape=(3, 224, 224),
                 updater=None, dataType="float32"):
        self.numClasses = numClasses
        self.seed = seed
        self.inputShape = inputShape
        self.updater = updater or Nesterovs(1e-2, 0.9)
        # "bfloat16" = TPU-idiomatic training dtype (the analog of the
        # reference's NeuralNetConfiguration.dataType(DataType.HALF));
        # measured on v5e it is ~1.5-2.6x the f32 throughput at b>=64
        self.dataType = dataType

    def conf(self):
        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .dataType(self.dataType)
             .updater(self.updater).weightInit(WeightInit.RELU)
             .graphBuilder()
             .addInputs("in"))
        g.setInputTypes(InputType.convolutional(h, w, c))

        def conv(name, n, k, s, inp, act="identity", pad_same=True):
            g.addLayer(name,
                       ConvolutionLayer.Builder().nOut(n)
                       .kernelSize([k, k]).stride([s, s])
                       .convolutionMode(ConvolutionMode.SAME if pad_same
                                        else ConvolutionMode.TRUNCATE)
                       .activation(act).build(), inp)
            return name

        def bn(name, inp, act="identity"):
            g.addLayer(name,
                       BatchNormalization.Builder().activation(act).build(),
                       inp)
            return name

        # stem
        x = conv("conv1", 64, 7, 2, "in")
        x = bn("bn1", x, "relu")
        g.addLayer("pool1",
                   SubsamplingLayer.Builder().kernelSize([3, 3])
                   .stride([2, 2]).convolutionMode(ConvolutionMode.SAME)
                   .build(), x)
        x = "pool1"

        def bottleneck(tag, inp, filters, stride, project):
            f1, f2, f3 = filters
            a = conv(f"{tag}_c1", f1, 1, stride, inp)
            a = bn(f"{tag}_b1", a, "relu")
            a = conv(f"{tag}_c2", f2, 3, 1, a)
            a = bn(f"{tag}_b2", a, "relu")
            a = conv(f"{tag}_c3", f3, 1, 1, a)
            a = bn(f"{tag}_b3", a)
            if project:
                s = conv(f"{tag}_proj", f3, 1, stride, inp)
                s = bn(f"{tag}_projbn", s)
            else:
                s = inp
            g.addVertex(f"{tag}_add", ElementWiseVertex("Add"), a, s)
            g.addLayer(f"{tag}_out",
                       ActivationLayer.Builder().activation("relu").build(),
                       f"{tag}_add")
            return f"{tag}_out"

        stages = [
            ("s2", 3, (64, 64, 256), 1),
            ("s3", 4, (128, 128, 512), 2),
            ("s4", 6, (256, 256, 1024), 2),
            ("s5", 3, (512, 512, 2048), 2),
        ]
        for stage, blocks, filters, stride in stages:
            for i in range(blocks):
                x = bottleneck(f"{stage}_{i}", x, filters,
                               stride if i == 0 else 1, i == 0)

        g.addLayer("avgpool", GlobalPoolingLayer.Builder().build(), x)
        g.addLayer("out",
                   OutputLayer.Builder().nOut(self.numClasses)
                   .activation("softmax").lossFunction("mcxent").build(),
                   "avgpool")
        g.setOutputs("out")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


class TextGenerationLSTM(ZooModel):
    """Reference: zoo.model.TextGenerationLSTM (GravesLSTM char-RNN
    baseline, BASELINE.json configs[2])."""

    def __init__(self, vocabSize=77, hidden=256, seqLength=100, seed=123,
                 updater=None):
        self.vocabSize = vocabSize
        self.hidden = hidden
        self.seqLength = seqLength
        self.seed = seed
        self.updater = updater or Adam(2e-3)

    def conf(self):
        return (NeuralNetConfiguration.Builder().seed(self.seed)
                .updater(self.updater).weightInit(WeightInit.XAVIER)
                .list()
                .layer(LSTM.Builder().nOut(self.hidden).activation("tanh")
                       .build())
                .layer(LSTM.Builder().nOut(self.hidden).activation("tanh")
                       .build())
                .layer(RnnOutputLayer.Builder().nOut(self.vocabSize)
                       .activation("softmax").lossFunction("mcxent").build())
                .setInputType(InputType.recurrent(self.vocabSize,
                                                  self.seqLength))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


class UNet(ZooModel):
    """Reference: zoo.model.UNet (encoder-decoder segmentation net with
    skip concatenations; Deconvolution2D upsampling). Width `base` scales
    the published 64-filter config down for small inputs."""

    def __init__(self, numClasses=1, seed=123, inputShape=(3, 128, 128),
                 base=64, updater=None, dataType="float32"):
        self.numClasses = numClasses
        self.seed = seed
        self.inputShape = inputShape
        self.base = base
        self.updater = updater or Adam(1e-3)
        self.dataType = dataType

    def conf(self):
        from deeplearning4j_tpu.nn import MergeVertex

        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .dataType(self.dataType)
             .updater(self.updater).weightInit(WeightInit.RELU)
             .graphBuilder().addInputs("in"))
        g.setInputTypes(InputType.convolutional(h, w, c))

        def conv(name, n, inp, act="relu", k=3):
            g.addLayer(name, ConvolutionLayer.Builder().nOut(n)
                       .kernelSize([k, k]).stride([1, 1])
                       .convolutionMode(ConvolutionMode.SAME)
                       .activation(act).build(), inp)
            return name

        def down(tag, n, inp):
            a = conv(f"{tag}_c1", n, inp)
            a = conv(f"{tag}_c2", n, a)
            g.addLayer(f"{tag}_pool", SubsamplingLayer.Builder()
                       .kernelSize([2, 2]).stride([2, 2]).build(), a)
            return a, f"{tag}_pool"

        def up(tag, n, inp, skip):
            g.addLayer(f"{tag}_up", Deconvolution2D.Builder().nOut(n)
                       .kernelSize([2, 2]).stride([2, 2])
                       .convolutionMode(ConvolutionMode.SAME)
                       .activation("relu").build(), inp)
            g.addVertex(f"{tag}_cat", MergeVertex(), f"{tag}_up", skip)
            a = conv(f"{tag}_c1", n, f"{tag}_cat")
            return conv(f"{tag}_c2", n, a)

        b = self.base
        s1, x = down("d1", b, "in")
        s2, x = down("d2", b * 2, x)
        s3, x = down("d3", b * 4, x)
        x = conv("mid_c1", b * 8, x)
        x = conv("mid_c2", b * 8, x)
        x = up("u3", b * 4, x, s3)
        x = up("u2", b * 2, x, s2)
        x = up("u1", b, x, s1)
        # 1x1 conv to class logits + per-pixel sigmoid loss (UNet's
        # published single-channel mask head)
        conv("logits", self.numClasses, x, act="identity", k=1)
        g.addLayer("out", LossLayer(lossFunction="xent",
                                    activation="sigmoid"), "logits")
        g.setOutputs("out")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


class SqueezeNet(ZooModel):
    """Reference: zoo.model.SqueezeNet (v1.1: fire modules — 1x1
    squeeze, parallel 1x1/3x3 expands concatenated)."""

    def __init__(self, numClasses=1000, seed=123, inputShape=(3, 227, 227),
                 updater=None, dataType="float32"):
        self.numClasses = numClasses
        self.seed = seed
        self.inputShape = inputShape
        self.updater = updater or Adam(1e-3)
        self.dataType = dataType

    def conf(self):
        from deeplearning4j_tpu.nn import MergeVertex

        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .dataType(self.dataType)
             .updater(self.updater).weightInit(WeightInit.RELU)
             .graphBuilder().addInputs("in"))
        g.setInputTypes(InputType.convolutional(h, w, c))

        def fire(tag, inp, squeeze, expand):
            g.addLayer(f"{tag}_sq", ConvolutionLayer.Builder().nOut(squeeze)
                       .kernelSize([1, 1]).stride([1, 1])
                       .activation("relu").build(), inp)
            g.addLayer(f"{tag}_e1", ConvolutionLayer.Builder().nOut(expand)
                       .kernelSize([1, 1]).stride([1, 1])
                       .activation("relu").build(), f"{tag}_sq")
            g.addLayer(f"{tag}_e3", ConvolutionLayer.Builder().nOut(expand)
                       .kernelSize([3, 3]).stride([1, 1])
                       .convolutionMode(ConvolutionMode.SAME)
                       .activation("relu").build(), f"{tag}_sq")
            g.addVertex(f"{tag}_cat", MergeVertex(), f"{tag}_e1",
                        f"{tag}_e3")
            return f"{tag}_cat"

        g.addLayer("conv1", ConvolutionLayer.Builder().nOut(64)
                   .kernelSize([3, 3]).stride([2, 2]).activation("relu")
                   .build(), "in")
        g.addLayer("pool1", SubsamplingLayer.Builder().kernelSize([3, 3])
                   .stride([2, 2]).build(), "conv1")
        x = fire("f2", "pool1", 16, 64)
        x = fire("f3", x, 16, 64)
        g.addLayer("pool3", SubsamplingLayer.Builder().kernelSize([3, 3])
                   .stride([2, 2]).build(), x)
        x = fire("f4", "pool3", 32, 128)
        x = fire("f5", x, 32, 128)
        g.addLayer("pool5", SubsamplingLayer.Builder().kernelSize([3, 3])
                   .stride([2, 2]).build(), x)
        x = fire("f6", "pool5", 48, 192)
        x = fire("f7", x, 48, 192)
        x = fire("f8", x, 64, 256)
        x = fire("f9", x, 64, 256)
        g.addLayer("drop", DropoutLayer.Builder().dropOut(0.5).build(), x)
        g.addLayer("conv10", ConvolutionLayer.Builder()
                   .nOut(self.numClasses).kernelSize([1, 1]).stride([1, 1])
                   .activation("relu").build(), "drop")
        g.addLayer("gap", GlobalPoolingLayer.Builder().build(), "conv10")
        g.addLayer("out", LossLayer(lossFunction="mcxent",
                                    activation="softmax"), "gap")
        g.setOutputs("out")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


class Xception(ZooModel):
    """Reference: zoo.model.Xception (depthwise-separable convolutions
    with residual shortcuts; `blocks` scales the published 8-block middle
    flow for small inputs)."""

    def __init__(self, numClasses=1000, seed=123, inputShape=(3, 299, 299),
                 blocks=8, updater=None, dataType="float32"):
        self.numClasses = numClasses
        self.seed = seed
        self.inputShape = inputShape
        self.blocks = blocks
        self.updater = updater or Adam(1e-3)
        self.dataType = dataType

    def conf(self):
        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .dataType(self.dataType)
             .updater(self.updater).weightInit(WeightInit.RELU)
             .graphBuilder().addInputs("in"))
        g.setInputTypes(InputType.convolutional(h, w, c))

        def sep(name, n, inp, act="relu"):
            g.addLayer(name, SeparableConvolution2D.Builder().nOut(n)
                       .kernelSize([3, 3]).stride([1, 1])
                       .convolutionMode(ConvolutionMode.SAME)
                       .activation(act).build(), inp)
            return name

        def bn(name, inp, act="identity"):
            g.addLayer(name, BatchNormalization.Builder().activation(act)
                       .build(), inp)
            return name

        # entry flow (compressed: conv stem + one strided sep block)
        g.addLayer("conv1", ConvolutionLayer.Builder().nOut(32)
                   .kernelSize([3, 3]).stride([2, 2]).activation("relu")
                   .build(), "in")
        x = bn("bn1", "conv1", "relu")
        g.addLayer("conv2", ConvolutionLayer.Builder().nOut(64)
                   .kernelSize([3, 3]).stride([1, 1]).activation("relu")
                   .build(), x)
        x = bn("bn2", "conv2", "relu")
        mid = 128
        a = sep("entry_s1", mid, x)
        a = bn("entry_b1", a, "relu")
        a = sep("entry_s2", mid, a)
        a = bn("entry_b2", a)
        g.addLayer("entry_pool", SubsamplingLayer.Builder()
                   .kernelSize([3, 3]).stride([2, 2])
                   .convolutionMode(ConvolutionMode.SAME).build(), a)
        g.addLayer("entry_proj", ConvolutionLayer.Builder().nOut(mid)
                   .kernelSize([1, 1]).stride([2, 2]).build(), x)
        g.addVertex("entry_add", ElementWiseVertex("Add"), "entry_pool",
                    "entry_proj")
        x = "entry_add"

        # middle flow: residual triple-separable blocks
        for i in range(self.blocks):
            tag = f"mid{i}"
            a = sep(f"{tag}_s1", mid, x)
            a = bn(f"{tag}_b1", a, "relu")
            a = sep(f"{tag}_s2", mid, a)
            a = bn(f"{tag}_b2", a, "relu")
            a = sep(f"{tag}_s3", mid, a)
            a = bn(f"{tag}_b3", a)
            g.addVertex(f"{tag}_add", ElementWiseVertex("Add"), a, x)
            x = f"{tag}_add"

        # exit flow
        a = sep("exit_s1", mid * 2, x)
        a = bn("exit_b1", a, "relu")
        g.addLayer("gap", GlobalPoolingLayer.Builder().build(), a)
        g.addLayer("out", OutputLayer.Builder().nOut(self.numClasses)
                   .activation("softmax").lossFunction("mcxent").build(),
                   "gap")
        g.setOutputs("out")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


class TinyYOLO(ZooModel):
    """Reference: zoo.model.TinyYOLO (tiny-YOLOv2 on VOC: 5 anchor priors,
    20 classes, 416x416 input -> 13x13 grid)."""

    PRIORS = [[1.08, 1.19], [3.42, 4.41], [6.63, 11.38], [9.42, 5.11],
              [16.62, 10.52]]

    def __init__(self, numClasses=20, seed=123, inputShape=(3, 416, 416),
                 boundingBoxPriors=None, updater=None):
        self.numClasses = numClasses
        self.seed = seed
        self.inputShape = inputShape
        self.priors = (boundingBoxPriors if boundingBoxPriors is not None
                       else self.PRIORS)
        self.updater = updater or Adam(1e-3)

    def conf(self):
        from deeplearning4j_tpu.nn.conf.objdetect import Yolo2OutputLayer

        c, h, w = self.inputShape
        b = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(self.updater).weightInit(WeightInit.RELU).list())

        def conv(n, k=3):
            return (ConvolutionLayer.Builder().nOut(n).kernelSize([k, k])
                    .convolutionMode(ConvolutionMode.SAME)
                    .activation("identity").hasBias(False).build())

        def bn():
            return (BatchNormalization.Builder().activation("leakyrelu")
                    .build())

        for n in (16, 32, 64, 128, 256):
            b = (b.layer(conv(n)).layer(bn())
                 .layer(SubsamplingLayer.Builder().kernelSize([2, 2])
                        .stride([2, 2]).build()))
        # stride-1 SAME pool keeps the 13x13 grid (tiny-YOLOv2 layer 6)
        b = (b.layer(conv(512)).layer(bn())
             .layer(SubsamplingLayer.Builder().kernelSize([2, 2])
                    .stride([1, 1])
                    .convolutionMode(ConvolutionMode.SAME).build()))
        for n in (1024, 1024):
            b = b.layer(conv(n)).layer(bn())
        n_out = len(self.priors) * (5 + self.numClasses)
        return (b.layer(ConvolutionLayer.Builder().nOut(n_out)
                        .kernelSize([1, 1])
                        .convolutionMode(ConvolutionMode.SAME)
                        .activation("identity").build())
                .layer(Yolo2OutputLayer(boundingBoxPriors=self.priors))
                .setInputType(InputType.convolutional(h, w, c))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


class YOLO2(ZooModel):
    """Reference: zoo.model.YOLO2 — Darknet-19 backbone + the SpaceToDepth
    'reorg' passthrough merging the 26x26 mid-level features into the
    13x13 head (built as a ComputationGraph, like the reference)."""

    PRIORS = [[0.57273, 0.677385], [1.87446, 2.06253], [3.33843, 5.47434],
              [7.88282, 3.52778], [9.77052, 9.16828]]

    def __init__(self, numClasses=80, seed=123, inputShape=(3, 416, 416),
                 boundingBoxPriors=None, updater=None):
        self.numClasses = numClasses
        self.seed = seed
        self.inputShape = inputShape
        self.priors = (boundingBoxPriors if boundingBoxPriors is not None
                       else self.PRIORS)
        self.updater = updater or Adam(1e-3)

    def conf(self):
        from deeplearning4j_tpu.nn import MergeVertex
        from deeplearning4j_tpu.nn.conf.layers import SpaceToDepth
        from deeplearning4j_tpu.nn.conf.objdetect import Yolo2OutputLayer

        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(self.updater).weightInit(WeightInit.RELU)
             .graphBuilder()
             .addInputs("in"))
        g.setInputTypes(InputType.convolutional(h, w, c))

        idx = [0]

        def conv(n, k, x):
            name = f"c{idx[0]}"
            idx[0] += 1
            g.addLayer(name, ConvolutionLayer.Builder().nOut(n)
                       .kernelSize([k, k])
                       .convolutionMode(ConvolutionMode.SAME)
                       .activation("identity").hasBias(False).build(), x)
            g.addLayer(name + "b", BatchNormalization.Builder()
                       .activation("leakyrelu").build(), name)
            return name + "b"

        def pool(x):
            name = f"p{idx[0]}"
            idx[0] += 1
            g.addLayer(name, SubsamplingLayer.Builder().kernelSize([2, 2])
                       .stride([2, 2]).build(), x)
            return name

        # darknet-19 trunk
        x = conv(32, 3, "in")
        x = pool(x)
        x = conv(64, 3, x)
        x = pool(x)
        for n1, n2 in ((128, 64), (256, 128)):
            x = conv(n1, 3, x)
            x = conv(n2, 1, x)
            x = conv(n1, 3, x)
            x = pool(x)
        x = conv(512, 3, x)
        x = conv(256, 1, x)
        x = conv(512, 3, x)
        x = conv(256, 1, x)
        x = conv(512, 3, x)
        passthrough = x                     # 26x26x512 mid-level features
        x = pool(x)
        x = conv(1024, 3, x)
        x = conv(512, 1, x)
        x = conv(1024, 3, x)
        x = conv(512, 1, x)
        x = conv(1024, 3, x)
        x = conv(1024, 3, x)
        x = conv(1024, 3, x)

        # reorg passthrough: 1x1 conv to 64ch, then 26x26x64 -> 13x13x256,
        # concat with the 13x13x1024 head (YOLOv2 layout)
        p = conv(64, 1, passthrough)
        g.addLayer("reorg", SpaceToDepth.Builder().blockSize(2).build(), p)
        g.addVertex("cat", MergeVertex(), "reorg", x)
        x = conv(1024, 3, "cat")
        n_out = len(self.priors) * (5 + self.numClasses)
        g.addLayer("head", ConvolutionLayer.Builder().nOut(n_out)
                   .kernelSize([1, 1]).convolutionMode(ConvolutionMode.SAME)
                   .activation("identity").build(), x)
        g.addLayer("out", Yolo2OutputLayer(boundingBoxPriors=self.priors),
                   "head")
        g.setOutputs("out")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


class NASNet(ZooModel):
    """Reference: zoo.model.NASNet (NASNet-A Mobile: numBlocks normal
    cells per stage, reduction cells between stages,
    penultimateFilters = 24 * base filter count). Cell topology follows
    NASNet-A: each cell squeezes its two inputs (h, h_prev) to the
    stage's filter count with 1x1 conv+BN, runs the published 5-branch
    separable-conv/pool block mix, and concatenates the branch outputs;
    reduction cells stride 2 with a strided 1x1 projection as the
    h_prev spatial adjust (capability-parity stand-in for the factorized
    reduction)."""

    def __init__(self, numClasses=1000, seed=123, inputShape=(3, 224, 224),
                 numBlocks=4, penultimateFilters=1056, stemFilters=32,
                 updater=None, dataType="float32"):
        if penultimateFilters % 24:
            raise ValueError(
                f"penultimateFilters must be divisible by 24 (NASNet-A "
                f"concat width), got {penultimateFilters}")
        self.numClasses = numClasses
        self.seed = seed
        self.inputShape = inputShape
        self.numBlocks = numBlocks
        self.penultimateFilters = penultimateFilters
        self.stemFilters = stemFilters
        self.updater = updater or Adam(1e-3)
        self.dataType = dataType

    def conf(self):
        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .dataType(self.dataType)
             .updater(self.updater).weightInit(WeightInit.RELU)
             .graphBuilder().addInputs("in"))
        g.setInputTypes(InputType.convolutional(h, w, c))
        f0 = self.penultimateFilters // 24

        def conv1x1(name, n, inp, stride=1):
            g.addLayer(f"{name}_c", ConvolutionLayer.Builder().nOut(n)
                       .kernelSize([1, 1]).stride([stride, stride])
                       .convolutionMode(ConvolutionMode.SAME)
                       .activation("relu").build(), inp)
            g.addLayer(name, BatchNormalization.Builder().build(),
                       f"{name}_c")
            return name

        def sep_block(name, n, k, stride, inp):
            """relu -> sepconv(k, stride) -> bn -> relu -> sepconv(k) -> bn
            (the NASNet separable stack)."""
            g.addLayer(f"{name}_s1", SeparableConvolution2D.Builder()
                       .nOut(n).kernelSize([k, k]).stride([stride, stride])
                       .convolutionMode(ConvolutionMode.SAME)
                       .activation("relu").build(), inp)
            g.addLayer(f"{name}_b1", BatchNormalization.Builder()
                       .activation("relu").build(), f"{name}_s1")
            g.addLayer(f"{name}_s2", SeparableConvolution2D.Builder()
                       .nOut(n).kernelSize([k, k]).stride([1, 1])
                       .convolutionMode(ConvolutionMode.SAME)
                       .activation("identity").build(), f"{name}_b1")
            g.addLayer(name, BatchNormalization.Builder().build(),
                       f"{name}_s2")
            return name

        def pool(name, kind, stride, inp):
            g.addLayer(name, SubsamplingLayer.Builder()
                       .poolingType(kind)
                       .kernelSize([3, 3]).stride([stride, stride])
                       .convolutionMode(ConvolutionMode.SAME).build(), inp)
            return name

        def add(name, a, b):
            g.addVertex(name, ElementWiseVertex("Add"), a, b)
            return name

        # spatial size (square) per tensor name: the h_prev input of a
        # cell that follows a reduction is at 2x the cell resolution, so
        # its 1x1 adjust must stride by size[p] // target
        sz = {}

        def normal_cell(tag, p, x, n):
            hq = conv1x1(f"{tag}_hq", n, x)
            # ceil-divide: odd sizes (e.g. 15 -> 8 under SAME/s2) need
            # stride 2 even though floor(15/8) = 1
            pq = conv1x1(f"{tag}_pq", n, p, stride=-(-sz[p] // sz[x]))
            sz[f"{tag}_out"] = sz[x]
            b1 = add(f"{tag}_b1", sep_block(f"{tag}_b1l", n, 3, 1, hq),
                     sep_block(f"{tag}_b1r", n, 5, 1, pq))
            b2 = add(f"{tag}_b2", sep_block(f"{tag}_b2l", n, 5, 1, pq),
                     sep_block(f"{tag}_b2r", n, 3, 1, pq))
            b3 = add(f"{tag}_b3", pool(f"{tag}_b3l", PoolingType.AVG, 1,
                                       hq), pq)
            b4 = add(f"{tag}_b4", pool(f"{tag}_b4l", PoolingType.AVG, 1,
                                       pq),
                     pool(f"{tag}_b4r", PoolingType.AVG, 1, pq))
            b5 = add(f"{tag}_b5", sep_block(f"{tag}_b5l", n, 3, 1, hq),
                     hq)
            g.addVertex(f"{tag}_out", MergeVertex(), pq, b1, b2, b3, b4,
                        b5)
            return f"{tag}_out"

        def reduction_cell(tag, p, x, n):
            target = -(-sz[x] // 2)
            hq = conv1x1(f"{tag}_hq", n, x)
            pq = conv1x1(f"{tag}_pq", n, p, stride=-(-sz[p] // target))
            sz[f"{tag}_out"] = target
            # pq is already stride-adjusted to the target size, so every
            # pq-side branch runs stride 1; hq-side branches stride 2
            b1 = add(f"{tag}_b1", sep_block(f"{tag}_b1l", n, 5, 2, hq),
                     sep_block(f"{tag}_b1r", n, 7, 1, pq))
            b2 = add(f"{tag}_b2", pool(f"{tag}_b2l", PoolingType.MAX, 2,
                                       hq),
                     sep_block(f"{tag}_b2r", n, 7, 1, pq))
            b3 = add(f"{tag}_b3", pool(f"{tag}_b3l", PoolingType.AVG, 2,
                                       hq),
                     sep_block(f"{tag}_b3r", n, 5, 1, pq))
            b4 = add(f"{tag}_b4", pool(f"{tag}_b4l", PoolingType.MAX, 2,
                                       hq),
                     sep_block(f"{tag}_b4r", n, 3, 1, b1))
            b5 = add(f"{tag}_b5", pool(f"{tag}_b5l", PoolingType.AVG, 1,
                                       b1), b2)
            g.addVertex(f"{tag}_out", MergeVertex(), b2, b3, b4, b5)
            return f"{tag}_out"

        # stem
        g.addLayer("stem_conv", ConvolutionLayer.Builder()
                   .nOut(self.stemFilters).kernelSize([3, 3])
                   .stride([2, 2]).convolutionMode(ConvolutionMode.SAME)
                   .build(), "in")
        g.addLayer("stem_bn", BatchNormalization.Builder().build(),
                   "stem_conv")
        sz["stem_bn"] = -(-h // 2)
        p, x = "stem_bn", reduction_cell("stem_r1", "stem_bn", "stem_bn",
                                         f0 // 2 or 1)
        p, x = x, reduction_cell("stem_r2", p, x, f0 // 2 or 1)

        filters = f0
        for stage in range(3):
            for i in range(self.numBlocks):
                p, x = x, normal_cell(f"s{stage}n{i}", p, x, filters)
            if stage < 2:
                p, x = x, reduction_cell(f"s{stage}r", p, x, filters * 2)
                filters *= 2

        g.addLayer("relu_out", ActivationLayer.Builder()
                   .activation("relu").build(), x)
        g.addLayer("gap", GlobalPoolingLayer.Builder().build(),
                   "relu_out")
        g.addLayer("out", OutputLayer.Builder().nOut(self.numClasses)
                   .activation("softmax").lossFunction("mcxent").build(),
                   "gap")
        g.setOutputs("out")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


class VGG19(VGG16):
    """Reference: zoo.model.VGG19 — VGG16 with a 4th conv in the last
    three blocks (same builder, different BLOCKS)."""

    BLOCKS = ((64, 2), (128, 2), (256, 4), (512, 4), (512, 4))


class FaceNetNN4Small2(ZooModel):
    """Reference: zoo.model.FaceNetNN4Small2 — the face-embedding model
    trained with CenterLossOutputLayer. Inception-style graph: stem convs,
    mixed 1x1/3x3/5x5/pool towers merged on the channel axis, embedding
    dense layer, center-loss softmax head."""

    def __init__(self, numClasses=10, seed=123, inputShape=(3, 96, 96),
                 embeddingSize=128, lambdaCoeff=2e-4, updater=None):
        self.numClasses = numClasses
        self.seed = seed
        self.inputShape = inputShape
        self.embeddingSize = embeddingSize
        self.lambdaCoeff = lambdaCoeff
        self.updater = updater or Adam(1e-3)

    def conf(self):
        from deeplearning4j_tpu.nn import (
            CenterLossOutputLayer, L2NormalizeVertex, MergeVertex)

        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(self.updater).weightInit(WeightInit.RELU)
             .graphBuilder().addInputs("in"))
        g.setInputTypes(InputType.convolutional(h, w, c))

        def conv(name, src, n, k, s=1):
            g.addLayer(name, ConvolutionLayer.Builder().nOut(n)
                       .kernelSize([k, k]).stride([s, s])
                       .convolutionMode(ConvolutionMode.SAME)
                       .activation("identity").hasBias(False).build(), src)
            g.addLayer(name + "_bn", BatchNormalization.Builder()
                       .activation("relu").build(), name)
            return name + "_bn"

        # stem
        x = conv("stem1", "in", 64, 7, 2)
        g.addLayer("stem_pool", SubsamplingLayer.Builder()
                   .kernelSize([3, 3]).stride([2, 2])
                   .convolutionMode(ConvolutionMode.SAME).build(), x)
        x = conv("stem2", "stem_pool", 64, 1)
        x = conv("stem3", x, 192, 3)
        g.addLayer("stem_pool2", SubsamplingLayer.Builder()
                   .kernelSize([3, 3]).stride([2, 2])
                   .convolutionMode(ConvolutionMode.SAME).build(), x)
        x = "stem_pool2"

        # inception blocks: (1x1, 3x3 reduce->3x3, 5x5 reduce->5x5, pool->1x1)
        def inception(tag, src, n1, r3, n3, r5, n5, np_):
            t1 = conv(f"{tag}_1x1", src, n1, 1)
            t3 = conv(f"{tag}_3r", src, r3, 1)
            t3 = conv(f"{tag}_3x3", t3, n3, 3)
            t5 = conv(f"{tag}_5r", src, r5, 1)
            t5 = conv(f"{tag}_5x5", t5, n5, 5)
            g.addLayer(f"{tag}_pool", SubsamplingLayer.Builder()
                       .kernelSize([3, 3]).stride([1, 1])
                       .convolutionMode(ConvolutionMode.SAME).build(), src)
            tp = conv(f"{tag}_poolproj", f"{tag}_pool", np_, 1)
            g.addVertex(f"{tag}_cat", MergeVertex(), t1, t3, t5, tp)
            return f"{tag}_cat"

        x = inception("inc1", x, 64, 96, 128, 16, 32, 32)
        x = inception("inc2", x, 64, 96, 128, 32, 64, 64)
        g.addLayer("red_pool", SubsamplingLayer.Builder()
                   .kernelSize([3, 3]).stride([2, 2])
                   .convolutionMode(ConvolutionMode.SAME).build(), x)
        x = inception("inc3", "red_pool", 128, 96, 192, 32, 64, 64)

        # embedding + center-loss head
        g.addLayer("gap", GlobalPoolingLayer.Builder().build(), x)
        g.addLayer("embedding", DenseLayer.Builder()
                   .nOut(self.embeddingSize).activation("identity").build(),
                   "gap")
        g.addVertex("l2norm", L2NormalizeVertex(), "embedding")
        g.addLayer("out", CenterLossOutputLayer.Builder()
                   .nOut(self.numClasses).lambdaCoeff(self.lambdaCoeff)
                   .activation("softmax").lossFunction("mcxent").build(),
                   "l2norm")
        g.setOutputs("out")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


class InceptionResNetV1(ZooModel):
    """Reference: zoo.model.InceptionResNetV1 (the FaceNet-class
    inception-resnet: stem + residual inception blocks with a scale on
    the residual branch, embedding + center-loss head like
    FaceNetNN4Small2)."""

    def __init__(self, numClasses=10, seed=123, inputShape=(3, 96, 96),
                 embeddingSize=128, blocksA=2, blocksB=2, lambdaCoeff=2e-4,
                 updater=None):
        self.numClasses = numClasses
        self.seed = seed
        self.inputShape = inputShape
        self.embeddingSize = embeddingSize
        self.blocksA = blocksA
        self.blocksB = blocksB
        self.lambdaCoeff = lambdaCoeff
        self.updater = updater or Adam(1e-3)

    def conf(self):
        from deeplearning4j_tpu.nn import (
            CenterLossOutputLayer, L2NormalizeVertex, MergeVertex,
            ScaleVertex)

        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(self.updater).weightInit(WeightInit.RELU)
             .graphBuilder().addInputs("in"))
        g.setInputTypes(InputType.convolutional(h, w, c))

        def conv(name, src, n, k, s=1, act="relu"):
            g.addLayer(name, ConvolutionLayer.Builder().nOut(n)
                       .kernelSize([k, k]).stride([s, s])
                       .convolutionMode(ConvolutionMode.SAME)
                       .activation("identity").hasBias(False).build(), src)
            g.addLayer(name + "_bn", BatchNormalization.Builder()
                       .activation(act).build(), name)
            return name + "_bn"

        # stem: conv s2, conv, conv, pool -> width 64
        x = conv("stem1", "in", 32, 3, 2)
        x = conv("stem2", x, 32, 3)
        x = conv("stem3", x, 64, 3)
        g.addLayer("stem_pool", SubsamplingLayer.Builder()
                   .kernelSize([3, 3]).stride([2, 2])
                   .convolutionMode(ConvolutionMode.SAME).build(), x)
        x = conv("stem4", "stem_pool", 128, 1)

        def block(tag, src, width, mid, scale=0.17):
            """Inception-resnet block: two towers -> 1x1 up-proj,
            residual-added with a scale (the V1 stabilization)."""
            t1 = conv(f"{tag}_1x1", src, mid, 1)
            t2 = conv(f"{tag}_3a", src, mid, 1)
            t2 = conv(f"{tag}_3b", t2, mid, 3)
            g.addVertex(f"{tag}_cat", MergeVertex(), t1, t2)
            up = conv(f"{tag}_up", f"{tag}_cat", width, 1, act="identity")
            g.addVertex(f"{tag}_scale", ScaleVertex(scale), up)
            g.addVertex(f"{tag}_add", ElementWiseVertex("Add"), src,
                        f"{tag}_scale")
            g.addLayer(f"{tag}_act", ActivationLayer.Builder()
                       .activation("relu").build(), f"{tag}_add")
            return f"{tag}_act"

        for i in range(self.blocksA):
            x = block(f"ira{i}", x, 128, 32)
        # reduction: stride-2 pool + channel up-projection
        g.addLayer("redA_pool", SubsamplingLayer.Builder()
                   .kernelSize([3, 3]).stride([2, 2])
                   .convolutionMode(ConvolutionMode.SAME).build(), x)
        x = conv("redA_proj", "redA_pool", 256, 1)
        for i in range(self.blocksB):
            x = block(f"irb{i}", x, 256, 64, scale=0.1)

        g.addLayer("gap", GlobalPoolingLayer.Builder().build(), x)
        g.addLayer("embedding", DenseLayer.Builder()
                   .nOut(self.embeddingSize).activation("identity").build(),
                   "gap")
        g.addVertex("l2norm", L2NormalizeVertex(), "embedding")
        g.addLayer("out", CenterLossOutputLayer.Builder()
                   .nOut(self.numClasses).lambdaCoeff(self.lambdaCoeff)
                   .activation("softmax").lossFunction("mcxent").build(),
                   "l2norm")
        g.setOutputs("out")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()
