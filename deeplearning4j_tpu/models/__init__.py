"""Model zoo + flagship models (reference L6: deeplearning4j-zoo,
SURVEY.md §2.7)."""

from deeplearning4j_tpu.models.zoo import (  # noqa: F401
    AlexNet, Darknet19, FaceNetNN4Small2, InceptionResNetV1, LeNet,
    NASNet, ResNet50, SimpleCNN, SqueezeNet, TextGenerationLSTM,
    TinyYOLO, UNet, VGG16, VGG19, Xception, YOLO2, ZooModel)
from deeplearning4j_tpu.models.bert import (  # noqa: F401
    BertConfig, BertTrainer, forward as bert_forward,
    init_params as bert_init_params, mlm_loss, param_specs as
    bert_param_specs, synthetic_mlm_batch)
