"""BERT-capability transformer encoder, TPU-first.

Reference capability: the BERT-base SameDiff TF-import path (SURVEY.md
§3.4, BASELINE.json configs[3]). The reference imports a frozen GraphDef
and interprets it op-by-op; here the model is a native graph-level module:
pure init/forward functions over an explicit param pytree, compiled to ONE
XLA step with GSPMD shardings:

  - data parallel: batch axis over 'data'
  - tensor parallel: Megatron column/row pairs over 'model' (QKV + FFN-in
    column-parallel, attn-out + FFN-out row-parallel)
  - sequence parallel: ring attention over 'seq' (SURVEY.md §5
    long-context: absent in the reference, additive here)

bfloat16 activations with float32 params/optimizer state (MXU-friendly);
the LM head ties the embedding matrix."""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS, MODEL_AXIS, SEQ_AXIS, spec_for)
from deeplearning4j_tpu.parallel.ring_attention import ring_attention

try:  # TPU-only Mosaic kernel; absent/unusable on the CPU test platform
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as _pallas_flash)
except Exception:  # pragma: no cover
    _pallas_flash = None


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn: int = 3072
    max_len: int = 512
    type_vocab: int = 2
    dropout: float = 0.1
    compute_dtype: str = "bfloat16"   # activations; params stay f32
    layer_norm_eps: float = 1e-12
    # MoE variant: n_experts > 0 replaces every layer's dense FFN with a
    # GShard/Switch top-k MoE block whose experts shard over the `expert`
    # mesh axis (dp x ep training through the same BertTrainer)
    n_experts: int = 0
    moe_k: int = 2
    moe_capacity: float = 1.5
    moe_aux_weight: float = 1e-2
    # "auto" routes by sequence length: dense softmax up to T=1024
    # (measured on v5e, XLA's fused dense attention beats the Pallas
    # flash kernel ~2x at BERT-base shapes — head_dim 64 pads to the
    # kernel's 128-wide MXU lane), and the Pallas flash kernel for
    # longer 128-divisible T on TPU, where the quadratic [B,H,T,T]
    # score tensor makes dense untenable. "dense"/"flash"/"dpa" force
    # a specific implementation.
    attention_impl: str = "auto"

    @property
    def head_dim(self):
        return self.hidden // self.num_heads


def init_params(cfg: BertConfig, key) -> dict:
    h, f, v = cfg.hidden, cfg.ffn, cfg.vocab_size
    std = 0.02
    keys = jax.random.split(key, 6 + cfg.num_layers)

    def norm(k, shape):
        return jax.random.normal(k, shape, jnp.float32) * std

    params = {
        "tok_emb": norm(keys[0], (v, h)),
        "pos_emb": norm(keys[1], (cfg.max_len, h)),
        "type_emb": norm(keys[2], (cfg.type_vocab, h)),
        "emb_ln": {"g": jnp.ones((h,)), "b": jnp.zeros((h,))},
        "layers": [],
        "mlm_bias": jnp.zeros((v,)),
    }
    for i in range(cfg.num_layers):
        k = jax.random.split(keys[6 + i], 6)
        layer = {
            "qkv_w": norm(k[0], (h, 3 * h)),
            "qkv_b": jnp.zeros((3 * h,)),
            "out_w": norm(k[1], (h, h)),
            "out_b": jnp.zeros((h,)),
            "ln1": {"g": jnp.ones((h,)), "b": jnp.zeros((h,))},
            "ln2": {"g": jnp.ones((h,)), "b": jnp.zeros((h,))},
        }
        if cfg.n_experts > 0:
            from deeplearning4j_tpu.parallel.moe import moe_init

            layer["moe"] = moe_init(k[2], h, f, cfg.n_experts)
        else:
            layer.update({
                "ffn_in_w": norm(k[2], (h, f)),
                "ffn_in_b": jnp.zeros((f,)),
                "ffn_out_w": norm(k[3], (f, h)),
                "ffn_out_b": jnp.zeros((h,)),
            })
        params["layers"].append(layer)
    return params


def param_specs(cfg: BertConfig) -> dict:
    """Megatron-style PartitionSpecs matching init_params structure."""
    layer = {
        "qkv_w": P(None, MODEL_AXIS), "qkv_b": P(MODEL_AXIS),
        "out_w": P(MODEL_AXIS, None), "out_b": P(),
        "ln1": {"g": P(), "b": P()},
        "ln2": {"g": P(), "b": P()},
    }
    if cfg.n_experts > 0:
        from deeplearning4j_tpu.parallel.moe import moe_param_specs

        layer["moe"] = moe_param_specs()
    else:
        layer.update({
            "ffn_in_w": P(None, MODEL_AXIS), "ffn_in_b": P(MODEL_AXIS),
            "ffn_out_w": P(MODEL_AXIS, None), "ffn_out_b": P(),
        })
    return {
        "tok_emb": P(None, MODEL_AXIS),
        "pos_emb": P(),
        "type_emb": P(),
        "emb_ln": {"g": P(), "b": P()},
        "layers": [dict(layer) for _ in range(cfg.num_layers)],
        "mlm_bias": P(),
    }


def _layer_norm(x, g, b, eps):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


def _dropout(x, rate, key):
    """Inverted dropout from 16-bit random draws: half the RNG bytes of
    bernoulli's f32 uniforms (measured ~3 ms/step at BERT-base shapes).
    Keep probability quantizes to 1/65536 — immaterial for dropout."""
    thresh = np.uint16(round((1.0 - rate) * 65536) - 1)
    bits = jax.random.bits(key, x.shape, jnp.uint16)
    return jnp.where(bits <= thresh, x / (1.0 - rate), 0)


def _dense_attention(q, k, v):
    hd = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(hd)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


def _attention(q, k, v, mesh, cfg: BertConfig):
    """[B,H,T,D] attention. seq axis -> ring attention; otherwise a Pallas
    flash kernel on TPU (blocked online-softmax, no [B,H,T,T] in HBM;
    sharded over data/model axes via shard_map) with a dense fallback."""
    if mesh is not None and SEQ_AXIS in mesh.axis_names:
        return ring_attention(q, k, v, mesh)
    impl = cfg.attention_impl
    if impl == "auto":
        # measured on v5e (tools/probe_bert): XLA dense attention beats
        # the Pallas flash kernel ~2x at T=512 (head_dim 64 pads the
        # kernel's 128-wide MXU lane), but dense materializes the
        # [B,H,T,T] scores, whose memory grows quadratically — at long T
        # flash's O(T) memory wins regardless of the lane penalty. The
        # kernel is TPU-Mosaic-only and needs T divisible by its 128
        # block; anything else stays dense.
        t = q.shape[-2]
        impl = ("flash" if t > 1024 and t % 128 == 0
                and _pallas_flash is not None
                and jax.default_backend() == "tpu" else "dense")
    if impl == "dpa":
        # jax.nn.dot_product_attention expects [B,T,H,D]
        qt, kt, vt = (jnp.swapaxes(a, 1, 2) for a in (q, k, v))
        out = jax.nn.dot_product_attention(qt, kt, vt)
        return jnp.swapaxes(out, 1, 2)
    if impl != "flash":
        return _dense_attention(q, k, v)
    if _pallas_flash is None:
        raise RuntimeError(
            "attention_impl='flash' requested but the Pallas TPU flash "
            "kernel is unavailable on this platform (import failed); use "
            "'dense' or 'auto'")
    scale = 1.0 / math.sqrt(q.shape[-1])

    def local(q_, k_, v_):
        return _pallas_flash(q_, k_, v_, causal=False, sm_scale=scale)

    if mesh is None or math.prod(mesh.devices.shape) == 1:
        return local(q, k, v)
    # batch over 'data', heads over 'model': both are embarrassingly
    # parallel for attention, so the kernel runs per-shard unchanged
    spec = spec_for(mesh, DATA_AXIS, MODEL_AXIS, None, None)
    fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec)
    return fn(q, k, v)


def encoder_layer(lp, x, cfg: BertConfig, mesh=None, li=0,
                  deterministic=True, rng=None):
    """One transformer encoder block (post-LN like original BERT).
    x: [B, T, H] in compute dtype -> ([B, T, H], aux_loss scalar).
    aux_loss is the MoE load-balancing loss (0.0 for dense FFN layers)."""
    dtype = x.dtype
    b, t = x.shape[0], x.shape[1]
    nh, hd = cfg.num_heads, cfg.head_dim
    qkv = x @ lp["qkv_w"].astype(dtype) + lp["qkv_b"].astype(dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    to_heads = lambda a: jnp.transpose(  # noqa: E731
        a.reshape(b, t, nh, hd), (0, 2, 1, 3))
    q, k, v = to_heads(q), to_heads(k), to_heads(v)
    att = _attention(q, k, v, mesh, cfg)
    att = jnp.transpose(att, (0, 2, 1, 3)).reshape(b, t, nh * hd)
    att = att @ lp["out_w"].astype(dtype) + lp["out_b"].astype(dtype)
    if not deterministic and cfg.dropout > 0 and rng is not None:
        att = _dropout(att, cfg.dropout, jax.random.fold_in(rng, 2 * li))
    x = _layer_norm((x + att).astype(jnp.float32), lp["ln1"]["g"],
                    lp["ln1"]["b"], cfg.layer_norm_eps).astype(dtype)
    aux = jnp.zeros((), jnp.float32)
    if "moe" in lp:
        from deeplearning4j_tpu.parallel.moe import moe_apply

        # gate_w stays f32: moe_apply's gating math runs in f32 and
        # pre-truncating the gate weights to bf16 would move routing
        # decisions near ties
        mp = {k: (v if k == "gate_w" else v.astype(dtype))
              for k, v in lp["moe"].items()}
        hdn, aux = moe_apply(mp, x.reshape(b * t, -1), k=cfg.moe_k,
                             capacity_factor=cfg.moe_capacity)
        hdn = hdn.reshape(b, t, -1)
        aux = aux.astype(jnp.float32)
    else:
        hdn = jax.nn.gelu(x @ lp["ffn_in_w"].astype(dtype)
                          + lp["ffn_in_b"].astype(dtype))
        hdn = hdn @ lp["ffn_out_w"].astype(dtype) \
            + lp["ffn_out_b"].astype(dtype)
    if not deterministic and cfg.dropout > 0 and rng is not None:
        hdn = _dropout(hdn, cfg.dropout,
                       jax.random.fold_in(rng, 2 * li + 1))
    x = _layer_norm((x + hdn).astype(jnp.float32), lp["ln2"]["g"],
                    lp["ln2"]["b"], cfg.layer_norm_eps).astype(dtype)
    return x, aux


def embed(params, cfg: BertConfig, tokens, type_ids=None):
    """tokens [B, T] -> embedded+LN'd activations [B, T, H] in compute
    dtype."""
    t = tokens.shape[1]
    x = params["tok_emb"][tokens]                       # [B,T,H] f32 gather
    x = x + params["pos_emb"][None, :t, :]
    if type_ids is not None:
        x = x + params["type_emb"][type_ids]
    x = _layer_norm(x, params["emb_ln"]["g"], params["emb_ln"]["b"],
                    cfg.layer_norm_eps)
    return x.astype(jnp.dtype(cfg.compute_dtype))


def forward_with_aux(params, cfg: BertConfig, tokens, type_ids=None,
                     mesh=None, deterministic=True, rng=None):
    """tokens: [B, T] int32 -> (hidden states [B, T, H], total MoE aux
    loss)."""
    x = embed(params, cfg, tokens, type_ids)
    aux_total = jnp.zeros((), jnp.float32)
    for li, lp in enumerate(params["layers"]):
        x, aux = encoder_layer(lp, x, cfg, mesh=mesh, li=li,
                               deterministic=deterministic, rng=rng)
        aux_total = aux_total + aux
    return x, aux_total


def forward(params, cfg: BertConfig, tokens, type_ids=None, mesh=None,
            deterministic=True, rng=None):
    """tokens: [B, T] int32 -> hidden states [B, T, H]."""
    return forward_with_aux(params, cfg, tokens, type_ids, mesh,
                            deterministic, rng)[0]


def mlm_loss(params, cfg: BertConfig, tokens, labels, mesh=None,
             deterministic=False, rng=None):
    """Masked-LM loss; labels = -100 for unmasked positions (ignored).
    LM head ties tok_emb."""
    hs, aux = forward_with_aux(params, cfg, tokens, mesh=mesh,
                               deterministic=deterministic, rng=rng)
    logits = (hs.astype(jnp.float32) @ params["tok_emb"].T
              + params["mlm_bias"])
    logp = jax.nn.log_softmax(logits, axis=-1)
    valid = labels >= 0
    safe = jnp.where(valid, labels, 0)
    tok_lp = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    n = jnp.maximum(jnp.sum(valid), 1)
    loss = -jnp.sum(jnp.where(valid, tok_lp, 0.0)) / n
    return loss + cfg.moe_aux_weight * aux


def mlm_loss_masked(params, cfg: BertConfig, tokens, positions, mlm_labels,
                    weights, mesh=None, deterministic=False, rng=None):
    """Masked-LM loss scoring ONLY the masked positions (the standard BERT
    pretraining head: TF BERT's max_predictions_per_seq gather). The full
    [B,T,V] logits tensor is never built — at BERT-base shapes that tensor
    is ~1 GB in f32 and its log_softmax is pure HBM traffic (the round-1
    MFU sink alongside dense attention).

    positions [B,M] int32, mlm_labels [B,M] int32, weights [B,M] f32
    (0 = padding when a row has fewer than M masked tokens)."""
    hs, aux = forward_with_aux(params, cfg, tokens, mesh=mesh,
                               deterministic=deterministic, rng=rng)
    gathered = jnp.take_along_axis(hs, positions[..., None], axis=1)
    # bf16 x bf16 MXU matmul with f32 accumulation
    logits = jnp.einsum(
        "bmh,vh->bmv", gathered, params["tok_emb"].astype(gathered.dtype),
        preferred_element_type=jnp.float32) + params["mlm_bias"]
    logp = jax.nn.log_softmax(logits, axis=-1)
    tok_lp = jnp.take_along_axis(logp, mlm_labels[..., None],
                                 axis=-1)[..., 0]
    n = jnp.maximum(jnp.sum(weights), 1.0)
    loss = -jnp.sum(tok_lp * weights) / n
    return loss + cfg.moe_aux_weight * aux


def mlm_max_preds(seq_len):
    """Stable masked-slot count (like TF BERT max_predictions_per_seq) so
    the executable shape never depends on the random mask draw. Shared by
    BertTrainer and BertPipelineTrainer — their step-for-step parity
    depends on the identical formula."""
    return max(1, int(0.15 * seq_len) + 1)


def mlm_gather(labels, max_preds=None):
    """Host-side: labels [B,T] with -100 at unmasked positions ->
    (positions [B,M], mlm_labels [B,M], weights [B,M]) for
    mlm_loss_masked. M = max_preds or the max masked count in the batch."""
    labels = np.asarray(labels)
    b, t = labels.shape
    counts = (labels >= 0).sum(axis=1)
    m = int(max_preds or max(int(counts.max()), 1))
    positions = np.zeros((b, m), np.int32)
    mlm_labels = np.zeros((b, m), np.int32)
    weights = np.zeros((b, m), np.float32)
    for i in range(b):
        pos = np.nonzero(labels[i] >= 0)[0][:m]
        positions[i, :len(pos)] = pos
        mlm_labels[i, :len(pos)] = labels[i, pos]
        weights[i, :len(pos)] = 1.0
    return positions, mlm_labels, weights


class BertTrainer:
    """One donated jitted step: fwd + bwd + Adam, with dp/tp/sp shardings."""

    def __init__(self, cfg: BertConfig, mesh: Mesh, lr=1e-4, seed=0):
        self.cfg = cfg
        self.mesh = mesh
        self.lr = lr
        key = jax.random.key(seed)
        specs = param_specs(cfg)
        to_sharding = lambda s: NamedSharding(  # noqa: E731
            mesh, P(*[a if a in mesh.axis_names else None
                      for a in (s or P())]))
        self.p_sh = jax.tree_util.tree_map(
            to_sharding, specs, is_leaf=lambda x: isinstance(x, P))
        params = init_params(cfg, key)
        self.params = jax.device_put(params, self.p_sh)
        zeros = lambda: jax.tree_util.tree_map(  # noqa: E731
            jnp.zeros_like, self.params)
        self.opt = {"m": zeros(), "v": zeros()}
        self.o_sh = {"m": self.p_sh, "v": self.p_sh}
        self.batch_sh = NamedSharding(mesh, spec_for(mesh, DATA_AXIS,
                                                     SEQ_AXIS))
        # masked-position tensors [B,M]: data-sharded only (M != seq axis)
        self.pos_sh = NamedSharding(mesh, spec_for(mesh, DATA_AXIS))
        self._step_fn = None
        self._step = 0

    def _step_math(self, params, opt, tokens, positions, mlm_labels,
                   weights, rng, t):
        cfg, mesh, lr = self.cfg, self.mesh, self.lr
        loss, grads = jax.value_and_grad(mlm_loss_masked)(
            params, cfg, tokens, positions, mlm_labels, weights,
            mesh=mesh, deterministic=False, rng=rng)
        b1, b2, eps = 0.9, 0.999, 1e-8
        m = jax.tree_util.tree_map(
            lambda m_, g: b1 * m_ + (1 - b1) * g, opt["m"], grads)
        v = jax.tree_util.tree_map(
            lambda v_, g: b2 * v_ + (1 - b2) * g * g, opt["v"], grads)
        tt = t + 1
        mhat = jax.tree_util.tree_map(lambda m_: m_ / (1 - b1 ** tt), m)
        vhat = jax.tree_util.tree_map(lambda v_: v_ / (1 - b2 ** tt), v)
        params = jax.tree_util.tree_map(
            lambda p, mh, vh: p - lr * mh / (jnp.sqrt(vh) + eps),
            params, mhat, vhat)
        return loss, params, {"m": m, "v": v}

    def _build(self):
        repl = NamedSharding(self.mesh, P())

        def step(params, opt, tokens, positions, mlm_labels, weights, rng,
                 t):
            return self._step_math(params, opt, tokens, positions,
                                   mlm_labels, weights, rng, t)

        return jax.jit(
            step,
            in_shardings=(self.p_sh, self.o_sh, self.batch_sh, self.pos_sh,
                          self.pos_sh, self.pos_sh, repl, repl),
            out_shardings=(repl, self.p_sh, self.o_sh),
            donate_argnums=(0, 1),
        )

    def _build_multi(self, repeats=1):
        """K training steps in ONE device launch: lax.scan over a stacked
        [K, ...] batch dimension. Amortizes per-dispatch host/RPC latency
        (the axon tunnel costs ~25 ms per launch — larger than a whole
        BERT-base step) the way an on-device input pipeline would.
        repeats > 1 makes R passes over the same K batches (slope-based
        benchmarking / tiny-corpus epochs); last pass's losses return."""
        repl = NamedSharding(self.mesh, P())

        def stack_sh(sh):
            return NamedSharding(self.mesh, P(None, *sh.spec))

        def many(params, opt, tokens_k, pos_k, lab_k, w_k, rng0, t0):
            def body(carry, xs):
                params, opt, t = carry
                tokens, pos, lab, w = xs
                rng = jax.random.fold_in(rng0, t)
                loss, params, opt = self._step_math(
                    params, opt, tokens, pos, lab, w, rng, t)
                return (params, opt, t + 1), loss

            def scan_once(carry, _):
                return jax.lax.scan(body, carry,
                                    (tokens_k, pos_k, lab_k, w_k))

            carry = (params, opt, t0)
            if repeats == 1:
                carry, losses = scan_once(carry, None)
            else:
                carry, losses_r = jax.lax.scan(scan_once, carry, None,
                                               length=repeats)
                losses = losses_r[-1]
            params, opt, _ = carry
            return losses, params, opt

        return jax.jit(
            many,
            in_shardings=(self.p_sh, self.o_sh, stack_sh(self.batch_sh),
                          stack_sh(self.pos_sh), stack_sh(self.pos_sh),
                          stack_sh(self.pos_sh), repl, repl),
            out_shardings=(repl, self.p_sh, self.o_sh),
            donate_argnums=(0, 1),
        )

    def train_steps(self, tokens_k, labels_k, repeats: int = 1):
        """Run K = tokens_k.shape[0] optimizer steps in one launch
        (R*K with repeats=R). tokens_k/labels_k: [K, B, T]. Returns the
        [K] losses of the last pass."""
        if not isinstance(getattr(self, "_multi_fn", None), dict):
            self._multi_fn = {}
        if repeats not in self._multi_fn:
            self._multi_fn[repeats] = self._build_multi(repeats)
        k, b, t = np.asarray(tokens_k).shape
        pos_k, lab_k, w_k = [], [], []
        for i in range(k):
            p_, l_, w_ = mlm_gather(labels_k[i],
                                    max_preds=self._max_preds(t))
            pos_k.append(p_)
            lab_k.append(l_)
            w_k.append(w_)
        rng0 = jax.random.key(self._step + 1, impl="rbg")
        import time

        from deeplearning4j_tpu import telemetry

        t_launch = (time.perf_counter() if telemetry.enabled()
                    else None)
        it0 = self._step
        losses, self.params, self.opt = self._multi_fn[repeats](
            self.params, self.opt, jnp.asarray(tokens_k, jnp.int32),
            np.stack(pos_k), np.stack(lab_k), np.stack(w_k), rng0,
            jnp.asarray(self._step, jnp.int32))
        self._step += k * repeats
        if t_launch is not None:
            # ISSUE 10 cost attribution: per-step FLOPs from the HLO
            # cost model of the scanned module (lower-only, no second
            # compile), published as dl4j_flops_per_step{executable=
            # "bert"}; the live dl4j_mfu gauge uses the launch's
            # dispatch wall from the SECOND launch on, when dispatch-
            # queue backpressure makes it equal device time (the PR-1
            # step-time argument — the first launch returns as soon as
            # the work is enqueued and would overstate MFU wildly)
            from deeplearning4j_tpu.telemetry import costmodel

            n_steps = k * repeats
            per_step = (time.perf_counter() - t_launch) / max(1, n_steps)
            self._launches = getattr(self, "_launches", 0) + 1
            # warm from the second launch on: dispatch-queue
            # backpressure from launch N-1 makes the wall honest (the
            # throttle inside attribute_launch additionally keeps an
            # unmaterialized microsecond dispatch wall from printing an
            # absurd over-peak MFU)
            costmodel.attribute_launch(
                "bert", self._multi_fn[repeats],
                (self.params, self.opt,
                 jnp.asarray(tokens_k, jnp.int32), np.stack(pos_k),
                 np.stack(lab_k), np.stack(w_k), rng0,
                 jnp.asarray(it0, jnp.int32)),
                self, per_step, self._launches >= 2)
        return losses

    def train_step(self, tokens, labels):
        """tokens [B,T] int32; labels [B,T] with -100 at unmasked
        positions. The masked-position gather happens host-side so the
        device step only scores the ~15% of positions that matter."""
        if self._step_fn is None:
            self._step_fn = self._build()
        positions, mlm_labels, weights = mlm_gather(
            labels, max_preds=self._max_preds(np.asarray(tokens).shape[1]))
        # rbg PRNG: XLA's RngBitGenerator is far cheaper than threefry for
        # the ~380M dropout bits a BERT-base step draws (~17 ms/step on
        # v5e); dropout only needs statistical, not reproducible-forever,
        # randomness
        rng = jax.random.key(self._step + 1, impl="rbg")
        # step counter as a traced scalar — a static arg would recompile
        # the executable every step
        loss, self.params, self.opt = self._step_fn(
            self.params, self.opt, jnp.asarray(tokens, jnp.int32),
            positions, mlm_labels, weights, rng,
            jnp.asarray(self._step, jnp.int32))
        self._step += 1
        return loss

    def _max_preds(self, seq_len):
        return mlm_max_preds(seq_len)


def synthetic_mlm_batch(cfg: BertConfig, batch, seq_len, seed=0,
                        mask_frac=0.15):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(3, cfg.vocab_size, (batch, seq_len))
    labels = np.full((batch, seq_len), -100, np.int64)
    n_mask = max(1, int(mask_frac * seq_len))
    for i in range(batch):
        pos = rng.choice(seq_len, n_mask, replace=False)
        labels[i, pos] = tokens[i, pos]
        tokens[i, pos] = 1  # [MASK]
    return tokens.astype(np.int32), labels.astype(np.int64)
