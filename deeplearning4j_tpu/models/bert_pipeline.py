"""Pipeline-parallel training for the flagship BERT encoder.

VERDICT round-2 item 2: pipeline parallelism must be a capability of the
framework's flagship model, not a standalone toy. This module trains the
SAME BertConfig/init_params model as models/bert.py on a dp x pp mesh:

- the L encoder layers are split into S = mesh.shape['pipe'] stages of
  L/S layers; per-layer param trees are stacked to leaves [S, L/S, ...]
  whose leading axis is sharded over `pipe` (device s holds stage s);
- embeddings + the tied MLM head are replicated over `pipe` (they are
  ~25M params at BERT-base — small next to the encoder stack) and the
  batch is sharded over `data` as usual;
- the GPipe schedule (S + M - 1 ticks of `ppermute` inside `shard_map`,
  bubble (S-1)/(S+M-1)) comes from parallel/pipeline.pipeline_apply; the
  backward pipeline falls out of jax.grad reversing every ppermute;
- each stage runs its L/S layers with lax.scan over the stacked layer
  axis, so the stage body is ONE traced layer regardless of depth.

Dropout is supported: pipeline_apply hands each stage the microbatch
index it is consuming, and the stage derives its mask keys as
fold_in(fold_in(step_rng, microbatch), global_layer_index) — the same
keys on the forward and backward retrace, schedule-independent. With
cfg.dropout == 0 the path is bit-identical to before; loss-curve parity
with the single-device BertTrainer is tested at dropout 0 in
tests/test_pipeline_moe.py (with dropout on, the rng STREAMS differ from
single-device by construction, so only training progress is asserted).

Reference capability: ABSENT in the reference (SURVEY.md §2.6 pipeline
row: "NO — XLA multi-computation + collective permute" is the prescribed
TPU design), so this is additive capability on the flagship.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.models.bert import (
    BertConfig, embed, encoder_layer, init_params, mlm_gather,
    mlm_max_preds)
from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS, PIPE_AXIS, spec_for)
from deeplearning4j_tpu.parallel.pipeline import pipeline_apply


def stack_layer_params(cfg: BertConfig, params: dict, n_stages: int):
    """Split init_params' output into (emb_head, stages):
    emb_head = everything but the layers; stages = per-layer trees stacked
    to leaves [S, L/S, ...] via the shared pipeline_trainer helper (the
    same stacking any MultiLayerNetwork gets)."""
    from deeplearning4j_tpu.parallel.pipeline_trainer import (
        stack_run_params)

    if cfg.num_layers % n_stages:
        raise ValueError(
            f"num_layers={cfg.num_layers} not divisible by "
            f"pipe={n_stages}")
    stacked = stack_run_params(params["layers"], n_stages)
    emb_head = {k: v for k, v in params.items() if k != "layers"}
    return emb_head, stacked


def unstack_layer_params(stacked) -> list:
    """Inverse of stack_layer_params: [S, L/S, ...] leaves -> list of L
    per-layer param dicts (for checkpoint interchange with BertTrainer)."""
    from deeplearning4j_tpu.parallel.pipeline_trainer import (
        unstack_run_params)

    return unstack_run_params(stacked)


class BertPipelineTrainer:
    """GPipe training of the flagship BERT on a dp x pp mesh: one donated
    jitted step = fwd pipeline + bwd pipeline + Adam."""

    def __init__(self, cfg: BertConfig, mesh: Mesh, microbatches: int = 4,
                 lr: float = 1e-4, seed: int = 0):
        if cfg.n_experts > 0:
            raise ValueError(
                "BertPipelineTrainer does not support MoE configs: the "
                "pipeline stage loop discards the load-balancing aux "
                "loss, so the objective would silently differ from "
                "BertTrainer's — train MoE variants on a dp x ep mesh "
                "via BertTrainer instead")
        self.cfg = cfg
        self.mesh = mesh
        self.microbatches = microbatches
        self.lr = lr
        self.n_stages = mesh.shape.get(PIPE_AXIS, 1)
        emb, stages = stack_layer_params(
            cfg, init_params(cfg, jax.random.key(seed)), self.n_stages)

        repl = NamedSharding(mesh, P())
        stage_sh = NamedSharding(mesh, spec_for(mesh, PIPE_AXIS))
        self.p_sh = {
            "emb": jax.tree_util.tree_map(lambda _: repl, emb),
            "stages": jax.tree_util.tree_map(lambda _: stage_sh, stages),
        }
        self.params = jax.device_put({"emb": emb, "stages": stages},
                                     self.p_sh)
        zeros = lambda: jax.tree_util.tree_map(  # noqa: E731
            jnp.zeros_like, self.params)
        self.opt = {"m": zeros(), "v": zeros()}
        self.o_sh = {"m": self.p_sh, "v": self.p_sh}
        # [M, mb, ...] batches: microbatch axis unsharded, batch over data
        self.x_sh = NamedSharding(mesh, spec_for(mesh, None, DATA_AXIS))
        self._step_fn = None
        self._step = 0

    # -- forward through the pipeline ---------------------------------------
    def _stage_fn(self, stage_params, x, mb_idx, rng=None):
        cfg = self.cfg
        per = cfg.num_layers // self.n_stages
        deterministic = rng is None or cfg.dropout <= 0
        base = None if deterministic else jax.random.fold_in(rng, mb_idx)
        if PIPE_AXIS in self.mesh.axis_names:
            stage_off = jax.lax.axis_index(PIPE_AXIS) * per
        else:
            stage_off = jnp.int32(0)

        def body(h, xs):
            lp, li_local = xs
            key = (None if deterministic
                   else jax.random.fold_in(base, stage_off + li_local))
            y, _aux = encoder_layer(lp, h, cfg, mesh=None, li=0,
                                    deterministic=deterministic, rng=key)
            return y, None

        y, _ = jax.lax.scan(
            body, x, (stage_params, jnp.arange(per, dtype=jnp.int32)))
        return y

    def _loss(self, params, tokens_mb, positions, mlm_labels, weights,
              rng):
        cfg, mesh = self.cfg, self.mesh
        m, mb, t = tokens_mb.shape
        full = {"layers": [], **params["emb"]}
        x = embed(full, cfg, tokens_mb.reshape(m * mb, t))
        x = x.reshape(m, mb, t, -1)
        y = pipeline_apply(
            lambda p, h, i: self._stage_fn(p, h, i, rng),
            params["stages"], x, mesh)
        hs = y.reshape(m * mb, t, -1)
        gathered = jnp.take_along_axis(
            hs, positions.reshape(m * mb, -1)[..., None], axis=1)
        logits = jnp.einsum(
            "bmh,vh->bmv", gathered,
            params["emb"]["tok_emb"].astype(gathered.dtype),
            preferred_element_type=jnp.float32) + params["emb"]["mlm_bias"]
        logp = jax.nn.log_softmax(logits, axis=-1)
        tok_lp = jnp.take_along_axis(
            logp, mlm_labels.reshape(m * mb, -1)[..., None], axis=-1)[..., 0]
        w = weights.reshape(m * mb, -1)
        n = jnp.maximum(jnp.sum(w), 1.0)
        return -jnp.sum(tok_lp * w) / n

    # -- one donated compiled step ------------------------------------------
    def _build(self):
        repl = NamedSharding(self.mesh, P())
        lr = self.lr

        def step(params, opt, tokens_mb, positions, mlm_labels, weights,
                 rng, t):
            loss, grads = jax.value_and_grad(self._loss)(
                params, tokens_mb, positions, mlm_labels, weights, rng)
            b1, b2, eps = 0.9, 0.999, 1e-8
            m = jax.tree_util.tree_map(
                lambda m_, g: b1 * m_ + (1 - b1) * g, opt["m"], grads)
            v = jax.tree_util.tree_map(
                lambda v_, g: b2 * v_ + (1 - b2) * g * g, opt["v"], grads)
            tt = t + 1
            mhat = jax.tree_util.tree_map(lambda m_: m_ / (1 - b1 ** tt), m)
            vhat = jax.tree_util.tree_map(lambda v_: v_ / (1 - b2 ** tt), v)
            params = jax.tree_util.tree_map(
                lambda p, mh, vh: p - lr * mh / (jnp.sqrt(vh) + eps),
                params, mhat, vhat)
            return loss, params, {"m": m, "v": v}

        return jax.jit(
            step,
            in_shardings=(self.p_sh, self.o_sh, self.x_sh, self.x_sh,
                          self.x_sh, self.x_sh, repl, repl),
            out_shardings=(repl, self.p_sh, self.o_sh),
            donate_argnums=(0, 1),
        )

    def train_step(self, tokens, labels):
        """tokens [B, T] int32, labels [B, T] (-100 = unmasked). B is split
        into `microbatches` GPipe microbatches; returns the scalar loss."""
        if self._step_fn is None:
            self._step_fn = self._build()
        tokens = np.asarray(tokens)
        b, t = tokens.shape
        m = self.microbatches
        if b % m:
            raise ValueError(f"batch {b} not divisible by "
                             f"microbatches {m}")
        positions, mlm_labels, weights = mlm_gather(
            labels, max_preds=mlm_max_preds(t))
        mb = b // m
        rng = jax.random.key(self._step + 1, impl="rbg")
        loss, self.params, self.opt = self._step_fn(
            self.params, self.opt,
            jnp.asarray(tokens.reshape(m, mb, t), jnp.int32),
            positions.reshape(m, mb, -1), mlm_labels.reshape(m, mb, -1),
            weights.reshape(m, mb, -1), rng,
            jnp.asarray(self._step, jnp.int32))
        self._step += 1
        return loss
