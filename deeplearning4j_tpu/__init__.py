"""deeplearning4j_tpu — a TPU-native deep-learning framework with the
capabilities of deeplearning4j (reference: yangkf1985/deeplearning4j).

Not a port: the reference's per-op JNI dispatch into CUDA kernels
(SURVEY.md §3.3) is replaced by whole-step XLA compilation — layers are
pure-function emitters, training steps are jitted with donated device-resident
parameters, and distributed sync is in-step XLA collectives over an ICI mesh
instead of the Aeron parameter server (SURVEY.md §2.6).

Capability map (reference layer -> this package):
  ND4J INDArray / Nd4j factory       -> deeplearning4j_tpu.ndarray
  SameDiff graph autodiff            -> deeplearning4j_tpu.autodiff
  NeuralNetConfiguration / networks  -> deeplearning4j_tpu.nn
  DataVec ETL                        -> deeplearning4j_tpu.datasets
  ParallelWrapper / Spark scale-out  -> deeplearning4j_tpu.parallel
  Model zoo                          -> deeplearning4j_tpu.models
  Evaluation                         -> deeplearning4j_tpu.evaluation
  ModelSerializer / listeners / etc. -> deeplearning4j_tpu.utils
  DataType knob                      -> deeplearning4j_tpu.precision
                                        (policies, loss scaling, int8 PTQ)
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.ndarray import Nd4j, INDArray  # noqa: F401
from deeplearning4j_tpu.backend import Nd4jBackend  # noqa: F401
from deeplearning4j_tpu.runtime import RuntimeConfig  # noqa: F401
