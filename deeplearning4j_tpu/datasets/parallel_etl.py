"""Parallel local ETL: multiprocessing executors for TransformProcess
pipelines and image-tree ingestion, with async device prefetch.

Reference capability: the reference executes DataVec pipelines on Spark
(`datavec-spark`) or the multi-threaded local executor
(`datavec-local` LocalTransformExecutor) and streams batches into
training via async iterators (SURVEY.md §2.4 executor rows; VERDICT
round-2 missing item 6: the single-threaded record-by-record
TransformProcess would starve a ResNet-class config). TPU-first design:

- host-side ETL scales across PROCESSES (Python parses/decodes with the
  GIL held — threads cannot scale image decode), using the `fork` start
  method so TransformProcess closures and file lists are inherited, not
  pickled;
- workers produce whole BATCH arrays (one IPC transfer per batch, not
  per record) tagged with sequence numbers; the parent reorders so batch
  order is deterministic regardless of worker scheduling;
- the parent optionally `jax.device_put`s each assembled batch on
  arrival (async dispatch), so the accelerator upload overlaps the next
  batch's decode — the AsyncDataSetIterator idea, pushed down to the
  process pool.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import queue as queue_mod

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator

# fork-inherited globals (set in the parent right before forking): the
# executor's TransformProcess / image spec reach workers without pickling
_WORK = {}


def _default_workers():
    return max(1, (os.cpu_count() or 1))


def _fork_ctx():
    """The 'fork' start method, or None where it does not exist (Windows)
    or is unsafe as a non-default (macOS, spawn-default since 3.8): the
    _WORK global-inheritance scheme is fork-only, so callers degrade to
    their serial path instead of crashing (ADVICE r3)."""
    import sys
    if sys.platform in ("win32", "darwin"):
        return None
    try:
        return mp.get_context("fork")
    except ValueError:
        return None


# ---------------------------------------------------------------------------
# TransformProcess executor
# ---------------------------------------------------------------------------

def _tp_chunk(args):
    lo, hi = args
    tp = _WORK["tp"]
    records = _WORK["records"]
    out = []
    for r in records[lo:hi]:
        res = tp.executeRecord(r)
        if res is not None:
            out.append(res)
    return out


class LocalTransformExecutor:
    """Chunked multi-process TransformProcess execution (reference:
    org.datavec.local.transforms.LocalTransformExecutor)."""

    @staticmethod
    def execute(records, transform_process, numWorkers=None,
                chunkSize=1024):
        records = list(records)
        n = len(records)
        workers = numWorkers or _default_workers()
        ctx = _fork_ctx()
        if workers <= 1 or n <= chunkSize or ctx is None:
            return transform_process.execute(records)
        _WORK["tp"] = transform_process
        _WORK["records"] = records
        try:
            chunks = [(lo, min(lo + chunkSize, n))
                      for lo in range(0, n, chunkSize)]
            with ctx.Pool(workers) as pool:
                parts = pool.map(_tp_chunk, chunks)
        finally:
            _WORK.clear()
        out = []
        for p in parts:
            out.extend(p)
        return out


# ---------------------------------------------------------------------------
# parallel image ingestion
# ---------------------------------------------------------------------------

def _decode_batch(files, labels, label_of, loader, transform, batch_size,
                  seq, epoch_seed):
    """Decode/augment ONE batch — the single source of truth for both the
    forked _image_worker and the serial fallback (identical seeding, so
    the two paths are deterministically interchangeable)."""
    chunk = files[seq * batch_size:(seq + 1) * batch_size]
    rng = np.random.default_rng(epoch_seed + (seq,))
    feats, idxs = [], []
    for path in chunk:
        arr = loader.asMatrix(path)
        if transform is not None:
            arr = transform.transform(arr, rng)
        feats.append(arr)
        idxs.append(labels.index(label_of(path)))
    return (np.stack(feats).astype(np.float32),
            np.asarray(idxs, np.int32))


def _image_worker(worker_id, n_workers, batch_size, n_batches, out_q,
                  seed):
    """Decode/augment whole batches (worker w owns batches w, w+W, ...)
    and push (seq, features, label_idx) tuples."""
    files = _WORK["files"]
    labels = _WORK["labels"]
    label_of = _WORK["label_of"]
    loader = _WORK["loader"]
    transform = _WORK["transform"]
    try:
        for seq in range(worker_id, n_batches, n_workers):
            feats, idxs = _decode_batch(files, labels, label_of, loader,
                                        transform, batch_size, seq, seed)
            out_q.put((seq, feats, idxs))
        out_q.put(("done", worker_id, None))
    except Exception as e:  # surfaced by the parent
        out_q.put(("error", worker_id, f"{type(e).__name__}: {e}"))


class ParallelImageDataSetIterator(DataSetIterator):
    """Image-tree -> DataSet iterator whose decode/augment runs across
    `numWorkers` processes; batches arrive in deterministic order and are
    optionally pre-staged on the accelerator.

    Capability analog of ImageRecordReader + RecordReaderDataSetIterator
    + AsyncDataSetIterator fused, at the throughput the reference gets
    from its multi-threaded ETL (SURVEY.md §2.4)."""

    def __init__(self, split, height, width, channels=3, batchSize=32,
                 labelGenerator=None, imageTransform=None, numWorkers=None,
                 prefetchToDevice=False, seed=0, queueSize=8):
        super().__init__(batchSize)
        from deeplearning4j_tpu.datasets.image import (
            NativeImageLoader, ParentPathLabelGenerator)

        self._split = split
        self._loader = NativeImageLoader(height, width, channels)
        self._label_gen = labelGenerator or ParentPathLabelGenerator()
        self._transform = imageTransform
        self._workers = numWorkers or _default_workers()
        self._prefetch = prefetchToDevice
        self._seed = seed
        self._qsize = queueSize

        files = [f for f in split.locations()
                 if f.lower().endswith((".png", ".jpg", ".jpeg", ".bmp",
                                        ".gif"))]
        self._files = files
        self._labels = sorted({self._label_gen.getLabelForPath(f)
                               for f in files})
        # ceil: the final partial batch is produced too (the serial
        # reader path yields every record; silently dropping the tail
        # would train on a fixed subset forever)
        self._n_batches = -(-len(files) // batchSize)
        if self._n_batches == 0:
            raise ValueError("no images found")
        self._procs = []
        self._reorder = {}
        self._next_seq = 0
        self._queue = None
        self._live_workers = 0
        self._epoch = 0
        self._tele = None  # loop instruments, bound on first next()

    def getLabels(self):
        return list(self._labels)

    def totalOutcomes(self):
        return len(self._labels)

    def _serial_batch(self, seq):
        """In-process fallback for one batch on hosts without the fork
        start method — same _decode_batch, same seeding as the workers."""
        return _decode_batch(self._files, self._labels,
                             self._label_gen.getLabelForPath, self._loader,
                             self._transform, self._batch, seq,
                             self._epoch_seed)

    def _start(self):
        ctx = _fork_ctx()
        if ctx is None:
            self._queue = "serial"
            self._epoch_seed = (self._seed, self._epoch)
            self._epoch += 1
            self._live_workers = 0
            self._reorder = {}
            self._next_seq = 0
            return
        self._queue = ctx.Queue(maxsize=self._qsize)
        _WORK["files"] = self._files
        _WORK["labels"] = self._labels
        _WORK["label_of"] = self._label_gen.getLabelForPath
        _WORK["loader"] = self._loader
        _WORK["transform"] = self._transform
        try:
            n = min(self._workers, self._n_batches)
            # fold the epoch counter into the augmentation seed so
            # reset() does not replay identical random transforms
            epoch_seed = (self._seed, self._epoch)
            self._epoch += 1
            self._procs = [
                ctx.Process(target=_image_worker,
                            args=(w, n, self._batch, self._n_batches,
                                  self._queue, epoch_seed), daemon=True)
                for w in range(n)
            ]
            for p in self._procs:
                p.start()
        finally:
            _WORK.clear()
        self._live_workers = len(self._procs)
        self._reorder = {}
        self._next_seq = 0

    def hasNext(self):
        return self._next_seq < self._n_batches

    def next(self):
        import time

        from deeplearning4j_tpu import telemetry

        if not self.hasNext():
            raise StopIteration
        # bound once per iterator; while disabled this stays a single
        # flag check per batch (loop_instruments returns None)
        tele = self._tele
        if tele is None:
            tele = self._tele = telemetry.loop_instruments("image_etl")
        if tele is not None:
            t0 = time.perf_counter()
        if self._queue is None:
            self._start()
        if self._queue == "serial":
            self._reorder[self._next_seq] = \
                self._serial_batch(self._next_seq)
        while self._next_seq not in self._reorder:
            try:
                seq, a, b = self._queue.get(timeout=300)
            except queue_mod.Empty:
                raise RuntimeError("image workers stalled (>300 s)")
            if seq == "error":
                raise RuntimeError(f"image worker {a} failed: {b}")
            if seq == "done":
                self._live_workers -= 1
                if self._live_workers == 0 and \
                        self._next_seq not in self._reorder and \
                        not self._reorder:
                    raise RuntimeError(
                        "workers finished but batches are missing")
                continue
            self._reorder[seq] = (a, b)
        feats, idxs = self._reorder.pop(self._next_seq)
        if tele is not None:
            # time this consumer spent blocked on the worker pool (decode
            # wait), the per-batch analog of the trainers' etl metric
            tele.record_etl_wait(time.perf_counter() - t0)
            tele.examples.inc(feats.shape[0])
        self._next_seq += 1
        labels = np.zeros((feats.shape[0], len(self._labels)), np.float32)
        labels[np.arange(feats.shape[0]), idxs] = 1.0
        if self._prefetch:
            import jax

            feats = jax.device_put(feats)
            labels = jax.device_put(labels)
        ds = DataSet(feats, labels)
        if self.preProcessor is not None:
            self.preProcessor.preProcess(ds)
        return ds

    def reset(self):
        self._shutdown()
        self._queue = None
        self._next_seq = 0
        self._reorder = {}

    def _shutdown(self):
        for p in self._procs:
            if p.is_alive():
                p.terminate()
        for p in self._procs:
            p.join(timeout=5)
        self._procs = []

    def __del__(self):  # best-effort cleanup
        try:
            self._shutdown()
        except Exception:
            pass
