"""Streaming parallel ETL: a persistent multiprocess worker pool with
shared-memory batch transport (ISSUE 6 tentpole).

Reference capability: the reference executes DataVec pipelines on Spark
(`datavec-spark`) or the multi-threaded local executor
(`datavec-local` LocalTransformExecutor) and streams batches into
training via async iterators (SURVEY.md §2.4 executor rows). The TPU
rebuild is organized around four compounding optimizations:

1. **persistent workers** — an :class:`EtlWorkerPool` forks once and
   survives ``reset()``/epoch boundaries; each epoch the parent sends a
   small *work order* (seed, shuffle flag, batch->file assignment
   parameters) down per-worker command queues instead of re-forking,
   so steady-state epochs pay zero process-start cost and multiple
   iterators can share one pool handle (no ``_WORK`` global races);
2. **shared-memory transport** — workers write decoded batches into a
   :class:`ShmRing` (``multiprocessing.shared_memory``) as uint8 when
   the decode needs no resample (``NativeImageLoader.asBytes``), a 4x
   IPC-byte cut over pickling float32 through an ``mp.Queue``, with the
   float cast deferred to the consumer (or the device, via
   ``floatOutput=False`` + ``DevicePrefetcher``);
3. **seeded epoch shuffling** — batch->file assignment reshuffles per
   epoch from ``(seed, epoch)``, deterministic under resume
   (``set_epoch`` + the ``[offset:]`` tail view ElasticTrainer slices);
4. **per-host sharding** — in multi-process pods each host decodes only
   its ``process_index``-strided shard of the (sorted) file list, so a
   pod decodes each image exactly once.

Batch values are BIT-IDENTICAL across the serial, forked-queue, and
shared-memory paths for the same ``(seed, epoch)`` — all three funnel
through :func:`_decode_batch` with the same rng derivation.
"""

from __future__ import annotations

import atexit
import itertools
import os
import queue as queue_mod
import time

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator

# fork-inherited globals for the chunked TransformProcess executor (set
# synchronously around an ephemeral Pool — image ETL no longer uses this)
_WORK = {}

# distinct rng stream tag for the epoch permutation (the augmentation
# stream is seeded (seed, epoch, seq) without it)
_PERM_TAG = 104729


def _default_workers():
    return max(1, (os.cpu_count() or 1))


def _fork_ctx():
    """The 'fork' start method, or None where it does not exist (Windows)
    or is unsafe as a non-default (macOS, spawn-default since 3.8): the
    pool's queue/semaphore-inheritance scheme is fork-only, so callers
    degrade to their serial path instead of crashing (ADVICE r3)."""
    import multiprocessing as mp
    import sys
    if sys.platform in ("win32", "darwin"):
        return None
    try:
        return mp.get_context("fork")
    except ValueError:
        return None


def _shm_available():
    try:
        from multiprocessing import shared_memory  # noqa: F401

        return True
    except ImportError:  # pragma: no cover
        return False


# ---------------------------------------------------------------------------
# TransformProcess executor (unchanged one-shot chunked pool)
# ---------------------------------------------------------------------------

def _tp_chunk(args):
    lo, hi = args
    tp = _WORK["tp"]
    records = _WORK["records"]
    out = []
    for r in records[lo:hi]:
        res = tp.executeRecord(r)
        if res is not None:
            out.append(res)
    return out


class LocalTransformExecutor:
    """Chunked multi-process TransformProcess execution (reference:
    org.datavec.local.transforms.LocalTransformExecutor)."""

    @staticmethod
    def execute(records, transform_process, numWorkers=None,
                chunkSize=1024):
        records = list(records)
        n = len(records)
        workers = numWorkers or _default_workers()
        ctx = _fork_ctx()
        if workers <= 1 or n <= chunkSize or ctx is None:
            return transform_process.execute(records)
        _WORK["tp"] = transform_process
        _WORK["records"] = records
        try:
            chunks = [(lo, min(lo + chunkSize, n))
                      for lo in range(0, n, chunkSize)]
            with ctx.Pool(workers) as pool:
                parts = pool.map(_tp_chunk, chunks)
        finally:
            _WORK.clear()
        out = []
        for p in parts:
            out.extend(p)
        return out


# ---------------------------------------------------------------------------
# the single source of truth for one batch's values
# ---------------------------------------------------------------------------

def _epoch_perm(n_files, seed, epoch):
    """The epoch's batch->file-index assignment: a permutation drawn
    from (seed, epoch) on its own rng stream, identical wherever it is
    recomputed (parent, worker, resumed process)."""
    rng = np.random.default_rng((seed, epoch, _PERM_TAG))
    return rng.permutation(n_files)


def _decode_batch(files, label_idx, label_gen, loader, transform,
                  batch_size, seq, seed, epoch, perm):
    """Decode/augment ONE batch — shared verbatim by the serial path and
    every pool worker (identical seeding, so the transports are
    deterministically interchangeable). Returns (features, label_idxs)
    where features is uint8 [N,C,H,W] when no resample/augment was
    needed (``asBytes`` succeeded for every image) else float32; the
    uint8 form casts to the float32 form exactly."""
    lo = seq * batch_size
    if perm is not None:
        sel = perm[lo:lo + batch_size]
    else:
        sel = range(lo, min(lo + batch_size, len(files)))
    rng = np.random.default_rng((seed, epoch) + (seq,))
    feats, idxs = [], []
    all_u8 = transform is None
    for i in sel:
        path = files[i]
        if all_u8:
            arr = loader.asBytes(path)
            if arr is None:
                all_u8 = False
                feats = [a.astype(np.float32) for a in feats]
                arr = loader.asMatrix(path)
        else:
            arr = loader.asMatrix(path)
            if transform is not None:
                arr = transform.transform(arr, rng)
        feats.append(arr)
        idxs.append(label_idx[label_gen.getLabelForPath(path)])
    stacked = np.stack(feats)
    if stacked.dtype not in (np.uint8, np.float32):
        stacked = stacked.astype(np.float32)
    return stacked, np.asarray(idxs, np.int32)


# ---------------------------------------------------------------------------
# shared-memory batch ring
# ---------------------------------------------------------------------------

class _RawShmAttach:
    """Worker-side attachment to a parent-created segment by mmapping
    ``/dev/shm/<name>`` directly. ``SharedMemory(name=...)`` would also
    work but registers the attachment with the resource tracker
    (bpo-39959), which under fork produces spurious leaked-segment
    warnings at worker exit; the parent alone owns create/unlink, so
    workers stay off the tracker's books entirely. Linux-only — exactly
    the platforms where the fork-based pool runs at all."""

    def __init__(self, name):
        import mmap

        path = f"/dev/shm/{name.lstrip('/')}"
        fd = os.open(path, os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            self._mmap = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self.buf = memoryview(self._mmap)

    def close(self):
        try:
            self.buf.release()
            self._mmap.close()
        except Exception:
            pass


def _attach_shm(name):
    try:
        return _RawShmAttach(name)
    except OSError:  # pragma: no cover - nonstandard shm mount
        from multiprocessing import shared_memory

        return shared_memory.SharedMemory(name=name)


class ShmRing:
    """Fixed-slot shared-memory ring for decoded batches.

    Layout: ``slots`` one-byte occupancy flags (64-byte padded), then
    ``slots`` payload regions of ``slot_bytes``. Worker ``w`` of
    ``n_active`` owns the disjoint block of ``k = slots // n_active``
    slots starting at ``w*k`` and cycles through it, so every slot has
    exactly ONE writer (its owner) and one reader (the parent) — the
    occupancy flag is a plain SPSC handshake. A worker waiting on
    ``flags[slot] == 0`` is waiting for its OWN batch from ``k``
    iterations ago (a strictly smaller seq) to be consumed, and the
    parent consumes seqs in order, so the batch the parent blocks on
    always has a free slot: bounded buffering, deadlock-free, with no
    extra queue of free-slot tokens (which could not be created after
    the pool forked anyway).

    Memory-ordering note: the parent never reads a slot until the
    worker's result MESSAGE for it arrives (an mp.Queue pipe write/read
    — kernel-synchronized), so payload visibility does not ride the
    flag. The flag itself only gates slot REUSE; its store/load pair is
    plain shared memory, which is safe on TSO hosts (x86). On weakly
    ordered CPUs (aarch64) the parent's payload copy could in principle
    still be in flight when its flag store becomes visible — use
    ``transport="queue"`` there, or raise queueSize so reuse lags
    reads."""

    def __init__(self, slots, slot_bytes):
        from multiprocessing import shared_memory

        self.slots = int(slots)
        self.slot_bytes = int(slot_bytes)
        self.data_off = ((self.slots + 63) // 64) * 64
        size = self.data_off + self.slots * self.slot_bytes
        self.shm = shared_memory.SharedMemory(create=True, size=size)
        self.flags = np.frombuffer(self.shm.buf, np.uint8, self.slots, 0)
        self.flags[:] = 0

    @property
    def descriptor(self):
        return {"name": self.shm.name, "slots": self.slots,
                "slot_bytes": self.slot_bytes,
                "data_off": self.data_off}

    def read(self, slot, shape, dtype, cast=None):
        """Copy slot payload out as a host array; with ``cast`` the
        copy and the dtype conversion fuse into one pass (the uint8 ->
        float32 consumer cast never touches an intermediate buffer).
        The slot is reusable the moment this returns."""
        n = int(np.prod(shape))
        view = np.frombuffer(self.shm.buf, dtype, n,
                             self.data_off + slot * self.slot_bytes)
        view = view.reshape(shape)
        if cast is not None and cast != view.dtype:
            return view.astype(cast)
        return view.copy()

    def free(self, slot):
        self.flags[slot] = 0

    def occupancy(self):
        return int(self.flags.sum())

    def close(self):
        # release the parent's buffer views BEFORE closing the mapping
        # (BufferError otherwise), then unlink — workers hold their own
        # attachments until they see the close_ring command
        self.flags = None
        try:
            self.shm.close()
            self.shm.unlink()
        except Exception:
            pass


class _WorkerRing:
    """A worker's view of a parent ShmRing (attach by name)."""

    def __init__(self, descr):
        self.shm = _attach_shm(descr["name"])
        self.slots = descr["slots"]
        self.slot_bytes = descr["slot_bytes"]
        self.data_off = descr["data_off"]
        self.flags = np.frombuffer(self.shm.buf, np.uint8, self.slots, 0)

    def write(self, slot, arr, stall_timeout):
        """Wait for the slot to be consumed, then store the batch."""
        deadline = time.monotonic() + stall_timeout
        while self.flags[slot]:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"shm ring slot {slot} not freed within "
                    f"{stall_timeout:.0f} s (consumer gone?)")
            time.sleep(0.0005)
        flat = arr.reshape(-1)
        view = np.frombuffer(self.shm.buf, arr.dtype, flat.size,
                             self.data_off + slot * self.slot_bytes)
        view[:] = flat
        self.flags[slot] = 1

    def close(self):
        self.flags = None
        try:
            self.shm.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# persistent worker pool
# ---------------------------------------------------------------------------

def _run_epoch(wid, order, specs, rings, out_q, credits, cancel):
    """Execute one work order inside a worker: decode this worker's
    strided share of the epoch's batches and publish each one. The
    shared ``cancel`` value names the newest abandoned job — checking
    it between batches bounds a mid-epoch reset's wasted decode at one
    batch per worker instead of the rest of the epoch."""
    job = order["job"]
    held = False   # a credit is held but not yet transferred via put()
    try:
        n_active = order["n_active"]
        if wid >= n_active:
            out_q.put(("done", job, wid))
            return
        spec = specs[order["spec"]]
        files = spec["files"]
        # sampled-trace ids riding the work order across the fork
        # (ISSUE 10): the worker cannot reach the parent's tracer ring,
        # so it ships finished span RECORDS back on the results queue
        # and the parent materializes them (tracing.ingest in _handle)
        trace = order.get("trace")
        perm = (_epoch_perm(len(files), order["seed"], order["epoch"])
                if order["shuffle"] else None)
        ring = None
        descr = order.get("ring")
        if descr is not None:
            ring = rings.get(descr["name"])
            if ring is None:
                ring = rings[descr["name"]] = _WorkerRing(descr)
        # each worker OWNS a disjoint block of k ring slots and cycles
        # through it — exactly one writer per slot, so the occupancy
        # flag handshake is a single-producer/single-consumer protocol
        # regardless of how n_active divides the slot count
        k = ring.slots // n_active if ring is not None else 0
        for j, seq in enumerate(range(order["start"] + wid,
                                      order["n_batches"], n_active)):
            if cancel.value >= job:
                break
            # backpressure: a bounded number of in-flight batches
            # pool-wide. The parent releases a batch's credit when it
            # parks a ring batch (slot occupancy bounds shm memory) or
            # consumes/drains a queue batch (the credit bounds
            # host-heap queue memory)
            credits.acquire()
            held = True
            t_dec = time.perf_counter() if trace is not None else 0.0
            feats, idxs = _decode_batch(
                files, spec["label_idx"], spec["label_gen"],
                spec["loader"], spec["transform"], order["batch_size"],
                seq, order["seed"], order["epoch"], perm)
            if trace is not None:
                # CLOCK_MONOTONIC is shared across the fork, so these
                # timestamps line up with the parent's spans
                out_q.put(("span", job, {
                    "name": "etl.decode", "trace_id": trace[0],
                    "parent_id": trace[1], "start": t_dec,
                    "end": time.perf_counter(),
                    "attrs": {"seq": seq, "worker": wid,
                              "rows": int(feats.shape[0])}}))
            if ring is None or feats.nbytes > ring.slot_bytes:
                # queue fallback also catches transform output larger
                # than the slot (e.g. an up-sizing ResizeImageTransform)
                # instead of overflowing into neighboring slots
                out_q.put(("batch", job, seq, None, feats, idxs))
            else:
                slot = wid * k + (j % k)
                ring.write(slot, feats, order["stall"])
                out_q.put(("batch", job, seq,
                           (descr["name"], slot, feats.shape,
                            feats.dtype.char), None, idxs))
            held = False
        out_q.put(("done", job, wid))
    except Exception as e:  # surfaced by the parent
        out_q.put(("error", job, wid, f"{type(e).__name__}: {e}", held))


def _pool_worker(wid, cmd_q, out_q, credits, cancel):
    """Worker main loop: consume commands until told to stop. Work
    orders are processed strictly in submission order; dataset specs
    and shm rings are cached across epochs (the persistence that kills
    the per-epoch fork+pickle cost)."""
    specs, rings = {}, {}
    while True:
        cmd = cmd_q.get()
        kind = cmd[0]
        if kind == "stop":
            break
        if kind == "dataset":
            specs[cmd[1]] = cmd[2]
        elif kind == "drop_dataset":
            specs.pop(cmd[1], None)
        elif kind == "close_ring":
            ring = rings.pop(cmd[1], None)
            if ring is not None:
                ring.close()
        elif kind == "epoch":
            _run_epoch(wid, cmd[1], specs, rings, out_q, credits,
                       cancel)
    for ring in rings.values():
        ring.close()


class EtlWorkerPool:
    """Persistent decode workers shared across epochs (and, if passed
    around as a handle, across iterators).

    Channels are all created BEFORE the fork so they are inherited:
    one command queue per worker (work orders, dataset specs, ring
    lifecycle), one shared results queue, and one pool-wide credit
    semaphore bounding in-flight decoded batches (``maxInflight``).

    Work orders from different iterators serialize per worker — sharing
    a pool between iterators consumed in *lockstep* (e.g. ``zip``) can
    therefore stall; give concurrent iterators their own pools."""

    def __init__(self, numWorkers=None, maxInflight=32):
        self.size = numWorkers or _default_workers()
        self.max_inflight = int(maxInflight)
        self._ctx = _fork_ctx()
        self._procs = []
        self._cmd_qs = []
        self._out_q = None
        self._credits = None
        self._cancel = None
        self._spec_counter = itertools.count()
        self._job_counter = itertools.count()
        self._closed = False

    @property
    def available(self):
        return self._ctx is not None

    def _ensure_started(self):
        if self._procs or self._ctx is None or self._closed:
            return
        ctx = self._ctx
        self._cmd_qs = [ctx.Queue() for _ in range(self.size)]
        self._out_q = ctx.Queue()
        self._credits = ctx.BoundedSemaphore(self.max_inflight)
        # newest abandoned job id (monotonic): workers poll it between
        # batches so a mid-epoch reset stops the decode within one
        # batch instead of decode-and-discarding the rest of the epoch
        self._cancel = ctx.Value("l", -1)
        self._procs = [
            ctx.Process(target=_pool_worker,
                        args=(w, self._cmd_qs[w], self._out_q,
                              self._credits, self._cancel),
                        daemon=True, name=f"dl4j-etl-{w}")
            for w in range(self.size)
        ]
        for p in self._procs:
            p.start()
        _live_pools.add(self)

    def broadcast(self, cmd):
        self._ensure_started()
        for q in self._cmd_qs:
            q.put(cmd)

    def register_dataset(self, spec) -> int:
        """Ship a dataset spec (file list, label map, loader, transform)
        to every worker ONCE; epochs then reference it by id. The spec
        is test-pickled HERE so an unpicklable loader/transform fails
        loudly at registration instead of as an opaque KeyError from
        the queue's feeder thread."""
        import pickle

        try:
            pickle.dumps(spec)
        except Exception as e:
            raise TypeError(
                f"ETL dataset spec is not picklable into workers "
                f"(loader/transform/labelGenerator must be module-level "
                f"classes): {type(e).__name__}: {e}") from e
        spec_id = next(self._spec_counter)
        self.broadcast(("dataset", spec_id, spec))
        return spec_id

    def submit_epoch(self, order) -> int:
        job = next(self._job_counter)
        order = dict(order, job=job)
        self.broadcast(("epoch", order))
        return job

    def release_credit(self):
        try:
            self._credits.release()
        except ValueError:  # pragma: no cover - drain raced a release
            pass

    def cancel_job(self, job):
        """Tell workers to abandon this (and any older) work order."""
        if self._cancel is not None and job is not None:
            with self._cancel.get_lock():
                if job > self._cancel.value:
                    self._cancel.value = job

    def results(self):
        return self._out_q

    def dead_workers(self):
        return [p for p in self._procs
                if not p.is_alive() and p.exitcode not in (0, None)]

    def shutdown(self):
        """Stop workers (idempotent). Queued work is abandoned."""
        if self._closed:
            return
        self._closed = True
        for q in self._cmd_qs:
            try:
                q.put(("stop",))
            except Exception:
                pass
        for p in self._procs:
            p.join(timeout=2)
        for p in self._procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=2)
        self._procs = []
        _live_pools.discard(self)

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


_live_pools: set = set()
_shared_pools: dict = {}


def shared_pool(numWorkers=None) -> EtlWorkerPool:
    """A process-wide pool handle keyed by worker count — iterators
    passed the same handle reuse the same forked workers instead of
    each forking their own."""
    n = numWorkers or _default_workers()
    pool = _shared_pools.get(n)
    if pool is None or pool._closed:
        pool = _shared_pools[n] = EtlWorkerPool(n)
    return pool


@atexit.register
def _shutdown_pools():  # pragma: no cover - interpreter teardown
    for pool in list(_live_pools):
        try:
            pool.shutdown()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# the iterator
# ---------------------------------------------------------------------------

class _EpochTail(DataSetIterator):
    """A one-epoch view of a ParallelImageDataSetIterator starting at
    batch ``offset`` — what ``ElasticTrainer`` gets from ``data[k:]``
    when replaying the unconsumed suffix of an interrupted epoch.
    Iterating it plays the parent's CURRENT epoch from ``offset``
    (workers are ordered to skip the consumed prefix, not decode and
    drop it) and leaves the parent positioned at the next epoch."""

    def __init__(self, parent, offset):
        super().__init__(parent.batch())
        self._parent = parent
        self._offset = int(offset)
        # mid-epoch, parent._epoch already points at the NEXT epoch
        # (consumed by _start); the tail must replay the one in flight
        self._epoch = (parent._epoch_playing if parent._epoch_started
                       else parent._epoch)

    def __len__(self):
        return max(0, self._parent._n_batches - self._offset)

    @property
    def hostSharded(self):
        return self._parent.hostSharded

    def reset(self):
        p = self._parent
        p.reset()
        p.set_epoch(self._epoch)
        p._start_from = self._offset

    def hasNext(self):
        return self._parent.hasNext()

    def next(self):
        return self._parent.next()

    def __iter__(self):
        self.reset()
        return self

    def __next__(self):
        if not self._parent.hasNext():
            raise StopIteration
        return self._parent.next()


class ParallelImageDataSetIterator(DataSetIterator):
    """Image-tree -> DataSet iterator whose decode/augment runs on a
    persistent worker pool; batches arrive in deterministic order over
    a shared-memory ring (or a queue), optionally reshuffled per epoch
    and sharded per host.

    Capability analog of ImageRecordReader + RecordReaderDataSetIterator
    + AsyncDataSetIterator fused (SURVEY.md §2.4), rebuilt as a
    streaming engine (ISSUE 6).

    Parameters beyond the classic set:

    - ``shuffle``: reshuffle the batch->file assignment each epoch from
      ``(seed, epoch)`` (deterministic under resume via ``set_epoch``);
    - ``transport``: ``"auto"`` (shm where available, else queue, else
      serial) | ``"shm"`` | ``"queue"`` | ``"serial"``;
    - ``pool``: an :class:`EtlWorkerPool` handle to share workers with
      other iterators (default: a private pool, persistent across
      epochs, shut down by ``close()``);
    - ``shardByHost``: ``"auto"`` (shard when ``jax.process_count() >
      1``) | True | False — each host decodes only its
      ``process_index``-strided shard of the sorted file list;
    - ``stallTimeout``: seconds next() waits on the pool before
      declaring the workers stalled (was hardcoded 300);
    - ``floatOutput``: False keeps uint8 features in the DataSet (pair
      with DevicePrefetcher's deviceTransform to normalize on device).
    """

    def __init__(self, split, height, width, channels=3, batchSize=32,
                 labelGenerator=None, imageTransform=None, numWorkers=None,
                 prefetchToDevice=False, seed=0, queueSize=8,
                 shuffle=False, transport="auto", pool=None,
                 shardByHost="auto", stallTimeout=300.0,
                 floatOutput=True, startEpoch=0):
        super().__init__(batchSize)
        from deeplearning4j_tpu.datasets.image import (
            NativeImageLoader, ParentPathLabelGenerator)

        self._split = split
        self._loader = NativeImageLoader(height, width, channels)
        self._label_gen = labelGenerator or ParentPathLabelGenerator()
        self._transform = imageTransform
        self._workers = numWorkers or _default_workers()
        self._prefetch = prefetchToDevice
        self._seed = seed
        self._qsize = max(2, int(queueSize))
        self._shuffle = bool(shuffle)
        self._stall = float(stallTimeout)
        self._float_out = bool(floatOutput)
        self._sample_shape = (channels, height, width)

        files = [f for f in split.locations()
                 if f.lower().endswith((".png", ".jpg", ".jpeg", ".bmp",
                                        ".gif"))]
        # labels come from the FULL tree (the class-index mapping must
        # be identical on every host), files from this host's shard
        self._labels = sorted({self._label_gen.getLabelForPath(f)
                               for f in files})
        # O(1) label lookup passed to workers (was labels.index(...) —
        # a linear scan per image)
        self._label_idx = {lab: i for i, lab in enumerate(self._labels)}
        if shardByHost == "auto":
            import jax

            shardByHost = jax.process_count() > 1
        if shardByHost:
            import jax

            nhosts = jax.process_count()
            shard = sorted(files)[jax.process_index()::nhosts]
            # every host must run the SAME number of batches per epoch
            # — a shorter shard would exit the epoch early and desync
            # the pod's SPMD collectives — so short shards wrap around
            # (deterministically) up to the longest shard's length
            target = -(-len(files) // nhosts)
            files = [shard[i % len(shard)] for i in range(target)] \
                if shard else []
        self._host_sharded = bool(shardByHost)
        self._files = files
        # ceil: the final partial batch is produced too (the serial
        # reader path yields every record; silently dropping the tail
        # would train on a fixed subset forever)
        self._n_batches = -(-len(files) // batchSize)
        if self._n_batches == 0:
            raise ValueError("no images found")

        self._transport = self._resolve_transport(transport)
        self._pool = None
        self._own_pool = False
        if self._transport != "serial":
            # a private pool's in-flight credit bound follows queueSize
            # (the pre-rebuild mp.Queue(maxsize=queueSize) memory
            # contract); shared pools keep their own maxInflight
            self._pool = pool or EtlWorkerPool(
                self._workers,
                maxInflight=max(self._qsize, self._workers + 1))
            self._own_pool = pool is None
            if not self._pool.available:  # pragma: no cover - platform
                self._transport = "serial"
                self._pool = None
        self._spec_id = None
        self._ring = None

        self._epoch = int(startEpoch)
        self._start_from = 0       # first batch of the next epoch (tail)
        self._epoch_started = False  # this epoch's _start() has run
        self._started = False      # a pool work order is in flight
        self._job = None
        self._done = 0
        self._reorder = {}
        self._next_seq = 0
        self._perm = None
        self._epoch_playing = 0
        self._tele = None          # loop instruments, bound on first next()
        self._etl_tele = None

    # -- introspection -------------------------------------------------------
    @property
    def hostSharded(self):
        """True when this host's batches cover only its own file shard
        — multi-host trainers must then assemble per-process global
        batches (mesh.host_sharded_batch) instead of assuming every
        process feeds the identical batch."""
        return self._host_sharded

    def getLabels(self):
        return list(self._labels)

    def totalOutcomes(self):
        return len(self._labels)

    def __len__(self):
        return self._n_batches

    def __getitem__(self, key):
        """Only tail slices (``it[k:]``) are supported — the shape
        ElasticTrainer uses to replay the rest of an interrupted
        epoch."""
        if not (isinstance(key, slice) and key.stop is None
                and key.step in (None, 1)):
            raise TypeError(
                "ParallelImageDataSetIterator supports only it[k:] "
                "tail slices")
        return _EpochTail(self, key.start or 0)

    def set_epoch(self, epoch):
        """Position the NEXT epoch to play as ``epoch`` (resume
        alignment: a freshly built iterator in a restarted process is
        told which epoch the checkpoint left off in)."""
        if self._epoch_started:
            self.reset()
        self._epoch = int(epoch)

    # -- internals -----------------------------------------------------------
    def _resolve_transport(self, transport):
        if transport not in ("auto", "shm", "queue", "serial"):
            raise ValueError(f"unknown transport {transport!r}")
        if self._workers <= 1 and transport == "auto":
            return "serial"
        if _fork_ctx() is None:
            return "serial"
        if transport == "auto":
            import platform

            # the ring's flag handshake assumes TSO (see ShmRing);
            # weakly ordered hosts default to the queue transport
            tso = platform.machine().lower() in ("x86_64", "amd64",
                                                 "i686", "i386")
            return "shm" if (_shm_available() and tso) else "queue"
        if transport == "shm" and not _shm_available():
            raise RuntimeError(
                "transport='shm' requested but "
                "multiprocessing.shared_memory is unavailable")
        return transport

    def _slot_bytes(self):
        per = int(np.prod(self._sample_shape))
        return self._batch * per * 4  # float32 worst case; uint8 uses 1/4

    def _ensure_ring(self):
        if self._ring is None:
            # at least one owned slot per possible active worker
            # (k = slots // n_active >= 1 in every epoch order)
            self._ring = ShmRing(max(self._qsize, self._pool.size),
                                 self._slot_bytes())
        return self._ring

    def _instruments(self):
        from deeplearning4j_tpu import telemetry

        if self._tele is None:
            self._tele = telemetry.loop_instruments("image_etl")
            self._etl_tele = telemetry.etl_instruments("image_etl")
        return self._tele, self._etl_tele

    def _start(self):
        """Submit this epoch's work order (or prime the serial path)."""
        epoch = self._epoch
        self._epoch += 1
        self._epoch_playing = epoch
        self._epoch_started = True
        self._perm = (_epoch_perm(len(self._files), self._seed, epoch)
                      if self._shuffle else None)
        start, self._start_from = self._start_from, 0
        self._next_seq = start
        self._reorder = {}
        self._done = 0
        if self._transport == "serial":
            self._started = False
            self._job = None
            return
        from deeplearning4j_tpu.telemetry import tracing

        order = {
            "spec": self._register_spec(),
            "seed": self._seed, "epoch": epoch,
            "shuffle": self._shuffle,
            "n_batches": self._n_batches,
            "batch_size": self._batch,
            "n_active": max(1, min(self._pool.size,
                                   self._n_batches - start)),
            "start": start,
            "stall": self._stall,
            "ring": (self._ensure_ring().descriptor
                     if self._transport == "shm" else None),
            # (trace_id, span_id) of the sampled training trace, or
            # None: workers decode under this identity and ship
            # etl.decode span records back beside their batches
            "trace": tracing.current_ids(),
        }
        self._job = self._pool.submit_epoch(order)
        self._started = True

    def _register_spec(self):
        if self._spec_id is None:
            self._spec_id = self._pool.register_dataset({
                "files": self._files,
                "label_idx": self._label_idx,
                "label_gen": self._label_gen,
                "loader": self._loader,
                "transform": self._transform,
            })
        return self._spec_id

    def _serial_batch(self, seq):
        """In-process fallback for one batch — same _decode_batch, same
        seeding as the workers."""
        return _decode_batch(self._files, self._label_idx,
                             self._label_gen, self._loader,
                             self._transform, self._batch, seq,
                             self._seed, self._epoch_playing, self._perm)

    def _handle(self, msg, drain=False):
        """Process one pool message (all bookkeeping lives here: done
        accounting, credit recycling, slot turnover). An "error" is
        ALSO its worker's terminal marker — counting it toward _done is
        what lets drains/finishes complete immediately instead of
        waiting out the stall timeout for a done that will never
        come."""
        kind, job = msg[0], msg[1]
        if kind == "span":
            # worker-produced span record (holds no credit, no slot):
            # materialize it into the parent's tracer ring — stale-job
            # and drain spans are simply dropped
            if job == self._job and not drain:
                from deeplearning4j_tpu.telemetry import tracing

                tracing.ingest(msg[2])
            return False
        if kind == "error":
            if msg[4]:   # the failing worker held an unconsumed credit
                self._pool.release_credit()
            if job == self._job:
                self._done += 1
                if not drain:
                    raise RuntimeError(
                        f"image worker {msg[2]} failed: {msg[3]}")
            return False
        if kind == "done":
            if job == self._job:
                self._done += 1
            return True
        # batch
        _, _, seq, shm_ref, feats, idxs = msg
        stale = job != self._job or drain
        if shm_ref is not None:
            ring_name, slot, shape, dtype_char = shm_ref
            mine = (self._ring is not None
                    and ring_name == self._ring.shm.name)
            if not mine:
                stale = True
            if stale:
                if mine:
                    self._ring.free(slot)
                self._pool.release_credit()
                return False
            # the batch PARKS in its ring slot until next() consumes it
            # (no copy here), and its credit is released NOW: the slot
            # block (freed at consumption) is the shm memory bound, so
            # holding the credit while parked adds nothing — and would
            # let run-ahead workers pin every credit while the worker
            # producing the parent's next needed batch starves in
            # acquire(). Deadlock-free: the parent consumes seqs in
            # order, so the batch it blocks on always finds its owner's
            # slot block free and a credit released here.
            self._pool.release_credit()
            self._reorder[seq] = (shm_ref, idxs)
            return True
        if stale:
            self._pool.release_credit()
            return False
        # queue-transport batches keep their credit until next()
        # consumes them: the decoded payload sits on the host heap, so
        # the credit IS the memory bound (the pre-rebuild
        # mp.Queue(maxsize=queueSize) contract) — releasing on receipt
        # would let a straggler-stalled epoch park unboundedly many
        # float batches in the reorder dict
        self._reorder[seq] = (None, feats, idxs)
        return True

    def _pump(self):
        """Block until self._next_seq lands in the reorder buffer,
        draining pool messages (gap detection per ISSUE 6 satellite:
        all workers done + target seq missing raises immediately
        instead of spinning into the stall timeout)."""
        deadline = time.monotonic() + self._stall
        while self._next_seq not in self._reorder:
            if self._done >= self._pool.size:
                raise RuntimeError(
                    f"all ETL workers finished epoch "
                    f"{self._epoch_playing} but batch {self._next_seq} "
                    f"was never produced (worker crash gap)")
            try:
                msg = self._pool.results().get(
                    timeout=min(5.0, self._stall))
            except queue_mod.Empty:
                dead = self._pool.dead_workers()
                if dead:
                    raise RuntimeError(
                        f"{len(dead)} ETL worker(s) died "
                        f"(exitcodes {[p.exitcode for p in dead]}) "
                        f"without reporting an error")
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"image workers stalled (> {self._stall:.0f} s; "
                        f"configure with stallTimeout=)")
                continue
            self._handle(msg)

    # -- iteration -----------------------------------------------------------
    def hasNext(self):
        if not self._epoch_started:
            return self._start_from < self._n_batches
        return self._next_seq < self._n_batches

    def next(self):
        if not self.hasNext():
            raise StopIteration
        tele, etele = self._instruments()
        if tele is not None:
            t0 = time.perf_counter()
        if not self._epoch_started:
            self._start()
        if self._transport == "serial":
            feats, idxs = self._serial_batch(self._next_seq)
        else:
            self._pump()
            entry = self._reorder.pop(self._next_seq)
            if entry[0] is not None:
                # ring-parked batch: fused copy+cast out of the slot,
                # then recycle the slot (its credit was released at
                # park time — see _handle)
                (_, slot, shape, dchar), idxs = entry
                cast = np.float32 if self._float_out else None
                feats = self._ring.read(slot, shape, np.dtype(dchar),
                                        cast=cast)
                self._ring.free(slot)
            else:
                _, feats, idxs = entry
                self._pool.release_credit()
        self._next_seq += 1
        if self._next_seq >= self._n_batches:
            self._finish_epoch()
        if tele is not None:
            # time this consumer spent blocked on the worker pool
            tele.record_etl_wait(time.perf_counter() - t0)
            tele.examples.inc(feats.shape[0])
        if etele is not None:
            etele.decoded.inc(feats.shape[0])
            if self._ring is not None:
                etele.ring_occupancy.set(self._ring.occupancy())
            try:
                etele.queue_depth.set(
                    self._pool.results().qsize()
                    if self._pool is not None else 0)
            except (NotImplementedError, OSError):  # pragma: no cover
                pass
        if self._float_out and feats.dtype != np.float32:
            feats = feats.astype(np.float32)
        labels = np.zeros((feats.shape[0], len(self._labels)), np.float32)
        labels[np.arange(feats.shape[0]), idxs] = 1.0
        if self._prefetch:
            import jax

            feats = jax.device_put(feats)
            labels = jax.device_put(labels)
        ds = DataSet(feats, labels)
        if self.preProcessor is not None:
            self.preProcessor.preProcess(ds)
        return ds

    def _pool_live(self):
        return (self._pool is not None and not self._pool._closed
                and self._pool._procs)

    def _finish_epoch(self):
        """Collect the epoch's remaining pool messages (done markers —
        all batches are consumed by now) so the pool is quiescent
        before the next work order."""
        self._quiesce()

    def _drain_epoch(self):
        """Abandon an in-flight epoch: cancel the order (workers stop
        within one batch) and consume everything still in flight,
        recycling slots and credits, so the pool is reusable (reset
        mid-epoch, exceptions, close)."""
        if self._started and self._pool_live():
            self._pool.cancel_job(self._job)
        self._quiesce()
        for entry in self._reorder.values():
            if entry[0] is not None:  # parked shm batch holds its slot
                if self._ring is not None:
                    self._ring.free(entry[0][1])
            elif self._pool is not None and not self._pool._closed:
                # parked queue batch still holds its credit
                self._pool.release_credit()
        self._reorder = {}

    def _quiesce(self):
        """Pump pool messages in drain mode until every worker's
        terminal marker (done or error) for the current job arrived."""
        if self._started and self._pool_live():
            deadline = time.monotonic() + self._stall
            while self._done < self._pool.size:
                try:
                    msg = self._pool.results().get(timeout=1.0)
                except queue_mod.Empty:
                    if self._pool.dead_workers() or \
                            time.monotonic() > deadline:
                        break
                    continue
                self._handle(msg, drain=True)
        self._started = False
        self._job = None

    def reset(self):
        self._drain_epoch()
        self._epoch_started = False
        self._next_seq = 0
        self._start_from = 0

    def close(self):
        """Release pool + ring resources. The iterator is dead after
        this (persistent-pool lifecycle is explicit; __del__ is the
        best-effort fallback)."""
        try:
            self._drain_epoch()
        except Exception:
            pass
        if self._ring is not None:
            if self._pool is not None and not self._pool._closed \
                    and self._pool._procs:
                self._pool.broadcast(("close_ring", self._ring.shm.name))
            self._ring.close()
            self._ring = None
        if self._pool is not None:
            if self._spec_id is not None and not self._own_pool \
                    and not self._pool._closed and self._pool._procs:
                self._pool.broadcast(("drop_dataset", self._spec_id))
            if self._own_pool:
                self._pool.shutdown()
            self._pool = None

    def __del__(self):  # best-effort cleanup
        try:
            self.close()
        except Exception:
            pass
