"""Record readers and the record→DataSet bridge.

Reference capability: DataVec's RecordReader/InputSplit API
(org.datavec.api.records.reader.impl.csv.CSVRecordReader, FileSplit) and
deeplearning4j-core's RecordReaderDataSetIterator (SURVEY.md §2.4). Host-side
CPU parsing, exactly like the reference — ETL never touches the device."""

from __future__ import annotations

import csv
import glob
import os

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator


class InputSplit:
    def locations(self) -> list:
        raise NotImplementedError


class FileSplit(InputSplit):
    def __init__(self, path, extensions=None, recursive=True):
        self.path = path
        self.extensions = extensions
        self.recursive = recursive

    def locations(self):
        if os.path.isfile(self.path):
            return [self.path]
        pattern = "**/*" if self.recursive else "*"
        files = sorted(glob.glob(os.path.join(self.path, pattern),
                                 recursive=self.recursive))
        files = [f for f in files if os.path.isfile(f)]
        if self.extensions:
            files = [f for f in files
                     if any(f.endswith(e) for e in self.extensions)]
        return files


class ListStringSplit(InputSplit):
    """In-memory lines (reference: ListStringSplit)."""

    def __init__(self, data: list):
        self.data = list(data)

    def locations(self):
        return self.data


class RecordReader:
    def initialize(self, split: InputSplit):
        raise NotImplementedError

    def hasNext(self) -> bool:
        raise NotImplementedError

    def next(self) -> list:
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError


class CollectionRecordReader(RecordReader):
    """Reader over an in-memory list of records (reference:
    org.datavec.api.records.reader.impl.collection
    .CollectionRecordReader) — the bridge from executeJoin /
    TransformProcess.execute output back into the iterator stack."""

    def __init__(self, records):
        self.records = list(records)
        self._pos = 0

    def initialize(self, split=None):
        self._pos = 0

    def hasNext(self):
        return self._pos < len(self.records)

    def next(self):
        if not self.hasNext():
            raise StopIteration
        rec = self.records[self._pos]
        self._pos += 1
        return list(rec)

    def reset(self):
        self._pos = 0


class CSVRecordReader(RecordReader):
    """Reference: CSVRecordReader(numLinesToSkip, delimiter)."""

    def __init__(self, skipNumLines=0, delimiter=","):
        self.skip = skipNumLines
        self.delimiter = delimiter
        self._records: list = []
        self._pos = 0

    def initialize(self, split: InputSplit):
        self._records = []
        if isinstance(split, ListStringSplit):
            rows = csv.reader(split.data, delimiter=self.delimiter)
            self._records = [r for r in rows][self.skip:]
        else:
            for path in split.locations():
                rows = self._read_file(path)
                self._records.extend(rows[self.skip:])
        self._pos = 0
        return self

    def _read_file(self, path):
        """Fully-numeric CSV files parse to float records — through the
        native C kernel when the toolchain is available, else through
        numpy — so record values are IDENTICAL with or without g++.
        Anything non-numeric (quoting, string columns, ragged rows)
        falls back to the general csv module and yields strings."""
        from deeplearning4j_tpu import native

        with open(path, "rb") as f:
            blob = f.read()
        if native.available():
            mat = native.csv_parse(blob, self.delimiter)
            if mat is not None:
                return mat.tolist()
        else:
            try:
                import io

                mat = np.loadtxt(io.BytesIO(blob), dtype=np.float32,
                                 delimiter=self.delimiter, ndmin=2,
                                 comments=None)
                return mat.tolist()
            except ValueError:
                pass
        with open(path, newline="") as f:
            return list(csv.reader(f, delimiter=self.delimiter))

    def hasNext(self):
        return self._pos < len(self._records)

    def next(self):
        r = self._records[self._pos]
        self._pos += 1
        return r

    def reset(self):
        self._pos = 0


class LineRecordReader(RecordReader):
    def __init__(self):
        self._lines: list = []
        self._pos = 0

    def initialize(self, split: InputSplit):
        self._lines = []
        if isinstance(split, ListStringSplit):
            self._lines = list(split.data)
        else:
            for path in split.locations():
                with open(path) as f:
                    self._lines.extend(line.rstrip("\n") for line in f)
        self._pos = 0
        return self

    def hasNext(self):
        return self._pos < len(self._lines)

    def next(self):
        line = self._lines[self._pos]
        self._pos += 1
        return [line]

    def reset(self):
        self._pos = 0


class RecordReaderDataSetIterator(DataSetIterator):
    """records -> DataSet minibatches (reference:
    org.deeplearning4j.datasets.datavec.RecordReaderDataSetIterator).

    Classification: labelIndex column holds an int class -> one-hot of
    numPossibleLabels. Regression: regression=True, labelIndex..labelIndexTo
    columns are float targets."""

    def __init__(self, recordReader: RecordReader, batchSize=32,
                 labelIndex=-1, numPossibleLabels=None, regression=False,
                 labelIndexTo=None):
        super().__init__(batchSize)
        self.reader = recordReader
        self.labelIndex = labelIndex
        self.numPossibleLabels = numPossibleLabels
        self.regression = regression
        self.labelIndexTo = labelIndexTo if labelIndexTo is not None \
            else labelIndex

    def reset(self):
        self.reader.reset()
        self._peek = None

    def _infer_num_labels(self):
        """Full pre-scan so every batch one-hots with the same width (a
        first-batch-only guess breaks when a later batch holds a higher
        class index)."""
        n = getattr(self.reader, "numLabels", None)
        if callable(n) and n():
            self.numPossibleLabels = n()
            return
        li = self.labelIndex
        max_idx = -1
        while self.reader.hasNext():
            rec = self.reader.next()
            idx = li if li >= 0 else len(rec) + li
            max_idx = max(max_idx, int(float(rec[idx])))
        self.reader.reset()
        if max_idx < 0:
            raise ValueError("no records to infer numPossibleLabels from")
        self.numPossibleLabels = max_idx + 1

    def _next_batch(self):
        if not self.regression and self.numPossibleLabels is None:
            self._infer_num_labels()
        feats, labels = [], []
        while len(feats) < self._batch and self.reader.hasNext():
            rec = self.reader.next()
            if rec and isinstance(rec[0], np.ndarray) and rec[0].ndim > 1:
                # tensor record (ImageRecordReader): [tensor, classIdx]
                feats.append(np.asarray(rec[0], np.float32))
                labels.append([float(rec[1])] if len(rec) > 1 else [0.0])
                continue
            rec = [float(v) for v in rec]
            li, lj = self.labelIndex, self.labelIndexTo
            if li < 0:
                li = lj = len(rec) + li
            lab = rec[li:lj + 1]
            feat = rec[:li] + rec[lj + 1:]
            feats.append(feat)
            labels.append(lab)
        if not feats:
            return None
        f = np.asarray(feats, np.float32)
        if self.regression:
            l = np.asarray(labels, np.float32)
        else:
            idx = np.asarray(labels, np.int64).reshape(-1)
            l = np.eye(self.numPossibleLabels, dtype=np.float32)[idx]
        return DataSet(f, l)


class RecordReaderMultiDataSetIterator(DataSetIterator):
    """Joins NAMED record readers into MultiDataSets for multi-input /
    multi-output ComputationGraphs (reference:
    org.deeplearning4j.datasets.datavec.RecordReaderMultiDataSetIterator
    + its Builder: addReader / addInput(column ranges) /
    addOutputOneHot / addOutput).

    Readers advance in lockstep; each input/output takes a column range
    of one reader's records per example.
    """

    class Builder:
        def __init__(self, batchSize=32):
            self._batch = batchSize
            self._readers: dict[str, RecordReader] = {}
            self._inputs: list = []    # (reader, colFrom, colTo)
            self._outputs: list = []   # (reader, colFrom, colTo, oneHotN)

        def addReader(self, name, reader):
            self._readers[name] = reader
            return self

        def addInput(self, readerName, columnFrom=0, columnTo=None):
            self._inputs.append((readerName, columnFrom, columnTo))
            return self

        def addOutput(self, readerName, columnFrom=0, columnTo=None):
            self._outputs.append((readerName, columnFrom, columnTo, None))
            return self

        def addOutputOneHot(self, readerName, column, numClasses):
            self._outputs.append((readerName, column, column,
                                  int(numClasses)))
            return self

        def build(self):
            if not self._inputs or not self._outputs:
                raise ValueError("declare at least one input and output")
            missing = {r for r, *_ in self._inputs + self._outputs
                       } - set(self._readers)
            if missing:
                raise ValueError(f"undeclared readers: {sorted(missing)}")
            return RecordReaderMultiDataSetIterator(self)

    def __init__(self, builder: "RecordReaderMultiDataSetIterator.Builder"):
        super().__init__(builder._batch)
        self._readers = builder._readers
        self._inputs = builder._inputs
        self._outputs = builder._outputs

    def reset(self):
        for r in self._readers.values():
            r.reset()
        self._peek = None

    def _records_row(self):
        """One aligned row of floats per reader, or None when exhausted.

        All readers are checked for exhaustion before any is consumed, so
        mismatched-length readers raise instead of silently dropping the
        records already pulled from the longer ones.
        """
        state = {name: r.hasNext() for name, r in self._readers.items()}
        if not any(state.values()):
            return None
        if not all(state.values()):
            done = sorted(n for n, h in state.items() if not h)
            live = sorted(n for n, h in state.items() if h)
            raise ValueError(
                f"readers out of alignment: {done} exhausted while "
                f"{live} still have records")
        return {name: [float(v) for v in r.next()]
                for name, r in self._readers.items()}

    @staticmethod
    def _cols(rec, c0, c1):
        n = len(rec)
        c1 = n - 1 if c1 is None else (c1 if c1 >= 0 else n + c1)
        c0 = c0 if c0 >= 0 else n + c0
        if not (0 <= c0 <= c1 < n):
            raise ValueError(
                f"column range [{c0}, {c1}] out of bounds for a record "
                f"of width {n}")
        return rec[c0:c1 + 1]

    def _next_batch(self):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet

        rows = []
        while len(rows) < self._batch:
            row = self._records_row()
            if row is None:
                break
            rows.append(row)
        if not rows:
            return None
        feats = []
        for name, c0, c1 in self._inputs:
            feats.append(np.asarray(
                [self._cols(r[name], c0, c1) for r in rows], np.float32))
        labels = []
        for name, c0, c1, onehot in self._outputs:
            vals = [self._cols(r[name], c0, c1) for r in rows]
            if onehot is not None:
                idx = np.asarray([int(v[0]) for v in vals])
                if idx.min() < 0 or idx.max() >= onehot:
                    bad = idx[(idx < 0) | (idx >= onehot)][0]
                    raise ValueError(
                        f"one-hot output from reader {name!r} column "
                        f"{c0}: class index {bad} outside "
                        f"[0, {onehot})")
                labels.append(np.eye(onehot, dtype=np.float32)[idx])
            else:
                labels.append(np.asarray(vals, np.float32))
        # preprocessing happens once, in the base next()
        return MultiDataSet(feats, labels)
