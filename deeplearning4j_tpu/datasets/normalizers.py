"""Data normalizers with fit/transform/revert + persistence.

Reference capability: org.nd4j.linalg.dataset.api.preprocessor.
{NormalizerStandardize, NormalizerMinMaxScaler, ImagePreProcessingScaler}
(SURVEY.md §2.4 "Normalizers"): fitted on a DataSetIterator, applied as a
preProcessor on iterators, persisted alongside models (ModelSerializer
addNormalizerToModel capability)."""

from __future__ import annotations

import numpy as np


class Normalizer:
    def fit(self, data):
        """Accepts a DataSet or a DataSetIterator."""
        if hasattr(data, "reset"):
            data.reset()
            stats = None
            while data.hasNext():
                ds = data.next()
                stats = self._accumulate(stats, ds.getFeatures())
            self._finalize(stats)
            data.reset()
        else:
            f = data.getFeatures() if hasattr(data, "getFeatures") else data
            self._finalize(self._accumulate(None, np.asarray(f)))
        return self

    def preProcess(self, ds):
        ds.setFeatures(self.transform(ds.getFeatures()))

    def transform(self, features):
        raise NotImplementedError

    def revert(self, features):
        raise NotImplementedError

    # persistence
    def save(self, path):
        # np.savez silently appends .npz; normalize so load(path) matches
        if not str(path).endswith(".npz"):
            path = str(path) + ".npz"
        np.savez(path, __class__=type(self).__name__, **self._state())

    @staticmethod
    def load(path) -> "Normalizer":
        if not str(path).endswith(".npz"):
            path = str(path) + ".npz"
        z = np.load(path, allow_pickle=True)
        cls = {c.__name__: c for c in (NormalizerStandardize,
                                       NormalizerMinMaxScaler,
                                       ImagePreProcessingScaler)}[
            str(z["__class__"])]
        obj = cls.__new__(cls)
        obj._load_state(z)
        return obj


class NormalizerStandardize(Normalizer):
    """Per-feature (x - mean) / std via streaming sufficient statistics."""

    def __init__(self):
        self.mean = None
        self.std = None

    def _accumulate(self, stats, f):
        f = np.asarray(f, np.float64).reshape(f.shape[0], -1)
        if stats is None:
            stats = [0, np.zeros(f.shape[1]), np.zeros(f.shape[1])]
        stats[0] += f.shape[0]
        stats[1] += f.sum(axis=0)
        stats[2] += (f ** 2).sum(axis=0)
        return stats

    def _finalize(self, stats):
        n, s, s2 = stats
        self.mean = (s / n).astype(np.float32)
        var = np.maximum(s2 / n - (s / n) ** 2, 0.0)
        self.std = np.sqrt(var).astype(np.float32)
        self.std[self.std < 1e-8] = 1.0

    def transform(self, f):
        shape = f.shape
        f2 = np.asarray(f, np.float32).reshape(shape[0], -1)
        return ((f2 - self.mean) / self.std).reshape(shape)

    def revert(self, f):
        shape = f.shape
        f2 = np.asarray(f, np.float32).reshape(shape[0], -1)
        return (f2 * self.std + self.mean).reshape(shape)

    def _state(self):
        return {"mean": self.mean, "std": self.std}

    def _load_state(self, z):
        self.mean = z["mean"]
        self.std = z["std"]


class NormalizerMinMaxScaler(Normalizer):
    def __init__(self, minRange=0.0, maxRange=1.0):
        self.minRange = minRange
        self.maxRange = maxRange
        self.dataMin = None
        self.dataMax = None

    def _accumulate(self, stats, f):
        f = np.asarray(f, np.float64).reshape(f.shape[0], -1)
        lo, hi = f.min(axis=0), f.max(axis=0)
        if stats is None:
            return [lo, hi]
        return [np.minimum(stats[0], lo), np.maximum(stats[1], hi)]

    def _finalize(self, stats):
        self.dataMin = stats[0].astype(np.float32)
        self.dataMax = stats[1].astype(np.float32)

    def transform(self, f):
        shape = f.shape
        f2 = np.asarray(f, np.float32).reshape(shape[0], -1)
        rng = np.maximum(self.dataMax - self.dataMin, 1e-8)
        y = (f2 - self.dataMin) / rng
        y = y * (self.maxRange - self.minRange) + self.minRange
        return y.reshape(shape)

    def revert(self, f):
        shape = f.shape
        f2 = np.asarray(f, np.float32).reshape(shape[0], -1)
        rng = np.maximum(self.dataMax - self.dataMin, 1e-8)
        y = (f2 - self.minRange) / (self.maxRange - self.minRange)
        return (y * rng + self.dataMin).reshape(shape)

    def _state(self):
        return {"dataMin": self.dataMin, "dataMax": self.dataMax,
                "minRange": self.minRange, "maxRange": self.maxRange}

    def _load_state(self, z):
        self.dataMin = z["dataMin"]
        self.dataMax = z["dataMax"]
        self.minRange = float(z["minRange"])
        self.maxRange = float(z["maxRange"])


class ImagePreProcessingScaler(Normalizer):
    """Pixel scaling [0,255] -> [minRange,maxRange] (no fit needed)."""

    def __init__(self, minRange=0.0, maxRange=1.0, maxPixelVal=255.0):
        self.minRange = minRange
        self.maxRange = maxRange
        self.maxPixelVal = maxPixelVal

    def fit(self, data):
        return self

    def transform(self, f):
        f = np.asarray(f, np.float32)
        return (f / self.maxPixelVal) * (self.maxRange - self.minRange) \
            + self.minRange

    def revert(self, f):
        f = np.asarray(f, np.float32)
        return (f - self.minRange) / (self.maxRange - self.minRange) \
            * self.maxPixelVal

    def _state(self):
        return {"minRange": self.minRange, "maxRange": self.maxRange,
                "maxPixelVal": self.maxPixelVal}

    def _load_state(self, z):
        self.minRange = float(z["minRange"])
        self.maxRange = float(z["maxRange"])
        self.maxPixelVal = float(z["maxPixelVal"])
