"""DataSetIterator abstraction + async prefetch.

Reference capability: org.nd4j.linalg.dataset.api.iterator.DataSetIterator
(SURVEY.md §2.4) and deeplearning4j-core's AsyncDataSetIterator. Iterators
are python-iterable AND expose the reference's hasNext/next/reset protocol,
so both `for ds in it` and the DL4J idiom work. AsyncDataSetIterator
prefetches batches on a host thread — the host-side half of the
double-buffered H2D pipeline (SURVEY.md §7 step 6); the device half is the
compiled step's async dispatch."""

from __future__ import annotations

import math
import os
import queue
import threading

from deeplearning4j_tpu.datasets.dataset import DataSet


def _positive_finite_timeout(value, what, shown=None):
    """inf/nan/<=0 'wait forever' timeouts would turn the reset() wedge
    guard back into the indefinite hang it exists to prevent."""
    if not (value > 0 and math.isfinite(value)):
        display = value if shown is None else shown
        raise ValueError(
            f"{what} must be a positive finite number of seconds, got "
            f"{display!r}; use a large value for very slow sources")


class DataSetIterator:
    """Base: subclasses implement reset() and _next_batch() -> DataSet|None."""

    def __init__(self, batch_size=32):
        self._batch = batch_size
        self.preProcessor = None

    # -- reference protocol --------------------------------------------------
    def batch(self):
        return self._batch

    def setPreProcessor(self, pp):
        self.preProcessor = pp

    def getPreProcessor(self):
        return self.preProcessor

    def hasNext(self) -> bool:
        if getattr(self, "_peek", None) is None:
            self._peek = self._next_batch()
        return self._peek is not None

    def next(self) -> DataSet:
        if getattr(self, "_peek", None) is not None:
            ds, self._peek = self._peek, None
        else:
            ds = self._next_batch()
        if ds is None:
            raise StopIteration
        if self.preProcessor is not None:
            self.preProcessor.preProcess(ds)
        return ds

    def reset(self):
        raise NotImplementedError

    def resetSupported(self):
        return True

    def asyncSupported(self):
        return True

    def _next_batch(self):
        raise NotImplementedError

    # -- python protocol -----------------------------------------------------
    def __iter__(self):
        self.reset()
        return self

    def __next__(self) -> DataSet:
        try:
            return self.next()
        except StopIteration:
            raise


class ListDataSetIterator(DataSetIterator):
    """Iterate over an in-memory list of DataSets or one big DataSet split
    into minibatches (reference: ListDataSetIterator)."""

    def __init__(self, data, batch_size=32):
        super().__init__(batch_size)
        if isinstance(data, DataSet):
            self._list = data.batchBy(batch_size)
        else:
            self._list = list(data)
        self._pos = 0

    def reset(self):
        self._pos = 0
        self._peek = None

    def _next_batch(self):
        if self._pos >= len(self._list):
            return None
        ds = self._list[self._pos]
        self._pos += 1
        if not isinstance(ds, DataSet):
            f, l = ds
            ds = DataSet(f, l)
        return ds

    def totalExamples(self):
        return sum(d.numExamples() if isinstance(d, DataSet) else len(d[0])
                   for d in self._list)


class ExistingDataSetIterator(ListDataSetIterator):
    """Reference: ExistingDataSetIterator — wraps an existing collection."""


class AsyncDataSetIterator(DataSetIterator):
    """Wraps any DataSetIterator with a background prefetch thread and a
    bounded queue (reference: deeplearning4j AsyncDataSetIterator with
    queue size N). Keeps the accelerator fed while the host parses the
    next batch.

    Wedge detection applies at reset() only: restarting over a producer
    stuck inside the base iterator would interleave two producers on it,
    so reset() raises after join_timeout with no progress. A reset()
    before anything was consumed (notably __iter__'s implicit one on a
    just-built iterator) is a no-op — the fresh producer already sits
    at an epoch start, so there is nothing to rewind and a slow first
    batch is never mistaken for a wedge. Mid-epoch
    consumption (next()) deliberately blocks without a deadline — a
    legitimately slow source (cold storage, first-batch compile stall)
    is indistinguishable from a wedged one there, and a guessed timeout
    would abort healthy training runs."""

    _END = object()
    _JOIN_TIMEOUT = 5.0

    def __init__(self, base: DataSetIterator, queue_size: int = 4,
                 join_timeout: float | None = None):
        super().__init__(base.batch())
        self._base = base
        self._qsize = queue_size
        # per-instance override for sources whose next() legitimately
        # takes longer than the default before reset() declares them
        # wedged; None defers to DL4J_ASYNC_JOIN_TIMEOUT (reachable when
        # a fit() path auto-wraps the iterator) then the class attribute
        if join_timeout is not None:
            # fail at the misconfiguration site, not mid-training
            _positive_finite_timeout(join_timeout, "join_timeout")
        self._join_timeout = join_timeout
        self._queue: queue.Queue = None
        self._thread = None
        self._start()

    def _start(self):
        self._base.reset()
        self._queue = queue.Queue(maxsize=self._qsize)
        self._error = None
        self._done = False
        self._consumed = False  # anything taken off the queue yet?

        def produce():
            try:
                while True:
                    if not self._base.hasNext():
                        break
                    self._queue.put(self._base.next())
            except Exception as e:  # surface in consumer
                self._error = e
            finally:
                self._queue.put(self._END)

        self._thread = threading.Thread(target=produce, daemon=True,
                                        name="dl4j:etl:result-drain")
        self._thread.start()

    def reset(self):
        # drain current thread then restart; drain only while _END is
        # still in flight (if the consumer already took it, a blind
        # get() would block forever on the empty queue), then join so
        # the old producer can't interleave with the new epoch's
        if not self._consumed:
            # untouched producer: the in-flight production IS the start
            # of an epoch (ctor/_start just reset the base), so reset()
            # has nothing to rewind. Crucially this covers __iter__'s
            # reset() on a just-constructed iterator — draining there
            # would declare a legitimately slow FIRST batch (cold
            # storage, compile stall) wedged under default timeouts
            return
        t = self._thread
        if t is not None and t.is_alive():
            # timeout resolution + error construction only on this
            # path: the per-epoch happy case (producer already done and
            # exited) skips straight to the restart
            timeout = self._join_timeout
            if timeout is None:
                raw = os.environ.get("DL4J_ASYNC_JOIN_TIMEOUT")
                if raw is None:
                    timeout = self._JOIN_TIMEOUT
                else:
                    try:
                        timeout = float(raw)
                    except ValueError:
                        timeout = math.nan  # rejected just below
                    # garbage env values would hang the wedge guard
                    # exactly the way it exists to prevent
                    _positive_finite_timeout(
                        timeout, "DL4J_ASYNC_JOIN_TIMEOUT", shown=raw)

            def _wedged():
                return RuntimeError(
                    "AsyncDataSetIterator.reset(): producer thread "
                    f"would not stop (no progress within {timeout}s "
                    "wait windows); base iterator appears wedged, "
                    "refusing to restart over a live producer (pass "
                    "join_timeout= or set DL4J_ASYNC_JOIN_TIMEOUT for "
                    "slow sources)")

            if not self._done:
                # drain to _END; a slow source keeps the drain alive as
                # long as items arrive — only two consecutive empty
                # windows (no progress for 2x timeout) declare it wedged
                empty_windows = 0
                while True:
                    try:
                        item = self._queue.get(timeout=timeout)
                    except queue.Empty:
                        if not t.is_alive():
                            break  # producer exited; nothing to drain
                        empty_windows += 1
                        if empty_windows >= 2:
                            raise _wedged()
                        continue
                    if item is self._END:
                        break
                    empty_windows = 0
            t.join(timeout=timeout)
            if t.is_alive():
                # restarting now would have old and new producers
                # interleave on self._base — the exact race the join
                # exists to prevent
                raise _wedged()
        self._start()
        self._peek = None

    def _next_batch(self):
        if self._done:
            return None  # exhausted: don't block on the dead producer
        item = self._queue.get()
        self._consumed = True
        if item is self._END:
            self._done = True
            if self._error is not None:
                raise self._error
            return None
        return item

    def resetSupported(self):
        return self._base.resetSupported()
