"""DataSetIterator abstraction + async prefetch.

Reference capability: org.nd4j.linalg.dataset.api.iterator.DataSetIterator
(SURVEY.md §2.4) and deeplearning4j-core's AsyncDataSetIterator. Iterators
are python-iterable AND expose the reference's hasNext/next/reset protocol,
so both `for ds in it` and the DL4J idiom work. AsyncDataSetIterator
prefetches batches on a host thread — the host-side half of the
double-buffered H2D pipeline (SURVEY.md §7 step 6); the device half is the
compiled step's async dispatch."""

from __future__ import annotations

import queue
import threading

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSetIterator:
    """Base: subclasses implement reset() and _next_batch() -> DataSet|None."""

    def __init__(self, batch_size=32):
        self._batch = batch_size
        self.preProcessor = None

    # -- reference protocol --------------------------------------------------
    def batch(self):
        return self._batch

    def setPreProcessor(self, pp):
        self.preProcessor = pp

    def getPreProcessor(self):
        return self.preProcessor

    def hasNext(self) -> bool:
        if getattr(self, "_peek", None) is None:
            self._peek = self._next_batch()
        return self._peek is not None

    def next(self) -> DataSet:
        if getattr(self, "_peek", None) is not None:
            ds, self._peek = self._peek, None
        else:
            ds = self._next_batch()
        if ds is None:
            raise StopIteration
        if self.preProcessor is not None:
            self.preProcessor.preProcess(ds)
        return ds

    def reset(self):
        raise NotImplementedError

    def resetSupported(self):
        return True

    def asyncSupported(self):
        return True

    def _next_batch(self):
        raise NotImplementedError

    # -- python protocol -----------------------------------------------------
    def __iter__(self):
        self.reset()
        return self

    def __next__(self) -> DataSet:
        try:
            return self.next()
        except StopIteration:
            raise


class ListDataSetIterator(DataSetIterator):
    """Iterate over an in-memory list of DataSets or one big DataSet split
    into minibatches (reference: ListDataSetIterator)."""

    def __init__(self, data, batch_size=32):
        super().__init__(batch_size)
        if isinstance(data, DataSet):
            self._list = data.batchBy(batch_size)
        else:
            self._list = list(data)
        self._pos = 0

    def reset(self):
        self._pos = 0
        self._peek = None

    def _next_batch(self):
        if self._pos >= len(self._list):
            return None
        ds = self._list[self._pos]
        self._pos += 1
        if not isinstance(ds, DataSet):
            f, l = ds
            ds = DataSet(f, l)
        return ds

    def totalExamples(self):
        return sum(d.numExamples() if isinstance(d, DataSet) else len(d[0])
                   for d in self._list)


class ExistingDataSetIterator(ListDataSetIterator):
    """Reference: ExistingDataSetIterator — wraps an existing collection."""


class AsyncDataSetIterator(DataSetIterator):
    """Wraps any DataSetIterator with a background prefetch thread and a
    bounded queue (reference: deeplearning4j AsyncDataSetIterator with
    queue size N). Keeps the accelerator fed while the host parses the
    next batch."""

    _END = object()

    def __init__(self, base: DataSetIterator, queue_size: int = 4):
        super().__init__(base.batch())
        self._base = base
        self._qsize = queue_size
        self._queue: queue.Queue = None
        self._thread = None
        self._start()

    def _start(self):
        self._base.reset()
        self._queue = queue.Queue(maxsize=self._qsize)
        self._error = None
        self._done = False

        def produce():
            try:
                while True:
                    if not self._base.hasNext():
                        break
                    self._queue.put(self._base.next())
            except Exception as e:  # surface in consumer
                self._error = e
            finally:
                self._queue.put(self._END)

        self._thread = threading.Thread(target=produce, daemon=True)
        self._thread.start()

    def reset(self):
        # drain current thread then restart; drain only while _END is
        # still in flight (if the consumer already took it, a blind
        # get() would block forever on the empty queue), then join so
        # the old producer can't interleave with the new epoch's
        t = self._thread
        if t is not None and t.is_alive():
            if not self._done:
                while self._queue.get() is not self._END:
                    pass
            t.join(timeout=5.0)
        self._start()
        self._peek = None

    def _next_batch(self):
        if self._done:
            return None  # exhausted: don't block on the dead producer
        item = self._queue.get()
        if item is self._END:
            self._done = True
            if self._error is not None:
                raise self._error
            return None
        return item

    def resetSupported(self):
        return self._base.resetSupported()
