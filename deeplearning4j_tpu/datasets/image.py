"""Image loading + augmentation pipeline.

Reference capability: `datavec-data-image` —
org.datavec.image.recordreader.ImageRecordReader (+
ParentPathLabelGenerator), org.datavec.image.loader.NativeImageLoader
(JavaCPP OpenCV) and org.datavec.image.transform.* augmentations
(SURVEY.md §2.4; VERDICT.md round-1 missing item 2: "without an image
input path the ResNet-50 north-star config cannot be trained
end-to-end"). Decoding is host-side PIL/numpy — ETL stays off the
device; arrays come out NCHW float32, the layout every conv layer here
expects."""

from __future__ import annotations

import os

import numpy as np

from deeplearning4j_tpu.datasets.records import InputSplit, RecordReader


def _require_pil():
    try:
        from PIL import Image  # noqa: F401

        return Image
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "image loading needs Pillow (PIL), which is unavailable") from e


class PathLabelGenerator:
    def getLabelForPath(self, path) -> str:
        raise NotImplementedError


class ParentPathLabelGenerator(PathLabelGenerator):
    """Label = name of the file's parent directory (the reference's
    standard image-folder-tree convention)."""

    def getLabelForPath(self, path):
        return os.path.basename(os.path.dirname(os.path.abspath(path)))


def _bilinear_resize_chw(src_hwc_u8: np.ndarray, oh: int,
                         ow: int) -> np.ndarray:
    """numpy twin of the native resize_hwc_to_chw kernel: half-pixel-center
    classic bilinear (no antialiasing), [H,W,C]u8 -> [C,oh,ow]f32."""
    h, w, _ = src_hwc_u8.shape
    fy = np.clip((np.arange(oh) + 0.5) * h / oh - 0.5, 0, None)
    fx = np.clip((np.arange(ow) + 0.5) * w / ow - 0.5, 0, None)
    y0 = np.minimum(fy.astype(np.int64), h - 1)
    x0 = np.minimum(fx.astype(np.int64), w - 1)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (fy - y0)[:, None, None].astype(np.float32)
    wx = (fx - x0)[None, :, None].astype(np.float32)
    s = src_hwc_u8.astype(np.float32)
    top = s[y0][:, x0] * (1 - wx) + s[y0][:, x1] * wx
    bot = s[y1][:, x0] * (1 - wx) + s[y1][:, x1] * wx
    return (top * (1 - wy) + bot * wy).transpose(2, 0, 1)


class NativeImageLoader:
    """Decode one image file -> [C,H,W] float32 (reference:
    org.datavec.image.loader.NativeImageLoader, minus OpenCV)."""

    def __init__(self, height, width, channels=3):
        self.height, self.width, self.channels = height, width, channels

    def _decode_hwc(self, path_or_image) -> np.ndarray:
        """Decode + color-convert to [H,W,C] uint8 at SOURCE resolution
        (no resize)."""
        img = path_or_image
        if isinstance(img, np.ndarray):
            if img.dtype != np.uint8:
                raise ValueError(
                    f"asMatrix ndarray input must be uint8 [H,W,C] "
                    f"(got dtype {img.dtype}); normalize AFTER loading "
                    f"with a DataNormalization, not before")
            hwc = img[:, :, None] if img.ndim == 2 else img
        else:
            if not hasattr(img, "convert"):
                Image = _require_pil()
                img = Image.open(path_or_image)
            img = img.convert("L" if self.channels == 1 else "RGB")
            hwc = np.asarray(img, np.uint8)
            if hwc.ndim == 2:
                hwc = hwc[:, :, None]
        if hwc.shape[2] != self.channels:
            if self.channels == 1:
                # luma conversion, same coefficients as PIL convert("L")
                hwc = (hwc[:, :, :3].astype(np.float32)
                       @ np.asarray([0.299, 0.587, 0.114], np.float32))
                hwc = hwc.astype(np.uint8)[:, :, None]
            elif self.channels == 3 and hwc.shape[2] == 1:
                hwc = np.repeat(hwc, 3, axis=2)
            else:
                raise ValueError(
                    f"cannot convert {hwc.shape[2]}-channel image to "
                    f"{self.channels} channels")
        if hwc.shape[0] == 0 or hwc.shape[1] == 0:
            raise ValueError(f"empty image {hwc.shape}")
        return hwc

    def asMatrix(self, path_or_image) -> np.ndarray:
        """Resize semantics are classic half-pixel-center bilinear (OpenCV
        INTER_LINEAR — what the reference's NativeImageLoader does), NOT
        PIL's antialiased downscale. The native kernel and the numpy
        fallback implement the SAME math, so pixel values do not depend
        on whether the g++ toolchain was present. PIL is used only to
        decode files and convert color modes."""
        from deeplearning4j_tpu import native

        hwc = self._decode_hwc(path_or_image)
        if hwc.shape[0] == self.height and hwc.shape[1] == self.width:
            # identity resize: half-pixel-center bilinear at 1:1 scale
            # maps every output pixel exactly onto its source pixel
            # (fy = i, wy = 0), so the interpolation reduces to a cast
            return np.ascontiguousarray(
                hwc.transpose(2, 0, 1)).astype(np.float32)
        if native.available():
            chw = native.resize_hwc_to_chw(hwc, self.height, self.width)
            if chw is not None:
                return chw
        return _bilinear_resize_chw(hwc, self.height, self.width)

    def asBytes(self, path_or_image) -> np.ndarray | None:
        """[C,H,W] uint8 when the decoded image is ALREADY exactly
        height x width (no resample needed), else None. The uint8 form
        is bit-faithful: ``asBytes(p).astype(float32) == asMatrix(p)``
        whenever it is available, which is what lets ETL workers ship
        quarter-size decode output over IPC and defer the float cast to
        the consumer (or the device) without changing a single pixel."""
        hwc = self._decode_hwc(path_or_image)
        if hwc.shape[0] == self.height and hwc.shape[1] == self.width:
            return np.ascontiguousarray(hwc.transpose(2, 0, 1))
        return None


# ---------------------------------------------------------------------------
# augmentation transforms (reference: org.datavec.image.transform)
# ---------------------------------------------------------------------------

class ImageTransform:
    """Transforms operate on [C,H,W] float arrays with an optional rng."""

    def transform(self, arr: np.ndarray, rng=None) -> np.ndarray:
        raise NotImplementedError


class ResizeImageTransform(ImageTransform):
    def __init__(self, newHeight, newWidth):
        self.h, self.w = newHeight, newWidth

    def transform(self, arr, rng=None):
        Image = _require_pil()
        chans = [np.asarray(
            Image.fromarray(c).resize((self.w, self.h),
                                      Image.Resampling.BILINEAR),
            np.float32) for c in arr]
        return np.stack(chans, 0)


class FlipImageTransform(ImageTransform):
    """flipMode: 0 = vertical, 1 = horizontal, -1 = both (OpenCV codes,
    same as the reference); None = random choice per call."""

    def __init__(self, flipMode=1):
        self.mode = flipMode

    def transform(self, arr, rng=None):
        mode = self.mode
        if mode is None:
            mode = (rng or np.random.default_rng()).integers(-1, 2)
        if mode in (0, -1):
            arr = arr[:, ::-1, :]
        if mode in (1, -1):
            arr = arr[:, :, ::-1]
        return np.ascontiguousarray(arr)


class CropImageTransform(ImageTransform):
    """Random crop by up to the given margins (reference semantics)."""

    def __init__(self, cropTop=0, cropLeft=0, cropBottom=0, cropRight=0):
        if cropLeft == 0 and cropBottom == 0 and cropRight == 0 \
                and cropTop > 0:
            # single-arg form crops all sides up to N
            cropLeft = cropBottom = cropRight = cropTop
        self.t, self.l, self.b, self.r = (cropTop, cropLeft, cropBottom,
                                          cropRight)

    def transform(self, arr, rng=None):
        rng = rng or np.random.default_rng()
        _, h, w = arr.shape
        t = int(rng.integers(0, self.t + 1))
        l = int(rng.integers(0, self.l + 1))
        b = int(rng.integers(0, self.b + 1))
        r = int(rng.integers(0, self.r + 1))
        return np.ascontiguousarray(arr[:, t:h - b, :][:, :, l:w - r])


class ScaleImageTransform(ImageTransform):
    def __init__(self, delta):
        self.delta = delta

    def transform(self, arr, rng=None):
        rng = rng or np.random.default_rng()
        s = 1.0 + float(rng.uniform(-self.delta, self.delta))
        Image = _require_pil()
        _, h, w = arr.shape
        nh, nw = max(1, int(h * s)), max(1, int(w * s))
        chans = [np.asarray(
            Image.fromarray(c).resize((nw, nh),
                                      Image.Resampling.BILINEAR),
            np.float32) for c in arr]
        return np.stack(chans, 0)


class PipelineImageTransform(ImageTransform):
    """Sequence of (transform, probability) applied in order (reference:
    PipelineImageTransform; shuffle=False ordering)."""

    def __init__(self, transforms, seed=None):
        # accepts [transform, ...] or [(transform, prob), ...]
        self.steps = [(t, 1.0) if isinstance(t, ImageTransform) else t
                      for t in transforms]
        self.rng = np.random.default_rng(seed)

    def transform(self, arr, rng=None):
        rng = rng or self.rng
        for t, p in self.steps:
            if p >= 1.0 or rng.random() < p:
                arr = t.transform(arr, rng)
        return arr


# ---------------------------------------------------------------------------
# ImageRecordReader
# ---------------------------------------------------------------------------

class ImageRecordReader(RecordReader):
    """Walk an image-folder tree -> records [image [C,H,W] f32, labelIdx]
    (reference: org.datavec.image.recordreader.ImageRecordReader).

    Labels are the sorted unique values from the label generator, fixed
    at initialize() so the class-index mapping is stable across epochs."""

    def __init__(self, height, width, channels=3,
                 labelGenerator: PathLabelGenerator | None = None,
                 imageTransform: ImageTransform | None = None, seed=None):
        self.loader = NativeImageLoader(height, width, channels)
        self.labelGen = labelGenerator
        self.imageTransform = imageTransform
        self.rng = np.random.default_rng(seed)
        self._files = []
        self._labels = []
        self._pos = 0

    def initialize(self, split: InputSplit):
        self._files = [f for f in split.locations()
                       if f.lower().endswith((".png", ".jpg", ".jpeg",
                                              ".bmp", ".gif"))]
        if self.labelGen is not None:
            self._labels = sorted(
                {self.labelGen.getLabelForPath(f) for f in self._files})
        self._pos = 0

    def getLabels(self):
        return list(self._labels)

    def numLabels(self):
        return len(self._labels)

    def hasNext(self):
        return self._pos < len(self._files)

    def next(self):
        if not self.hasNext():
            raise StopIteration
        path = self._files[self._pos]
        self._pos += 1
        arr = self.loader.asMatrix(path)
        if self.imageTransform is not None:
            arr = self.imageTransform.transform(arr, self.rng)
        rec = [arr]
        if self.labelGen is not None:
            rec.append(self._labels.index(
                self.labelGen.getLabelForPath(path)))
        return rec

    def reset(self):
        self._pos = 0
