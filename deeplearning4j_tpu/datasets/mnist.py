"""MNIST iterator.

Reference capability: deeplearning4j-datasets
org.deeplearning4j.datasets.iterator.impl.MnistDataSetIterator (the
LeNet-MNIST baseline config input, BASELINE.json configs[0]). The
reference downloads the IDX files; this environment has no egress, so:

  1. if IDX files exist under `data_dir` (train-images-idx3-ubyte etc.,
     optionally .gz), they are loaded exactly like the reference;
  2. otherwise a DETERMINISTIC procedural digit set is synthesized:
     7-segment-style glyphs rendered onto 28x28 with random translation,
     scale jitter, and pixel noise. The synthetic set is learnable (a
     LeNet reaches >95% on it), making smoke benchmarks meaningful.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator

# 7-segment encodings per digit: segments (top, top-left, top-right, middle,
# bottom-left, bottom-right, bottom)
_SEGMENTS = {
    0: (1, 1, 1, 0, 1, 1, 1),
    1: (0, 0, 1, 0, 0, 1, 0),
    2: (1, 0, 1, 1, 1, 0, 1),
    3: (1, 0, 1, 1, 0, 1, 1),
    4: (0, 1, 1, 1, 0, 1, 0),
    5: (1, 1, 0, 1, 0, 1, 1),
    6: (1, 1, 0, 1, 1, 1, 1),
    7: (1, 0, 1, 0, 0, 1, 0),
    8: (1, 1, 1, 1, 1, 1, 1),
    9: (1, 1, 1, 1, 0, 1, 1),
}


def _render_digit(d, rng):
    """Render one 28x28 glyph with jitter."""
    img = np.zeros((28, 28), np.float32)
    seg = _SEGMENTS[d]
    # base glyph box ~ rows 4..24, cols 8..20, thickness 2
    t = 2
    x0, x1 = 8, 19
    y0, ym, y1 = 4, 13, 23
    bars = [
        (seg[0], (y0, y0 + t), (x0, x1 + 1)),          # top
        (seg[1], (y0, ym + 1), (x0, x0 + t)),          # top-left
        (seg[2], (y0, ym + 1), (x1 - t + 1, x1 + 1)),  # top-right
        (seg[3], (ym, ym + t), (x0, x1 + 1)),          # middle
        (seg[4], (ym, y1 + 1), (x0, x0 + t)),          # bottom-left
        (seg[5], (ym, y1 + 1), (x1 - t + 1, x1 + 1)),  # bottom-right
        (seg[6], (y1 - t + 1, y1 + 1), (x0, x1 + 1)),  # bottom
    ]
    for on, (r0, r1), (c0, c1) in bars:
        if on:
            img[r0:r1, c0:c1] = 1.0
    # jitter: translate +-3 px, brightness scale, additive noise
    dy, dx = rng.integers(-3, 4, size=2)
    img = np.roll(np.roll(img, dy, axis=0), dx, axis=1)
    img *= rng.uniform(0.7, 1.0)
    img += rng.normal(0, 0.08, img.shape).astype(np.float32)
    return np.clip(img, 0.0, 1.0)


def synthesize_mnist(n, seed=123):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n)
    images = np.stack([_render_digit(int(d), rng) for d in labels])
    return images.reshape(n, 784).astype(np.float32), labels.astype(np.int64)


def _read_idx(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(ndim)]
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _find_idx(data_dir, stem):
    for name in (stem, stem + ".gz"):
        p = os.path.join(data_dir, name)
        if os.path.exists(p):
            return p
    return None


class MnistDataSetIterator(DataSetIterator):
    def __init__(self, batch_size=128, train=True, seed=123, data_dir=None,
                 num_examples=None, binarize=False):
        super().__init__(batch_size)
        data_dir = data_dir or os.environ.get("MNIST_DIR")
        imgs = lbls = None
        if data_dir:
            stem = ("train" if train else "t10k")
            ip = _find_idx(data_dir, f"{stem}-images-idx3-ubyte")
            lp = _find_idx(data_dir, f"{stem}-labels-idx1-ubyte")
            if ip and lp:
                imgs = (_read_idx(ip).reshape(-1, 784).astype(np.float32)
                        / 255.0)
                lbls = _read_idx(lp).astype(np.int64)
        if imgs is None:
            n = num_examples or (10000 if train else 2000)
            imgs, lbls = synthesize_mnist(n, seed if train else seed + 1)
            self.synthetic = True
        else:
            self.synthetic = False
        if num_examples:
            imgs, lbls = imgs[:num_examples], lbls[:num_examples]
        if binarize:
            imgs = (imgs > 0.5).astype(np.float32)
        self._images = imgs
        self._onehot = np.eye(10, dtype=np.float32)[lbls]
        self._pos = 0

    def totalOutcomes(self):
        return 10

    def inputColumns(self):
        return 784

    def totalExamples(self):
        return self._images.shape[0]

    def reset(self):
        self._pos = 0
        self._peek = None

    def _next_batch(self):
        if self._pos >= self._images.shape[0]:
            return None
        i, j = self._pos, self._pos + self._batch
        self._pos = j
        return DataSet(self._images[i:j], self._onehot[i:j])
