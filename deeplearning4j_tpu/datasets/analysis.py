"""Column analysis over record sources (VERDICT r4 item 5).

Reference: org.datavec.local.transforms.AnalyzeLocal +
org.datavec.api.transform.analysis.DataAnalysis (SURVEY.md §2.4): one
pass over the data computing per-column statistics keyed by the
schema's column types — numeric columns get min/max/mean/stddev (Welford
one-pass, so a long stream never materializes), all columns get
total/missing counts, string/categorical columns get distinct values
with occurrence counts."""

from __future__ import annotations

import math

from deeplearning4j_tpu.datasets.transform import ColumnType


class NumericalColumnAnalysis:
    def __init__(self, name):
        self.name = name
        self.countTotal = 0
        self.countMissing = 0
        self._mean = 0.0
        self._m2 = 0.0
        self.min = None
        self.max = None

    def _update(self, value):
        self.countTotal += 1
        if value is None or value == "":
            self.countMissing += 1
            return
        v = float(value)
        n = self.countTotal - self.countMissing
        d = v - self._mean
        self._mean += d / n
        self._m2 += d * (v - self._mean)
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def getMin(self):
        return self.min

    def getMax(self):
        return self.max

    def getMean(self):
        return self._mean

    def getSampleStdev(self):
        n = self.countTotal - self.countMissing
        return math.sqrt(self._m2 / (n - 1)) if n > 1 else 0.0

    def __repr__(self):
        return (f"NumericalColumnAnalysis(min={self.min}, max={self.max},"
                f" mean={self._mean:.6g}, stdev={self.getSampleStdev():.6g},"
                f" count={self.countTotal}, missing={self.countMissing})")


class CategoricalColumnAnalysis:
    def __init__(self, name):
        self.name = name
        self.countTotal = 0
        self.countMissing = 0
        self.counts = {}

    def _update(self, value):
        self.countTotal += 1
        if value is None or value == "":
            self.countMissing += 1
            return
        key = str(value)
        self.counts[key] = self.counts.get(key, 0) + 1

    def getUnique(self):
        return len(self.counts)

    def getMapOfUniqueToCount(self):
        return dict(self.counts)

    def __repr__(self):
        return (f"CategoricalColumnAnalysis(unique={self.getUnique()}, "
                f"count={self.countTotal}, missing={self.countMissing})")


class DataAnalysis:
    def __init__(self, schema, analyses):
        self.schema = schema
        self._by_name = analyses

    def getColumnAnalysis(self, name):
        return self._by_name[name]

    def __repr__(self):
        lines = ["DataAnalysis:"]
        for c in self.schema.columns:
            lines.append(f"  {c[0]} ({c[1]}): {self._by_name[c[0]]!r}")
        return "\n".join(lines)


_NUMERIC = {ColumnType.Integer, ColumnType.Long, ColumnType.Double,
            ColumnType.Float}


class AnalyzeLocal:
    @staticmethod
    def analyze(schema, source) -> DataAnalysis:
        """source: a RecordReader (drained via hasNext/next) or any
        iterable of records."""
        cols = schema.columns
        analyses = {}
        for name, ctype, _meta in cols:
            analyses[name] = (NumericalColumnAnalysis(name)
                              if ctype in _NUMERIC
                              else CategoricalColumnAnalysis(name))
        if hasattr(source, "hasNext"):
            def gen():
                while source.hasNext():
                    yield source.next()
            records = gen()
        else:
            records = iter(source)
        for rec in records:
            if len(rec) != len(cols):
                raise ValueError(
                    f"record width {len(rec)} != schema width "
                    f"{len(cols)}: {rec!r}")
            for (name, _t, _m), val in zip(cols, rec):
                analyses[name]._update(val)
        return DataAnalysis(schema, analyses)
