"""Data/ETL layer (reference L3: DataVec + dataset iterators, SURVEY.md
§2.4)."""

from deeplearning4j_tpu.datasets.dataset import (  # noqa: F401
    DataSet, MultiDataSet, SplitTestAndTrain)
from deeplearning4j_tpu.datasets.iterator import (  # noqa: F401
    AsyncDataSetIterator, DataSetIterator, ExistingDataSetIterator,
    ListDataSetIterator)
from deeplearning4j_tpu.datasets.mnist import (  # noqa: F401
    MnistDataSetIterator, synthesize_mnist)
from deeplearning4j_tpu.datasets.records import (  # noqa: F401
    CollectionRecordReader, CSVRecordReader, FileSplit, InputSplit,
    LineRecordReader, ListStringSplit, RecordReader,
    RecordReaderDataSetIterator, RecordReaderMultiDataSetIterator)
from deeplearning4j_tpu.datasets.join import (  # noqa: F401
    Join, JoinType, executeJoin)
from deeplearning4j_tpu.datasets.analysis import (  # noqa: F401
    AnalyzeLocal, CategoricalColumnAnalysis, DataAnalysis,
    NumericalColumnAnalysis)
from deeplearning4j_tpu.datasets.normalizers import (  # noqa: F401
    ImagePreProcessingScaler, Normalizer, NormalizerMinMaxScaler,
    NormalizerStandardize)
from deeplearning4j_tpu.datasets.transform import (  # noqa: F401
    CategoricalColumnCondition, ColumnType, ConditionOp,
    DoubleColumnCondition, MathFunction, MathOp, Schema,
    StringColumnCondition, TransformProcess, TransformProcessRecordReader)
from deeplearning4j_tpu.datasets.image import (  # noqa: F401
    CropImageTransform, FlipImageTransform, ImageRecordReader,
    ImageTransform, NativeImageLoader, ParentPathLabelGenerator,
    PathLabelGenerator, PipelineImageTransform, ResizeImageTransform,
    ScaleImageTransform)
from deeplearning4j_tpu.datasets.parallel_etl import (  # noqa: F401
    EtlWorkerPool, LocalTransformExecutor, ParallelImageDataSetIterator,
    shared_pool)
from deeplearning4j_tpu.datasets.prefetch import (  # noqa: F401
    DeviceBatch, DevicePrefetcher, default_depth, set_default_depth)
