"""DataSet / MultiDataSet containers.

Reference capability: org.nd4j.linalg.dataset.{DataSet, MultiDataSet}
(SURVEY.md §2.4 "Iterator bridge"): features+labels (+masks) minibatch
containers with split/shuffle/save. Arrays stay host-side numpy until the
compiled step consumes them — device transfer happens once per step, not
per accessor."""

from __future__ import annotations

import numpy as np


def _np(x):
    if hasattr(x, "toNumpy"):
        return x.toNumpy()
    return np.asarray(x)


class DataSet:
    def __init__(self, features=None, labels=None, featuresMask=None,
                 labelsMask=None):
        self.features = _np(features) if features is not None else None
        self.labels = _np(labels) if labels is not None else None
        self.featuresMask = _np(featuresMask) if featuresMask is not None \
            else None
        self.labelsMask = _np(labelsMask) if labelsMask is not None else None

    # reference accessor names
    def getFeatures(self):
        return self.features

    def getLabels(self):
        return self.labels

    def getFeaturesMaskArray(self):
        return self.featuresMask

    def getLabelsMaskArray(self):
        return self.labelsMask

    def setFeatures(self, f):
        self.features = _np(f)

    def setLabels(self, l):
        self.labels = _np(l)

    def numExamples(self) -> int:
        return 0 if self.features is None else self.features.shape[0]

    def sample(self, n, rng=None) -> "DataSet":
        rng = rng or np.random.default_rng()
        idx = rng.choice(self.numExamples(), size=n, replace=False)
        return DataSet(self.features[idx],
                       None if self.labels is None else self.labels[idx])

    def splitTestAndTrain(self, fraction_or_n, rng=None):
        """fraction in (0,1) or absolute train count; returns SplitTestAndTrain
        with .train/.test (reference: DataSet.splitTestAndTrain)."""
        n = self.numExamples()
        n_train = int(fraction_or_n * n) if isinstance(
            fraction_or_n, float) and 0 < fraction_or_n < 1 \
            else int(fraction_or_n)
        train = DataSet(
            self.features[:n_train],
            None if self.labels is None else self.labels[:n_train])
        test = DataSet(
            self.features[n_train:],
            None if self.labels is None else self.labels[n_train:])
        return SplitTestAndTrain(train, test)

    def shuffle(self, seed=None):
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.numExamples())
        self.features = self.features[idx]
        if self.labels is not None:
            self.labels = self.labels[idx]
        if self.featuresMask is not None:
            self.featuresMask = self.featuresMask[idx]
        if self.labelsMask is not None:
            self.labelsMask = self.labelsMask[idx]

    def batchBy(self, batch_size) -> list:
        n = self.numExamples()
        return [DataSet(self.features[i:i + batch_size],
                        None if self.labels is None
                        else self.labels[i:i + batch_size])
                for i in range(0, n, batch_size)]

    def asList(self) -> list:
        return self.batchBy(1)

    @staticmethod
    def merge(datasets) -> "DataSet":
        return DataSet(
            np.concatenate([d.features for d in datasets]),
            np.concatenate([d.labels for d in datasets])
            if datasets[0].labels is not None else None)

    def save(self, path):
        # np.savez silently appends .npz; normalize so load(path) matches
        if not str(path).endswith(".npz"):
            path = str(path) + ".npz"
        np.savez(path, **{k: v for k, v in [
            ("features", self.features), ("labels", self.labels),
            ("featuresMask", self.featuresMask),
            ("labelsMask", self.labelsMask)] if v is not None})

    @staticmethod
    def load(path) -> "DataSet":
        if not str(path).endswith(".npz"):
            path = str(path) + ".npz"
        z = np.load(path)
        return DataSet(z.get("features"), z.get("labels"),
                       z.get("featuresMask"), z.get("labelsMask"))

    def __repr__(self):
        fs = None if self.features is None else self.features.shape
        ls = None if self.labels is None else self.labels.shape
        return f"DataSet(features={fs}, labels={ls})"


class SplitTestAndTrain:
    def __init__(self, train, test):
        self.train = train
        self.test = test

    def getTrain(self):
        return self.train

    def getTest(self):
        return self.test


class MultiDataSet:
    """Multi-input/multi-output container (reference:
    org.nd4j.linalg.dataset.MultiDataSet)."""

    def __init__(self, features=None, labels=None, featuresMasks=None,
                 labelsMasks=None):
        as_list = lambda v: None if v is None else [  # noqa: E731
            _np(x) for x in (v if isinstance(v, (list, tuple)) else [v])]
        self.features = as_list(features) or []
        self.labels = as_list(labels) or []
        self.featuresMasks = as_list(featuresMasks)
        self.labelsMasks = as_list(labelsMasks)

    def getFeatures(self, i=None):
        return self.features if i is None else self.features[i]

    def getLabels(self, i=None):
        return self.labels if i is None else self.labels[i]

    def numFeatureArrays(self):
        return len(self.features)

    def numLabelsArrays(self):
        return len(self.labels)

    def numExamples(self):
        return 0 if not self.features else self.features[0].shape[0]
