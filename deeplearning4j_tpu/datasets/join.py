"""Relational Join of two record sources (VERDICT r4 item 5).

Reference: org.datavec.api.transform.join.Join (SURVEY.md §2.4 transform
row — Schema/TransformProcess "map/filter/join"): a hash join on key
columns; output schema = left columns + right columns minus the right
key columns; Inner/LeftOuter/RightOuter/FullOuter types with None fill
for the missing side (the reference uses NullWritable)."""

from __future__ import annotations

from deeplearning4j_tpu.datasets.transform import Schema


class JoinType:
    INNER = "Inner"
    LEFT_OUTER = "LeftOuter"
    RIGHT_OUTER = "RightOuter"
    FULL_OUTER = "FullOuter"


class Join:
    def __init__(self, joinType, leftSchema, rightSchema,
                 leftColumns, rightColumns):
        self.joinType = joinType
        self.leftSchema = leftSchema
        self.rightSchema = rightSchema
        self.leftColumns = list(leftColumns)
        self.rightColumns = list(rightColumns)
        if len(self.leftColumns) != len(self.rightColumns):
            raise ValueError(
                f"join key arity mismatch: {self.leftColumns} vs "
                f"{self.rightColumns}")
        for n in self.leftColumns:
            leftSchema.getIndexOfColumn(n)   # raises if absent
        for n in self.rightColumns:
            rightSchema.getIndexOfColumn(n)

    # -- schema -------------------------------------------------------------
    def getOutputSchema(self) -> Schema:
        rkeys = set(self.rightColumns)
        cols = list(self.leftSchema.columns)
        cols += [c for c in self.rightSchema.columns if c[0] not in rkeys]
        names = [c[0] for c in cols]
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise ValueError(
                f"joined schema has duplicate non-key columns {dupes} — "
                "rename them before joining")
        return Schema(cols)

    # -- execution ----------------------------------------------------------
    def execute(self, leftRecords, rightRecords):
        """Hash join; multiple matches per key produce the cross product
        (standard relational semantics)."""
        lk = [self.leftSchema.getIndexOfColumn(n)
              for n in self.leftColumns]
        rk = [self.rightSchema.getIndexOfColumn(n)
              for n in self.rightColumns]
        r_rest = [i for i in range(self.rightSchema.numColumns())
                  if i not in set(rk)]
        table = {}
        for rr in rightRecords:
            table.setdefault(tuple(rr[i] for i in rk), []).append(rr)
        out, matched_right = [], set()
        for lr in leftRecords:
            key = tuple(lr[i] for i in lk)
            hits = table.get(key)
            if hits:
                matched_right.add(key)
                for rr in hits:
                    out.append(list(lr) + [rr[i] for i in r_rest])
            elif self.joinType in (JoinType.LEFT_OUTER,
                                   JoinType.FULL_OUTER):
                out.append(list(lr) + [None] * len(r_rest))
        if self.joinType in (JoinType.RIGHT_OUTER, JoinType.FULL_OUTER):
            n_left = self.leftSchema.numColumns()
            for key, rows in table.items():
                if key in matched_right:
                    continue
                for rr in rows:
                    left_fill = [None] * n_left
                    # key columns surface through the LEFT slots
                    for li, ki in zip(lk, range(len(key))):
                        left_fill[li] = key[ki]
                    out.append(left_fill + [rr[i] for i in r_rest])
        return out

    class Builder:
        def __init__(self, joinType=JoinType.INNER):
            self._type = joinType
            self._left = None
            self._right = None
            self._lcols = None
            self._rcols = None

        def setJoinType(self, joinType):
            self._type = joinType
            return self

        def setSchemas(self, leftSchema, rightSchema):
            self._left, self._right = leftSchema, rightSchema
            return self

        def setKeyColumns(self, *names):
            """Same key column names on both sides."""
            self._lcols = self._rcols = list(names)
            return self

        def setKeyColumnsLeft(self, *names):
            self._lcols = list(names)
            return self

        def setKeyColumnsRight(self, *names):
            self._rcols = list(names)
            return self

        def build(self) -> "Join":
            if self._left is None or self._right is None:
                raise ValueError("setSchemas(left, right) is required")
            if not self._lcols or not self._rcols:
                raise ValueError("join key columns are required")
            return Join(self._type, self._left, self._right,
                        self._lcols, self._rcols)


def executeJoin(join: Join, leftReader, rightReader):
    """Drain two RecordReaders and join them (reference analog:
    LocalTransformExecutor.executeJoin). Returns the joined records;
    feed them onward with CollectionRecordReader."""
    left = []
    while leftReader.hasNext():
        left.append(leftReader.next())
    right = []
    while rightReader.hasNext():
        right.append(rightReader.next())
    return join.execute(left, right)
